"""Benchmark driver: prints ONE JSON line to stdout.

Headline kernel: Krum robust aggregation — the reference's #1 hotspot, an
O(n^2 d) Python dict of pairwise norms plus a per-user sort
(reference defences.py:16-42).  Here it is one Gram matmul + top-k on the
TPU MXU (defenses/kernels.py).  The baseline is a NumPy/BLAS
implementation of the same exact semantics (defenses/oracle.py math,
vectorized Gram form — already far faster than the reference's Python
double loop, so the reported speedup is a *lower* bound on the advantage
over the reference itself) measured on this host's CPU.

Output: {"metric": "krum_agg_2048c_wall_ms", "value": <tpu_ms>,
         "unit": "ms", "vs_baseline": <cpu_ms / tpu_ms>}

Diagnostics (including a 10k-client TPU-only probe toward the
BASELINE.md north star) go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


N_CLIENTS = 2048
DIM = 79_510          # MNIST MLP wire dim (reference data_sets.py:13-23)
F_FRAC = 0.24         # reference default mal proportion (main.py:106)
REPEATS = 5


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def numpy_krum_ms(G: np.ndarray, f: int) -> float:
    """Reference-semantics Krum (sum of n-f smallest distances, argmin)
    in vectorized NumPy/BLAS — the strongest honest CPU baseline."""
    t0 = time.perf_counter()
    sq = np.einsum("nd,nd->n", G, G)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (G @ G.T)
    np.maximum(d2, 0.0, out=d2)
    D = np.sqrt(d2)
    np.fill_diagonal(D, np.inf)
    k = G.shape[0] - f
    srt = np.sort(D, axis=1)[:, : min(k, G.shape[0] - 1)]
    _ = G[int(np.argmin(srt.sum(axis=1)))]
    return 1e3 * (time.perf_counter() - t0)


def tpu_krum_ms(G, f, krum, jax) -> float:
    out = krum(G, G.shape[0], f)          # compile + warm
    jax.block_until_ready(out)
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        jax.block_until_ready(krum(G, G.shape[0], f))
        times.append(1e3 * (time.perf_counter() - t0))
    return float(np.median(times))


def ensure_live_backend(probe_timeout=240):
    """Guard against a dead TPU tunnel: probe jax backend init in a
    subprocess; on timeout re-exec on CPU so the bench always completes.
    (On this image a relay process brokers the TPU; if it is down, jax
    device init blocks forever.)"""
    import os
    import subprocess

    if os.environ.get("_BENCH_BACKEND_CHECKED"):
        return
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=probe_timeout, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        os.environ["_BENCH_BACKEND_CHECKED"] = "1"
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        log("TPU backend unreachable; falling back to CPU")
        os.environ.update(_BENCH_BACKEND_CHECKED="1", JAX_PLATFORMS="cpu",
                          PALLAS_AXON_POOL_IPS="")
        os.execve(sys.executable, [sys.executable] + sys.argv, os.environ)


def main():
    ensure_live_backend()
    import jax
    import jax.numpy as jnp

    from attacking_federate_learning_tpu.defenses.kernels import krum

    dev = jax.devices()[0]
    on_accel = dev.platform not in ("cpu",)
    n = N_CLIENTS if on_accel else 512  # keep the CPU fallback tractable
    log(f"device: {dev.platform} ({dev.device_kind}); "
        f"n={n} d={DIM} f={int(F_FRAC * n)}")

    rng = np.random.default_rng(0)
    G_host = rng.standard_normal((n, DIM)).astype(np.float32)
    f = int(F_FRAC * n)

    # --- baseline: NumPy/BLAS on host CPU ------------------------------
    cpu_ms = numpy_krum_ms(G_host, f)
    log(f"numpy/BLAS krum: {cpu_ms:.1f} ms")

    # --- ours: XLA kernel on the default device ------------------------
    krum_jit = jax.jit(krum, static_argnums=(1, 2))
    G = jax.device_put(jnp.asarray(G_host), dev)
    dev_ms = tpu_krum_ms(G, f, krum_jit, jax)
    log(f"xla krum ({dev.platform}): {dev_ms:.2f} ms "
        f"(median of {REPEATS})")

    # --- secondary: full FL round throughput (stderr diagnostic) --------
    try:
        from attacking_federate_learning_tpu.attacks import DriftAttack
        from attacking_federate_learning_tpu.config import ExperimentConfig
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.data.datasets import load_dataset

        for n_clients in (10, 512):
            cfg = ExperimentConfig(
                dataset="SYNTH_MNIST", users_count=n_clients,
                mal_prop=0.24, batch_size=64, epochs=1, defense="Krum")
            ds = load_dataset(cfg.dataset, seed=0, synth_train=8192,
                              synth_test=512)
            exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5),
                                      dataset=ds)
            reps = 20
            exp.run_span(0, reps)  # compile the scanned span
            jax.block_until_ready(exp.state.weights)
            t0 = time.perf_counter()
            exp.run_span(reps, reps)  # one device program for all rounds
            jax.block_until_ready(exp.state.weights)
            rps = reps / (time.perf_counter() - t0)
            log(f"fl_rounds_per_sec (Krum+ALIE, {n_clients} clients, "
                f"mnist-mlp, scanned span): {rps:.1f}")
    except Exception as e:
        log(f"round-throughput probe skipped: {type(e).__name__}: {e}")

    # --- north-star probe: 10k clients, TPU only (stderr) ---------------
    try:
        if not on_accel:
            raise RuntimeError("accelerator not available")
        n10k = 10_240
        G10 = jax.device_put(
            jnp.asarray(rng.standard_normal((n10k, DIM)).astype(np.float32)))
        ms10 = tpu_krum_ms(G10, int(F_FRAC * n10k), krum_jit, jax)
        log(f"north-star: krum @ {n10k} clients, d={DIM}: {ms10:.1f} ms")
        del G10
    except Exception as e:  # OOM on small hosts is fine — diagnostic only
        log(f"10k-client probe skipped: {type(e).__name__}: {e}")

    print(json.dumps({
        "metric": f"krum_agg_{n}c_wall_ms",
        "value": round(dev_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / dev_ms, 2),
    }))


if __name__ == "__main__":
    main()
