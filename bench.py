"""Benchmark driver: prints ONE JSON line to stdout.

Headline kernel: Krum robust aggregation — the reference's #1 hotspot, an
O(n^2 d) Python dict of pairwise norms plus a per-user sort
(reference defences.py:16-42).  Here it is the framework's dispatching
kernel (defenses/kernels.py): one Gram matmul + top-k on the TPU MXU, or
the host-BLAS path on the CPU backend (defenses/host.py).  The baseline is
a NumPy/BLAS implementation of the same exact semantics (defenses/oracle.py
math, vectorized Gram form — already far faster than the reference's Python
double loop, so the reported speedup is a *lower* bound on the advantage
over the reference itself) measured on this host's CPU.

Output: {"metric": "krum_agg_<n>c_wall_ms", "value": <ms>,
         "unit": "ms", "vs_baseline": <cpu_ms / our_ms>}

Diagnostics (per-impl table, MFU estimates, a 10k-client TPU-only probe
toward the BASELINE.md north star, FL round throughput) go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


N_CLIENTS = 2048
DIM = 79_510          # MNIST MLP wire dim (reference data_sets.py:13-23)
F_FRAC = 0.24         # reference default mal proportion (main.py:106)
REPEATS = 5

# Peak f32-accumulation matmul throughput used for the MFU estimate.
# TPU v5e: 197 TFLOP/s bf16, ~98 TFLOP/s f32 (public spec sheet numbers).
PEAK_FLOPS = {"tpu": 98e12, "axon": 98e12}


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def median_ms(fn, repeats=REPEATS):
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(1e3 * (time.perf_counter() - t0))
    return float(np.median(times))


def numpy_krum_ms(G: np.ndarray, f: int) -> float:
    """Reference-semantics Krum (sum of n-f smallest distances, argmin)
    in vectorized NumPy/BLAS — the strongest honest CPU baseline."""

    def run():
        sq = np.einsum("nd,nd->n", G, G)
        d2 = sq[:, None] + sq[None, :] - 2.0 * (G @ G.T)
        np.maximum(d2, 0.0, out=d2)
        D = np.sqrt(d2)
        np.fill_diagonal(D, np.inf)
        k = G.shape[0] - f
        srt = np.sort(D, axis=1)[:, : min(k, G.shape[0] - 1)]
        _ = G[int(np.argmin(srt.sum(axis=1)))]

    return median_ms(run)


def device_krum_ms(G, f, krum_fn, jax) -> float:
    out = krum_fn(G, G.shape[0], f)       # compile + warm
    jax.block_until_ready(out)
    return median_ms(lambda: jax.block_until_ready(krum_fn(G, G.shape[0], f)))


def bench_impl_table(G, f, jax, on_accel):
    """Per-impl diagnostic: every selectable distance engine at this n."""
    import functools

    from attacking_federate_learning_tpu.defenses.kernels import krum

    n = G.shape[0]
    rows = {}
    impls = ["xla"]
    if not on_accel:
        impls.append("host")
    else:
        impls.append("pallas")
    for impl in impls:
        try:
            if impl == "host":
                # Eager host-BLAS dispatch — zero-copy view, no callback.
                fn = functools.partial(krum, distance_impl="host")
                krum_fn = fn
            else:
                krum_fn = jax.jit(
                    functools.partial(krum, distance_impl=impl),
                    static_argnums=(1, 2))
            ms = device_krum_ms(G, f, krum_fn, jax)
            rows[impl] = ms
            log(f"  krum impl={impl:9s} n={n}: {ms:8.2f} ms")
        except Exception as e:
            log(f"  krum impl={impl:9s} n={n}: failed "
                f"({type(e).__name__}: {e})")
    return rows


def mfu_line(tag, flops, ms, platform):
    peak = PEAK_FLOPS.get(platform)
    if peak and ms > 0:
        achieved = flops / (ms * 1e-3)
        log(f"  mfu[{tag}]: {achieved / 1e12:.1f} TFLOP/s = "
            f"{100 * achieved / peak:.1f}% of f32 peak")


def main():
    from attacking_federate_learning_tpu.utils.backend import (
        ensure_live_backend
    )

    ensure_live_backend()
    import jax

    import jax.numpy as jnp

    from attacking_federate_learning_tpu.defenses.kernels import krum

    dev = jax.devices()[0]
    on_accel = dev.platform not in ("cpu",)
    n = N_CLIENTS if on_accel else 512  # keep the CPU fallback tractable
    f = int(F_FRAC * n)
    log(f"device: {dev.platform} ({dev.device_kind}); n={n} d={DIM} f={f}")

    rng = np.random.default_rng(0)
    G_host = rng.standard_normal((n, DIM)).astype(np.float32)

    # --- baseline: NumPy/BLAS on host CPU ------------------------------
    cpu_ms = numpy_krum_ms(G_host, f)
    log(f"numpy/BLAS krum: {cpu_ms:.1f} ms (median of {REPEATS})")

    # --- ours: the framework's dispatching kernel ----------------------
    # On an accelerator: the jitted XLA Gram-matmul path on the chip.
    # On the CPU fallback: distance_impl='auto' resolves to the host-BLAS
    # engine (defenses/host.py) — backend-aware dispatch is the product
    # behavior, not a bench trick.
    import functools

    G = jax.device_put(jnp.asarray(G_host), dev)
    if on_accel:
        krum_fn = jax.jit(krum, static_argnums=(1, 2))
    else:
        # Eager: distance_impl='auto' resolves to the host-BLAS engine.
        krum_fn = functools.partial(krum, distance_impl="auto")
    dev_ms = device_krum_ms(G, f, krum_fn, jax)
    impl = "xla/jit" if on_accel else "host-blas (auto)"
    log(f"framework krum [{impl}] ({dev.platform}): {dev_ms:.2f} ms "
        f"(median of {REPEATS})")
    # Gram matmul dominates: 2 n^2 d FLOPs.
    mfu_line("krum_gram", 2 * n * n * DIM, dev_ms, dev.platform)

    log("per-impl table:")
    bench_impl_table(G, f, jax, on_accel)

    # --- secondary: full FL round throughput (stderr diagnostic) --------
    try:
        from attacking_federate_learning_tpu.attacks import DriftAttack
        from attacking_federate_learning_tpu.config import ExperimentConfig
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.data.datasets import load_dataset

        for n_clients in (10, 512):
            cfg = ExperimentConfig(
                dataset="SYNTH_MNIST", users_count=n_clients,
                mal_prop=0.24, batch_size=64, epochs=1, defense="Krum")
            ds = load_dataset(cfg.dataset, seed=0, synth_train=8192,
                              synth_test=512)
            exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5),
                                      dataset=ds)
            reps = 20
            exp.run_span(0, reps)  # compile the scanned span
            jax.block_until_ready(exp.state.weights)
            t0 = time.perf_counter()
            exp.run_span(reps, reps)  # one device program for all rounds
            jax.block_until_ready(exp.state.weights)
            dt = time.perf_counter() - t0
            rps = reps / dt
            log(f"fl_rounds_per_sec (Krum+ALIE, {n_clients} clients, "
                f"mnist-mlp, scanned span): {rps:.1f}")
            # vmapped fwd/bwd of the MLP: ~6 * n * B * d FLOPs per round.
            mfu_line(f"fl_round_{n_clients}c",
                     reps * 6 * n_clients * cfg.batch_size * DIM, 1e3 * dt,
                     dev.platform)
    except Exception as e:
        log(f"round-throughput probe skipped: {type(e).__name__}: {e}")

    # --- backdoor rounds/sec: fused vs staged (stderr diagnostic) -------
    try:
        from attacking_federate_learning_tpu.attacks import make_attacker

        def backdoor_rps(fused, n_clients=32, reps=10):
            cfg = ExperimentConfig(
                dataset="SYNTH_MNIST", users_count=n_clients, mal_prop=0.25,
                batch_size=32, epochs=1, defense="TrimmedMean",
                backdoor="pattern", backdoor_fused=fused)
            ds = load_dataset(cfg.dataset, seed=0, synth_train=4096,
                              synth_test=256)
            exp = FederatedExperiment(
                cfg, attacker=make_attacker(cfg, dataset=ds), dataset=ds)
            exp.run_span(0, reps)
            jax.block_until_ready(exp.state.weights)
            t0 = time.perf_counter()
            exp.run_span(reps, reps)
            jax.block_until_ready(exp.state.weights)
            return reps / (time.perf_counter() - t0)

        log(f"backdoor_rounds_per_sec fused={backdoor_rps(True):.2f} "
            f"staged={backdoor_rps(False):.2f} "
            f"(32 clients, pattern trigger, TrimmedMean)")
    except Exception as e:
        log(f"backdoor probe skipped: {type(e).__name__}: {e}")

    # --- north-star probe: 10k clients, TPU only (stderr) ---------------
    try:
        if not on_accel:
            raise RuntimeError("accelerator not available")
        n10k = 10_240
        f10k = int(F_FRAC * n10k)
        krum_jit = jax.jit(krum, static_argnums=(1, 2))
        G10 = jax.device_put(
            jnp.asarray(rng.standard_normal((n10k, DIM)).astype(np.float32)))
        ms10 = device_krum_ms(G10, f10k, krum_jit, jax)
        log(f"north-star: krum @ {n10k} clients, d={DIM}: {ms10:.1f} ms")
        mfu_line("krum_gram_10k", 2 * n10k * n10k * DIM, ms10, dev.platform)
        log("per-impl table @ 10k:")
        bench_impl_table(G10, f10k, jax, on_accel)
        del G10
    except Exception as e:  # OOM on small hosts is fine — diagnostic only
        log(f"10k-client probe skipped: {type(e).__name__}: {e}")

    print(json.dumps({
        "metric": f"krum_agg_{n}c_wall_ms",
        "value": round(dev_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / dev_ms, 2),
    }))


if __name__ == "__main__":
    main()
