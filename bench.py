"""Benchmark driver: prints ONE JSON line to stdout.

Headline kernel: Krum robust aggregation — the reference's #1 hotspot, an
O(n^2 d) Python dict of pairwise norms plus a per-user sort
(reference defences.py:16-42).  Here it is the framework's dispatching
kernel (defenses/kernels.py): one Gram matmul + top-k on the TPU MXU, or
the host-BLAS path on the CPU backend (defenses/host.py).  The baseline is
a NumPy/BLAS implementation of the same exact semantics (defenses/oracle.py
math, vectorized Gram form — already far faster than the reference's Python
double loop, so the reported speedup is a *lower* bound on the advantage
over the reference itself) measured on this host's CPU.

Output: {"metric": "krum_agg_<n>c_wall_ms", "value": <ms>,
         "unit": "ms", "vs_baseline": <cpu_ms / our_ms>}

Diagnostics (per-impl table incl. the Mosaic-compiled pallas kernel, MFU,
the 10k-client north-star suite from BASELINE.md, FL round throughput) go
to stderr, with a recap block at the very end so the driver's tail capture
records the accelerator numbers.

Timing methodology (this box): the TPU is brokered by a relay, and
``jax.block_until_ready`` does NOT reliably wait for remote completion
through it (observed: a 667-GFLOP Gram matmul "finishing" in 0.09 ms).
Every timed section therefore dispatches K back-to-back executions and
then fetches one element of the LAST output to host — the single device
stream executes in dispatch order, so the fetch bounds all K — and
subtracts a separately-measured fetch round-trip.

Validity gate (round 4): the emitted JSON carries ``valid`` —
True only when every check passed; poisoned (with ``invalid_reasons``)
when a timed wall falls below the measured fetch RTT, when any implied
throughput exceeds the bf16 physical peak (both signatures of the
round-3 first-contact failure, where ``block_until_ready`` lied through
the relay), or when two f32 distance engines disagree on the Krum
selection index on-chip.  A garbage number can no longer be recorded as
a headline.

Hang protection is layered, because no single mechanism covers a relay
that dies mid-run (the round-2 failure mode): (a) each phase runs under
a SIGALRM bound — interrupts Python-level waits; (b) relay liveness is
re-probed (1 s port check) before every accelerator phase — catches a
death between phases without burning an alarm; (c) a daemon-thread
final deadline force-exits the process after flushing the recap and the
best-effort JSON line — covers a fetch blocked inside native code,
where a Python signal handler can never run.
"""

from __future__ import annotations

import json
import signal
import sys
import time
from contextlib import contextmanager

import numpy as np


N_CLIENTS = 2048
DIM = 79_510          # MNIST MLP wire dim (reference data_sets.py:13-23)
F_FRAC = 0.24         # reference default mal proportion (main.py:106)
REPEATS = 5
N_NORTH = 10_240      # BASELINE.md north star
HOST_FLOOR_10K_MS = 72_700.0  # measured host-BLAS floor @ 10,240 (BASELINE.md)

# Peak f32-accumulation matmul throughput used for the MFU estimate.
# TPU v5e: 197 TFLOP/s bf16, ~98 TFLOP/s f32 (public spec sheet numbers).
PEAK_FLOPS = {"tpu": 98e12, "axon": 98e12}
# Validity ceiling: NOTHING can beat the bf16 systolic peak.  A timed
# kernel whose implied throughput exceeds this is a broken measurement
# (the round-3 first-contact failure printed "7742% of peak" as a plain
# diagnostic; this gate makes that impossible to record as valid).
PEAK_BF16 = {"tpu": 197e12, "axon": 197e12}

RECAP: list[str] = []
RESULT: dict = {}   # headline snapshot for the final-deadline escape hatch
_EMITTED = False    # once-guard: main() + the deadline timer both emit
_T0 = time.perf_counter()   # bench start; anchors the window_s metadata
PHASES_DONE: list[str] = []  # names of phases that ran to completion
PHASE_TIMER = None  # utils.profiling.PhaseTimer, set in main() (the module
                    # imports jax, so construction waits for backend setup);
                    # every phase() logs into it and RESULT['phase_timing']
                    # carries the summary — BENCH_*.json gains phase-level
                    # wall-clock attribution, partial captures included.


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def recap(msg):
    log(msg)
    RECAP.append(msg)


def emit_result_json():
    global _EMITTED
    if RESULT and not _EMITTED:
        _EMITTED = True
        try:
            # Stamped at emit time so the deadline escape hatch records
            # whatever the cache saw up to the hang, too.
            from attacking_federate_learning_tpu.utils.costs import (
                cache_counts
            )
            RESULT["compile_cache"] = cache_counts()
        except Exception:
            pass
        print(json.dumps(RESULT), flush=True)


def mark_invalid(reason):
    """Poison the emitted JSON's validity and say why, loudly."""
    RESULT["valid"] = False
    reasons = RESULT.setdefault("invalid_reasons", [])
    if reason not in reasons:
        reasons.append(reason)
    recap(f"  !! VALIDITY: {reason}")


def arm_final_deadline(seconds):
    """Daemon timer: if the whole bench overruns (a fetch wedged inside
    native code — SIGALRM can't interrupt that — or simply too slow a
    link), flush the recap and the best-effort JSON line, then force-exit
    so the driver gets a clean record instead of an external kill with
    empty stdout.  The bound must exceed the sum of all per-phase alarms
    (~4980 s on accel since the hybrid phase joined) so a
    slow-but-progressing run is never cut."""
    import os
    import threading

    def fire():
        log(f"=== OVERALL DEADLINE ({seconds}s) hit "
            "(native hang or link too slow); "
            "force-exiting with banked results ===")
        for line in RECAP:
            log(line)
        emit_result_json()
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(0 if RESULT else 2)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


@contextmanager
def phase(name, seconds):
    """Run a bench phase under a wall-clock bound; skip (never hang) on
    timeout or error — a relay death mid-run must not kill the bench.
    Completed phases are recorded in the emitted JSON
    (``phases_completed``) so a partial capture says how far it got."""
    def handler(signum, frame):
        raise TimeoutError(f"exceeded {seconds}s")
    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(seconds)
    t0 = time.perf_counter()
    try:
        yield
        PHASES_DONE.append(name)
        RESULT["phases_completed"] = PHASES_DONE
    except Exception as e:
        recap(f"[{name}] SKIPPED after {time.perf_counter() - t0:.0f}s: "
              f"{type(e).__name__}: {e}")
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        if PHASE_TIMER is not None:
            # Wall-clock attribution even for skipped phases (the time
            # was spent either way); summary re-embedded each phase so
            # the deadline escape hatch emits whatever accumulated.
            PHASE_TIMER.totals[name] += time.perf_counter() - t0
            PHASE_TIMER.counts[name] += 1
            RESULT["phase_timing"] = PHASE_TIMER.summary()


def relay_alive():
    """Relay liveness probe with bounded retry-with-backoff (3 probes,
    0.5 s/1 s backoff — utils/backend.py:relay_ports_listening_retry):
    a slow-but-alive relay (accept queue full, mid-restart) must not be
    misclassified as dead and silently bench the run on CPU, while a
    truly dead relay still resolves in a few bounded seconds.  Every
    positive result stamps ``window_s`` in the emitted JSON — how long
    after bench start the relay was last seen alive — so a partial
    capture's timeline is interpretable."""
    from attacking_federate_learning_tpu.utils.backend import (
        relay_ports_listening_retry
    )
    alive = relay_ports_listening_retry(timeout=1.0)
    if alive:
        RESULT["window_s"] = round(time.perf_counter() - _T0, 1)
    return alive


def median_ms(fn, repeats=REPEATS):
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(1e3 * (time.perf_counter() - t0))
    return float(np.median(times))


def numpy_krum_ms(G: np.ndarray, f: int) -> float:
    """Reference-semantics Krum (sum of n-f smallest distances, argmin)
    in vectorized NumPy/BLAS — the strongest honest CPU baseline."""

    def run():
        sq = np.einsum("nd,nd->n", G, G)
        d2 = sq[:, None] + sq[None, :] - 2.0 * (G @ G.T)
        np.maximum(d2, 0.0, out=d2)
        D = np.sqrt(d2)
        np.fill_diagonal(D, np.inf)
        k = G.shape[0] - f
        srt = np.sort(D, axis=1)[:, : min(k, G.shape[0] - 1)]
        _ = G[int(np.argmin(srt.sum(axis=1)))]

    return median_ms(run)


def fetch1(out) -> float:
    """Host-fetch one element of (the first leaf of) ``out`` — the only
    sync primitive that provably waits for remote completion here.
    Slices a 1-element corner (never ravel: that would materialize a
    full copy of a multi-GB array before the fetch)."""
    import jax
    leaf = jax.tree_util.tree_leaves(out)[0]
    tiny = leaf[(slice(0, 1),) * leaf.ndim]
    return float(np.asarray(tiny).ravel()[0])


def fetch_rtt_ms(x, reps=5) -> float:
    """Cost of dispatching a trivial op on a 1-element corner of ``x``
    + fetching it: exactly the per-loop overhead the timed loops pay on
    their final fetch (no full-array copy — see fetch1).  Fresh value
    each rep so jax's host-copy cache can't lie."""
    ts = []
    corner = x[(slice(0, 1),) * x.ndim]
    for i in range(reps):
        y = corner + np.float32(i)
        t0 = time.perf_counter()
        float(np.asarray(y).ravel()[0])
        ts.append(1e3 * (time.perf_counter() - t0))
    return float(np.median(ts))


def timed_ms(make_out, iters=6, loops=3, rtt=0.0):
    """Median over ``loops`` of: dispatch ``iters`` back-to-back
    executions, fetch one element of the last output (in-order device
    stream => bounds all of them), minus fetch RTT, per iteration.
    Returns ``(ms, last_fetched_value, ok)``: the value so callers that
    need an output element (e.g. a selection index) don't pay an extra
    execution, and ``ok=False`` when the timing is untrustworthy — the
    raw wall fell below the measured fetch RTT (physically impossible
    for a real execution: the final fetch alone costs one RTT) or the
    RTT correction dominated the wall.  Clamped at 0.05 ms so a <=0
    result can't poison the vs_baseline division downstream."""
    val = fetch1(make_out())        # compile + warm
    ts = []
    ok = True
    for _ in range(loops):
        t0 = time.perf_counter()
        for _ in range(iters - 1):
            make_out()
        out = make_out()
        val = fetch1(out)
        wall = 1e3 * (time.perf_counter() - t0)
        if wall < rtt or rtt > 0.5 * wall:
            log(f"  (rtt {rtt:.1f} ms vs wall {wall:.1f} ms — timing "
                f"unreliable at this size)")
            ok = False
        ts.append(max((wall - rtt) / iters, 0.05))
    return float(np.median(ts)), val, ok


def device_krum_ms(G, f, krum_fn, iters=6, rtt=0.0):
    ms, _, ok = timed_ms(lambda: krum_fn(G, G.shape[0], f), iters=iters,
                         rtt=rtt)
    return ms, ok


def mfu_line(tag, flops, ms, platform, to_recap=False):
    """Log the implied throughput; returns the achieved fraction of the
    bf16 physical ceiling (None off-accelerator) so callers can gate
    validity — a fraction > 1.0 means the measurement is broken, never
    that the kernel is fast."""
    peak = PEAK_FLOPS.get(platform)
    if not peak or ms <= 0:
        return None
    achieved = flops / (ms * 1e-3)
    line = (f"  mfu[{tag}]: {achieved / 1e12:.1f} TFLOP/s = "
            f"{100 * achieved / peak:.1f}% of f32 peak")
    (recap if to_recap else log)(line)
    frac_ceiling = achieved / PEAK_BF16.get(platform, peak)
    if frac_ceiling > 1.0:
        mark_invalid(f"mfu[{tag}] implies {achieved / 1e12:.0f} TFLOP/s "
                     f"> bf16 physical peak — measurement broken")
    return frac_ceiling


def krum_score_two_ways(G, f, i):
    """One candidate's Krum score — the sum of its n-f smallest
    distances to the others (reference defences.py:16-42 semantics) —
    computed via BOTH distance formulations the engines use: the
    direct-difference form and the Gram form (which cancels
    catastrophically for near-equal rows).  Distances come back to host
    and are summed in float64 (effectively exact for f32 inputs at
    these n), so each returned score isolates the error of its distance
    FORMULATION — the spread between the two is a direct measurement of
    cross-engine score indeterminacy on this data.  Used only to
    adjudicate selection flips."""
    import jax.numpy as jnp

    n = G.shape[0]
    k = min(n - f, n - 1)
    gi = G[i]
    d_diff = jnp.sqrt(jnp.sum((G - gi[None, :]) ** 2, axis=1))
    sq = jnp.sum(G * G, axis=1)
    d2_gram = sq + jnp.sum(gi * gi) - 2.0 * (G @ gi)
    d_gram = jnp.sqrt(jnp.maximum(d2_gram, 0.0))
    out = []
    for dvec in (d_diff, d_gram):
        v = np.asarray(dvec, np.float64)
        v[i] = np.inf
        out.append(float(np.sum(np.sort(v)[:k])))
    return out[0], out[1]


def adjudicate_f32_flip(G, f, indices):
    """Decide whether an f32 cross-engine Krum index flip is a legal tie.

    Two correct f32 engines may legally disagree when the top-2 score
    gap is inside the engines' numeric indeterminacy — different
    summation orders AND different distance formulations (Gram vs
    direct difference; Gram cancellation error can dwarf summation
    noise when rows are close).  The band is therefore measured, not
    guessed: per candidate, the |diff-form − Gram-form| score spread on
    this very data (×4 safety), plus the analytic worst-case f32
    summation term n·(eps/2)·|score|.  A gap inside the band cannot be
    adjudicated by ANY f32 engine — the same ulp-band reality
    tests/test_native.py pins for the native Bulyan comparator.
    Returns ``(is_tie, gap, band)``; gaps above the band are real
    disagreements (correctness unproven — the caller poisons
    validity)."""
    scores = {int(i): krum_score_two_ways(G, f, int(i))
              for i in set(indices)}
    vals = [s for pair in scores.values() for s in pair]
    if not all(np.isfinite(v) for v in vals):
        return False, float("nan"), 0.0
    mids = [0.5 * (a + b) for a, b in scores.values()]
    gap = max(mids) - min(mids)
    spread = max(abs(a - b) for a, b in scores.values())
    band = 4.0 * spread + 0.5 * G.shape[0] * float(
        np.finfo(np.float32).eps) * max(abs(v) for v in vals)
    return gap <= band, gap, band


def gate_f32_disagreement(G, f, group, n):
    """The f32 half of the cross-impl agreement gate, routed through the
    tie adjudicator (ADVICE r4 #1).  f32 engines computing the same math
    MUST agree on any decisive score gap; a flip there means on-chip
    correctness is unproven, so no per-impl number (nor the headline
    that shares the xla engine) may be quoted as valid.  But a near-tied
    score can legally flip between engines (the ulp-band contract
    tests/test_native.py pins) — poisoning a whole capture over a
    legitimate tie would burn the window, so ties warn instead."""
    is_tie, gap, band = adjudicate_f32_flip(G, f, group.values())
    if is_tie:
        recap(f"  .. f32 flip at n={n} is a legal tie "
              f"(score gap {gap:.6g} <= indeterminacy band "
              f"{band:.6g}); warning only")
    else:
        mark_invalid(
            f"f32 distance impls disagree on the Krum index "
            f"at n={n} (score gap {gap:.6g} > tie band {band:.6g})")


def bench_impl_table(G, f, on_accel, rtt=0.0, iters=4):
    """Per-impl diagnostic: every selectable distance engine at this n —
    including the bf16-Gram MXU mode (distance_dtype='bfloat16') — with
    cross-impl Krum selection-index agreement (the on-chip pallas parity
    check VERDICT round-2 item #2 asks for)."""
    import functools

    import jax

    from attacking_federate_learning_tpu.defenses.kernels import krum_select

    n = G.shape[0]
    rows = {}
    idxs = {}
    if on_accel:
        variants = [("xla", None), ("pallas", None),
                    ("xla", "bfloat16"), ("pallas", "bfloat16")]
    else:
        variants = [("xla", None), ("host", None)]
    for impl, ddt in variants:
        label = impl + ("[bf16]" if ddt else "")
        try:
            if impl == "host":
                sel_fn = functools.partial(krum_select, distance_impl="host")
            else:
                sel_fn = jax.jit(
                    functools.partial(krum_select, distance_impl=impl,
                                      distance_dtype=ddt),
                    static_argnums=(1, 2))
            # krum_select returns the index itself, so the timed loop's
            # final fetch already holds it — no extra execution.
            ms, val, ok = timed_ms(lambda: sel_fn(G, n, f), iters=iters,
                                   rtt=rtt)
            idx = int(val)
            rows[label] = ms
            idxs[label] = idx
            recap(f"  krum impl={label:13s} n={n}: {ms:10.2f} ms  "
                  f"(select={idx}){'' if ok else '  [TIMING INVALID]'}")
        except Exception as e:
            recap(f"  krum impl={label:13s} n={n}: failed "
                  f"({type(e).__name__}: {e})")
    # Cross-impl agreement is checked WITHIN a dtype: on iid gaussian
    # data near-tied Krum scores make an f32-vs-bf16 selection flip
    # legitimate (tests/test_distance_impl.py), so mixing dtypes into
    # one set would false-alarm the xla-vs-pallas parity signal.
    for tag, group in (("f32", {k: v for k, v in idxs.items()
                                if "bf16" not in k}),
                       ("bf16", {k: v for k, v in idxs.items()
                                 if "bf16" in k})):
        if len(group) > 1 and len(set(group.values())) > 1:
            recap(f"  !! {tag} impl DISAGREEMENT at n={n}: {group}")
            if tag == "f32":
                gate_f32_disagreement(G, f, group, n)
        elif len(group) > 1:
            recap(f"  {tag} impls agree at n={n} "
                  f"(select={next(iter(group.values()))})")
    return rows


def headline_walls(G, n, f, platform, reps=3):
    """Measured per-stage walls for the headline Krum kernel: wrap it
    in the tier1_aggregate stage scope, run a few profiled reps, and
    book the capture onto the stage taxonomy against the compiled
    program's own instruction map (utils/walls.py).  Returns the
    summary dict for RESULT['walls'], or None when no capture is
    possible on this backend (the caller drops the key rather than
    recording zeros)."""
    import shutil
    import tempfile

    import jax

    from attacking_federate_learning_tpu.defenses.kernels import krum
    from attacking_federate_learning_tpu.utils import walls
    from attacking_federate_learning_tpu.utils.costs import (
        compiled_cost_facts, stage_attribution, stage_scope
    )
    from attacking_federate_learning_tpu.utils.profiling import (
        device_trace
    )

    def staged(g):
        with stage_scope("tier1_aggregate"):
            return krum(g, n, f)

    jitted = jax.jit(staged)
    compiled = jitted.lower(G).compile()
    fetch1(jitted(G))                                 # warm
    wdir = tempfile.mkdtemp(prefix="bench_walls_")
    try:
        with device_trace(wdir):
            for _ in range(reps):
                fetch1(jitted(G))
        rec = walls.book_trace(wdir, compiled.as_text(),
                               name="krum_staged", platform=platform)
    finally:
        shutil.rmtree(wdir, ignore_errors=True)
    if rec is None or rec.coverage.get("op_events", 0) == 0:
        return None
    att = stage_attribution(compiled.as_text(),
                            compiled_cost_facts(compiled))
    modeled = {"stages": {s: {"flops": v["flops"]}
                          for s, v in att["stages"].items()},
               "unattributed": {"flops": att["unattributed"]["flops"]}}
    agg = {"stages": rec.stages, "unattributed_us": rec.unattributed_us}
    return {"reps": reps,
            "stages": {s: round(v, 3) for s, v in rec.stages.items()},
            "unattributed_us": round(rec.unattributed_us, 3),
            "op_time_fraction": rec.coverage.get("op_time_fraction"),
            "vs_modeled": walls.measured_vs_modeled(agg, modeled)}


def main():
    from attacking_federate_learning_tpu.utils.backend import (
        enable_compile_cache, ensure_live_backend,
        install_aot_warning_collapse
    )

    # Before anything can compile (and hence load cached executables):
    # collapse the known same-host cpu_aot_loader SIGILL false positive
    # (only +prefer-no-scatter/+prefer-no-gather named — CLAUDE.md)
    # into one annotated line instead of a 2 KB feature dump at every
    # BENCH tail; a REAL cross-host mismatch (ISA features named)
    # still passes through verbatim.
    install_aot_warning_collapse()
    # Op-level trace events need the xprof flag in XLA_FLAGS before
    # the FIRST compile of the process (XLA parses the env once) — set
    # here so the headline measured-walls capture can book per-op
    # (utils/profiling.py:ensure_op_profiling; harmless everywhere
    # else).
    from attacking_federate_learning_tpu.utils.profiling import (
        ensure_op_profiling
    )
    ensure_op_profiling()
    ensure_live_backend()
    enable_compile_cache()
    import functools

    import jax
    import jax.numpy as jnp

    global PHASE_TIMER
    from attacking_federate_learning_tpu.utils.costs import (
        cache_counts, install_cache_counters
    )
    from attacking_federate_learning_tpu.utils.profiling import PhaseTimer

    # Compile-cache hit/miss accounting (utils/costs.py): installed
    # before the first compile so BENCH_*.json can say whether a fast
    # run was warm-cache or genuinely fast.
    install_cache_counters()
    PHASE_TIMER = PhaseTimer()

    from attacking_federate_learning_tpu.defenses.kernels import (
        bulyan, krum, trimmed_mean
    )

    dev = jax.devices()[0]
    on_accel = dev.platform not in ("cpu",)
    # Environment attribution (ISSUE 3 satellite): trajectory files must
    # say which toolchain produced them — this box runs jax 0.4.37
    # while some notes assume 0.9; record, don't assume.
    RESULT["env"] = {"jax": jax.__version__,
                     "platform": dev.platform,
                     "device_kind": dev.device_kind}
    # Accel phases sum to 5280 s, CPU phases to 4140 s (the two-tier
    # hierarchy north star added 600, the multichip-hier AOT facts
    # 300); keep the same class of slack above each so a
    # slow-but-progressing run is never cut (the measured CPU fallback
    # takes ~1,100 s; 4600 covers a contended box without weakening
    # the hang escape hatch).  tpu_capture.sh's outer bound (6000)
    # still exceeds the accel deadline, so the clean banked-results
    # exit stays the one that ends a slow run.
    deadline_timer = arm_final_deadline(5700 if on_accel else 4600)
    n = N_CLIENTS if on_accel else 512  # keep the CPU fallback tractable
    f = int(F_FRAC * n)
    recap(f"device: {dev.platform} ({dev.device_kind}); n={n} d={DIM} f={f}")

    rng = np.random.default_rng(0)

    # --- baseline: NumPy/BLAS on host CPU ------------------------------
    # The kernels are data-oblivious (matmul + sort), so the baseline's
    # data need not be bit-identical to the device run's.
    G_host = rng.standard_normal((n, DIM)).astype(np.float32)
    cpu_ms = numpy_krum_ms(G_host, f)
    recap(f"numpy/BLAS krum: {cpu_ms:.1f} ms (median of {REPEATS})")

    # --- ours: the framework's dispatching kernel ----------------------
    # On an accelerator: the jitted XLA Gram-matmul path on the chip,
    # with data GENERATED ON DEVICE (no multi-GB relay transfer).
    # On the CPU fallback: distance_impl='auto' resolves to the host-BLAS
    # engine (defenses/host.py) — backend-aware dispatch is the product
    # behavior, not a bench trick.
    if on_accel:
        key = jax.random.PRNGKey(0)
        G = jax.jit(
            lambda k: jax.random.normal(k, (n, DIM), jnp.float32))(key)
        fetch1(G)
        rtt = fetch_rtt_ms(G)
        log(f"fetch rtt: {rtt:.2f} ms")
        krum_fn = jax.jit(krum, static_argnums=(1, 2))
    else:
        G = jnp.asarray(G_host)
        rtt = 0.0
        # Eager: distance_impl='auto' resolves to the host-BLAS engine.
        krum_fn = functools.partial(krum, distance_impl="auto")

    dev_ms = None
    with phase("headline", 420):
        dev_ms, time_ok = device_krum_ms(G, f, krum_fn, rtt=rtt)
        impl = "xla/jit" if on_accel else "host-blas (auto)"
        recap(f"framework krum [{impl}] ({dev.platform}): {dev_ms:.2f} ms")
        # valid starts True and every gate can only poison it: RTT-floor
        # (time_ok), MFU <= bf16 physical peak (mfu_line), f32 impl
        # agreement (bench_impl_table below).
        RESULT.update(
            metric=f"krum_agg_{n}c_wall_ms", value=round(dev_ms, 3),
            unit="ms", vs_baseline=round(cpu_ms / dev_ms, 2), valid=True)
        if not time_ok:
            mark_invalid("headline wall fell below the measured fetch RTT")
        # Gram matmul dominates: 2 n^2 d FLOPs.
        mfu_line("krum_gram", 2 * n * n * DIM, dev_ms, dev.platform,
                 to_recap=True)
        try:
            # Static cost facts for the headline kernel (utils/costs.py,
            # ISSUE 3): XLA's own FLOP/bytes/memory accounting of the
            # jitted program rides next to the timed wall so a BENCH
            # record is interpretable without re-deriving the 2n^2d
            # analytic estimate.  AOT-analyzed; the compile is the one
            # the timed loop already warmed.
            from attacking_federate_learning_tpu.utils.costs import (
                analyze_lowered
            )
            krum_jit = jax.jit(krum, static_argnums=(1, 2))
            rec = analyze_lowered("krum_xla", krum_jit.lower(G, n, f))
            RESULT["cost"] = {rec.name: rec.gate_facts()}
            recap(f"  static cost [krum_xla]: flops={rec.flops:.3e} "
                  f"bytes={rec.bytes_accessed:.3e} "
                  f"peak={rec.peak_bytes / 1e6:.1f} MB")
            # Wire-ledger rollup for the headline cohort (ISSUE 15):
            # the per-seam protocol bytes the same (n, d) round moves,
            # priced from topology facts alone — next to the compute
            # cost so a BENCH record carries both sides of the budget.
            from attacking_federate_learning_tpu.utils.costs import (
                wire_ledger
            )
            RESULT["wire"] = wire_ledger(cohort=n, dim=DIM)
            recap(f"  wire ledger [flat n={n}]: "
                  f"{RESULT['wire']['total_bytes'] / 1e6:.1f} MB/round "
                  f"over {len(RESULT['wire']['seams'])} seams")
            # Measured stage walls for the same headline kernel
            # (ISSUE 16): a few profiled reps booked onto the stage
            # taxonomy (utils/walls.py) next to the modeled cost, so
            # one BENCH record carries modeled AND measured shares.
            # Distinct from RESULT['phase_timing'] (PhaseTimer: host
            # walls of whole bench phases) — this is device op time
            # within the kernel.  Skips cleanly (no 'walls' key) when
            # the capture is unavailable: non-CPU backend without the
            # FL_TEST_TPU gate, or the xprof flag missed this
            # process's first compile.
            wall_summary = headline_walls(G, n, f, dev.platform)
            if wall_summary is not None:
                RESULT["walls"] = wall_summary
                top = max(wall_summary["stages"],
                          key=lambda s: wall_summary["stages"][s],
                          default="-")
                recap(f"  measured walls [krum_staged]: "
                      + "  ".join(
                          f"{s}={us / 1e3:.1f}ms" for s, us in
                          wall_summary["stages"].items())
                      + f"  unattributed="
                        f"{wall_summary['unattributed_us'] / 1e3:.1f}ms"
                        f"  [top: {top}]")
        except Exception as e:
            log(f"  (static cost analysis unavailable: "
                f"{type(e).__name__}: {e})")

    if dev_ms is None:
        # Accelerator died under us before the headline — restart the
        # whole bench pinned to CPU so the driver still gets a number.
        if on_accel:
            from attacking_federate_learning_tpu.utils.backend import (
                _fallback_to_cpu
            )
            _fallback_to_cpu("accelerator failed mid-bench")
        raise SystemExit("CPU headline failed")

    with phase("impl-table", 420):
        log("per-impl table:")
        bench_impl_table(G, f, on_accel, rtt=rtt)

    # --- pallas defense suite (ISSUE 11): wall + cost-ledger facts ------
    # Off-TPU the kernels run interpret=True, so the walls are the CPU
    # emulation floor — meaningful only as a trajectory; the static
    # cost-ledger facts (AOT bytes-accessed/peak of the same programs)
    # and the kernel's exact declared models ride along so the BENCH
    # record carries numbers that do not depend on where it ran.
    with phase("pallas-defense", 600):
        from attacking_federate_learning_tpu.ops.pallas_defense import (
            krum_scores_cost, pallas_krum_scores, pallas_trimmed_mean_of
        )
        from attacking_federate_learning_tpu.utils.costs import (
            analyze_lowered
        )

        n_pal = n if on_accel else 256
        f_pal = int(F_FRAC * n_pal)
        Gp = G[:n_pal]
        pal = {"n": n_pal, "d": DIM, "interpret": not on_accel,
               "cells": {}}
        k_keep = n_pal - f_pal - 1

        def pal_cell(tag, jitted, *args):
            ms, _, ok = timed_ms(lambda: jitted(Gp, *args), iters=2,
                                 loops=2, rtt=rtt)
            rec = analyze_lowered(f"pallas_{tag}",
                                  jitted.lower(Gp, *args))
            cell = {"wall_ms": round(ms, 2), "timing_ok": ok,
                    **rec.gate_facts()}
            pal["cells"][tag] = cell
            recap(f"  pallas[{tag}] n={n_pal}: {ms:10.2f} ms  "
                  f"bytes={rec.bytes_accessed:.3e} "
                  f"peak={rec.peak_bytes / 1e6:.0f} MB"
                  f"{'' if ok else '  [TIMING INVALID]'}")

        pal_cell("krum_score_fusion",
                 jax.jit(lambda g: pallas_krum_scores(
                     g, n_pal, f_pal)[0]))
        pal_cell("trimmed_mean_tile",
                 jax.jit(lambda g: pallas_trimmed_mean_of(g, k_keep)))
        pal_cell("bulyan_selection",
                 jax.jit(functools.partial(
                     bulyan, selection_impl="pallas",
                     trim_impl="pallas"), static_argnums=(1, 2)),
                 n_pal, f_pal)
        # The exact declared models (ops/pallas_defense.py): the bench
        # n and the 10k north star, so the fusion-win trajectory is
        # interpretable without a TPU (tools/perf_gate.py --pallasproof
        # pins the 10k comparison in CI).
        pal["declared"] = {
            f"krum_score_fusion_{n_pal}c": krum_scores_cost(
                n_pal, DIM, f_pal),
            f"krum_score_fusion_{N_NORTH}c": krum_scores_cost(
                N_NORTH, DIM, int(F_FRAC * N_NORTH)),
        }
        RESULT["pallas"] = pal

    # --- north star: 10k clients (BASELINE.md), accel only --------------
    def gate():
        if not relay_alive():
            raise RuntimeError("relay gone")

    G10 = None
    f10 = int(F_FRAC * N_NORTH)
    if on_accel and relay_alive():
        with phase("north-star-data", 300):
            G10 = jax.jit(lambda k: jax.random.normal(
                k, (N_NORTH, DIM), jnp.float32))(jax.random.PRNGKey(1))
            fetch1(G10)
        with phase("north-star-krum", 600):
            if G10 is None:
                raise RuntimeError("G10 unavailable (creation failed)")
            ms10, ok10 = device_krum_ms(
                G10, f10, jax.jit(krum, static_argnums=(1, 2)),
                iters=3, rtt=rtt)
            recap(f"north-star: krum @ {N_NORTH} clients, d={DIM}: "
                  f"{ms10:.1f} ms (host-BLAS floor {HOST_FLOOR_10K_MS:.0f} ms"
                  f" => {HOST_FLOOR_10K_MS / ms10:.0f}x)"
                  f"{'' if ok10 else '  [TIMING INVALID]'}")
            mfu_line("krum_gram_10k", 2 * N_NORTH * N_NORTH * DIM, ms10,
                     dev.platform, to_recap=True)
            log("per-impl table @ 10k:")
            bench_impl_table(G10, f10, on_accel, rtt=rtt, iters=2)
        with phase("north-star-trimmed-mean", 420):
            gate()
            if G10 is None:
                raise RuntimeError("G10 unavailable (creation failed)")
            tm_fn = jax.jit(trimmed_mean, static_argnums=(1, 2))
            ms_tm, _, ok_tm = timed_ms(lambda: tm_fn(G10, N_NORTH, f10),
                                       iters=2, rtt=rtt)
            recap(f"north-star: trimmed_mean @ {N_NORTH}: {ms_tm:.1f} ms"
                  f"{'' if ok_tm else '  [TIMING INVALID]'}")
        with phase("north-star-bulyan-hybrid", 600):
            # VERDICT r3 #2: the exact-semantics accelerator path at
            # 10k — device Gram on the MXU, ONE (n, n) D marshal
            # (~420 MB) to the native host selection engine, device
            # gather + trim-mean.  Runs before the traced-exact phase:
            # this is the number the design argument needs most.
            gate()
            if G10 is None:
                raise RuntimeError("G10 unavailable (creation failed)")
            hy_fn = jax.jit(
                functools.partial(bulyan, selection_impl="host"),
                static_argnums=(1, 2))
            ms_hy, _, ok_hy = timed_ms(lambda: hy_fn(G10, N_NORTH, f10),
                                       iters=1, loops=2, rtt=rtt)
            recap(f"north-star: bulyan[exact, hybrid] @ {N_NORTH}: "
                  f"{ms_hy:.1f} ms (incl. the one (n,n) D marshal)"
                  f"{'' if ok_hy else '  [TIMING INVALID]'}")
        with phase("north-star-bulyan-batched", 420):
            gate()
            if G10 is None:
                raise RuntimeError("G10 unavailable (creation failed)")
            bq_fn = jax.jit(
                functools.partial(bulyan, batch_select=64),
                static_argnums=(1, 2))
            ms_bq, _, ok_bq = timed_ms(lambda: bq_fn(G10, N_NORTH, f10),
                                       iters=1, loops=2, rtt=rtt)
            recap(f"north-star: bulyan[q=64] @ {N_NORTH}: {ms_bq:.1f} ms"
                  f"{'' if ok_bq else '  [TIMING INVALID]'}")
        with phase("north-star-bulyan-exact", 600):
            gate()
            if G10 is None:
                raise RuntimeError("G10 unavailable (creation failed)")
            b1_fn = jax.jit(bulyan, static_argnums=(1, 2))
            ms_b1, _, ok_b1 = timed_ms(lambda: b1_fn(G10, N_NORTH, f10),
                                       iters=1, loops=1, rtt=rtt)
            recap(f"north-star: bulyan[q=1 exact] @ {N_NORTH}: "
                  f"{ms_b1:.1f} ms{'' if ok_b1 else '  [TIMING INVALID]'}")
        del G10
    elif on_accel:
        recap("north-star suite SKIPPED: relay died before it could run")
    else:
        # CPU fallback still proves the exact-semantics north star: the
        # native incremental selection (native/bulyan_select.cpp) makes
        # reference-exact q=1 Bulyan O(n^2) total — minutes, not hours,
        # on one core (vs ~6 h extrapolated for the rescore loop).
        G10h = None
        s_b1 = None
        with phase("north-star-bulyan-exact-host", 900):
            from attacking_federate_learning_tpu.defenses.host import (
                host_bulyan
            )
            from attacking_federate_learning_tpu.native import get_lib
            if get_lib() is None:
                # NumPy-fallback exact selection is multi-hour at 10k —
                # don't burn the phase budget discovering that.
                recap("north-star: bulyan exact host SKIPPED "
                      "(native kernel unavailable)")
            else:
                G10h = rng.standard_normal((N_NORTH, DIM),
                                           dtype=np.float32)
                t0 = time.perf_counter()
                host_bulyan(G10h, N_NORTH, f10)
                s_b1 = time.perf_counter() - t0
                recap(f"north-star: bulyan[q=1 exact, host native] @ "
                      f"{N_NORTH}: {s_b1:.1f} s")
                # NumPy operands hit the kernels' eager host branch
                # zero-copy — a jnp.asarray here would copy 3.26 GB per
                # call for nothing.
                t0 = time.perf_counter()
                trimmed_mean(G10h, N_NORTH, f10, impl="host")
                s_tmh = time.perf_counter() - t0
                recap(f"north-star: trimmed_mean[host native] @ "
                      f"{N_NORTH}: {s_tmh:.1f} s "
                      f"(XLA:CPU measured 943.5 s, BASELINE.md)")
                from attacking_federate_learning_tpu.defenses.median import (
                    median as median_defense
                )
                t0 = time.perf_counter()
                median_defense(G10h, N_NORTH, f10, impl="host")
                s_mdh = time.perf_counter() - t0
                recap(f"north-star: median[host native] @ {N_NORTH}: "
                      f"{s_mdh:.1f} s")
        # Two-tier hierarchy north star (ISSUE 6): the SAME exact
        # native Bulyan kernel, restructured as n/m per-megabatch
        # tier-1 passes + one tier-2 pass over the (n/m, d) estimates
        # (ops/federated.py placement).  Distance work drops n/m-fold
        # (16.7 TFLOP -> 0.87 TFLOP at m=512), which is the whole
        # argument for the hierarchical engine — target: beat the
        # flat exact-Bulyan 104.5 s BASELINE.md north star measured
        # above, on the same matrix, same box.
        with phase("hierarchy-north-star", 600):
            if G10h is None:
                recap("north-star: two-tier hierarchy SKIPPED "
                      "(native kernel unavailable)")
            else:
                from attacking_federate_learning_tpu.defenses.host import (
                    host_bulyan as _host_bulyan
                )
                from attacking_federate_learning_tpu.ops.federated import (
                    make_placement, tier1_assumed
                )
                m_mb = 512
                pl = make_placement(N_NORTH, f10, m_mb, "spread")
                S = pl.num_shards
                f1 = tier1_assumed(f10, S)    # ceil(f/S): 123 @ 0.24
                # Largest tier-2 bound Bulyan's S >= 4*f2 + 3 admits at
                # S=20 shards (ceil(f/m)=5 would need 23 shards).
                f2 = (S - 3) // 4
                t0 = time.perf_counter()
                ests = np.empty((S, DIM), np.float32)
                for s in range(S):
                    ests[s] = _host_bulyan(G10h[pl.grid[s]], m_mb, f1)
                _host_bulyan(ests, S, f2)
                s_hier = time.perf_counter() - t0
                vs = (f" ({s_b1 / s_hier:.1f}x vs flat exact "
                      f"{s_b1:.1f} s)" if s_b1 else "")
                recap(f"north-star: bulyan[two-tier hierarchy, host "
                      f"native] @ {N_NORTH}, megabatch {m_mb} "
                      f"(S={S}, f1={f1}, f2={f2}): {s_hier:.1f} s{vs}")
                RESULT["hierarchy"] = {
                    "clients": N_NORTH, "megabatch": m_mb,
                    "num_shards": S, "tier1_f": f1, "tier2_f": f2,
                    "two_tier_bulyan_s": round(s_hier, 1),
                    "flat_exact_bulyan_s": (round(s_b1, 1)
                                            if s_b1 else None)}
                del G10h
        # Hybrid-path cost model, CPU side (VERDICT r3 #2): what the
        # bulyan[selection_impl='host'] pure_callback pays to marshal
        # the (10240, 10240) f32 D (420 MB) through the callback
        # machinery on this backend — the D-fetch term of the hybrid,
        # measurable without the chip.  (The full hybrid at 10k needs
        # the device Gram; on XLA:CPU that alone is ~minutes, so only
        # the marshal term is benched here.)
        with phase("hybrid-d-marshal", 300):
            D10 = jnp.zeros((N_NORTH, N_NORTH), jnp.float32)

            def marshal_cb(d):
                return np.float32(d[0, 0])

            cb_fn = jax.jit(lambda d: jax.pure_callback(
                marshal_cb, jax.ShapeDtypeStruct((), jnp.float32), d))
            float(cb_fn(D10))   # compile + warm
            t0 = time.perf_counter()
            float(cb_fn(D10))
            s_marshal = time.perf_counter() - t0
            recap(f"hybrid D-marshal: (10240,10240) f32 pure_callback "
                  f"on {dev.platform}: {1e3 * s_marshal:.1f} ms")
            del D10
        # Hierarchical telemetry overhead at the 10,240-client
        # memproof point (ISSUE 8): the same n/m/d the perf-gate
        # memproof pins, Krum both tiers — hier span vs hier TELE span
        # wall clock over a 2-round scanned span (host fetch of the
        # stacked diagnostics included: that IS the telemetry cost
        # model) plus each program's static temp bytes, so the BENCH
        # record says what --telemetry costs where the engine is
        # actually sized to run.
        with phase("hier-tele-overhead", 600):
            from attacking_federate_learning_tpu.config import (
                ExperimentConfig
            )
            from attacking_federate_learning_tpu.core.engine import (
                FederatedExperiment
            )
            from attacking_federate_learning_tpu.data.datasets import (
                load_dataset
            )
            from attacking_federate_learning_tpu.utils.costs import (
                compiled_cost_facts
            )

            n_mp, m_mp = N_NORTH, 512
            ds_mp = load_dataset("SYNTH_MNIST", seed=0,
                                 synth_train=n_mp, synth_test=64)
            res_ht = {"clients": n_mp, "megabatch": m_mp}
            for tele in (False, True):
                cfg_ht = ExperimentConfig(
                    dataset="SYNTH_MNIST", users_count=n_mp,
                    mal_prop=0.24, batch_size=1, epochs=4, test_step=2,
                    seed=0, synth_train=n_mp, synth_test=64,
                    defense="Krum", aggregation="hierarchical",
                    megabatch=m_mp, tier2_defense="Krum",
                    telemetry=tele)
                exp_ht = FederatedExperiment(cfg_ht, dataset=ds_mp)
                tag = "tele_span" if tele else "span"
                if tele:
                    lowered = exp_ht._tele_span.lower(
                        exp_ht.state, jnp.asarray(0, jnp.int32), 2)
                else:
                    lowered = exp_ht._fused_span.lower(
                        exp_ht.state, jnp.asarray(0, jnp.int32),
                        jnp.asarray(2, jnp.int32))
                facts = compiled_cost_facts(lowered.compile())
                res_ht[f"{tag}_temp_bytes"] = int(facts["temp_bytes"])
                exp_ht.run_span(0, 2)          # compile + warm
                fetch1(exp_ht.state.weights)
                t0 = time.perf_counter()
                exp_ht.run_span(2, 2)
                fetch1(exp_ht.state.weights)
                if tele and exp_ht.last_span_telemetry is not None:
                    # The once-per-eval-interval host fetch of the
                    # stacked diagnostics is part of what telemetry
                    # costs — time it with the span.
                    jax.tree.map(np.asarray,
                                 exp_ht.last_span_telemetry[1])
                res_ht[f"{tag}_s"] = round(time.perf_counter() - t0, 3)
                del exp_ht
            res_ht["overhead_pct"] = round(
                100.0 * (res_ht["tele_span_s"] - res_ht["span_s"])
                / max(res_ht["span_s"], 1e-9), 1)
            res_ht["temp_overhead_pct"] = round(
                100.0 * (res_ht["tele_span_temp_bytes"]
                         - res_ht["span_temp_bytes"])
                / max(res_ht["span_temp_bytes"], 1), 1)
            recap(f"hier-tele overhead @ {n_mp} (m={m_mp}, Krum/Krum, "
                  f"2-round span): span {res_ht['span_s']:.1f} s vs "
                  f"tele {res_ht['tele_span_s']:.1f} s "
                  f"({res_ht['overhead_pct']:+.1f}%); temp "
                  f"{res_ht['span_temp_bytes'] / 1e6:.0f} -> "
                  f"{res_ht['tele_span_temp_bytes'] / 1e6:.0f} MB "
                  f"({res_ht['temp_overhead_pct']:+.1f}%)")
            RESULT["hier_telemetry"] = res_ht

    # --- multichip hier: SPMD vs scan tier-1 at the north star ----------
    # AOT-only static facts (ISSUE 12): collective bytes + temp bytes of
    # the SPMD client_map round (megabatch axis sharded over the mesh
    # clients axis, one explicit estimate all_gather) vs the sequential
    # scan round, at the 10,240-client memproof point.  Runs in a
    # CPU-pinned subprocess with 8 virtual devices (the parent backend
    # has one device and, on accel, must not touch the relay for what
    # is a deterministic static-HLO fact) — rehearse-safe, no TPU
    # needed; the live multi-chip execution leg is tpu_capture.sh
    # step 2.6 (tools/multichip_hier.py without --aot).
    with phase("multichip-hier", 300):
        import os
        import subprocess

        cmd = [sys.executable,
               os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "multichip_hier.py"),
               "--rehearse", "--aot", "--clients", str(N_NORTH),
               "--megabatch", "512"]
        env = dict(os.environ, PALLAS_AXON_POOL_IPS="",
                   JAX_PLATFORMS="cpu")
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=280, env=env)
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(
                f"multichip_hier rc={proc.returncode}: "
                f"{proc.stderr[-400:]}")
        mh = json.loads(proc.stdout.strip().splitlines()[-1])
        RESULT["multichip_hier"] = mh
        recap(f"multichip-hier @ {mh['clients']} (m={mh['megabatch']}, "
              f"S={mh['num_shards']}, {mh['clients_axis']}-way clients "
              f"axis): sharded collective "
              f"{mh['sharded']['collective_bytes'] / 1e6:.1f} MB "
              f"(S*d*4 = "
              f"{mh['collective_bytes_bound_S_d_4'] / 1e6:.1f} MB) "
              f"temp {mh['sharded']['temp_bytes'] / 1e6:.0f} MB vs "
              f"scan temp {mh['scan']['temp_bytes'] / 1e6:.0f} MB, "
              f"0 collective")

    # --- secondary: full FL round throughput (stderr diagnostic) --------
    with phase("fl-throughput", 600):
        if on_accel and not relay_alive():
            raise RuntimeError("relay gone")
        from attacking_federate_learning_tpu.attacks import DriftAttack
        from attacking_federate_learning_tpu.config import ExperimentConfig
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.data.datasets import load_dataset

        from attacking_federate_learning_tpu.utils.lifecycle import (
            run_id_for
        )

        for n_clients in (10, 512):
            cfg = ExperimentConfig(
                dataset="SYNTH_MNIST", users_count=n_clients,
                mal_prop=0.24, batch_size=64, epochs=1, defense="Krum")
            # Config-hash identity: the join key between this BENCH
            # record and the run registry (utils/registry.py ingests
            # BENCH_*.json; 'run_ids' is how its rows join runs/).
            RESULT.setdefault("run_ids", {})[
                f"fl_round_{n_clients}c"] = run_id_for(cfg)
            ds = load_dataset(cfg.dataset, seed=0, synth_train=8192,
                              synth_test=512)
            exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5),
                                      dataset=ds)
            reps = 20
            exp.run_span(0, reps)  # compile the scanned span
            fetch1(exp.state.weights)
            t0 = time.perf_counter()
            exp.run_span(reps, reps)  # one device program for all rounds
            fetch1(exp.state.weights)
            dt = time.perf_counter() - t0
            rps = reps / dt
            recap(f"fl_rounds_per_sec (Krum+ALIE, {n_clients} clients, "
                  f"mnist-mlp, scanned span): {rps:.1f}")
            # vmapped fwd/bwd of the MLP: ~6 * n * B * d FLOPs per round.
            mfu_line(f"fl_round_{n_clients}c",
                     reps * 6 * n_clients * cfg.batch_size * DIM, 1e3 * dt,
                     dev.platform)

    # --- backdoor rounds/sec: fused vs staged (stderr diagnostic) -------
    with phase("backdoor", 600):
        if on_accel and not relay_alive():
            raise RuntimeError("relay gone")
        from attacking_federate_learning_tpu.attacks import make_attacker
        from attacking_federate_learning_tpu.config import ExperimentConfig
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.data.datasets import load_dataset

        def backdoor_rps(fused, n_clients=32, reps=10):
            from attacking_federate_learning_tpu.utils.lifecycle import (
                run_id_for
            )

            cfg = ExperimentConfig(
                dataset="SYNTH_MNIST", users_count=n_clients, mal_prop=0.25,
                batch_size=32, epochs=1, defense="TrimmedMean",
                backdoor="pattern", backdoor_fused=fused)
            RESULT.setdefault("run_ids", {})[
                f"backdoor_{'fused' if fused else 'staged'}"] = (
                run_id_for(cfg))
            ds = load_dataset(cfg.dataset, seed=0, synth_train=4096,
                              synth_test=256)
            exp = FederatedExperiment(
                cfg, attacker=make_attacker(cfg, dataset=ds), dataset=ds)
            exp.run_span(0, reps)
            fetch1(exp.state.weights)
            t0 = time.perf_counter()
            exp.run_span(reps, reps)
            fetch1(exp.state.weights)
            return reps / (time.perf_counter() - t0)

        recap(f"backdoor_rounds_per_sec fused={backdoor_rps(True):.2f} "
              f"staged={backdoor_rps(False):.2f} "
              f"(32 clients, pattern trigger, TrimmedMean)")

    # Every recap line already streamed live (recap() echoes as it
    # banks), so the closing block repeats ONLY the essentials — one
    # block, each line once.  (r4's tail printed the full recap and then
    # re-printed the essentials, doubling the backdoor line and the
    # whole headline story — noise in the one artifact the driver
    # tails.)
    log("=== essentials ===")
    for line in RECAP:
        if ("device:" in line or "framework krum" in line
                or "north-star" in line or "mfu[krum" in line
                or "pallas[" in line or "VALIDITY" in line):
            log(line)

    deadline_timer.cancel()  # main() finished: only one emitter remains
    emit_result_json()


if __name__ == "__main__":
    main()
