"""``python -m attacking_federate_learning_tpu`` runs the experiment CLI."""

from attacking_federate_learning_tpu.cli import main

if __name__ == "__main__":
    main()
