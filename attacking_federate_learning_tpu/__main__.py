"""``python -m attacking_federate_learning_tpu`` runs the experiment CLI.

``python -m attacking_federate_learning_tpu report logs/run.jsonl``
dispatches to the run-report tool (report.py) via the same entry point.
"""

from attacking_federate_learning_tpu.cli import main

if __name__ == "__main__":
    main()
