"""Model abstraction: a pair of pure functions plus shape metadata.

A model is ``init(key) -> params`` and ``apply(params, x) -> log_probs``.
Params are ordered dicts in torch ``.parameters()`` order so the flat wire
vector (utils/flatten.py) matches the reference's byte layout.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

from attacking_federate_learning_tpu.utils.plugins import Registry


class Model(NamedTuple):
    name: str
    init: Callable            # (key) -> params pytree
    apply: Callable           # (params, x) -> (batch, classes) log-probs
    input_shape: Tuple[int, ...]   # per-example, e.g. (784,) or (3, 32, 32)
    num_classes: int


MODELS = Registry("model")


def get_model(name: str) -> Model:
    return MODELS[name]()
