"""CIFAR-10 CNN: 2 conv + 3 fc, log-softmax head.

Reproduces reference ``Cifar10Net`` (data_sets.py:33-61): conv1 3->16 k3
(xavier weight, data_sets.py:37), MaxPool(3); conv2 16->64 k4, MaxPool(4);
fc 64 -> 384 -> 192 -> 10.  Spatial trace on 32x32 NCHW input:
32 -conv3-> 30 -pool3-> 10 -conv4-> 7 -pool4-> 1.
Parameter order conv1.{weight,bias}, conv2.{weight,bias}, fc1..fc3 —
d = 117,706.
"""

from __future__ import annotations

from collections import OrderedDict

import jax

from attacking_federate_learning_tpu.models import layers as L
from attacking_federate_learning_tpu.models.base import MODELS, Model


def _init(key):
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    # OrderedDict in torch .parameters() definition order (wire format).
    return OrderedDict([
        ("conv1", L.conv_init(k1, 3, 16, 3, xavier=True)),
        ("conv2", L.conv_init(k2, 16, 64, 4)),
        ("fc1", L.linear_init(k3, 64 * 1 * 1, 384)),
        ("fc2", L.linear_init(k4, 384, 192)),
        ("fc3", L.linear_init(k5, 192, 10)),
    ])


def _apply(params, x):
    x = x.reshape((x.shape[0], 3, 32, 32))
    x = L.max_pool2d(jax.nn.relu(L.conv2d(params["conv1"], x)), 3)
    x = L.max_pool2d(jax.nn.relu(L.conv2d(params["conv2"], x)), 4)
    x = x.reshape((x.shape[0], -1))
    x = jax.nn.relu(L.linear(params["fc1"], x))
    x = jax.nn.relu(L.linear(params["fc2"], x))
    return L.log_softmax(L.linear(params["fc3"], x))


@MODELS.register("cifar10_cnn")
def cifar10_cnn() -> Model:
    return Model(name="cifar10_cnn", init=_init, apply=_apply,
                 input_shape=(3, 32, 32), num_classes=10)
