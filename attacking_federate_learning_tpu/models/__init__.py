from attacking_federate_learning_tpu.models.base import (  # noqa: F401
    MODELS, Model, get_model
)

# Import for registry side effects.
from attacking_federate_learning_tpu.models import mnist  # noqa: F401
from attacking_federate_learning_tpu.models import mnist_cnn  # noqa: F401
from attacking_federate_learning_tpu.models import cifar10  # noqa: F401
from attacking_federate_learning_tpu.models import wideresnet  # noqa: F401
from attacking_federate_learning_tpu.models import resnet  # noqa: F401
