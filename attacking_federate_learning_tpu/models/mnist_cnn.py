"""MNIST CNN (LeNet-style): 2 conv + 2 fc, log-softmax head.

Beyond-reference model filling BASELINE.json benchmark config #2
("MNIST CNN, 100 clients, Krum vs ALIE") — the reference itself ships only
the MLP for MNIST (reference data_sets.py:13-30).  Architecture follows the
classic torch MNIST example: conv1 1->10 k5, MaxPool(2); conv2 10->20 k5,
MaxPool(2); fc 320 -> 50 -> 10.  Spatial trace on 28x28 NCHW input:
28 -conv5-> 24 -pool2-> 12 -conv5-> 8 -pool2-> 4.
Parameter order conv1.{weight,bias}, conv2.{weight,bias}, fc1, fc2 —
d = 21,840.
"""

from __future__ import annotations

from collections import OrderedDict

import jax

from attacking_federate_learning_tpu.models import layers as L
from attacking_federate_learning_tpu.models.base import MODELS, Model


def _init(key):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    # OrderedDict in torch .parameters() definition order (wire format).
    return OrderedDict([
        ("conv1", L.conv_init(k1, 1, 10, 5)),
        ("conv2", L.conv_init(k2, 10, 20, 5)),
        ("fc1", L.linear_init(k3, 320, 50)),
        ("fc2", L.linear_init(k4, 50, 10)),
    ])


def _apply(params, x):
    x = x.reshape((x.shape[0], 1, 28, 28))
    x = L.max_pool2d(jax.nn.relu(L.conv2d(params["conv1"], x)), 2)
    x = L.max_pool2d(jax.nn.relu(L.conv2d(params["conv2"], x)), 2)
    x = x.reshape((x.shape[0], -1))
    x = jax.nn.relu(L.linear(params["fc1"], x))
    return L.log_softmax(L.linear(params["fc2"], x))


@MODELS.register("mnist_cnn")
def mnist_cnn() -> Model:
    return Model(name="mnist_cnn", init=_init, apply=_apply,
                 input_shape=(1, 28, 28), num_classes=10)
