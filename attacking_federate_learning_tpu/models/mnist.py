"""MNIST MLP: 784 -> 100 -> 10, log-softmax head.

Reproduces reference ``MnistNet`` (data_sets.py:13-30): fc1 xavier-uniform
weight (data_sets.py:17), fc2 torch-default init, ReLU between, inputs
flattened to 784 by the caller (reference user.py:71, server.py:101).
Parameter order fc1.weight, fc1.bias, fc2.weight, fc2.bias — d = 79,510.
"""

from __future__ import annotations

from collections import OrderedDict

import jax

from attacking_federate_learning_tpu.models import layers as L
from attacking_federate_learning_tpu.models.base import MODELS, Model


def _init(key):
    k1, k2 = jax.random.split(key)
    # OrderedDict in torch .parameters() definition order (wire format).
    return OrderedDict([
        ("fc1", L.linear_init(k1, 28 * 28, 100, xavier=True)),
        ("fc2", L.linear_init(k2, 100, 10)),
    ])


def _apply(params, x):
    # Accepts (B, 784) or image-shaped input; flattening mirrors the
    # reference's data.view(-1, 28*28) at the call sites.
    x = x.reshape((x.shape[0], -1))
    x = jax.nn.relu(L.linear(params["fc1"], x))
    x = L.linear(params["fc2"], x)
    return L.log_softmax(x)


@MODELS.register("mnist_mlp")
def mnist_mlp() -> Model:
    return Model(name="mnist_mlp", init=_init, apply=_apply,
                 input_shape=(784,), num_classes=10)
