"""ResNet-20 for CIFAR-10 (benchmark model, no reference analog).

BASELINE.md's benchmark configs name "CIFAR-10 ResNet-20, 100 clients" as a
measurement point; the reference has no ResNet for CIFAR-10 (its CIFAR10 net
is the small CNN, data_sets.py:33-61).  Standard He-et-al CIFAR ResNet:
3x3/16 stem, three stages of 3 post-activation basic blocks at [16, 32, 64]
channels with strides [1, 2, 2], strided 1x1 conv projection on downsample,
global average pool, linear head.  BatchNorm uses batch statistics (see
models/wideresnet.py docstring for the rationale).
"""

from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp

from attacking_federate_learning_tpu.models import layers as L
from attacking_federate_learning_tpu.models.base import MODELS, Model
from attacking_federate_learning_tpu.models.wideresnet import (
    batch_norm, bn_init, conv3x3, he_conv_init
)


def _block_init(key, in_ch, out_ch):
    k1, k2, k3 = jax.random.split(key, 3)
    p = OrderedDict([
        ("conv1", OrderedDict([("weight", he_conv_init(k1, in_ch, out_ch,
                                                       3))])),
        ("bn1", bn_init(out_ch)),
        ("conv2", OrderedDict([("weight", he_conv_init(k2, out_ch, out_ch,
                                                       3))])),
        ("bn2", bn_init(out_ch)),
    ])
    if in_ch != out_ch:
        p["proj"] = OrderedDict([("weight", he_conv_init(k3, in_ch, out_ch,
                                                         1))])
    return p


def _block_apply(p, x, stride):
    out = jax.nn.relu(batch_norm(p["bn1"], conv3x3(p["conv1"]["weight"], x,
                                                   stride)))
    out = batch_norm(p["bn2"], conv3x3(p["conv2"]["weight"], out, 1))
    if "proj" in p:
        x = L.conv2d({"weight": p["proj"]["weight"]}, x, stride=stride,
                     padding="VALID")
    return jax.nn.relu(x + out)


def make_resnet20(num_classes=10):
    n = 3
    channels = [16, 16, 32, 64]
    strides = [1, 2, 2]

    def init(key):
        keys = jax.random.split(key, 3 * n + 2)
        ki = iter(keys)
        params = OrderedDict([
            ("conv1", OrderedDict([("weight", he_conv_init(next(ki), 3, 16,
                                                           3))])),
            ("bn1", bn_init(16)),
        ])
        for g in range(3):
            blocks = OrderedDict()
            for b in range(n):
                blocks[f"b{b}"] = _block_init(
                    next(ki), channels[g] if b == 0 else channels[g + 1],
                    channels[g + 1])
            params[f"stage{g + 1}"] = blocks
        params["fc"] = L.linear_init(next(ki), channels[3], num_classes)
        return params

    def apply(params, x):
        x = x.reshape((x.shape[0], 3, 32, 32))
        out = jax.nn.relu(batch_norm(params["bn1"],
                                     conv3x3(params["conv1"]["weight"], x)))
        for g in range(3):
            blocks = params[f"stage{g + 1}"]
            for b in range(n):
                out = _block_apply(blocks[f"b{b}"], out,
                                   strides[g] if b == 0 else 1)
        out = L.avg_pool2d(out, 8)
        out = out.reshape((out.shape[0], -1))
        return L.log_softmax(L.linear(params["fc"], out))

    return Model(name="resnet20", init=init, apply=apply,
                 input_shape=(3, 32, 32), num_classes=num_classes)


@MODELS.register("resnet20")
def resnet20() -> Model:
    return make_resnet20(10)
