"""Functional layers with torch-compatible parameter layouts and inits.

Parameter layout parity matters because the wire format (flat vector, see
utils/flatten.py) must match the reference byte-for-byte in ordering:
Linear weights are (out, in) applied as ``x @ W.T + b`` and Conv weights are
(O, I, kH, kW) in NCHW, exactly torch's ``.parameters()`` layouts used by the
reference models (reference data_sets.py:13-61).

Init parity: the reference xavier-initializes only fc1/conv1 weights
(reference data_sets.py:17, :37) and leaves everything else at torch defaults
(kaiming_uniform(a=sqrt(5)) for weights -> U(-1/sqrt(fan_in), 1/sqrt(fan_in));
bias U(-1/sqrt(fan_in), 1/sqrt(fan_in))).
"""

from __future__ import annotations

import math
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax import lax


# --------------------------------------------------------------------------
# initializers
# --------------------------------------------------------------------------

def xavier_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    bound = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def torch_default_uniform(key, shape, fan_in, dtype=jnp.float32):
    # torch kaiming_uniform(a=sqrt(5)) reduces to U(+-1/sqrt(fan_in));
    # torch bias init uses the same bound.
    bound = 1.0 / math.sqrt(fan_in) if fan_in > 0 else 0.0
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def linear_init(key, in_features, out_features, xavier=False, dtype=jnp.float32):
    kw, kb = jax.random.split(key)
    if xavier:
        w = xavier_uniform(kw, (out_features, in_features), in_features,
                           out_features, dtype)
    else:
        w = torch_default_uniform(kw, (out_features, in_features), in_features,
                                  dtype)
    b = torch_default_uniform(kb, (out_features,), in_features, dtype)
    # OrderedDict: ravel_pytree sorts plain-dict keys, which would put bias
    # before weight and break wire-format parity with torch .parameters().
    return OrderedDict([("weight", w), ("bias", b)])


def conv_init(key, in_ch, out_ch, ksize, xavier=False, bias=True,
              dtype=jnp.float32):
    kw, kb = jax.random.split(key)
    fan_in = in_ch * ksize * ksize
    fan_out = out_ch * ksize * ksize
    shape = (out_ch, in_ch, ksize, ksize)
    if xavier:
        w = xavier_uniform(kw, shape, fan_in, fan_out, dtype)
    else:
        w = torch_default_uniform(kw, shape, fan_in, dtype)
    p = OrderedDict([("weight", w)])
    if bias:
        p["bias"] = torch_default_uniform(kb, (out_ch,), fan_in, dtype)
    return p


# --------------------------------------------------------------------------
# forward ops (NCHW throughout, matching the reference's torch layouts)
# --------------------------------------------------------------------------

def linear(p, x):
    return x @ p["weight"].T + p["bias"]


def conv2d(p, x, stride=1, padding="VALID"):
    y = lax.conv_general_dilated(
        x, p["weight"],
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    if "bias" in p:
        y = y + p["bias"][None, :, None, None]
    return y


def max_pool2d(x, ksize, stride=None):
    # torch MaxPool2d(k) defaults stride=k, no padding (floor mode) —
    # used by the reference CIFAR10 net (data_sets.py:38, :40).
    stride = stride or ksize
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 1, ksize, ksize),
        window_strides=(1, 1, stride, stride),
        padding="VALID",
    )


def avg_pool2d(x, ksize, stride=None):
    stride = stride or ksize
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, 1, ksize, ksize),
        window_strides=(1, 1, stride, stride),
        padding="VALID",
    )
    return summed / (ksize * ksize)


def log_softmax(x):
    return jax.nn.log_softmax(x, axis=-1)


def nll_loss(log_probs, targets):
    # torch NLLLoss(mean) over log-probabilities (reference user.py:36,
    # server.py:17).
    return -jnp.take_along_axis(
        log_probs, targets[:, None], axis=1
    ).squeeze(1).mean()
