"""WideResNet-40-4 for CIFAR-100.

Reproduces reference ``Cifar100Net`` (data_sets.py:108-149) — a
pre-activation WideResNet: 3x3 stem conv, three groups of 6 BasicBlocks
(data_sets.py:65-90) widening to [64, 128, 256] channels with strides
[1, 2, 2], final BN+ReLU, 8x8 average pool, linear head — with the
reference's init scheme (data_sets.py:130-138: conv ~ N(0, sqrt(2/(k*k*out))),
BN weight 1 / bias 0, fc bias 0 and torch-default fc weight).

In the reference this model is dead code (unselectable from the CLI,
main.py:114) and its BatchNorm running stats are buffers outside the wire
format (torch ``.parameters()`` excludes them), so an eval'd reference model
would normalize with never-updated init stats.  Here BatchNorm uses batch
statistics in both train and eval ("BatchNorm without running stats"), which
keeps the model a pure function of its trainable parameters — the wire
vector remains exactly the ``.parameters()`` sequence — and is the standard
choice for small-batch FL research.  Deviation documented; parameter order
and shapes match torch exactly.
"""

from __future__ import annotations

import math
from collections import OrderedDict

import jax
import jax.numpy as jnp

from attacking_federate_learning_tpu.models import layers as L
from attacking_federate_learning_tpu.models.base import MODELS, Model

BN_EPS = 1e-5  # torch BatchNorm2d default


def he_conv_init(key, in_ch, out_ch, ksize, dtype=jnp.float32):
    # Reference data_sets.py:130-133: N(0, sqrt(2/n)), n = k*k*out_channels.
    std = math.sqrt(2.0 / (ksize * ksize * out_ch))
    return jax.random.normal(key, (out_ch, in_ch, ksize, ksize), dtype) * std


def bn_init(ch, dtype=jnp.float32):
    # Reference data_sets.py:134-136: weight 1, bias 0.
    return OrderedDict([("weight", jnp.ones((ch,), dtype)),
                        ("bias", jnp.zeros((ch,), dtype))])


def batch_norm(p, x):
    """BN over (N, H, W) with batch statistics (see module docstring)."""
    mean = jnp.mean(x, axis=(0, 2, 3), keepdims=True)
    var = jnp.var(x, axis=(0, 2, 3), keepdims=True)
    xn = (x - mean) * jax.lax.rsqrt(var + BN_EPS)
    return xn * p["weight"][None, :, None, None] + p["bias"][None, :, None, None]


def conv3x3(w, x, stride=1):
    return L.conv2d({"weight": w}, x, stride=stride,
                    padding=[(1, 1), (1, 1)])


def basic_block_init(key, in_planes, out_planes):
    ks = jax.random.split(key, 3)
    p = OrderedDict([
        ("bn1", bn_init(in_planes)),
        ("conv1", OrderedDict([("weight",
                                he_conv_init(ks[0], in_planes, out_planes,
                                             3))])),
        ("bn2", bn_init(out_planes)),
        ("conv2", OrderedDict([("weight",
                                he_conv_init(ks[1], out_planes, out_planes,
                                             3))])),
    ])
    if in_planes != out_planes:
        p["convShortcut"] = OrderedDict([
            ("weight", he_conv_init(ks[2], in_planes, out_planes, 1))])
    return p


def basic_block_apply(p, x, stride):
    """Pre-activation block (reference data_sets.py:81-90): when the
    channel counts differ the pre-activation feeds both branches and the
    shortcut is a strided 1x1 conv on the activated input; otherwise the
    residual is the raw input."""
    equal = "convShortcut" not in p
    if equal:
        out = jax.nn.relu(batch_norm(p["bn1"], x))
        branch = out
        residual = x
    else:
        x = jax.nn.relu(batch_norm(p["bn1"], x))
        branch = x
        residual = L.conv2d({"weight": p["convShortcut"]["weight"]}, x,
                            stride=stride, padding="VALID")
    out = conv3x3(p["conv1"]["weight"], branch, stride)
    out = jax.nn.relu(batch_norm(p["bn2"], out))
    out = conv3x3(p["conv2"]["weight"], out, 1)
    return residual + out


def make_wideresnet(depth=40, widen_factor=4, num_classes=100,
                    name="wideresnet40_4"):
    assert (depth - 4) % 6 == 0
    n = (depth - 4) // 6
    channels = [16, 16 * widen_factor, 32 * widen_factor, 64 * widen_factor]
    strides = [1, 2, 2]

    def init(key):
        keys = jax.random.split(key, 3 * n + 3)
        ki = iter(keys)
        params = OrderedDict([
            ("conv1", OrderedDict([("weight",
                                    he_conv_init(next(ki), 3, channels[0],
                                                 3))]))
        ])
        for g in range(3):
            blocks = OrderedDict()
            in_p = channels[g]
            for b in range(n):
                blocks[f"b{b}"] = basic_block_init(
                    next(ki), in_p if b == 0 else channels[g + 1],
                    channels[g + 1])
            params[f"block{g + 1}"] = blocks
        params["bn1"] = bn_init(channels[3])
        # fc: bias zeroed (reference data_sets.py:137-138), weight
        # torch-default.
        fc = L.linear_init(next(ki), channels[3], num_classes)
        fc["bias"] = jnp.zeros_like(fc["bias"])
        params["fc"] = fc
        return params

    def apply(params, x):
        x = x.reshape((x.shape[0], 3, 32, 32))
        out = conv3x3(params["conv1"]["weight"], x, 1)
        for g in range(3):
            blocks = params[f"block{g + 1}"]
            for b in range(n):
                out = basic_block_apply(blocks[f"b{b}"], out,
                                        strides[g] if b == 0 else 1)
        out = jax.nn.relu(batch_norm(params["bn1"], out))
        out = L.avg_pool2d(out, 8)
        out = out.reshape((out.shape[0], -1))
        return L.log_softmax(L.linear(params["fc"], out))

    return Model(name=name, init=init, apply=apply,
                 input_shape=(3, 32, 32), num_classes=num_classes)


@MODELS.register("wideresnet40_4")
def wideresnet40_4() -> Model:
    return make_wideresnet(40, 4, 100)
