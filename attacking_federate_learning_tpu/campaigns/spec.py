"""Campaign specs: declarative sweep -> validated, identity-keyed cells.

A :class:`CampaignSpec` is the declarative form of what grid.py,
tools/fault_matrix.py and the one-off sweep shells each hand-rolled:
a ``base`` config, cartesian ``axes`` over config fields (plus the
pseudo-field ``attack``), and explicit ``cells`` overrides.  Expansion
is deterministic — same spec, same cell ids in the same order — and
every cell is pre-validated against the engine's composition-rejection
matrix (:func:`composition_reject_reason`): an invalid combo becomes a
``skipped`` cell carrying the rejection message, never a crashed run.

Cell identity is the config-hash ``run_id_for`` (utils/lifecycle.py)
extended with the attack name (:func:`cell_id_for`): the reference CSV
schema and the plain config hash both collapse attacks that share a
config (signflip vs alie), which would alias their journals.  The id
is the join key everywhere — the cell's run journal dir, its private
event log, and its row in ``runs/index.jsonl``.

:func:`hlo_signature` is the compile-cache grouping key: a hash over
the config fields that shape the *traced programs*.  ``seed`` is IN
(measured on this engine: the training set is baked into the fused
span as constants, so two seeds compile two programs); ``epochs`` and
the host-side io/cadence fields are OUT (the span program is sized by
``test_step``, not by how many spans run).  The signature is a
scheduling heuristic — the scheduler stamps measured hit/miss counts
(utils/costs.py cache counters) into the campaign manifest so the
grouping pays in evidence, not assumption.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
from typing import Optional

from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.utils.lifecycle import (
    _IDENTITY_EXCLUDED, run_id_for
)


# Config fields that do not shape the traced round/eval programs: io
# paths, host-side cadence/thresholds, and the horizon (spans are sized
# by test_step; epochs only changes how many identical spans run).
_HLO_INERT = ("output", "log_dir", "run_dir", "data_dir",
              "checkpoint_every", "checkpoint_acc_threshold", "epochs")


def _hashed(d: dict) -> str:
    return hashlib.sha1(
        json.dumps(d, sort_keys=True, default=str).encode()).hexdigest()


def cell_id_for(cfg: ExperimentConfig, attack: str = "auto") -> str:
    """Deterministic cell identity: ``run_id_for`` for the reference
    attack resolution ('auto'), extended with the attack name
    otherwise — two attacks sharing a config (signflip vs alie) must
    not share a journal."""
    if attack in (None, "auto"):
        return run_id_for(cfg)
    d = dataclasses.asdict(cfg)
    for k in _IDENTITY_EXCLUDED:
        d.pop(k, None)
    d["attack"] = attack
    return (f"{cfg.dataset}_{cfg.defense}_{attack}_s{cfg.seed}_"
            f"{_hashed(d)[:10]}")


def hlo_signature(cfg: Optional[ExperimentConfig],
                  attack: str = "auto") -> str:
    """Compile-cache grouping key (8 hex chars); 'invalid' for cells
    whose config never constructed."""
    if cfg is None:
        return "invalid"
    d = dataclasses.asdict(cfg)
    for k in _HLO_INERT:
        d.pop(k, None)
    d["attack"] = attack
    return _hashed(d)[:8]


def apply_attack(overrides: dict, attack: str) -> dict:
    """The grid drivers' attack -> config mapping, shared: 'none'
    zeroes the malicious cohort (num_std and mal_prop, grid.py's
    historical behavior), the backdoor attacks need a trigger (default
    'pattern')."""
    out = dict(overrides)
    if attack == "none":
        out["num_std"] = 0.0
        out["mal_prop"] = 0.0
    elif attack in ("backdoor", "backdoor_timed"):
        if not out.get("backdoor"):
            out["backdoor"] = "pattern"
    return out


# ---------------------------------------------------------------------------
# the composition-rejection matrix, pre-validated

def _cohort(cfg) -> tuple:
    """(m, m_mal) under partial participation — the engine's static
    cohort math (core/engine.py.__init__), reproduced host-side."""
    n, f = cfg.users_count, cfg.corrupted_count
    if cfg.participation < 1.0:
        m = max(1, int(round(cfg.participation * n)))
        m_mal = min(int(round(cfg.participation * f)), m)
        if f > 0 and m_mal == 0:
            raise ValueError(
                f"participation={cfg.participation} rounds the "
                f"malicious cohort to 0 while f={f} — the attack "
                f"would silently never run (static cohorts); raise "
                f"participation or set mal_prop=0 explicitly")
        if m - m_mal > n - f:
            raise ValueError(
                f"cohort needs {m - m_mal} honest clients but only "
                f"{n - f} exist (n={n}, f={f}, "
                f"participation={cfg.participation})")
        return m, m_mal
    return n, f


def composition_reject_reason(overrides: dict,
                              attack: str = "auto") -> Optional[str]:
    """The engine's composition-rejection matrix as a pure pre-check.

    Returns None when the (config, attack) cell is constructible and
    passes every *pure* engine-init check — the same check functions
    the engine calls (defenses/kernels.py check_defense_args /
    check_tier2_args, core/faults.py check_fault_support,
    core/async_rounds.py check_async_support,
    core/population.py check_traffic_support) plus the config
    dataclass's own ``__post_init__`` rejections — or the rejection
    message otherwise.  tests/test_campaign.py pins agreement between
    this pre-check and real construction for the known-invalid matrix,
    so the two can't drift silently; the executors still catch
    ValueError at cell start as the backstop for anything novel.
    """
    try:
        cfg = ExperimentConfig(**overrides)
    except (ValueError, TypeError) as e:
        return str(e)
    try:
        validate_composition(cfg, attack)
    except ValueError as e:
        return str(e)
    return None


def validate_composition(cfg: ExperimentConfig,
                         attack: str = "auto") -> None:
    """Raise ValueError for any (config, attack) the engine would
    reject at init (the pure checks only — nothing here touches a jax
    op or builds a model)."""
    from attacking_federate_learning_tpu.defenses.kernels import (
        TIER2_DEFENSES, check_defense_args, check_tier2_args
    )

    m, m_mal = _cohort(cfg)
    timed = attack == "backdoor_timed"
    if attack in ("backdoor", "backdoor_timed") and not cfg.backdoor:
        raise ValueError(
            f"--attack {attack} requires a trigger: -b pattern|1|2|3 "
            f"(the poison set derives from it)")
    if timed and cfg.aggregation != "async":
        raise ValueError(
            "a timed attack (attacks/backdoor.py TimedBackdoorAttack) "
            "games the async arrival schedule; it requires "
            "aggregation='async' — under synchronous topologies there "
            "is no arrival time to game")
    if cfg.aggregation == "hierarchical":
        from attacking_federate_learning_tpu.ops.federated import (
            tier1_assumed, tier2_assumed
        )

        if cfg.participation < 1.0:
            raise ValueError(
                "hierarchical aggregation requires full participation "
                "(placement assigns every client to a megabatch)")
        if cfg.data_placement != "device":
            raise ValueError(
                "hierarchical aggregation requires "
                "data_placement='device' (the scanned round gathers "
                "each megabatch's batch on device)")
        if cfg.backdoor and not cfg.backdoor_fused:
            raise ValueError(
                "hierarchical aggregation needs the fused backdoor "
                "path (drop --backdoor-staged)")
        if cfg.defense not in TIER2_DEFENSES:
            raise ValueError(
                f"hierarchical tier-1 defense must be one of "
                f"{sorted(TIER2_DEFENSES)} (the mask-aware kernel "
                f"set), got {cfg.defense!r}")
        if cfg.distance_impl in ("ring", "allgather", "host"):
            raise ValueError(
                f"hierarchical aggregation supports distance_impl in "
                f"auto/xla/pallas (got {cfg.distance_impl!r}): the "
                f"per-megabatch distance pass must stay inside the "
                f"scanned program")
        for knob in ("trimmed_mean_impl", "median_impl",
                     "bulyan_selection_impl", "bulyan_trim_impl"):
            if getattr(cfg, knob) == "host":
                # Mirrors engine._init_hierarchical: the pallas values
                # stay inside the scanned program and compose; only
                # the host kernels would pay a per-megabatch callback.
                raise ValueError(
                    f"hierarchical aggregation requires a device-"
                    f"resident {knob} ('xla' or 'pallas'; got 'host' — "
                    f"a host kernel would pure_callback once per "
                    f"megabatch per scan step)")
        S = cfg.users_count // cfg.megabatch
        f = cfg.corrupted_count
        t1 = (cfg.tier1_corrupted if cfg.tier1_corrupted is not None
              else tier1_assumed(f, S))
        t2 = (cfg.tier2_corrupted if cfg.tier2_corrupted is not None
              else tier2_assumed(f, cfg.megabatch))
        check_tier2_args(cfg.defense, cfg.megabatch, t1)
        check_tier2_args(cfg.tier2_defense or cfg.defense, S, t2)
        if cfg.mesh_shape is not None and cfg.mesh_shape[0] > 1:
            # The SPMD client_map's schedule check, via the SAME
            # function the engine init calls (ops/federated.py
            # spmd_schedule — ISSUE 12) so the pre-check and the real
            # rejection cannot drift: an S not divisible by the mesh
            # clients axis becomes a skipped cell, never a crash.
            # Host-side numpy only — no jax op, no device needed.
            from attacking_federate_learning_tpu.ops.federated import (
                make_placement, spmd_schedule
            )

            spmd_schedule(
                make_placement(cfg.users_count, f, cfg.megabatch,
                               cfg.mal_placement),
                cfg.mesh_shape[0])
    elif cfg.aggregation == "async":
        from attacking_federate_learning_tpu.core.async_rounds import (
            check_async_support
        )

        check_async_support(cfg)
        if cfg.async_buffer > m:
            raise ValueError(
                f"--async-buffer {cfg.async_buffer} exceeds the cohort "
                f"(m={m}): the FedBuff trigger would never fire — the "
                f"pending pool holds at most one update per client")
        try:
            check_defense_args(cfg.defense, cfg.async_buffer, m_mal)
        except ValueError as e:
            raise ValueError(
                f"--aggregation async aggregates exactly "
                f"k=--async-buffer rows per applied round, so the "
                f"defense bound applies at n=k: {e}") from e
        if (cfg.defense == "TrimmedMean"
                and cfg.async_buffer - m_mal - 1 < 1):
            raise ValueError(
                f"--aggregation async TrimmedMean keeps k - f - 1 rows "
                f"per applied round; got k={cfg.async_buffer}, "
                f"f={m_mal} — raise --async-buffer")
    else:
        check_defense_args(cfg.defense, m, m_mal)
    if cfg.faults is not None and cfg.faults.enabled:
        from attacking_federate_learning_tpu.core.faults import (
            check_fault_support
        )

        check_fault_support(cfg)
    if cfg.traffic is not None and cfg.traffic.enabled:
        from attacking_federate_learning_tpu.core.population import (
            check_traffic_support
        )

        check_traffic_support(cfg)


# ---------------------------------------------------------------------------
# the spec

@dataclasses.dataclass
class Cell:
    """One expanded campaign cell.  ``cfg`` is None when the config
    itself failed to construct (the skip reason says why)."""

    cell_id: str
    overrides: dict                      # merged base+axis+explicit
    attack: str = "auto"
    cfg: Optional[ExperimentConfig] = None
    priority: int = 0
    group: str = "invalid"               # hlo_signature
    skip: Optional[str] = None           # rejection message
    index: int = 0                       # spec expansion order

    def row(self) -> dict:
        """The stable descriptive fields stamped into journal records
        and the campaign manifest."""
        out = {"cell": self.cell_id, "attack": self.attack,
               "priority": self.priority, "group": self.group,
               "index": self.index}
        # The impl knobs ride along so `runs campaign` can render
        # impl-comparison tables (xla vs pallas vs host sweeps,
        # ISSUE 11) straight from the journal rows; the mesh/topology
        # knobs (ISSUE 12) let the same tables split SPMD vs scan
        # hierarchical cells.
        for k in ("dataset", "defense", "seed", "epochs", "aggregation",
                  "secagg", "aggregation_impl", "distance_impl",
                  "bulyan_selection_impl", "mesh_shape", "megabatch",
                  "mal_placement"):
            if self.cfg is not None:
                out[k] = getattr(self.cfg, k)
            elif k in self.overrides:
                out[k] = self.overrides[k]
        if isinstance(out.get("mesh_shape"), tuple):
            out["mesh_shape"] = list(out["mesh_shape"])  # JSONL-stable
        return out


@dataclasses.dataclass
class CampaignSpec:
    """Declarative sweep: ``base`` config kwargs, cartesian ``axes``
    (config fields + the pseudo-field 'attack'), explicit extra
    ``cells`` (each a dict of overrides; '_priority' rides along), and
    'field=value' -> int ``priorities`` rules (matching cells sum every
    matching rule; higher runs first)."""

    name: str = "campaign"
    base: dict = dataclasses.field(default_factory=dict)
    axes: dict = dataclasses.field(default_factory=dict)
    cells: list = dataclasses.field(default_factory=list)
    priorities: dict = dataclasses.field(default_factory=dict)
    deadline_s: float = 0.0
    order: str = "grouped"               # grouped | spec | shuffled

    # --- identity ---------------------------------------------------------
    def spec_hash(self) -> str:
        return _hashed({"base": self.base, "axes": self.axes,
                        "cells": self.cells})

    @property
    def campaign_id(self) -> str:
        return f"{self.name}_{self.spec_hash()[:10]}"

    # --- (de)serialization ------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=1,
                          default=str)

    @classmethod
    def from_json(cls, text: str) -> "CampaignSpec":
        blob = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(blob) - known
        if unknown:
            raise ValueError(
                f"unknown campaign-spec fields {sorted(unknown)} "
                f"(known: {sorted(known)})")
        return cls(**blob)

    @classmethod
    def load(cls, path: str) -> "CampaignSpec":
        with open(path) as f:
            return cls.from_json(f.read())

    # --- expansion --------------------------------------------------------
    def _priority_for(self, overrides: dict, attack: str,
                      explicit: Optional[int]) -> int:
        if explicit is not None:
            return int(explicit)
        prio = 0
        probe = dict(overrides, attack=attack)
        for rule, bump in self.priorities.items():
            if "=" not in rule:
                raise ValueError(
                    f"priority rule must be 'field=value', got {rule!r}")
            k, v = rule.split("=", 1)
            if str(probe.get(k)) == v:
                prio += int(bump)
        return prio

    def _make_cell(self, overrides: dict, attack: str,
                   explicit_priority: Optional[int], index: int) -> Cell:
        overrides = apply_attack(overrides, attack)
        skip = composition_reject_reason(overrides, attack)
        cfg = None
        try:
            cfg = ExperimentConfig(**overrides)
        except (ValueError, TypeError):
            pass                       # skip already carries the reason
        if cfg is not None:
            cell_id = cell_id_for(cfg, attack)
        else:
            probe = dict(overrides, attack=attack)
            cell_id = f"invalid_{_hashed(probe)[:10]}"
        return Cell(cell_id=cell_id, overrides=overrides, attack=attack,
                    cfg=cfg,
                    priority=self._priority_for(overrides, attack,
                                                explicit_priority),
                    group=hlo_signature(cfg, attack), skip=skip,
                    index=index)

    def expand(self) -> list:
        """Deterministic expansion: axes in insertion order, cartesian
        product in value order, explicit cells appended; duplicate
        cell ids are an error (two spellings of one config would race
        for one journal)."""
        cells, index = [], 0
        axis_names = list(self.axes)
        for combo in itertools.product(
                *(self.axes[a] for a in axis_names)) if axis_names else [()]:
            overrides = dict(self.base)
            overrides.update(dict(zip(axis_names, combo)))
            attack = overrides.pop("attack", "auto")
            cells.append(self._make_cell(overrides, attack, None, index))
            index += 1
        for extra in self.cells:
            overrides = dict(self.base)
            overrides.update(extra)
            prio = overrides.pop("_priority", None)
            attack = overrides.pop("attack", "auto")
            cells.append(self._make_cell(overrides, attack, prio, index))
            index += 1
        seen = {}
        for c in cells:
            if c.cell_id in seen:
                raise ValueError(
                    f"campaign {self.campaign_id}: duplicate cell id "
                    f"{c.cell_id} (indices {seen[c.cell_id]} and "
                    f"{c.index} expand to the same config+attack)")
            seen[c.cell_id] = c.index
        return cells


# ---------------------------------------------------------------------------
# cell -> CLI flags (the supervisor executor's child surface)

# ExperimentConfig field -> CLI flag for every value-typed field the
# reference-verbatim flag surface exposes (cli.py:build_parser).
_VALUE_FLAGS = (
    ("dataset", "-s"), ("users_count", "-n"), ("mal_prop", "-m"),
    ("num_std", "-z"), ("defense", "-d"), ("model", "--model"),
    ("batch_size", "-c"), ("epochs", "-e"),
    ("learning_rate", "-l"), ("participation", "--participation"),
    ("local_steps", "--local-steps"), ("partition", "--partition"),
    ("dirichlet_alpha", "--dirichlet-alpha"),
    ("style_strength", "--style-strength"), ("seed", "--seed"),
    ("data_dir", "--data-dir"), ("log_dir", "--log-dir"),
    ("run_dir", "--run-dir"), ("synth_train", "--synth-train"),
    ("synth_test", "--synth-test"), ("backend", "--backend"),
    ("data_placement", "--data-placement"),
    ("stream_prefetch", "--stream-prefetch"),
    ("stream_workers", "--stream-workers"),
    ("krum_scoring_method", "--krum-scoring-method"),
    ("bulyan_batch_select", "--bulyan-batch-select"),
    ("bulyan_selection_impl", "--bulyan-selection-impl"),
    ("bulyan_trim_impl", "--bulyan-trim-impl"),
    ("aggregation", "--aggregation"),
    ("aggregation_impl", "--aggregation-impl"),
    ("async_buffer", "--async-buffer"),
    ("async_max_staleness", "--async-max-staleness"),
    ("staleness_weight", "--staleness-weight"),
    ("megabatch", "--megabatch"), ("mal_placement", "--mal-placement"),
    ("secagg", "--secagg"), ("distance_impl", "--distance-impl"),
    ("distance_dtype", "--distance-dtype"),
    ("attack_direction", "--attack-direction"),
    ("dnc_iters", "--dnc-iters"), ("dnc_sketch_dim", "--dnc-sketch-dim"),
    ("dnc_filter_frac", "--dnc-filter-frac"),
    ("geomed_iters", "--geomed-iters"), ("geomed_eps", "--geomed-eps"),
    ("cclip_tau", "--cclip-tau"), ("cclip_iters", "--cclip-iters"),
    ("trimmed_mean_impl", "--trimmed-mean-impl"),
    ("median_impl", "--median-impl"),
)
# Optional[value] fields: emitted only when set.
_OPTIONAL_FLAGS = (
    ("tier2_defense", "--tier2-defense"),
    ("tier1_corrupted", "--tier1-corrupted"),
    ("tier2_corrupted", "--tier2-corrupted"),
    ("output", "-o"),
)
# Boolean store_true flags.
_BOOL_FLAGS = (
    ("remat", "--remat"), ("krum_paper_scoring", "--krum-paper-scoring"),
    ("server_uses_faded_lr", "--server-uses-faded-lr"),
    ("log_round_stats", "--round-stats"), ("telemetry", "--telemetry"),
)


def cfg_to_cli_args(cfg: ExperimentConfig, attack: str = "auto") -> list:
    """Express a cell as cli.py flags for the supervisor executor.

    Best-effort by construction (a handful of config fields have no
    CLI spelling — test_step, the shadow-train constants, grad_dtype);
    the scheduler therefore VERIFIES the round trip before launching:
    ``build_parser().parse_args(flags)`` -> ``config_from_args`` must
    reproduce the cell id, and a cell whose config is not expressible
    fails loudly instead of silently running a drifted config."""
    args = []
    for field, flag in _VALUE_FLAGS:
        args += [flag, str(getattr(cfg, field))]
    for field, flag in _OPTIONAL_FLAGS:
        v = getattr(cfg, field)
        if v is not None:
            args += [flag, str(v)]
    for field, flag in _BOOL_FLAGS:
        if getattr(cfg, field):
            args.append(flag)
    if cfg.checkpoint_every:
        # 0 (the config default) stays unspoken so the supervisor can
        # force its own resume-granularity default onto the child.
        args += ["--checkpoint-every", str(cfg.checkpoint_every)]
    bd = cfg.backdoor
    args += ["-b", "No" if bd is False else str(bd)]
    if not cfg.backdoor_fused:
        args.append("--backdoor-staged")
    if cfg.mesh_shape is not None:
        args += ["--mesh-shape", ",".join(str(x) for x in cfg.mesh_shape)]
    args += ["--augment", {None: "auto", True: "on",
                           False: "off"}[cfg.data_augment]]
    if cfg.faults is not None:
        f = cfg.faults
        args += ["--fault-dropout", str(f.dropout),
                 "--fault-straggler", str(f.straggler),
                 "--fault-straggler-delay", str(f.straggler_delay),
                 "--fault-corrupt", str(f.corrupt),
                 "--fault-corrupt-mode", f.corrupt_mode,
                 "--fault-shard-dropout", str(f.shard_dropout),
                 "--fault-shard-dropout-dwell",
                 str(f.shard_dropout_dwell)]
    if attack not in (None, "auto"):
        args += ["--attack", attack]
    return args


def verify_cli_round_trip(cell: Cell) -> Optional[str]:
    """Parse the cell's CLI spelling back into a config and compare
    identities; returns the problem string (None = exact).  Pure
    argparse — no jax."""
    from attacking_federate_learning_tpu.cli import (
        build_parser, config_from_args
    )

    args = cfg_to_cli_args(cell.cfg, cell.attack)
    try:
        ns = build_parser().parse_args(args)
        rebuilt = config_from_args(ns)
    except SystemExit:
        return f"cell {cell.cell_id}: CLI rejected flags {args}"
    got = cell_id_for(rebuilt, cell.attack)
    if got != cell.cell_id:
        deltas = {
            k: (v, getattr(rebuilt, k))
            for k, v in dataclasses.asdict(cell.cfg).items()
            if getattr(rebuilt, k, None) != v and k != "faults"}
        return (f"cell {cell.cell_id}: config not expressible via the "
                f"CLI flag surface (round-trip id {got}; field deltas "
                f"{deltas}) — fields without CLI flags (test_step, the "
                f"shadow-train constants, grad_dtype, ...) must stay at "
                f"their defaults under executor='supervisor'")
    return None
