"""Campaign driver CLI.

    python -m attacking_federate_learning_tpu.campaigns spec.json \
        [--executor supervisor|inline] [--order grouped|spec|shuffled] \
        [--cache-dir D --cache-budget-mb N] [--deadline SECS] [--dry-run]

Also dispatched as ``... cli campaign <spec.json> ...`` (cli.py).  The
spec is a CampaignSpec JSON (campaigns/spec.py; ARCHITECTURE.md
"Campaign engine" documents the format).  Exit status: 0 = every cell
done or skipped, 1 = some cell failed (or a bad spec), 75 = stopped
cleanly at the wall-clock deadline (re-invoke to continue — the
campaign journal resumes only the remaining cells).
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="attacking_federate_learning_tpu campaign",
        description="Run a declarative defense x attack x topology "
                    "sweep as a resumable, cache-aware campaign "
                    "(campaigns/scheduler.py).")
    p.add_argument("spec", help="CampaignSpec JSON path")
    p.add_argument("--executor", default="supervisor",
                   choices=["supervisor", "inline"],
                   help="'supervisor' runs each cell as a child under "
                        "tools/supervisor.py (bounded retries, journal "
                        "audit — the durable default); 'inline' runs "
                        "cells in-process, grid-style (shared caches, "
                        "fastest for small cells)")
    p.add_argument("--order", default=None,
                   choices=["grouped", "spec", "shuffled"],
                   help="cell ordering (default: the spec's; 'grouped' "
                        "= priority bands, HLO-signature groups "
                        "adjacent inside each; 'shuffled' is the "
                        "deterministic control arm)")
    p.add_argument("--run-dir", default=None,
                   help="campaign + run store root (default: the "
                        "spec base's run_dir, else 'runs')")
    p.add_argument("--cache-dir", default=None,
                   help="persistent compile-cache dir pinned onto "
                        "every cell (default: the ambient cache)")
    p.add_argument("--cache-budget-mb", default=0.0, type=float,
                   help="evict least-recently-used cache entries "
                        "between cells to stay under this many MB "
                        "(0 = unbounded; needs --cache-dir)")
    p.add_argument("--deadline", default=None, type=float,
                   metavar="SECS",
                   help="wall-clock budget for THIS invocation (the "
                        "relay-window seam): past it the campaign "
                        "checkpoints cleanly and exits 75")
    p.add_argument("--max-retries", default=2, type=int,
                   help="per-cell supervisor retry budget")
    p.add_argument("--no-journal-runs", action="store_true",
                   help="inline executor only: run cells without "
                        "per-run journals/registry stamps")
    p.add_argument("--no-cost-report", action="store_true",
                   help="supervisor executor: do not force "
                        "--cost-report onto cells (drops the per-cell "
                        "compile/cache evidence)")
    p.add_argument("--dry-run", action="store_true",
                   help="print the validated, ordered plan and exit")
    args = p.parse_args(argv)

    from attacking_federate_learning_tpu.campaigns.scheduler import (
        Campaign
    )
    from attacking_federate_learning_tpu.campaigns.spec import (
        CampaignSpec
    )

    try:
        spec = CampaignSpec.load(args.spec)
    except (OSError, ValueError, TypeError) as e:
        print(f"campaign: bad spec {args.spec}: {e}")
        return 1
    camp = Campaign(spec, run_dir=args.run_dir,
                    executor=args.executor, order=args.order,
                    cache_dir=args.cache_dir,
                    cache_budget_mb=args.cache_budget_mb,
                    max_retries=args.max_retries,
                    deadline_s=args.deadline,
                    journal_runs=not args.no_journal_runs,
                    cost_report=not args.no_cost_report)
    try:
        cells = camp.plan()
    except ValueError as e:
        print(f"campaign: bad spec {args.spec}: {e}")
        return 1
    if args.dry_run:
        print(f"== campaign {spec.campaign_id}: {len(cells)} cells, "
              f"order={camp.order}, executor={camp.executor_name} ==")
        for i, c in enumerate(cells):
            state = camp.journal.state_of(c.cell_id)
            note = (f"SKIP: {c.skip}" if c.skip else state)
            print(f"  {i:3d}  [{c.group}] p{c.priority}  "
                  f"{c.cell_id}  {note}")
        return 0
    if args.executor == "inline":
        # Backend selection must precede the first jax op (cli.py
        # apply_backend; the supervisor children do this themselves).
        from attacking_federate_learning_tpu.cli import apply_backend
        apply_backend(str(spec.base.get("backend", "auto")))
    rc = camp.run()
    man = camp.journal.read_manifest() or {}
    counts = man.get("counts", {})
    print(f"[campaign] {spec.campaign_id}: {man.get('status', '?')}  "
          + "  ".join(f"{k}={v}" for k, v in sorted(counts.items()))
          + f"  cache={man.get('cache', {})}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
