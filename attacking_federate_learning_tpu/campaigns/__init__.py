"""Campaign engine: resumable, cache-aware scheduling of whole
defense x attack x topology sweeps (ROADMAP item 5).

The supervisor, run registry and exactly-once journal (PRs 4-5) make
*single* runs durable; this package is the layer above — a scheduler
that expands a declarative :class:`CampaignSpec` into config cells,
pre-validates every cell against the engine's composition-rejection
matrix, orders them for compile-cache locality, and executes them
(in-process, grid-style, or through ``tools/supervisor.py``) under a
campaign-level exactly-once journal, so a SIGKILL mid-campaign costs
only the cell in flight.  ARCHITECTURE.md "Campaign engine" is the
contract; ``runs campaign <id>`` renders the result tables from the
run registry.
"""

from attacking_federate_learning_tpu.campaigns.journal import (  # noqa: F401
    CampaignJournal, TERMINAL_STATES
)
from attacking_federate_learning_tpu.campaigns.scheduler import (  # noqa: F401
    Campaign, EXIT_DEADLINE, order_cells
)
from attacking_federate_learning_tpu.campaigns.spec import (  # noqa: F401
    CampaignSpec, Cell, apply_attack, cell_id_for,
    composition_reject_reason, hlo_signature
)
