"""The campaign scheduler: ordering, budgets, executors, run loop.

One campaign = one ordered pass over the expanded cells of a
:class:`CampaignSpec` (spec.py), under the campaign-level exactly-once
journal (journal.py).  Three scheduling decisions live here:

- **Ordering** (:func:`order_cells`): priority bands first (higher
  runs first — the relay-window rule: the cells you must have land
  before the window closes), then compile-cache grouping inside each
  band — cells sharing an HLO signature (spec.py:hlo_signature) run
  adjacently so recompiles of shared programs hit the persistent
  cache while their entries are still resident.  ``--order shuffled``
  (deterministic, keyed on the campaign id) is the control arm the
  ordering proof measures against; ``--order spec`` preserves spec
  order inside bands.

- **Cache budget** (:func:`trim_cache`): an optional byte budget on
  the campaign's persistent-cache dir, enforced between cells by
  evicting least-recently-used entries (mtime of the entry or its
  ``-atime`` sidecar, whichever is newer).  This is what makes the
  ordering a real decision: with an unbounded durable cache every
  ordering hits equally (each unique program misses once); under a
  budget, adjacency is hits and interleaving is thrash.  Hit/miss
  evidence is measured, not assumed: the PR 3 cache counters
  (utils/costs.py) — per-cell deltas in-process (inline executor),
  per-run 'compile' events under ``--cost-report`` (supervisor
  executor) — are stamped into every cell record and totaled in the
  campaign manifest.

- **Deadline** (``deadline_s``): a wall-clock budget per invocation
  (the relay-window seam).  The scheduler checks it before launching
  each cell; past the deadline it writes a clean 'deadline' manifest
  and exits :data:`EXIT_DEADLINE` (75, EX_TEMPFAIL — resumable), and
  a re-invoke completes only the remaining cells.

Executors: ``inline`` runs cells in-process, grid.py-style (shared
model/data/jit caches — the fast path for small cells; one cell at a
time, this box is one core); ``supervisor`` runs each cell as a child
process under tools/supervisor.py (bounded retries, degradation
ladder, per-run journal audit — the durable path).  Both execute
SEQUENTIALLY: nproc=1 here, and the TPU admits one process at a time.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import importlib.util
import json
import os
import random
import time
from typing import Optional

from attacking_federate_learning_tpu.campaigns.journal import (
    CampaignJournal
)
from attacking_federate_learning_tpu.campaigns.spec import (
    CampaignSpec, cfg_to_cli_args, verify_cli_round_trip
)
from attacking_federate_learning_tpu.utils.metrics import (
    SCHEMA_VERSION, validate_event
)

EXIT_DEADLINE = 75      # EX_TEMPFAIL: checkpointed + resumable, like a
#                         preempted run (utils/lifecycle.py)
_KILL_RC = 137          # the injection seams mimic a SIGKILL


# ---------------------------------------------------------------------------
# ordering

def order_cells(cells, mode: str = "grouped", key: str = "") -> list:
    """Deterministic execution order.  Priority is always the primary
    key (higher first); inside a band, 'grouped' runs HLO-signature
    groups contiguously (groups in first-appearance order, spec order
    within), 'spec' keeps spec order, 'shuffled' applies a
    deterministic shuffle keyed on ``key`` (the measured control arm
    for the cache-ordering proof)."""
    if mode == "spec":
        return sorted(cells, key=lambda c: (-c.priority, c.index))
    if mode == "grouped":
        first_seen = {}
        for c in sorted(cells, key=lambda c: c.index):
            first_seen.setdefault(c.group, len(first_seen))
        return sorted(cells, key=lambda c: (-c.priority,
                                            first_seen[c.group], c.index))
    if mode == "shuffled":
        seed = int(hashlib.sha1(key.encode()).hexdigest()[:8], 16)
        shuffled = sorted(cells, key=lambda c: c.index)
        random.Random(seed).shuffle(shuffled)
        rank = {c.cell_id: i for i, c in enumerate(shuffled)}
        return sorted(cells, key=lambda c: (-c.priority, rank[c.cell_id]))
    raise ValueError(
        f"order must be 'grouped', 'spec' or 'shuffled', got {mode!r}")


def adjacency(cells) -> int:
    """Number of adjacent same-group pairs in an ordering — the pure
    quantity grouped ordering maximizes (tests pin it; the measured
    hit counts are the evidence it pays)."""
    return sum(a.group == b.group for a, b in zip(cells, cells[1:]))


# ---------------------------------------------------------------------------
# persistent-cache budget

def cache_dir_bytes(path: str) -> int:
    total = 0
    try:
        for name in os.listdir(path):
            try:
                total += os.path.getsize(os.path.join(path, name))
            except OSError:
                pass
    except OSError:
        pass
    return total


def trim_cache(path: str, budget_bytes: int) -> int:
    """Evict least-recently-used cache entries (with their ``-atime``
    sidecars) until the dir fits the budget; returns entries evicted.
    Recency = the newer of the entry's and its sidecar's mtime, so a
    backend that touches sidecars on hit gets true LRU and one that
    doesn't degrades to FIFO — either way deterministic."""
    if budget_bytes <= 0 or not os.path.isdir(path):
        return 0
    entries = []
    for name in os.listdir(path):
        if name.endswith("-atime"):
            continue
        p = os.path.join(path, name)
        side = os.path.join(path, name + "-atime")
        try:
            size = os.path.getsize(p)
            mtime = os.path.getmtime(p)
        except OSError:
            continue
        try:
            mtime = max(mtime, os.path.getmtime(side))
            size += os.path.getsize(side)
        except OSError:
            side = None
        entries.append((mtime, size, p, side))
    total = sum(e[1] for e in entries)
    evicted = 0
    for mtime, size, p, side in sorted(entries):
        if total <= budget_bytes:
            break
        for victim in (p, side):
            if victim is not None:
                try:
                    os.unlink(victim)
                except OSError:
                    pass
        total -= size
        evicted += 1
    return evicted


def compile_event_cache_counts(events_path: str,
                               offset: int = 0) -> dict:
    """Hit/miss totals from a run's 'compile' events (the PR 3 cache
    attribution a ``--cost-report`` child emits).  ``offset`` skips an
    existing byte prefix: a cell re-run under a second campaign
    APPENDS to the same private log, and the earlier attempts' events
    are not this execution's evidence."""
    hits = misses = 0
    try:
        with open(events_path) as f:
            f.seek(offset)
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "compile":
                    hits += rec.get("cache") == "hit"
                    misses += rec.get("cache") == "miss"
    except OSError:
        pass
    return {"cache_hits": hits, "cache_misses": misses}


# ---------------------------------------------------------------------------
# executors

class InlineExecutor:
    """Grid-style in-process execution: one FederatedExperiment per
    cell, datasets cached across cells, per-cell persistent-cache
    hit/miss deltas from the process-wide counters."""

    def __init__(self):
        self._datasets = {}

    def _dataset(self, cfg):
        key = (cfg.dataset, cfg.seed, cfg.synth_train, cfg.synth_test,
               cfg.data_dir)
        if key not in self._datasets:
            from attacking_federate_learning_tpu.data.datasets import (
                load_dataset
            )
            self._datasets[key] = load_dataset(
                cfg.dataset, cfg.data_dir, cfg.seed,
                synth_train=cfg.synth_train, synth_test=cfg.synth_test)
        return self._datasets[key]

    def run(self, cell, camp) -> dict:
        from attacking_federate_learning_tpu.attacks import make_attacker
        from attacking_federate_learning_tpu.core.engine import (
            FederatedExperiment
        )
        from attacking_federate_learning_tpu.utils.costs import (
            cache_counts, install_cache_counters
        )
        from attacking_federate_learning_tpu.utils.lifecycle import (
            RunJournal
        )
        from attacking_federate_learning_tpu.utils.metrics import RunLogger

        cfg = cell.cfg
        t0 = time.time()
        try:
            # Backstop for rejections the pre-validation matrix does
            # not know (construction inside the try, like grid.py).
            attacker = make_attacker(
                cfg, dataset=self._dataset(cfg),
                name=None if cell.attack == "auto" else cell.attack)
            exp = FederatedExperiment(cfg, attacker=attacker,
                                      dataset=self._dataset(cfg))
        except ValueError as e:
            return {"state": "skipped", "reason": str(e)}
        journal = (RunJournal(cfg.run_dir, cell.cell_id)
                   if camp.journal_runs else None)
        install_cache_counters()
        before = dict(cache_counts())
        os.makedirs(cfg.log_dir, exist_ok=True)
        try:
            with RunLogger(cfg, cfg.output, cfg.log_dir,
                           jsonl_name=cell.cell_id) as logger:
                out = exp.run(logger, journal=journal)
                events = logger.jsonl_path
        except FloatingPointError as e:     # the backdoor nan guard
            return {"state": "failed", "reason": str(e), "rc": 76,
                    "wall_s": round(time.time() - t0, 2)}
        finally:
            if journal is not None:
                journal.close()
        after = cache_counts()
        res = {"state": "done", "rc": 0,
               "wall_s": round(time.time() - t0, 2),
               "rounds": cfg.epochs, "events": os.path.abspath(events),
               "cache_hits": after["hits"] - before["hits"],
               "cache_misses": after["misses"] - before["misses"]}
        if out["accuracies"]:
            res["final_accuracy"] = round(float(out["accuracies"][-1]), 4)
            res["max_accuracy"] = round(
                float(max(out["accuracies"])), 4)
        if cfg.backdoor and hasattr(exp.attacker, "test_asr"):
            res["final_asr"] = round(
                float(exp.attacker.test_asr(exp.state.weights)), 4)
        return res


def _load_supervisor():
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "tools", "supervisor.py")
    spec = importlib.util.spec_from_file_location("fl_supervisor", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class SupervisorExecutor:
    """Each cell is a child CLI run under tools/supervisor.py: bounded
    retries, degradation ladder, pinned ``--run-id`` = the cell id,
    post-run journal audit.  ``--cost-report`` is forced onto cells so
    the compile/cache attribution lands in the private event log (the
    campaign's measured cache evidence)."""

    def __init__(self):
        self._sup = None

    def run(self, cell, camp) -> dict:
        if self._sup is None:
            self._sup = _load_supervisor()
        problem = verify_cli_round_trip(cell)
        if problem:
            return {"state": "failed", "reason": problem, "rc": 2}
        child = cfg_to_cli_args(cell.cfg, cell.attack)
        if camp.cost_report and "--cost-report" not in child:
            child.append("--cost-report")
        opts = self._sup.build_opts(
            run_id=cell.cell_id, verify_journal=True,
            max_retries=camp.max_retries,
            events=os.path.join(camp.dir,
                                f"supervisor_{cell.cell_id}.jsonl"),
            child_env=camp.child_env())
        # The child's private event log appends across campaigns (same
        # cell id => same file); only events written by THIS execution
        # count as its cache evidence.
        log_path = os.path.join(cell.cfg.log_dir,
                                cell.cell_id + ".jsonl")
        try:
            log_offset = os.path.getsize(log_path)
        except OSError:
            log_offset = 0
        t0 = time.time()
        rc = self._sup.Supervisor(opts, child).supervise()
        res = {"state": "done" if rc == 0 else "failed", "rc": rc,
               "wall_s": round(time.time() - t0, 2)}
        man_path = os.path.join(cell.cfg.run_dir, cell.cell_id,
                                "manifest.json")
        try:
            with open(man_path) as f:
                man = json.load(f)
        except (OSError, json.JSONDecodeError):
            man = {}
        for k in ("final_accuracy", "max_accuracy", "final_asr",
                  "rounds_per_s", "events"):
            if k in man:
                res[k] = man[k]
        if "rounds_committed" in man:
            res["rounds"] = man["rounds_committed"]
        if isinstance(res.get("events"), str):
            res.update(compile_event_cache_counts(res["events"],
                                                  offset=log_offset))
        if rc != 0:
            res.setdefault("reason",
                           f"supervision failed (rc={rc}); see "
                           f"supervisor_{cell.cell_id}.jsonl")
        return res


_EXECUTORS = {"inline": InlineExecutor, "supervisor": SupervisorExecutor}


# ---------------------------------------------------------------------------
# the campaign

class _EphemeralJournal(CampaignJournal):
    """In-memory journal for journal-less sweeps (grid.py's historical
    contract: no runs/ artifacts unless asked).  Same interface, no
    disk, no resume."""

    def __init__(self, campaign_id: str):
        self.campaign_id = campaign_id
        self.dir = None
        self.journal_path = self.manifest_path = self.events_path = None
        self._fh = None
        self.cells = {}
        self.attempt = 0
        self.torn_lines = 0

    def _append(self, rec):
        pass

    def write_manifest(self, status, **extra):
        pass

    def read_manifest(self):
        return None


class Campaign:
    """One scheduled pass over a spec's cells.  ``run()`` returns 0
    (all terminal cells done/skipped), 1 (some cell failed), or
    :data:`EXIT_DEADLINE` (stopped cleanly at the wall-clock deadline;
    re-invoke to continue)."""

    def __init__(self, spec: CampaignSpec, run_dir: Optional[str] = None,
                 executor: str = "inline", order: Optional[str] = None,
                 cache_dir: Optional[str] = None,
                 cache_budget_mb: float = 0.0, max_retries: int = 2,
                 deadline_s: Optional[float] = None,
                 journal_runs: bool = True, cost_report: bool = True,
                 persist: bool = True, checks=None, on_cell=None,
                 clock=time.monotonic,
                 kill_after_cells: Optional[int] = None,
                 kill_before_commit: Optional[int] = None):
        self.spec = spec
        self.run_dir = run_dir or spec.base.get("run_dir", "runs")
        if isinstance(executor, str):
            if executor not in _EXECUTORS:
                raise ValueError(
                    f"executor must be one of {sorted(_EXECUTORS)}, "
                    f"got {executor!r}")
            self.executor_name = executor
            self.executor = _EXECUTORS[executor]()
        else:
            # An executor INSTANCE (anything with .run(cell, campaign))
            # — the test seam, and the door to future backends.
            self.executor_name = type(executor).__name__
            self.executor = executor
        self.order = order or spec.order
        self.cache_dir = cache_dir
        self.cache_budget_mb = float(cache_budget_mb)
        self.max_retries = int(max_retries)
        self.deadline_s = (float(deadline_s) if deadline_s is not None
                           else float(spec.deadline_s))
        self.journal_runs = journal_runs
        self.cost_report = cost_report
        self.checks = checks
        self.on_cell = on_cell
        self.clock = clock
        env = os.environ.get
        self.kill_after_cells = (
            kill_after_cells if kill_after_cells is not None
            else int(env("FL_CAMPAIGN_KILL_AFTER_CELLS") or 0) or None)
        self.kill_before_commit = (
            kill_before_commit if kill_before_commit is not None
            else int(env("FL_CAMPAIGN_KILL_BEFORE_COMMIT") or 0) or None)
        self.journal = (CampaignJournal(self.run_dir, spec.campaign_id)
                        if persist
                        else _EphemeralJournal(spec.campaign_id))
        self.dir = self.journal.dir or self.run_dir

    # --- campaign event stream (schema v8 'campaign' kind) ---------------
    def emit(self, phase: str, **fields):
        rec = {"kind": "campaign", "v": SCHEMA_VERSION,
               "campaign": self.spec.campaign_id, "phase": phase,
               "t": round(time.time(), 3), **fields}
        validate_event(rec)
        if self.journal.events_path is not None:
            with open(self.journal.events_path, "a") as f:
                f.write(json.dumps(rec, default=str) + "\n")

    # --- planning ---------------------------------------------------------
    def plan(self) -> list:
        return order_cells(self.spec.expand(), self.order,
                           self.spec.campaign_id)

    # --- cache environment ------------------------------------------------
    def child_env(self) -> dict:
        """Env overrides for supervisor-executor children: pin the
        campaign cache dir and drop the persistent-cache write floor
        so short cell compiles still produce measurable hit/miss
        attribution."""
        if not self.cache_dir:
            return {}
        return {"JAX_COMPILATION_CACHE_DIR": os.path.abspath(
                    self.cache_dir),
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0"}

    @contextlib.contextmanager
    def _inline_cache(self):
        """Repoint the in-process persistent cache at the campaign dir
        for the duration (inline executor only); restores the ambient
        setting afterwards."""
        if self.cache_dir is None or self.executor_name != "inline":
            yield
            return
        import jax

        from attacking_federate_learning_tpu.utils.costs import (
            install_cache_counters
        )

        old_dir = jax.config.jax_compilation_cache_dir
        old_min = jax.config.jax_persistent_cache_min_compile_time_secs
        os.makedirs(self.cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(self.cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        install_cache_counters()
        try:
            yield
        finally:
            jax.config.update("jax_compilation_cache_dir", old_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              old_min)

    # --- adoption (the zero-duplicate-stamps path) ------------------------
    def _adopt(self, cell) -> Optional[dict]:
        """A cell whose OWN run journal already says 'done' (the kill
        landed between the run finish and the campaign commit) is
        adopted: its metrics are read from the run manifest and the
        cell commits without re-executing — so the engine's registry
        stamp is never duplicated."""
        if not self.journal_runs or cell.cfg is None:
            return None
        man_path = os.path.join(cell.cfg.run_dir, cell.cell_id,
                                "manifest.json")
        try:
            with open(man_path) as f:
                man = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if man.get("status") != "done":
            return None
        res = {"rc": 0, "adopted": True,
               "rounds": man.get("rounds_committed")}
        for k in ("final_accuracy", "max_accuracy", "final_asr",
                  "rounds_per_s", "events"):
            if k in man:
                res[k] = man[k]
        if isinstance(res.get("events"), str):
            res.update(compile_event_cache_counts(res["events"]))
        return res

    # --- manifest ---------------------------------------------------------
    def _cell_rows(self, cells) -> dict:
        rows = {}
        for c in cells:
            row = c.row()
            row["state"] = self.journal.state_of(c.cell_id)
            if c.skip:
                row["reason"] = c.skip
            rec = self.journal.cells.get(c.cell_id)
            if rec:
                for k in ("reason", "final_accuracy", "max_accuracy",
                          "final_asr", "rounds", "wall_s",
                          "rounds_per_s", "rc",
                          "cache_hits", "cache_misses", "cache_bytes",
                          "adopted", "events"):
                    if k in rec:
                        row[k] = rec[k]
            rows[c.cell_id] = row
        return rows

    def _cache_totals(self) -> dict:
        hits = misses = 0
        for rec in self.journal.cells.values():
            hits += int(rec.get("cache_hits") or 0)
            misses += int(rec.get("cache_misses") or 0)
        out = {"hits": hits, "misses": misses,
               "budget_mb": self.cache_budget_mb}
        if self.cache_dir:
            out["dir"] = os.path.abspath(self.cache_dir)
            out["bytes"] = cache_dir_bytes(self.cache_dir)
        return out

    def _write_manifest(self, status: str, cells, **extra):
        self.journal.write_manifest(
            status, name=self.spec.name,
            spec_hash=self.spec.spec_hash(), order=self.order,
            executor=self.executor_name, axes=list(self.spec.axes),
            deadline_s=self.deadline_s, cache=self._cache_totals(),
            cells=self._cell_rows(cells), **extra)

    # --- the run loop -----------------------------------------------------
    def _commit(self, cell, state: str, cells, **fields):
        self.journal.commit_cell(cell.cell_id, state, **fields)
        self.emit(f"cell_{state}", cell=cell.cell_id, **{
            k: v for k, v in fields.items()
            if k in ("reason", "rc", "adopted", "cache_hits",
                     "cache_misses", "final_accuracy", "final_asr")})
        self._write_manifest("running", cells)
        if self.on_cell is not None:
            row = self._cell_rows([cell])[cell.cell_id]
            self.on_cell(cell, row)

    def run(self) -> int:
        t0 = self.clock()
        cells = self.plan()
        attempt = self.journal.start_attempt()
        already = sum(not self.journal.fresh(c.cell_id) for c in cells)
        self.emit("campaign_start", attempt=attempt, cells=len(cells),
                  resumed=already, order=self.order,
                  executor=self.executor_name)
        self._write_manifest("running", cells)
        executed = 0
        with self._inline_cache():
            for cell in cells:
                if not self.journal.fresh(cell.cell_id):
                    continue                       # exactly-once gate
                if cell.skip is not None:
                    # Composition-rejected at expansion: never executed.
                    self._commit(cell, "skipped", cells,
                                 reason=cell.skip)
                    continue
                if (self.deadline_s
                        and self.clock() - t0 > self.deadline_s):
                    # The relay-window seam: checkpoint cleanly, leave
                    # the remaining cells pending, exit resumable.
                    self.emit("deadline",
                              elapsed_s=round(self.clock() - t0, 2),
                              remaining=sum(
                                  self.journal.fresh(c.cell_id)
                                  for c in cells))
                    self.journal.finish("deadline")
                    self._write_manifest("deadline", cells)
                    self.journal.close()
                    return EXIT_DEADLINE
                adopted = self._adopt(cell)
                if adopted is not None:
                    self._commit(cell, "done", cells, **adopted)
                    continue
                self.emit("cell_start", cell=cell.cell_id,
                          group=cell.group, priority=cell.priority)
                result = self.executor.run(cell, self)
                executed += 1
                if self.cache_dir and self.cache_budget_mb > 0:
                    trim_cache(self.cache_dir,
                               int(self.cache_budget_mb * 1e6))
                if self.cache_dir:
                    result["cache_bytes"] = cache_dir_bytes(
                        self.cache_dir)
                if (result.get("state") == "done"
                        and self.checks is not None):
                    errors = self.checks(cell, result)
                    if errors:
                        result["state"] = "failed"
                        result["reason"] = "; ".join(errors)
                if self.kill_before_commit == executed:
                    os._exit(_KILL_RC)   # injection: die with the cell
                    #                      finished but uncommitted
                state = result.pop("state")
                self._commit(cell, state, cells, **result)
                if self.kill_after_cells == executed:
                    os._exit(_KILL_RC)   # injection: die between cells
        # Status over the WHOLE journal, not this invocation: a resume
        # that completes the remaining cells still reports a campaign
        # with a previously-failed cell as failed.
        failed = sum(rec.get("state") == "failed"
                     for rec in self.journal.cells.values())
        status = "failed" if failed else "done"
        self.emit("campaign_done", status=status, executed=executed,
                  failed=failed, cache=json.dumps(self._cache_totals()))
        self.journal.finish(status)
        self._write_manifest(status, cells)
        self.journal.close()
        return 1 if failed else 0
