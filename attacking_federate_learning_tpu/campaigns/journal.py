"""Campaign-level exactly-once accounting: journal + atomic manifest.

Same durability contract as the per-run :class:`RunJournal`
(utils/lifecycle.py), one level up: an append-only
``runs/campaigns/<campaign_id>/journal.jsonl`` whose records are
committed *after* the work they describe, plus a ``manifest.json``
rewritten same-dir-tmp + ``os.replace`` at every transition.  A
SIGKILL at any point leaves at most one torn line, which the next
attempt seals and the reader skips; a cell enters the journal at most
once because the scheduler consults :meth:`fresh` before executing and
commits exactly one terminal record per cell.

Cell states: ``done`` (executed to completion, or *adopted* — the
cell's own run journal says 'done', so a kill between the run finish
and the campaign commit re-commits without re-running, which is what
keeps ``runs/index.jsonl`` free of duplicate stamps), ``failed``
(supervision exhausted / the run diverged; terminal — a re-invoke does
not retry it unless asked), ``skipped`` (composition-rejected before
any execution, message attached).  Anything not in the journal is
``pending``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

TERMINAL_STATES = ("done", "failed", "skipped")


class CampaignJournal:
    """Append-only journal + atomic manifest under
    ``<run_dir>/campaigns/<campaign_id>/``."""

    def __init__(self, run_dir: str, campaign_id: str):
        self.campaign_id = campaign_id
        self.dir = os.path.join(run_dir, "campaigns", campaign_id)
        os.makedirs(self.dir, exist_ok=True)
        self.journal_path = os.path.join(self.dir, "journal.jsonl")
        self.manifest_path = os.path.join(self.dir, "manifest.json")
        self.events_path = os.path.join(self.dir, "events.jsonl")
        self._fh = None
        self.cells: dict = {}     # cell_id -> last terminal record
        self.attempt = 0
        self.torn_lines = 0
        self._replay()

    # --- replay ----------------------------------------------------------
    def records(self) -> list:
        if not os.path.exists(self.journal_path):
            return []
        out, torn = [], 0
        with open(self.journal_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    torn += 1        # a SIGKILL mid-append leaves one
        self.torn_lines = torn
        return out

    def _replay(self):
        for rec in self.records():
            k = rec.get("kind")
            if k == "cell" and rec.get("state") in TERMINAL_STATES:
                self.cells[rec["cell"]] = rec
            elif k == "attempt":
                self.attempt = max(self.attempt, int(rec["attempt"]))

    # --- append path (torn-tail sealing, flush + fsync) ------------------
    def _append(self, rec: dict):
        if self._fh is None:
            if (os.path.exists(self.journal_path)
                    and os.path.getsize(self.journal_path) > 0):
                with open(self.journal_path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    needs_seal = f.read(1) != b"\n"
                if needs_seal:
                    with open(self.journal_path, "a") as f:
                        f.write("\n")
            self._fh = open(self.journal_path, "a")
        rec.setdefault("t", round(time.time(), 3))
        self._fh.write(json.dumps(rec, default=str) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    # --- transitions ------------------------------------------------------
    def start_attempt(self) -> int:
        self.attempt += 1
        self._append({"kind": "attempt", "attempt": self.attempt})
        return self.attempt

    def fresh(self, cell_id: str) -> bool:
        """True when the cell has no terminal record yet — the gate the
        scheduler consults before executing (exactly-once)."""
        return cell_id not in self.cells

    def state_of(self, cell_id: str) -> str:
        rec = self.cells.get(cell_id)
        return rec["state"] if rec else "pending"

    def commit_cell(self, cell_id: str, state: str, **fields):
        """Commit one terminal record for a cell; recommitting a cell
        is an error (the scheduler must gate on fresh())."""
        if state not in TERMINAL_STATES:
            raise ValueError(
                f"cell state must be one of {TERMINAL_STATES}, "
                f"got {state!r}")
        if not self.fresh(cell_id):
            raise ValueError(
                f"cell {cell_id} already committed as "
                f"{self.state_of(cell_id)!r} (exactly-once violation)")
        rec = {"kind": "cell", "cell": cell_id, "state": state,
               "attempt": self.attempt, **fields}
        self._append(rec)
        self.cells[cell_id] = rec

    def finish(self, status: str, **extra):
        self._append({"kind": "finish", "status": status})
        self.write_manifest(status, **extra)

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # --- manifest ---------------------------------------------------------
    def write_manifest(self, status: str, **extra):
        counts = {}
        for rec in self.cells.values():
            counts[rec["state"]] = counts.get(rec["state"], 0) + 1
        man = {"campaign_id": self.campaign_id, "status": status,
               "attempt": self.attempt,
               "cells_committed": len(self.cells), "counts": counts,
               "torn_lines": self.torn_lines,
               "updated": round(time.time(), 3)}
        man.update(extra)
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(man, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.manifest_path)

    def read_manifest(self) -> Optional[dict]:
        if not os.path.exists(self.manifest_path):
            return None
        with open(self.manifest_path) as f:
            return json.load(f)

    # --- the exactly-once invariant, checked mechanically -----------------
    def verify(self, expected_cells=None) -> list:
        """Audit the raw journal; returns problem strings (empty =
        clean).  Every cell must carry at most one terminal record;
        with ``expected_cells`` (ids), unknown cells are flagged and —
        when the campaign finished — missing ones too."""
        problems = []
        seen: dict = {}
        finished = None
        for rec in self.records():
            k = rec.get("kind")
            if k == "cell":
                cid = rec.get("cell")
                seen[cid] = seen.get(cid, 0) + 1
                if rec.get("state") not in TERMINAL_STATES:
                    problems.append(
                        f"cell {cid}: non-terminal state "
                        f"{rec.get('state')!r} in the journal")
            elif k == "finish":
                finished = rec.get("status")
        dups = sorted(c for c, n in seen.items() if n > 1)
        if dups:
            problems.append(f"cells committed more than once: {dups}")
        if expected_cells is not None:
            expected = set(expected_cells)
            stray = sorted(set(seen) - expected)
            if stray:
                problems.append(f"journal carries unknown cells: {stray}")
            if finished == "done":
                missing = sorted(expected - set(seen))
                if missing:
                    problems.append(
                        f"campaign finished 'done' but cells were "
                        f"never committed: {missing}")
        return problems
