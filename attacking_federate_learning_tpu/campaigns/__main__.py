from attacking_federate_learning_tpu.campaigns.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
