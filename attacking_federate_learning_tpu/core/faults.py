"""In-jit fault injection and the pre-aggregation quarantine.

The reference simulator (and the faithful rebuild) assumes every client
returns a finite, fresh gradient every round; the only failure either
survives is the backdoor shadow-train nan guard.  Real cohorts are
dominated by dropped clients, stragglers and damaged updates, so this
module gives the engine a DETERMINISTIC fault model that runs *inside*
the fused round program (core/engine.py):

- Every draw flows from a PRNG key folded with the round index, so the
  schedule is a pure function of ``(FaultConfig, seed, round)``:
  identical across runs, across resume boundaries, and across the
  host-side replay (:func:`fault_masks` runs unmodified under trace and
  eagerly) that tools/fault_matrix.py and the tests use to verify the
  emitted 'fault' events against the injected schedule.
- All shapes are fixed.  Dropout zeroes a row and flips its quarantine
  bit; stragglers read a ``(delay, m, d)`` ring buffer carried through
  the scanned span; corruption overwrites honest rows in place.  The
  no-fault path is untouched — the engine only threads fault state when
  ``cfg.faults`` is enabled, so the zero-fault HLO stays bit-identical.

Seams:

- :func:`apply_faults` sits on the SUBMITTED update matrix, after the
  attack seam.  The attack owns rows [0, f); corruption draws from
  honest rows only, so the Byzantine threat model and the benign-fault
  model never alias.
- :func:`quarantine` is the server-side half: it masks non-finite and
  dropped rows, zeroes them (so the distance engines never see
  NaN/Inf), and hands the effective-cohort mask to the mask-aware
  defense kernels (defenses/kernels.py ``mask=`` seam).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


# Defenses that accept the quarantine mask (the ``mask=`` kwarg).  The
# engine refuses fault injection with any other defense up front — a
# defense that silently averaged zeroed dropout rows would corrupt the
# aggregate, which is exactly the failure mode this subsystem exists to
# prevent.
MASK_AWARE_DEFENSES = ("NoDefense", "Krum", "TrimmedMean", "Bulyan",
                       "Median")


def check_fault_support(cfg):
    """Fail fast on configs the fault model cannot honor (engine init)."""
    if cfg.defense not in MASK_AWARE_DEFENSES:
        raise ValueError(
            f"faults need a mask-aware defense {MASK_AWARE_DEFENSES}, "
            f"got {cfg.defense!r} (the quarantine mask must reach the "
            f"kernel; defenses/kernels.py)")
    if cfg.faults.straggler > 0 and cfg.participation < 1.0:
        raise ValueError(
            "straggler faults need participation=1.0: the stale ring "
            "buffer is indexed by cohort row, and under partial "
            "participation rows are different clients each round; for "
            "a straggler regime the server is designed around, use "
            "--aggregation async instead — there straggler faults "
            "become extra arrival delay in the buffered round "
            "(core/async_rounds.py)")
    host_impls = [
        ("distance_impl", cfg.distance_impl),
        ("trimmed_mean_impl", cfg.trimmed_mean_impl),
        ("median_impl", cfg.median_impl),
        ("bulyan_selection_impl", cfg.bulyan_selection_impl),
        ("bulyan_trim_impl", cfg.bulyan_trim_impl),
    ]
    for name, val in host_impls:
        if val == "host":
            raise ValueError(
                f"faults are incompatible with {name}='host': the host "
                f"engines return only aggregates/indices and have no "
                f"mask seam (defenses/host.py)")


def fault_key(cfg):
    """The fault subsystem's own key stream, derived from (but distinct
    from) the experiment seed unless FaultConfig.seed overrides it."""
    seed = cfg.faults.seed if cfg.faults.seed is not None else cfg.seed
    return jax.random.key(seed ^ 0x0FA7175)


def init_fault_state(faults, m, d):
    """Fixed-shape device state threaded through the round program.

    ``{'stale': (delay, m, d) f32}`` ring buffer when stragglers are
    configured (slot ``t % delay`` holds the cohort's submissions from
    round ``t - delay``), else an empty pytree — the engine passes it
    through jit either way only when faults are enabled.
    """
    if faults.straggler > 0:
        return {"stale": jnp.zeros((faults.straggler_delay, m, d),
                                   jnp.float32)}
    return {}


def fault_masks(key, t, m, m_mal, faults):
    """The round-t injection schedule: three (m,) bool masks.

    Pure in ``(key, t)`` — runs identically traced (inside the fused
    round) and eagerly (the host replay that validates emitted events).
    Dropout wins over the other two; corruption draws from honest rows
    only; stragglers are suppressed while the ring buffer is cold
    (t < delay), so the counts always describe faults actually applied.
    """
    kt = jax.random.fold_in(key, t)
    k_drop, k_stale, k_corr = jax.random.split(kt, 3)
    drop = jax.random.uniform(k_drop, (m,)) < faults.dropout
    stale = (jax.random.uniform(k_stale, (m,)) < faults.straggler) & ~drop
    stale = stale & (t >= faults.straggler_delay)
    honest = jnp.arange(m) >= m_mal
    corrupt = ((jax.random.uniform(k_corr, (m,)) < faults.corrupt)
               & ~drop & ~stale & honest)
    return drop, stale, corrupt


def apply_faults(grads, t, key, state, faults, m_mal):
    """Inject the round-t faults into the submitted (m, d) matrix.

    Returns ``(faulted, dropped, new_state, stats)``.  ``dropped`` is
    the (m,) bool dropout mask (rows already zeroed — :func:`quarantine`
    folds it into the effective-cohort mask); ``stats`` are fixed-shape
    scalar counts keyed ``fault_*`` so they ride the engine's telemetry
    plumbing into per-round 'fault' events.
    """
    m = grads.shape[0]
    drop, stale, corrupt = fault_masks(key, t, m, m_mal, faults)

    if faults.straggler > 0:
        # Read the round t-delay submissions BEFORE overwriting the slot
        # with this round's fresh (pre-fault) matrix: a straggler
        # submits what it computed delay rounds ago; what it computed
        # THIS round enters the buffer for round t+delay.
        slot = jnp.mod(t, faults.straggler_delay)
        old = lax.dynamic_index_in_dim(state["stale"], slot, 0,
                                       keepdims=False)
        new_state = {"stale": lax.dynamic_update_index_in_dim(
            state["stale"], grads.astype(jnp.float32), slot, 0)}
        grads = jnp.where(stale[:, None], old.astype(grads.dtype), grads)
    else:
        new_state = state

    if faults.corrupt > 0:
        if faults.corrupt_mode == "scale":
            grads = grads * jnp.where(corrupt, faults.corrupt_scale,
                                      1.0).astype(grads.dtype)[:, None]
        else:
            bad = {"nan": jnp.nan, "inf": jnp.inf}[faults.corrupt_mode]
            grads = jnp.where(corrupt[:, None],
                              jnp.asarray(bad, grads.dtype), grads)

    grads = jnp.where(drop[:, None], jnp.zeros((), grads.dtype), grads)
    stats = {
        "fault_injected_dropout": jnp.sum(drop).astype(jnp.int32),
        "fault_injected_straggler": jnp.sum(stale).astype(jnp.int32),
        "fault_injected_corrupt": jnp.sum(corrupt).astype(jnp.int32),
    }
    return grads, drop, new_state, stats


def quarantine(grads, dropped):
    """Pre-aggregation quarantine: the server masks what it can SEE.

    Non-finite rows (corrupt in flight) and dropped rows (no update)
    are excluded from the effective cohort and zeroed so the distance
    engines stay NaN-free; everything else — including stale and
    bit-scaled-but-finite rows — is the robust aggregation's problem,
    exactly as in a real deployment.  Returns ``(clean, mask, stats)``
    with ``mask`` (m,) bool True for aggregable rows.
    """
    finite = jnp.isfinite(grads.astype(jnp.float32)).all(axis=1)
    mask = finite & ~dropped
    clean = jnp.where(mask[:, None], grads, jnp.zeros((), grads.dtype))
    stats = {"fault_quarantined":
             (grads.shape[0] - jnp.sum(mask)).astype(jnp.int32)}
    return clean, mask, stats
