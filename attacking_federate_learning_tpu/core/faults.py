"""In-jit fault injection and the pre-aggregation quarantine.

The reference simulator (and the faithful rebuild) assumes every client
returns a finite, fresh gradient every round; the only failure either
survives is the backdoor shadow-train nan guard.  Real cohorts are
dominated by dropped clients, stragglers and damaged updates, so this
module gives the engine a DETERMINISTIC fault model that runs *inside*
the fused round program (core/engine.py):

- Every draw flows from a PRNG key folded with the round index, so the
  schedule is a pure function of ``(FaultConfig, seed, round)``:
  identical across runs, across resume boundaries, and across the
  host-side replay (:func:`fault_masks` runs unmodified under trace and
  eagerly) that tools/fault_matrix.py and the tests use to verify the
  emitted 'fault' events against the injected schedule.
- All shapes are fixed.  Dropout zeroes a row and flips its quarantine
  bit; stragglers read a ``(delay, m, d)`` ring buffer carried through
  the scanned span; corruption overwrites honest rows in place.  The
  no-fault path is untouched — the engine only threads fault state when
  ``cfg.faults`` is enabled, so the zero-fault HLO stays bit-identical.

Seams:

- :func:`apply_faults` sits on the SUBMITTED update matrix, after the
  attack seam.  The attack owns rows [0, f); corruption draws from
  honest rows only, so the Byzantine threat model and the benign-fault
  model never alias.
- :func:`quarantine` is the server-side half: it masks non-finite and
  dropped rows, zeroes them (so the distance engines never see
  NaN/Inf), and hands the effective-cohort mask to the mask-aware
  defense kernels (defenses/kernels.py ``mask=`` seam).

Hierarchical fault domains (ISSUE 19): under ``aggregation=
'hierarchical'`` the same PRNG discipline extends to two granularities.
(a) Per-client faults draw per MEGABATCH — :func:`shard_fault_masks`
folds the shard id into the round key, so every shard owns a distinct
replayable stream and the (m,) quarantine mask feeds the UNCHANGED
mask-aware tier-1 kernel inside the scan step; the straggler ring
grows a shard axis (``(delay, S, m, d)``, :func:`init_hier_fault_state`)
and each scan step reads/writes only its shard's slab.  (b) The
correlated shard-DOMAIN axis (``FaultConfig.shard_dropout``) kills
whole megabatches at once: :func:`domain_alive_row` draws a per-domain
death onset per round and holds it for ``shard_dropout_dwell`` rounds
(a dwell-windowed schedule — pure in ``(key, t)``, so it runs
identically inside the scanned program, across resume boundaries, and
in the host replay).  A dead domain's tier-1 estimate flows into
tier-2 with ``alive_counts == 0`` and is excluded by the shard_*
kernels' mask seam; the tier-2 defense-validity watchdog
(:func:`plan_tier2_actions`, extending the PR 17 traffic ladder to
``f2`` vs surviving shards) plans remask → bounds-valid-fallback →
hold on the host, and the device program selects on the planned int —
no data-dependent shapes anywhere.  :func:`hier_fault_schedule` is the
host ground truth tools/fault_matrix.py diffs emitted 'fault' events
against, per-shard counts included.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


# Defenses that accept the quarantine mask (the ``mask=`` kwarg).  The
# engine refuses fault injection with any other defense up front — a
# defense that silently averaged zeroed dropout rows would corrupt the
# aggregate, which is exactly the failure mode this subsystem exists to
# prevent.
MASK_AWARE_DEFENSES = ("NoDefense", "Krum", "TrimmedMean", "Bulyan",
                       "Median")


def check_fault_support(cfg):
    """Fail fast on configs the fault model cannot honor (engine init
    AND campaigns/spec.py pre-validation — both call this exact
    function, so the pre-check message and the construction message
    cannot drift)."""
    if cfg.defense not in MASK_AWARE_DEFENSES:
        raise ValueError(
            f"faults need a mask-aware defense {MASK_AWARE_DEFENSES}, "
            f"got {cfg.defense!r} (the quarantine mask must reach the "
            f"kernel; defenses/kernels.py)")
    if (cfg.faults.shard_dropout > 0
            and cfg.aggregation != "hierarchical"):
        raise ValueError(
            "--fault-shard-dropout models correlated shard-DOMAIN "
            "death and needs --aggregation hierarchical (+ "
            "--megabatch): flat and async rounds have no megabatch/"
            "device domains to kill — use --fault-dropout for "
            "per-client loss there")
    if (cfg.faults.straggler > 0 and cfg.aggregation == "hierarchical"
            and cfg.mesh_shape is not None
            and tuple(cfg.mesh_shape)[0] > 1):
        raise ValueError(
            "straggler faults do not compose with the hierarchical "
            "SPMD client_map (--mesh-shape clients axis > 1): the "
            "(delay, S, m, d) stale ring buffer is a cross-round carry "
            "the shard_map program cannot thread — run the sequential "
            "scan (clients axis 1) or drop --fault-straggler "
            "(dropout/corrupt/shard-dropout are stateless and compose)")
    if cfg.faults.straggler > 0 and cfg.participation < 1.0:
        raise ValueError(
            "straggler faults need participation=1.0: the stale ring "
            "buffer is indexed by cohort row, and under partial "
            "participation rows are different clients each round; for "
            "a straggler regime the server is designed around, use "
            "--aggregation async instead — there straggler faults "
            "become extra arrival delay in the buffered round "
            "(core/async_rounds.py)")
    host_impls = [
        ("distance_impl", cfg.distance_impl),
        ("trimmed_mean_impl", cfg.trimmed_mean_impl),
        ("median_impl", cfg.median_impl),
        ("bulyan_selection_impl", cfg.bulyan_selection_impl),
        ("bulyan_trim_impl", cfg.bulyan_trim_impl),
    ]
    for name, val in host_impls:
        if val == "host":
            raise ValueError(
                f"faults are incompatible with {name}='host': the host "
                f"engines return only aggregates/indices and have no "
                f"mask seam (defenses/host.py)")


def fault_key(cfg):
    """The fault subsystem's own key stream, derived from (but distinct
    from) the experiment seed unless FaultConfig.seed overrides it."""
    seed = cfg.faults.seed if cfg.faults.seed is not None else cfg.seed
    return jax.random.key(seed ^ 0x0FA7175)


def init_fault_state(faults, m, d):
    """Fixed-shape device state threaded through the round program.

    ``{'stale': (delay, m, d) f32}`` ring buffer when stragglers are
    configured (slot ``t % delay`` holds the cohort's submissions from
    round ``t - delay``), else an empty pytree — the engine passes it
    through jit either way only when faults are enabled.
    """
    if faults.straggler > 0:
        return {"stale": jnp.zeros((faults.straggler_delay, m, d),
                                   jnp.float32)}
    return {}


def fault_masks(key, t, m, m_mal, faults):
    """The round-t injection schedule: three (m,) bool masks.

    Pure in ``(key, t)`` — runs identically traced (inside the fused
    round) and eagerly (the host replay that validates emitted events).
    Dropout wins over the other two; corruption draws from honest rows
    only; stragglers are suppressed while the ring buffer is cold
    (t < delay), so the counts always describe faults actually applied.
    """
    kt = jax.random.fold_in(key, t)
    k_drop, k_stale, k_corr = jax.random.split(kt, 3)
    drop = jax.random.uniform(k_drop, (m,)) < faults.dropout
    stale = (jax.random.uniform(k_stale, (m,)) < faults.straggler) & ~drop
    stale = stale & (t >= faults.straggler_delay)
    honest = jnp.arange(m) >= m_mal
    corrupt = ((jax.random.uniform(k_corr, (m,)) < faults.corrupt)
               & ~drop & ~stale & honest)
    return drop, stale, corrupt


def apply_faults(grads, t, key, state, faults, m_mal):
    """Inject the round-t faults into the submitted (m, d) matrix.

    Returns ``(faulted, dropped, new_state, stats)``.  ``dropped`` is
    the (m,) bool dropout mask (rows already zeroed — :func:`quarantine`
    folds it into the effective-cohort mask); ``stats`` are fixed-shape
    scalar counts keyed ``fault_*`` so they ride the engine's telemetry
    plumbing into per-round 'fault' events.
    """
    m = grads.shape[0]
    drop, stale, corrupt = fault_masks(key, t, m, m_mal, faults)

    if faults.straggler > 0:
        # Read the round t-delay submissions BEFORE overwriting the slot
        # with this round's fresh (pre-fault) matrix: a straggler
        # submits what it computed delay rounds ago; what it computed
        # THIS round enters the buffer for round t+delay.
        slot = jnp.mod(t, faults.straggler_delay)
        old = lax.dynamic_index_in_dim(state["stale"], slot, 0,
                                       keepdims=False)
        new_state = {"stale": lax.dynamic_update_index_in_dim(
            state["stale"], grads.astype(jnp.float32), slot, 0)}
        grads = jnp.where(stale[:, None], old.astype(grads.dtype), grads)
    else:
        new_state = state

    if faults.corrupt > 0:
        if faults.corrupt_mode == "scale":
            grads = grads * jnp.where(corrupt, faults.corrupt_scale,
                                      1.0).astype(grads.dtype)[:, None]
        else:
            bad = {"nan": jnp.nan, "inf": jnp.inf}[faults.corrupt_mode]
            grads = jnp.where(corrupt[:, None],
                              jnp.asarray(bad, grads.dtype), grads)

    grads = jnp.where(drop[:, None], jnp.zeros((), grads.dtype), grads)
    stats = {
        "fault_injected_dropout": jnp.sum(drop).astype(jnp.int32),
        "fault_injected_straggler": jnp.sum(stale).astype(jnp.int32),
        "fault_injected_corrupt": jnp.sum(corrupt).astype(jnp.int32),
    }
    return grads, drop, new_state, stats


def quarantine(grads, dropped):
    """Pre-aggregation quarantine: the server masks what it can SEE.

    Non-finite rows (corrupt in flight) and dropped rows (no update)
    are excluded from the effective cohort and zeroed so the distance
    engines stay NaN-free; everything else — including stale and
    bit-scaled-but-finite rows — is the robust aggregation's problem,
    exactly as in a real deployment.  Returns ``(clean, mask, stats)``
    with ``mask`` (m,) bool True for aggregable rows.
    """
    finite = jnp.isfinite(grads.astype(jnp.float32)).all(axis=1)
    mask = finite & ~dropped
    clean = jnp.where(mask[:, None], grads, jnp.zeros((), grads.dtype))
    stats = {"fault_quarantined":
             (grads.shape[0] - jnp.sum(mask)).astype(jnp.int32)}
    return clean, mask, stats


# ---------------------------------------------------------------------------
# hierarchical fault domains (ISSUE 19)

# The domain schedule's own sub-stream: folded once on top of the fault
# key so shard-domain onsets never collide with the per-client draws.
_DOMAIN_SALT = 0x5AD0

# Tier-2 ladder fallback (the coordinate-wise bounds-valid default,
# mirroring TrafficConfig.fallback_defense's default): when the
# configured tier-2 defense's validity bound fails against the
# surviving-shard count, the round degrades to the masked shard median.
TIER2_FALLBACK = "Median"


def init_hier_fault_state(faults, num_shards, megabatch, d):
    """Hier mirror of :func:`init_fault_state`: the straggler ring
    grows a shard axis — ``{'stale': (delay, S, m, d) f32}`` — so each
    megabatch scan step reads/writes only its own ``(m, d)`` slab
    (slot ``t % delay``, row ``sid``).  Total bytes equal the flat
    full-participation ring (delay · n · d).  Empty pytree when
    stragglers are off (dropout/corrupt/shard-dropout are stateless).
    """
    if faults.straggler > 0:
        return {"stale": jnp.zeros(
            (faults.straggler_delay, num_shards, megabatch, d),
            jnp.float32)}
    return {}


def shard_fault_masks(key, t, sid, m, c_mal, faults):
    """Per-megabatch mirror of :func:`fault_masks`: the (m,) injection
    draw for shard ``sid``, keyed ``fold_in(fold_in(key, t), sid)`` so
    every shard owns a distinct stream that replays identically on the
    host (``sid`` may be traced — it rides the client_map scan).
    Malicious rows are the megabatch's FIRST ``c_mal`` rows (the
    Placement invariant), so corruption draws from honest rows only,
    exactly like the flat draw."""
    kt = jax.random.fold_in(jax.random.fold_in(key, t), sid)
    k_drop, k_stale, k_corr = jax.random.split(kt, 3)
    drop = jax.random.uniform(k_drop, (m,)) < faults.dropout
    stale = (jax.random.uniform(k_stale, (m,)) < faults.straggler) & ~drop
    stale = stale & (t >= faults.straggler_delay)
    honest = jnp.arange(m) >= c_mal
    corrupt = ((jax.random.uniform(k_corr, (m,)) < faults.corrupt)
               & ~drop & ~stale & honest)
    return drop, stale, corrupt


def domain_alive_row(key, t, num_shards, faults):
    """(S,) bool domain-liveness at round t — the correlated
    shard-domain schedule.  Shard s is DEAD iff any death onset fired
    in the dwell window (t - dwell, t]: onsets draw per ``(round,
    shard)`` from the ``_DOMAIN_SALT`` sub-stream, and the window scan
    is a fixed-shape stack over the dwell offsets (negative rounds
    suppressed), so the schedule is pure in ``(key, t)`` and runs
    identically traced and eagerly."""
    if faults.shard_dropout <= 0:
        return jnp.ones((num_shards,), bool)
    kd = jax.random.fold_in(key, _DOMAIN_SALT)

    def onset(off):
        t0 = t - off
        u = jax.random.uniform(jax.random.fold_in(kd, t0),
                               (num_shards,))
        return (u < faults.shard_dropout) & (t0 >= 0)

    offs = jnp.arange(faults.shard_dropout_dwell)
    return ~jax.vmap(onset)(offs).any(axis=0)


def apply_shard_faults(grads, t, sid, key, old_slab, faults, c_mal):
    """Inject shard ``sid``'s round-t faults into its (m, d) megabatch
    matrix (the hier scan-step seam; flat mirror: :func:`apply_faults`).

    ``old_slab`` is the shard's stale-ring slice for round ``t - delay``
    (``None`` when stragglers are off).  Returns ``(faulted, dropped,
    stats, fresh)`` — ``fresh`` is the PRE-fault f32 matrix destined
    for the shard's ring slot (what this cohort computed THIS round,
    surfacing at ``t + delay``), and ``stats`` are per-shard int32
    scalar counts (client_map stacks them to (S,); the engine sums for
    the round totals and keeps the per-shard vectors for the event).
    """
    m = grads.shape[0]
    drop, stale, corrupt = shard_fault_masks(key, t, sid, m, c_mal,
                                             faults)
    fresh = grads.astype(jnp.float32)
    if faults.straggler > 0:
        grads = jnp.where(stale[:, None], old_slab.astype(grads.dtype),
                          grads)
    if faults.corrupt > 0:
        if faults.corrupt_mode == "scale":
            grads = grads * jnp.where(corrupt, faults.corrupt_scale,
                                      1.0).astype(grads.dtype)[:, None]
        else:
            bad = {"nan": jnp.nan, "inf": jnp.inf}[faults.corrupt_mode]
            grads = jnp.where(corrupt[:, None],
                              jnp.asarray(bad, grads.dtype), grads)
    grads = jnp.where(drop[:, None], jnp.zeros((), grads.dtype), grads)
    stats = {
        "injected_dropout": jnp.sum(drop).astype(jnp.int32),
        "injected_straggler": jnp.sum(stale).astype(jnp.int32),
        "injected_corrupt": jnp.sum(corrupt).astype(jnp.int32),
    }
    return grads, drop, stats, fresh


def plan_tier2_actions(shards_alive, tier2_name, f2,
                       fallback=TIER2_FALLBACK):
    """The tier-2 watchdog's host-side ladder plan: one action int per
    round, from that round's surviving-shard count (shards whose
    ``alive_counts`` entry is > 0).  Extends the PR 17 traffic ladder
    (core/population.py plan_action — REMASK/FALLBACK/HOLD ordering
    and the per-defense validity bounds) to tier 2: ``f2`` is the
    kernel's STATIC corrupted-shard count, checked against the
    SURVIVING shard count."""
    import numpy as np

    from attacking_federate_learning_tpu.core.population import (
        plan_action
    )

    return np.asarray(
        [plan_action(tier2_name, fallback, int(s), int(f2), 1)
         for s in shards_alive], np.int32)


def hier_fault_schedule(key, t0, count, placement, faults):
    """Host replay of the hier fault schedule for rounds [t0,
    t0+count): the ground truth a faulted hierarchical run's emitted
    'fault' events are diffed against (tools/fault_matrix.py) and the
    input to the tier-2 ladder plan.  Reuses the exact primitive draws
    the scanned program runs (:func:`shard_fault_masks`,
    :func:`domain_alive_row`) eagerly, so the counts match
    bit-for-bit.  Quarantine accounting mirrors the server's
    visibility: dropped rows plus non-finite corruption ('nan'/'inf');
    'scale' corruption stays finite and aggregable.

    Returns a list of per-round dicts with the event payload fields
    (``injected_*``, ``quarantined``, ``shards_dead``, ``shard_alive``
    per-shard counts) plus ``shards_alive`` — the surviving-shard
    count the ladder plans on."""
    import numpy as np

    S, m = placement.num_shards, placement.megabatch
    rows = []
    for i in range(int(count)):
        t = int(t0) + i
        dom = np.asarray(domain_alive_row(key, t, S, faults))
        n_drop = n_stale = n_corr = n_quar = 0
        alive = np.zeros(S, np.int64)
        for sid in range(S):
            drop, stale, corrupt = (
                np.asarray(x) for x in shard_fault_masks(
                    key, t, sid, m, placement.mal_counts[sid], faults))
            n_drop += int(drop.sum())
            n_stale += int(stale.sum())
            n_corr += int(corrupt.sum())
            q = drop | (corrupt if faults.corrupt_mode in ("nan", "inf")
                        else np.zeros_like(corrupt))
            n_quar += int(q.sum())
            alive[sid] = int((~q).sum()) * int(dom[sid])
        rows.append({
            "round": t,
            "injected_dropout": n_drop,
            "injected_straggler": n_stale,
            "injected_corrupt": n_corr,
            "quarantined": n_quar,
            "shards_dead": int(S - dom.sum()),
            "shard_alive": [int(a) for a in alive],
            "shards_alive": int((alive > 0).sum()),
        })
    return rows
