"""Batched client computation.

The reference runs N sequential ``User.step`` calls per round, each loading
the broadcast weights into a private net copy and doing one minibatch
forward/backward with no local optimizer step (reference server.py:54-56,
user.py:83-92).  Here the entire client population is one call:

    grads = vmap(grad(loss))(broadcast_weights, client_xs, client_ys)

over stacked per-client batches, returning the (n, d) flat gradient matrix
directly in wire format.  Under pjit the client axis shards across devices
(parallel/), which is the TPU-native form of the reference's simulated data
parallelism (SURVEY.md §2.2).
"""

from __future__ import annotations

import jax

from attacking_federate_learning_tpu.models.base import Model
from attacking_federate_learning_tpu.models.layers import nll_loss
from attacking_federate_learning_tpu.utils.flatten import FlatParams


def make_loss_fn(model: Model, flat: FlatParams, remat: bool = False):
    """Mean-NLL loss on flat wire-format weights (reference user.py:36,
    :77-79: log_softmax head + NLLLoss).

    ``remat=True`` wraps the loss in ``jax.checkpoint`` so the backward
    pass recomputes activations instead of storing them — the standard
    HBM/FLOPs trade for big models (WRN-40-4) or big client cohorts,
    where the vmapped (n, B, activations) footprint dominates memory.
    """

    def loss_fn(flat_w, x, y):
        params = flat.unravel(flat_w)
        return nll_loss(model.apply(params, x), y)

    return jax.checkpoint(loss_fn) if remat else loss_fn


def make_client_grad_fn(model: Model, flat: FlatParams, remat: bool = False):
    """(d,), (n, B, ...), (n, B) -> (n, d) per-client gradients."""
    grad_fn = jax.grad(make_loss_fn(model, flat, remat))

    def clients_grads(flat_w, xs, ys):
        return jax.vmap(grad_fn, in_axes=(None, 0, 0))(flat_w, xs, ys)

    return clients_grads


def make_client_update_fn(model: Model, flat: FlatParams,
                          local_steps: int = 1, remat: bool = False):
    """FedAvg-style local training (beyond-reference: the reference is
    strictly FedSGD — one minibatch gradient, never a local optimizer
    step, user.py:80).

    With ``local_steps == 1`` this IS :func:`make_client_grad_fn` (exact
    reference semantics, lr-independent).  With k > 1 each client runs k
    plain-SGD steps at the dispatched (faded) ``lr_train`` and reports the
    pseudo-gradient ``(w0 - w_k) / lr_report``, where ``lr_report`` is the
    lr the *server* will multiply back in — the FedAvg-as-FedSGD reduction
    is exact only when the divisor matches the server's multiplier (which,
    reference quirk, is the constant base lr while clients fade,
    reference server.py:89 vs :50-52).

    Signature: (d,), (n, k, B, ...), (n, k, B), lr_train, lr_report
    -> (n, d).
    """
    if local_steps == 1:
        base = make_client_grad_fn(model, flat, remat)

        def clients_update(flat_w, xs, ys, lr_train, lr_report):
            # Squeeze the k=1 step axis; lrs are unused (parity: the
            # reference's client optimizer never steps).
            return base(flat_w, xs[:, 0], ys[:, 0])

        return clients_update

    grad_fn = jax.grad(make_loss_fn(model, flat, remat))

    def one_client(flat_w, xs, ys, lr_train, lr_report):
        def step(w, batch):
            x, y = batch
            return w - lr_train * grad_fn(w, x, y), None

        wk, _ = jax.lax.scan(step, flat_w, (xs, ys))
        return (flat_w - wk) / lr_report

    def clients_update(flat_w, xs, ys, lr_train, lr_report):
        return jax.vmap(one_client, in_axes=(None, 0, 0, None, None))(
            flat_w, xs, ys, lr_train, lr_report)

    return clients_update
