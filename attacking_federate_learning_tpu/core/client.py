"""Batched client computation.

The reference runs N sequential ``User.step`` calls per round, each loading
the broadcast weights into a private net copy and doing one minibatch
forward/backward with no local optimizer step (reference server.py:54-56,
user.py:83-92).  Here the entire client population is one call:

    grads = vmap(grad(loss))(broadcast_weights, client_xs, client_ys)

over stacked per-client batches, returning the (n, d) flat gradient matrix
directly in wire format.  Under pjit the client axis shards across devices
(parallel/), which is the TPU-native form of the reference's simulated data
parallelism (SURVEY.md §2.2).
"""

from __future__ import annotations

import jax

from attacking_federate_learning_tpu.models.base import Model
from attacking_federate_learning_tpu.models.layers import nll_loss
from attacking_federate_learning_tpu.utils.flatten import FlatParams


def make_loss_fn(model: Model, flat: FlatParams):
    """Mean-NLL loss on flat wire-format weights (reference user.py:36,
    :77-79: log_softmax head + NLLLoss)."""

    def loss_fn(flat_w, x, y):
        params = flat.unravel(flat_w)
        return nll_loss(model.apply(params, x), y)

    return loss_fn


def make_client_grad_fn(model: Model, flat: FlatParams):
    """(d,), (n, B, ...), (n, B) -> (n, d) per-client gradients."""
    grad_fn = jax.grad(make_loss_fn(model, flat))

    def clients_grads(flat_w, xs, ys):
        return jax.vmap(grad_fn, in_axes=(None, 0, 0))(flat_w, xs, ys)

    return clients_grads
