from attacking_federate_learning_tpu.core.engine import (  # noqa: F401
    FederatedExperiment
)
from attacking_federate_learning_tpu.core.server import (  # noqa: F401
    ServerState, faded_learning_rate, init_server_state, momentum_update
)
