"""FedBuff-style asynchronous buffered rounds (``aggregation='async'``).

The synchronous cohort — every client's round-t update aggregated at
round t — is the simulator fiction (ROADMAP item 4); production
federated serving is asynchronous.  This module gives the engine a
DETERMINISTIC asynchrony model that runs *inside* the fused round
program (core/engine.py ``_build_async_round_fns``), FedBuff-flavored
(Nguyen et al., arXiv 2106.06639; TurboSVM-FL's lazy-client regime and
CLIP's straggler analysis are the PAPERS.md anchors):

- Every client computes a fresh update every round, but the update
  ARRIVES ``s`` rounds later, ``s`` drawn per (client, round) from a
  PRNG keyed on ``(seed, round)`` — the whole arrival schedule is a
  pure function of the config, identical across runs, across resume
  boundaries, and under the host-side replay (:func:`replay_schedule`)
  the tests and tools/fault_matrix.py diff emitted events against.
- In-flight updates ride a fixed-shape ``(D, m, d)`` ring (slot
  ``t % D`` holds round-t arrivals; ``D = async_max_staleness + 1``)
  with an occupancy mask and per-entry birth rounds.  A client's newer
  update landing on a slot that still holds an older in-flight one
  SUPERSEDES it (the client sends its latest — counted, not hidden).
- Arrivals merge into a one-slot-per-client PENDING pool (an arrival
  supersedes the client's older pending update).  The server applies
  an update only once ``k = async_buffer`` updates are pending —
  FedBuff's buffer trigger — consuming the FIRST k in FIFO order
  (oldest birth first, ties to the lowest client id); with fewer than
  k pending the round is a server no-op and the pool keeps filling.
  A delivered round therefore aggregates EXACTLY k rows, which is
  what lets the engine enforce the defense validity bounds at n=k
  (a Bulyan async round needs k >= 4f+3, exactly like a flat cohort
  of k).  A pending update whose staleness exceeds
  ``async_max_staleness`` is EVICTED (over-stale), and non-finite
  pending rows (fault corruption in flight) are quarantined — both
  masked, never aggregated.
- Delivered rows carry their STALENESS ``t - birth`` into (a) the
  attack seam (``AttackContext.staleness`` — the delivered-cohort view
  ALIE recalibrates its envelope against, and the channel the timed
  backdoor games) and (b) the staleness-weight function
  (``staleness_weight``: 'none' | 'poly' | 'const') whose ``(m,)``
  weight vector threads into the mask-aware defense kernels
  (defenses/kernels.py ``weights=`` seam).

Fault composition (core/faults.py): the same ``fault_masks`` schedule
drives async faults — *dropout* means the update is never submitted
(no ring write), *straggler* means EXTRA ARRIVAL DELAY
(``+ straggler_delay``, clipped to the ring depth) instead of the sync
path's separate stale ring, and *corrupt* damages the submitted row in
flight (non-finite variants are quarantined at delivery).  The threat
split survives: corruption stays honest-rows-only, the attack seam
owns rows [0, f).

Timing-aware attack surface: an attacker with ``timed = True``
(attacks/backdoor.py TimedBackdoorAttack) controls its own emission
and always submits with delay 0 — its delivered rows are always fresh
(full staleness weight, tightest clip envelope), at the price of FIFO
priority (freshest-born rows board the k-bus last).  The attacker
controls CONTENT and EMISSION TIME, never the server's arrival
timestamps: staleness weights cannot be forged.

All shapes are fixed; the whole step is pure jax, so spans scan it and
the async state (ring + pending, six arrays) checkpoints through the
Checkpointer ``extra=`` seam exactly like the fault ring buffer.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from attacking_federate_learning_tpu.core.faults import fault_masks


# Staleness-weight functions w(s) for delivered rows (s >= 0 rounds):
#   'none'   w = 1           (pure FedBuff first-k, no discount)
#   'poly'   w = 1/sqrt(1+s) (the FedBuff paper's polynomial discount)
#   'const'  w = 1 if fresh else 0.5 (a flat stale discount)
STALENESS_WEIGHTS = ("none", "poly", "const")

# FIFO tie-break sentinel: unoccupied pending slots sort after every
# real entry.  f32 keys (birth*m + id) stay exact below 2^24 — birth is
# a round index and m a cohort size, both far under that.
_EMPTY_KEY = jnp.inf


@dataclasses.dataclass(frozen=True)
class AsyncSpec:
    """Static facts of one engine's async round (engine _init_async)."""

    buffer: int          # k: pending updates consumed per round (FIFO)
    max_staleness: int   # eviction bound; ring depth = max_staleness+1
    weighting: str       # 'none' | 'poly' | 'const'
    timed: bool = False  # attacker forces its own delay to 0

    @property
    def depth(self) -> int:
        return self.max_staleness + 1


def async_key(cfg):
    """The async subsystem's own key stream, derived from (but distinct
    from) the experiment seed — mirroring core/faults.py:fault_key."""
    return jax.random.key(cfg.seed ^ 0x0A57C)


def init_async_state(spec: AsyncSpec, m: int, d: int):
    """Fixed-shape device state threaded through the async round
    program: the in-flight ring (``buf``/``occ``/``birth``, one slot
    per arrival round) and the server's pending pool
    (``pbuf``/``pocc``/``pbirth``, one slot per client).  Every array
    checkpoints through the Checkpointer ``extra=`` seam."""
    D = spec.depth
    return {
        "buf": jnp.zeros((D, m, d), jnp.float32),
        "occ": jnp.zeros((D, m), bool),
        "birth": jnp.zeros((D, m), jnp.int32),
        "pbuf": jnp.zeros((m, d), jnp.float32),
        "pocc": jnp.zeros((m,), bool),
        "pbirth": jnp.zeros((m,), jnp.int32),
    }


def draw_delays(key, t, m, m_mal, spec: AsyncSpec, faults=None,
                fkey=None, latency=None):
    """The round-t arrival schedule: ``(delay, drop, corrupt)``.

    ``delay`` (m,) int32 in [0, depth): uniform per (client, round),
    plus ``straggler_delay`` extra rounds for straggler-fault rows
    (clipped to the ring depth — a straggler cannot out-wait the
    buffer), and forced to 0 for the attacker's rows under a timed
    attack (the attacker controls its own emission).  Pure in
    ``(key, t)``: runs identically traced and eagerly, which is what
    :func:`replay_schedule` relies on.  ``drop``/``corrupt`` are the
    composed fault masks ((m,) bool, all-False without faults) —
    drawn from ``fkey`` (the fault subsystem's OWN key stream,
    core/faults.py:fault_key, defaulting to ``key``), so the injected
    schedule is identical to the sync path's and the host replay
    tools/fault_matrix.py validates against stays shared.

    ``latency`` (traffic engine, core/population.py): an optional
    ``(scales, tail)`` pair — per-cohort-slot heavy-tail Pareto scales
    and the shared tail exponent — that replaces the uniform draw with
    a discretized Pareto delay (still pure in ``(key, t)``; same
    clipping to the ring depth).  None is the legacy uniform draw,
    byte-identical.
    """
    kt = jax.random.fold_in(key, t)
    if latency is not None:
        from attacking_federate_learning_tpu.core.population import (
            traffic_delays
        )
        scales, tail = latency
        delay = traffic_delays(key, t, scales, tail, spec.depth)
    else:
        delay = jax.random.randint(kt, (m,), 0, spec.depth)
    if faults is not None:
        drop, stale, corrupt = fault_masks(
            key if fkey is None else fkey, t, m, m_mal, faults)
        delay = jnp.where(
            stale,
            jnp.minimum(delay + faults.straggler_delay, spec.depth - 1),
            delay)
    else:
        drop = corrupt = jnp.zeros((m,), bool)
    if spec.timed and m_mal > 0:
        # Static slice: the timed attacker's rows [0, f) always emit
        # fresh.  Benign faults still apply (dropout is the network's
        # call, not the attacker's).
        delay = delay.at[:m_mal].set(0)
    return delay.astype(jnp.int32), drop, corrupt


def staleness_weights(staleness, delivered, weighting: str):
    """(m,) f32 contribution weights for the delivered rows; zero off
    the delivered mask (so weighted estimators never read them).
    ``weighting='none'`` returns None — the kernels' unweighted masked
    path, byte-identical to the fault-quarantine contract."""
    if weighting == "none":
        return None
    s = jnp.maximum(staleness, 0).astype(jnp.float32)
    if weighting == "poly":
        w = 1.0 / jnp.sqrt(1.0 + s)
    else:  # 'const'
        w = jnp.where(s > 0, 0.5, 1.0)
    return jnp.where(delivered, w, 0.0).astype(jnp.float32)


def async_step(grads, t, key, spec: AsyncSpec, state, m_mal,
               faults=None, fkey=None, latency=None):
    """One async round against the submitted (m, d) matrix.

    Submits round-t updates into the ring at their drawn arrival slots,
    takes delivery of slot ``t % D``, merges arrivals into the pending
    pool, evicts over-stale / quarantines non-finite pending rows, and
    — once at least ``k`` updates are pending (FedBuff's buffer
    trigger) — consumes the ``k`` oldest FIFO; below the trigger the
    round delivers nothing (the engine holds the server state).

    Returns ``(delivered_grads, delivered, staleness, new_state,
    stats)``:

    - ``delivered_grads`` (m, d): the consumed updates, zero outside
      the mask (distance engines stay NaN-free, same convention as
      core/faults.py:quarantine);
    - ``delivered`` (m,) bool: the aggregation mask;
    - ``staleness`` (m,) int32: ``t - birth`` on delivered rows, -1
      elsewhere — the ``AttackContext.staleness`` view;
    - ``stats``: fixed-shape ``async_*`` scalars/vectors (delivered /
      pending / in-flight counts, evictions, supersessions, the
      staleness histogram) that ride the engine's telemetry plumbing
      into per-round v7 'async' events, plus the ``fault_*`` counts
      when faults compose.
    """
    D, m = spec.depth, grads.shape[0]
    k = min(spec.buffer, m)
    delay, drop, corrupt = draw_delays(key, t, m, m_mal, spec, faults,
                                       fkey, latency)

    submitted = grads.astype(jnp.float32)
    stats = {}
    if faults is not None:
        if faults.corrupt > 0:
            if faults.corrupt_mode == "scale":
                submitted = submitted * jnp.where(
                    corrupt, faults.corrupt_scale, 1.0)[:, None]
            else:
                bad = {"nan": jnp.nan, "inf": jnp.inf}[faults.corrupt_mode]
                submitted = jnp.where(corrupt[:, None],
                                      jnp.float32(bad), submitted)
        _, stale_mask, _ = fault_masks(
            key if fkey is None else fkey, t, m, m_mal, faults)
        stats.update({
            "fault_injected_dropout": jnp.sum(drop).astype(jnp.int32),
            "fault_injected_straggler":
                jnp.sum(stale_mask).astype(jnp.int32),
            "fault_injected_corrupt": jnp.sum(corrupt).astype(jnp.int32),
        })

    # --- submit: row i -> ring slot (t + delay_i) % D ------------------
    slot_of = jnp.mod(t + delay, D)                      # (m,)
    write = (slot_of[None, :] == jnp.arange(D)[:, None]) & ~drop[None, :]
    superseded_inflight = jnp.sum(write & state["occ"]).astype(jnp.int32)
    buf = jnp.where(write[:, :, None], submitted[None, :, :],
                    state["buf"])
    occ = state["occ"] | write
    birth = jnp.where(write, jnp.asarray(t, jnp.int32), state["birth"])

    # --- deliver slot t % D, then clear it -----------------------------
    slot = jnp.mod(t, D)
    arr_occ = lax.dynamic_index_in_dim(occ, slot, 0, keepdims=False)
    arr_buf = lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)
    arr_birth = lax.dynamic_index_in_dim(birth, slot, 0, keepdims=False)
    occ = lax.dynamic_update_index_in_dim(
        occ, jnp.zeros((m,), bool), slot, 0)

    # --- merge arrivals into the pending pool (supersede) --------------
    # Freshness rule: a client's NEWER computation supersedes its
    # pending older one, but an out-of-order late arrival (lower birth
    # than the pending entry) is discarded — a fresher pending update
    # must never be overwritten by a staler one.  Both directions
    # count as 'superseded' (one of the two updates was displaced).
    take = arr_occ & (~state["pocc"] | (arr_birth >= state["pbirth"]))
    superseded_pending = jnp.sum(arr_occ & state["pocc"]).astype(jnp.int32)
    pbuf = jnp.where(take[:, None], arr_buf, state["pbuf"])
    pbirth = jnp.where(take, arr_birth, state["pbirth"])
    pocc = state["pocc"] | arr_occ

    # --- age, evict over-stale, quarantine non-finite ------------------
    # Stage ledger (utils/costs.py): the server-side screen on pending
    # rows is the ``quarantine`` stage (the ring mechanics around it
    # stay 'deliver', the engine's call-site scope).
    from attacking_federate_learning_tpu.utils.costs import stage_scope

    with stage_scope("quarantine"):
        stal = jnp.asarray(t, jnp.int32) - pbirth        # (m,)
        over = pocc & (stal > spec.max_staleness)
        evicted = jnp.sum(over).astype(jnp.int32)
        pocc = pocc & ~over
        finite = jnp.isfinite(pbuf).all(axis=1)
        quarantined = jnp.sum(pocc & ~finite).astype(jnp.int32)
        pocc = pocc & finite

    # --- FedBuff trigger: consume the k oldest pending (FIFO) only
    # once k are available; otherwise hold (server no-op round) -------
    order_key = jnp.where(pocc, pbirth.astype(jnp.float32) * m
                          + jnp.arange(m, dtype=jnp.float32), _EMPTY_KEY)
    neg, idxs = lax.top_k(-order_key, k)
    live = jnp.isfinite(neg) & (jnp.sum(pocc) >= k)
    delivered = jnp.zeros((m,), bool).at[idxs].set(live)
    delivered_grads = jnp.where(delivered[:, None], pbuf, 0.0)
    staleness = jnp.where(delivered, stal, -1).astype(jnp.int32)
    pocc_after = pocc & ~delivered

    new_state = {"buf": buf, "occ": occ, "birth": birth,
                 "pbuf": pbuf, "pocc": pocc_after, "pbirth": pbirth}

    # Staleness histogram over the delivered rows: fixed (D,) shape.
    hist = jnp.sum(
        (staleness[None, :] == jnp.arange(D)[:, None]) & delivered[None, :],
        axis=1).astype(jnp.int32)
    stats.update({
        "async_delivered": jnp.sum(delivered).astype(jnp.int32),
        "async_pending": jnp.sum(pocc_after).astype(jnp.int32),
        "async_in_flight": jnp.sum(occ).astype(jnp.int32),
        "async_evicted": evicted,
        "async_quarantined": quarantined,
        "async_superseded": superseded_inflight + superseded_pending,
        "async_staleness_hist": hist,
    })
    return delivered_grads, delivered, staleness, new_state, stats


def replay_schedule(cfg, m, m_mal, epochs, timed=False):
    """Host-side replay of the async delivery dynamics — NO gradients,
    just the occupancy/ordering machinery (the content-free projection
    of :func:`async_step`), recomputed with plain numpy from the same
    PRNG draws.  Returns one dict per round with the counts a v7
    'async' event must carry; tools/fault_matrix.py's async leg and
    tests/test_async.py diff emitted events against this.
    """
    spec = AsyncSpec(buffer=cfg.async_buffer,
                     max_staleness=cfg.async_max_staleness,
                     weighting=cfg.staleness_weight, timed=timed)
    key = async_key(cfg)
    D = spec.depth
    k = min(spec.buffer, m)
    faults = cfg.faults if (cfg.faults is not None
                            and cfg.faults.enabled) else None
    fkey = None
    if faults is not None:
        from attacking_federate_learning_tpu.core.faults import fault_key
        fkey = fault_key(cfg)
    latency = None
    tr = getattr(cfg, "traffic", None)
    if tr is not None and tr.enabled:
        # Traffic engine: the replay must draw the same heavy-tail
        # latency delays the device ring does (core/population.py).
        from attacking_federate_learning_tpu.core.population import (
            async_latency_for_cfg
        )
        latency = async_latency_for_cfg(cfg, m)
    occ = np.zeros((D, m), bool)
    birth = np.zeros((D, m), np.int64)
    pocc = np.zeros((m,), bool)
    pbirth = np.zeros((m,), np.int64)
    rows = []
    for t in range(epochs):
        delay, drop, _ = (np.asarray(x) for x in
                          draw_delays(key, t, m, m_mal, spec, faults,
                                      fkey, latency))
        slots = (t + delay) % D
        superseded = int(occ[slots, np.arange(m)][~drop].sum())
        write = ~drop
        occ[slots[write], np.arange(m)[write]] = True
        birth[slots[write], np.arange(m)[write]] = t
        slot = t % D
        arr = occ[slot].copy()
        occ[slot] = False
        superseded += int((arr & pocc).sum())
        take = arr & (~pocc | (birth[slot] >= pbirth))
        pbirth = np.where(take, birth[slot], pbirth)
        pocc = pocc | arr
        stal = t - pbirth
        over = pocc & (stal > spec.max_staleness)
        evicted = int(over.sum())
        pocc = pocc & ~over
        order_key = np.where(pocc, pbirth * m + np.arange(m), np.inf)
        idxs = np.argsort(order_key, kind="stable")[:k]
        live = np.isfinite(order_key[idxs]) & (int(pocc.sum()) >= k)
        delivered = np.zeros((m,), bool)
        delivered[idxs[live]] = True
        hist = np.zeros((D,), np.int64)
        for s in stal[delivered]:
            if 0 <= s < D:
                hist[s] += 1
        pocc = pocc & ~delivered
        rows.append({
            "delivered": int(delivered.sum()),
            "pending": int(pocc.sum()),
            "in_flight": int(occ.sum()),
            "evicted": evicted,
            "superseded": superseded,
            "staleness_hist": hist.tolist(),
            "delivered_mask": delivered,
            "staleness": np.where(delivered, stal, -1),
        })
    return rows


def check_async_support(cfg):
    """Fail fast on configs the async round cannot honor (engine init)
    — the loud-rejection contract of the hierarchical/secagg modes,
    message text pinned by tests/test_async.py."""
    from attacking_federate_learning_tpu.core.faults import (
        MASK_AWARE_DEFENSES
    )

    if cfg.defense not in MASK_AWARE_DEFENSES:
        raise ValueError(
            f"--aggregation async needs a mask-aware defense "
            f"{MASK_AWARE_DEFENSES}, got {cfg.defense!r} (the delivered-"
            f"cohort mask and staleness weights must reach the kernel; "
            f"defenses/kernels.py)")
    if cfg.participation < 1.0:
        raise ValueError(
            "--aggregation async requires participation=1.0: the "
            "in-flight ring and pending pool are indexed by cohort row, "
            "and under partial participation rows are different clients "
            "each round")
    if cfg.data_placement != "device":
        raise ValueError(
            "--aggregation async requires data_placement='device': the "
            "buffered span is one scanned device program (host "
            "streaming feeds one round per program by design)")
    if cfg.backdoor and not cfg.backdoor_fused:
        raise ValueError(
            "--aggregation async needs the fused backdoor path (drop "
            "--backdoor-staged): delivery, staleness weighting and the "
            "attack seam all live inside the fused round program")
    host_impls = [
        ("distance_impl", cfg.distance_impl),
        ("trimmed_mean_impl", cfg.trimmed_mean_impl),
        ("median_impl", cfg.median_impl),
        ("bulyan_selection_impl", cfg.bulyan_selection_impl),
        ("bulyan_trim_impl", cfg.bulyan_trim_impl),
    ]
    for name, val in host_impls:
        if val == "host":
            raise ValueError(
                f"--aggregation async is incompatible with "
                f"{name}='host': the host engines have no mask/weight "
                f"seam (defenses/host.py)")
