"""Client-population registry & deterministic traffic engine.

The reference simulator (and every engine here before this module)
equates "n clients" with n resident gradient rows drawn every round.
Production FL samples each round's cohort from a population of millions
whose availability is bursty, correlated and heavy-tailed — *who shows
up when* changes outcomes as much as the aggregation rule does.  This
module gives the engines that population without ever materializing it:

- :class:`PopulationRegistry` — P registered clients (P >> cohort m)
  whose per-client persistent state (data-shard archetype, femnist-style
  transform id, reliability profile, churn dwell/phase, latency profile)
  is materialized LAZILY from counter-based PRNG streams (splitmix64
  over (seed, salt, pid)).  The registry object holds scalars only — no
  (P,) array ever exists on host or device; memory scales with the
  cohort m, never the population P (pinned structurally by
  tests/test_traffic.py the way perf_gate --memproof pins HBM).
- A deterministic arrival process: a diurnal-modulated base rate with
  per-client blockwise on/off churn (each client holds its availability
  state for ``dwell_i`` consecutive rounds — a stateless alternating-
  renewal approximation of Markov on/off churn, chosen so availability
  is a pure function of ``(seed, pid, t)`` and therefore replayable and
  resume-exact with NO carried traffic state), heavy-tail (discretized
  Pareto) straggler latencies for the async delivery ring, and a
  time-correlated colluder-arrival knob (sybil burst window: colluders
  arrive only inside a periodic window, boosted by period/width so the
  AVERAGE arrived-colluder mass matches the uniform profile —
  participation itself becomes an attack axis at fixed average f).
- The defense-validity watchdog: per-round effective-cohort accounting
  (arrived rows / arrived-malicious rows through the existing
  mask-aware kernel seam) and a declared degradation ladder evaluated
  on host at schedule time — re-mask the configured defense to the
  arrived sub-cohort while its validity bound holds (Krum m_eff >=
  2f+3, Bulyan m_eff >= 4f+3, with f the kernel's STATIC assumed
  corrupted count: the masked kernels trim f rows whatever arrived),
  else fall back to a bounds-valid defense (trimmed-mean/median), else
  hold the round as a FedBuff-style no-op.  Every decision is a
  versioned 'traffic' event (schema v11) and the whole schedule is
  PRNG-replayable on host (:func:`replay_traffic` — the
  fault_matrix-style event diff).

Engine composition matrix (ARCHITECTURE.md "Population & traffic"):
flat gets the full model (sampled cohorts + churn + ladder + sybil
burst); async keeps its resident ring but draws arrival delay from the
latency profile instead of the uniform 0..D draw; hierarchical
resamples each megabatch's client slots from the population per round
(rounds stay full — placement assigns every slot — so churn/ladder do
not apply there); host-streaming, secagg and staged attacks are
rejected loudly.  Traffic-off leaves every compiled program
byte-identical (PERF_BASELINE untouched).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp


# Degradation-ladder actions, in declared order.  The host watchdog
# plans one action per round; the device program selects on the planned
# int (never branches on data), so the schedule replays exactly.
TRAFFIC_REMASK = 0    # configured defense over the arrived sub-cohort
TRAFFIC_FALLBACK = 1  # bounds-valid fallback defense (trimmed-mean/median)
TRAFFIC_HOLD = 2      # FedBuff-style no-op round (state holds)
ACTION_NAMES = ("remask", "fallback", "hold")

# Validity bounds m_eff >= bound(f) for the mask-aware kernels, with f
# the kernel's STATIC corrupted count (the masked kernels trim/score
# against f rows whatever actually arrived — core/faults.py's
# masked == survivor-submatrix contract).  Krum uses the selection-
# safety bound 2f+3 (strictly stronger than kernels.py's 2f+1 runnable
# bound); Bulyan its 4f+3; the coordinate trims need 2f+1 rows to
# leave one; NoDefense averages whatever arrived.
DEFENSE_MIN_COHORT = {
    "NoDefense": lambda f: 1,
    "Krum": lambda f: 2 * f + 3,
    "TrimmedMean": lambda f: 2 * f + 1,
    "Median": lambda f: 2 * f + 1,
    "Bulyan": lambda f: 4 * f + 3,
}


def defense_min_cohort(name: str, f: int) -> int:
    return DEFENSE_MIN_COHORT[name](int(f))


def plan_action(defense: str, fallback: str, m_eff: int, f_kernel: int,
                min_cohort: int) -> int:
    """The watchdog's per-round ladder decision (host, schedule time)."""
    if m_eff >= max(min_cohort, defense_min_cohort(defense, f_kernel)):
        return TRAFFIC_REMASK
    if m_eff >= max(min_cohort, defense_min_cohort(fallback, f_kernel)):
        return TRAFFIC_FALLBACK
    return TRAFFIC_HOLD


def traffic_key(cfg):
    """The traffic subsystem's own jax key stream (hier slot resampling
    and async latency draws), derived from — but distinct from — the
    experiment seed unless TrafficConfig.seed overrides it; mirrors
    core/faults.py:fault_key."""
    seed = (cfg.traffic.seed if cfg.traffic.seed is not None
            else cfg.seed)
    return jax.random.key(seed ^ 0x7AF1C)


def legacy_cohort(part_key, t, n, f, m, m_mal):
    """The legacy ``--participation`` cohort draw, relocated verbatim
    from core/engine.py:_participants: the first m_mal entries are
    malicious ids (< f), the rest honest — random identities, static
    counts.  This IS the population sampler's uniform-reliability
    compat profile: traffic-off partial participation routes through
    here, bit-compatible with every pre-population run
    (tests/test_traffic.py pins the draw against the inline formula;
    tests/test_participation.py pins its invariants)."""
    k1, k2 = jax.random.split(jax.random.fold_in(part_key, t))
    mal = jax.random.choice(k1, f, (m_mal,), replace=False)
    hon = f + jax.random.choice(k2, n - f, (m - m_mal,),
                                replace=False)
    return jnp.concatenate([mal, hon]).astype(jnp.int32)


# --- counter-based PRNG streams (splitmix64, vectorized numpy) --------
# Per-client state is a pure function of (seed, salt, pid[, block]) —
# nothing is stored, so the registry stays O(1) however large P grows,
# and the schedule replays identically across process restarts.

_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)

_SALT_SHARD = 1
_SALT_REL = 2
_SALT_DWELL = 3
_SALT_PHASE = 4
_SALT_LAT = 5
_SALT_ON = 6
_SALT_DRAW = 7


def _mix(x):
    # uint64 wraparound is the algorithm; numpy flags scalar overflow
    # (arrays wrap silently) — silence it locally, not globally.
    with np.errstate(over="ignore"):
        x = np.asarray(x, np.uint64)
        x = (x ^ (x >> np.uint64(30))) * _M1
        x = (x ^ (x >> np.uint64(27))) * _M2
        return x ^ (x >> np.uint64(31))


def _fold(acc, s):
    with np.errstate(over="ignore"):
        return _mix(np.asarray(acc, np.uint64)
                    ^ (np.asarray(s, np.uint64) + _GAMMA))


def _u01(h):
    # Top 53 bits -> [0, 1) double, the usual splitmix-to-uniform map.
    return (np.asarray(h, np.uint64) >> np.uint64(11)).astype(
        np.float64) * (1.0 / (1 << 53))


@dataclasses.dataclass
class TrafficSchedule:
    """One host-planned span of traffic rounds [t0, t0+count): the scan
    inputs of the engine's ``traffic_span`` (static shapes, malicious-
    first rows) plus the per-round 'traffic' event payloads — the host
    ground truth the emitted events are diffed against."""

    t0: int
    count: int
    shard_ids: np.ndarray   # (count, m) int32, rows [0, m_mal) malicious
    arrived: np.ndarray     # (count, m) bool — the effective-cohort mask
    action: np.ndarray      # (count,) int32 ladder decision
    events: list            # count dicts (round/arrived/f_eff/action/...)


class PopulationRegistry:
    """Lazy registry of P clients; see the module docstring.

    Colluders are pids [0, F) with F = max(1, round(P*f/n)) (the
    population mirrors the cohort's malicious fraction); a colluder's
    data-shard archetype lands in [0, f), an honest client's in [f, n)
    — so a sampled cohort's malicious-first rows keep the engines'
    rows-[0, f) attack invariant, and a population client materializes
    as exactly its archetype's data shard + femnist-style transform
    (only n archetypes of client DATA ever exist; distinct population
    clients may share one, which is the point of P >> n).
    """

    def __init__(self, tcfg, n: int, f: int, seed: int):
        self.tcfg = tcfg
        self.n, self.f = int(n), int(f)
        self.P = int(tcfg.population)
        self.F = (max(1, int(round(self.P * f / n))) if f > 0 else 0)
        self.seed = tcfg.seed if tcfg.seed is not None else seed
        self._base = _mix(np.uint64(np.uint64(self.seed) + _GAMMA))

    # -- per-client persistent state (lazy, vectorized) ---------------
    def _h(self, salt, pids, extra=None):
        h = _fold(_fold(self._base, salt), pids)
        if extra is not None:
            h = _fold(h, extra)
        return h

    def client_state(self, pids):
        """Materialize per-client state for the GIVEN pids only."""
        pids = np.asarray(pids, np.int64)
        t = self.tcfg
        malicious = pids < self.F
        shard = np.where(
            malicious,
            self._h(_SALT_SHARD, pids) % np.uint64(max(self.f, 1)),
            np.uint64(self.f)
            + self._h(_SALT_SHARD, pids) % np.uint64(self.n - self.f),
        ).astype(np.int64)
        reliability = (t.reliability_lo
                       + (t.reliability_hi - t.reliability_lo)
                       * _u01(self._h(_SALT_REL, pids)))
        dwell = 1 + (self._h(_SALT_DWELL, pids)
                     % np.uint64(max(t.churn_dwell, 1))).astype(np.int64)
        phase = (self._h(_SALT_PHASE, pids)
                 % dwell.astype(np.uint64)).astype(np.int64)
        # Per-client latency scale: spread around the configured scale
        # so the Pareto tails differ per client, not just per draw.
        latency = t.latency_scale * (0.5 + 1.0 * _u01(
            self._h(_SALT_LAT, pids)))
        return {"malicious": malicious, "shard": shard,
                "style_id": shard, "reliability": reliability,
                "dwell": dwell, "phase": phase, "latency": latency}

    # -- arrival process ----------------------------------------------
    def arrival_rate(self, t: int) -> float:
        """Diurnal-modulated base arrival rate at round t."""
        tc = self.tcfg
        r = tc.rate * (1.0 + tc.diurnal_amp
                       * np.sin(2.0 * np.pi * t / tc.diurnal_period))
        return float(max(r, 0.0))

    def available(self, pids, t: int, state=None):
        """(len(pids),) bool availability at round t — pure in
        ``(seed, pid, t)``.  Each client's on/off state is drawn once
        per ``dwell_i``-round block (correlated churn episodes); the
        sybil window reshapes the MALICIOUS arrival probability only."""
        pids = np.asarray(pids, np.int64)
        st = state if state is not None else self.client_state(pids)
        tc = self.tcfg
        block = ((t + st["phase"]) // st["dwell"]).astype(np.int64)
        u = _u01(self._h(_SALT_ON, pids, extra=block))
        p_on = np.clip(self.arrival_rate(t) * st["reliability"], 0.0, 1.0)
        if tc.sybil_burst_period > 0:
            in_win = (t % tc.sybil_burst_period) < tc.sybil_burst_width
            gain = tc.sybil_burst_period / tc.sybil_burst_width
            p_mal = np.clip(p_on * gain, 0.0, 1.0) if in_win else 0.0
            p_on = np.where(st["malicious"], p_mal, p_on)
        return u < p_on

    # -- cohort sampling ----------------------------------------------
    def _fill(self, t: int, k: int, malicious: bool):
        """Deterministic rejection-sampled fill of k cohort slots from
        one pool (colluders or honest): hash-drawn candidates, deduped,
        arrived-first.  When fewer than k candidates arrived, the
        absent candidates keep the gather shape (static (m,) ids) with
        ``arrived=False`` — that under-fill is what the watchdog
        degrades on."""
        if k == 0:
            return (np.zeros(0, np.int64), np.zeros(0, bool))
        lo, hi = (0, self.F) if malicious else (self.F, self.P)
        pool = hi - lo
        budget = max(8 * k, 64)
        salt = np.uint64(_SALT_DRAW + (10 if malicious else 20))
        if pool <= budget:
            # Small pool: a full hashed-order permutation, fresh per t.
            order = self._h(salt, np.arange(lo, hi), extra=t)
            cand = lo + np.argsort(order, kind="stable")
        else:
            j = np.arange(budget, dtype=np.int64)
            draw = lo + (self._h(salt, j, extra=t)
                         % np.uint64(pool)).astype(np.int64)
            _, first = np.unique(draw, return_index=True)
            cand = draw[np.sort(first)]
        avail = self.available(cand, t)
        here = cand[avail][:k]
        absent = cand[~avail][: k - len(here)]
        if len(here) + len(absent) < k:
            # Pathological (tiny pool, everything arrived or vanished):
            # repeat candidates to keep the static shape.
            pad = np.resize(cand, k - len(here) - len(absent))
            absent = np.concatenate([absent, pad])
        pids = np.concatenate([here, absent])[:k]
        arrived = np.zeros(k, bool)
        arrived[: len(here)] = True
        return pids.astype(np.int64), arrived

    def sample_cohort(self, t: int, m: int, m_mal: int):
        """Round-t cohort: (shard_ids (m,) int32 malicious-first,
        arrived (m,) bool, pids (m,) int64)."""
        mal_p, mal_a = self._fill(t, m_mal, malicious=True)
        hon_p, hon_a = self._fill(t, m - m_mal, malicious=False)
        pids = np.concatenate([mal_p, hon_p])
        arrived = np.concatenate([mal_a, hon_a])
        shard_ids = self.client_state(pids)["shard"].astype(np.int32)
        return shard_ids, arrived, pids


def traffic_schedule(registry: PopulationRegistry, t0: int, count: int,
                     m: int, m_mal: int, defense: str, fallback: str,
                     min_cohort: int) -> TrafficSchedule:
    """Host-planned schedule for rounds [t0, t0+count): cohorts, arrival
    masks, ladder actions and the 'traffic' event payloads.  Pure in
    (registry config, t) — stateless, so a resumed run regenerates its
    tail bit-for-bit and :func:`replay_traffic` diffs emitted events
    against an independent regeneration."""
    sids = np.zeros((count, m), np.int32)
    arr = np.zeros((count, m), bool)
    act = np.zeros((count,), np.int32)
    events = []
    for i in range(count):
        t = t0 + i
        sid, a, _pids = registry.sample_cohort(t, m, m_mal)
        sids[i], arr[i] = sid, a
        m_eff = int(a.sum())
        f_eff = int(a[:m_mal].sum())
        action = plan_action(defense, fallback, m_eff, m_mal, min_cohort)
        act[i] = action
        events.append({
            "round": int(t),
            "arrived": m_eff,
            "f_eff": f_eff,
            "cohort": int(m),
            "action": ACTION_NAMES[action],
            "defense": (defense if action == TRAFFIC_REMASK
                        else fallback if action == TRAFFIC_FALLBACK
                        else "none"),
        })
    return TrafficSchedule(t0=int(t0), count=int(count), shard_ids=sids,
                           arrived=arr, action=act, events=events)


def replay_traffic(cfg, epochs: int):
    """Regenerate the full traffic schedule for a finished run from its
    config alone — the fault_matrix-style host diff: emitted 'traffic'
    events must equal these rows exactly."""
    n, f = cfg.users_count, cfg.corrupted_count
    if cfg.participation < 1.0:
        m = max(1, int(round(cfg.participation * n)))
        m_mal = min(int(round(cfg.participation * f)), m)
    else:
        m, m_mal = n, f
    reg = PopulationRegistry(cfg.traffic, n, f, cfg.seed)
    sched = traffic_schedule(reg, 0, epochs, m, m_mal, cfg.defense,
                             cfg.traffic.fallback_defense,
                             cfg.traffic.min_cohort)
    return sched.events


# --- async latency profile (core/async_rounds.py:draw_delays) ---------
def async_latency_for_cfg(cfg, m: int):
    """(scales (m,) f32 jnp, tail float) for the async engine's
    heavy-tail delay draw: cohort row i is population client i for the
    malicious rows and F + (i - m_mal) for the honest ones (the async
    ring is resident, so the cohort<->pid map is fixed), each carrying
    its lazily-derived latency scale."""
    f = cfg.corrupted_count
    reg = PopulationRegistry(cfg.traffic, cfg.users_count, f, cfg.seed)
    m_mal = min(f, m)
    pids = np.concatenate([np.arange(m_mal),
                           reg.F + np.arange(m - m_mal)])
    scales = reg.client_state(pids)["latency"].astype(np.float32)
    return jnp.asarray(scales), float(cfg.traffic.latency_tail)


def traffic_delays(key, t, scales, tail, depth):
    """Heavy-tail straggler delay per cohort row: a discretized
    Pareto(tail) draw scaled by the per-client latency profile, clipped
    to the delivery-ring depth.  Pure in ``(key, t)`` — runs
    identically traced (inside the fused async round) and eagerly (the
    replay_schedule host diff)."""
    kt = jax.random.fold_in(key, t)
    u = jax.random.uniform(kt, scales.shape, minval=1e-6, maxval=1.0)
    raw = scales * (jnp.power(u, -1.0 / tail) - 1.0)
    return jnp.clip(raw, 0, depth - 1).astype(jnp.int32)


# --- hierarchical slot resampling ------------------------------------
def resample_slots(key, t, ids, c_mal, f, n):
    """Per-round population resampling of one megabatch's client slots
    (hier engine): malicious slots draw a shard archetype from [0, f),
    honest slots from [f, n) — the per-megabatch mirror of the
    rows-[0, c_mal) invariant.  Hier rounds stay FULL (placement
    assigns every slot), so churn/under-fill and the ladder do not
    apply; this is cohort-identity resampling only.  Pure in
    ``(key, t, ids[0])`` (placement id sets are disjoint, so the first
    id decorrelates megabatches)."""
    kt = jax.random.fold_in(jax.random.fold_in(key, t), ids[0])
    k1, k2 = jax.random.split(kt)
    mal = jax.random.randint(k1, ids.shape, 0, max(f, 1))
    hon = f + jax.random.randint(k2, ids.shape, 0, n - f)
    slot_mal = jnp.arange(ids.shape[0]) < c_mal
    return jnp.where(slot_mal, mal, hon).astype(ids.dtype)


def check_traffic_support(cfg):
    """Fail fast on configs the traffic engine cannot honor (engine
    init + campaigns/spec.py pre-validation), in the loud-rejection
    style of core/faults.py:check_fault_support."""
    from attacking_federate_learning_tpu.core.faults import (
        MASK_AWARE_DEFENSES
    )

    t = cfg.traffic
    if t.population < cfg.users_count:
        raise ValueError(
            f"--traffic-population must cover the cohort pool: "
            f"P={t.population} < users_count={cfg.users_count} (the "
            f"registry's shard archetypes span all n clients)")
    if cfg.secagg != "off":
        raise ValueError(
            "--traffic-population is incompatible with --secagg: "
            "pairwise masks are keyed on client identity, and sampled "
            "population cohorts re-key every row each round (the same "
            "structural fact that rejects --participation there)")
    if cfg.data_placement != "device":
        raise ValueError(
            "--traffic-population requires data_placement='device': "
            "the traffic schedule rides the scanned span as per-round "
            "scan inputs; the streaming mode feeds one round per "
            "program by design")
    if cfg.backdoor and not cfg.backdoor_fused:
        raise ValueError(
            "--traffic-population needs the fused backdoor path (drop "
            "--backdoor-staged): cohort sampling, the arrival mask and "
            "the degradation ladder all live inside the fused round "
            "program")
    if cfg.aggregation == "hierarchical":
        if cfg.mesh_shape is not None and tuple(cfg.mesh_shape)[0] > 1:
            raise ValueError(
                "--traffic-population with hierarchical aggregation "
                "does not compose with the SPMD client_map "
                "(--mesh-shape clients axis > 1): the per-round slot "
                "resampling draws keys inside the scanned megabatch "
                "body, which the shard_map program does not thread yet")
        return
    if cfg.aggregation == "async":
        return
    # Flat: the arrival mask and the ladder ride the mask-aware seam.
    if cfg.defense not in MASK_AWARE_DEFENSES:
        raise ValueError(
            f"--traffic-population needs a mask-aware defense "
            f"{MASK_AWARE_DEFENSES}, got {cfg.defense!r} (the arrival "
            f"mask must reach the kernel; defenses/kernels.py)")
    if t.fallback_defense not in MASK_AWARE_DEFENSES:
        raise ValueError(
            f"--traffic-fallback must be mask-aware "
            f"{MASK_AWARE_DEFENSES}, got {t.fallback_defense!r}")
    host_impls = [
        ("distance_impl", cfg.distance_impl),
        ("trimmed_mean_impl", cfg.trimmed_mean_impl),
        ("median_impl", cfg.median_impl),
        ("bulyan_selection_impl", cfg.bulyan_selection_impl),
        ("bulyan_trim_impl", cfg.bulyan_trim_impl),
    ]
    for name, val in host_impls:
        if val == "host":
            raise ValueError(
                f"--traffic-population is incompatible with "
                f"{name}='host': the host engines have no mask seam "
                f"(defenses/host.py)")
