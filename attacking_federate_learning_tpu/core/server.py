"""Server state and the aggregation update.

The reference server's global state is three arrays: the flat weight vector,
the (n, d) gradient matrix and the momentum velocity (reference
server.py:34-36), updated by ``v = mu*v - lr*g; w += v`` on the *constant*
base learning rate (server.py:89-90 — the faded lr reaches only the clients,
SURVEY.md §2.4 #7).  Here that state is an immutable NamedTuple and the
update is a pure function; the (n, d) matrix is never stored on the state —
it flows through the round function.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class ServerState(NamedTuple):
    weights: jax.Array    # (d,) flat wire-format weights
    velocity: jax.Array   # (d,) momentum buffer
    round: jax.Array      # () int32


def init_server_state(flat_weights) -> ServerState:
    return ServerState(
        weights=flat_weights,
        velocity=jnp.zeros_like(flat_weights),
        round=jnp.zeros((), jnp.int32),
    )


def momentum_update(state: ServerState, agg_grad, learning_rate,
                    momentum) -> ServerState:
    """Momentum-SGD step on the aggregated gradient (reference
    server.py:89-90)."""
    velocity = momentum * state.velocity - learning_rate * agg_grad
    return ServerState(
        weights=state.weights + velocity,
        velocity=velocity,
        round=state.round + 1,
    )


def faded_learning_rate(base_lr, fading_rate, epoch):
    """Hyperbolic LR fading (reference server.py:50-52)."""
    return base_lr * fading_rate / (epoch + fading_rate)
