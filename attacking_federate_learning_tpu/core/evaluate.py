"""Jitted test-set evaluation.

Reproduces the reference metric exactly (reference server.py:92-112): the
reported "average loss" is the *sum of per-batch mean NLLs* divided by the
test-set size — a quirk of ``test_loss += loss.item()`` with mean-reduction
batches (server.py:104-110) — plus the argmax-correct count.  The test set is
padded to a whole number of batches with a validity mask so the scan has
static shapes; masked per-batch means match the reference's short final
batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from attacking_federate_learning_tpu.models.base import Model
from attacking_federate_learning_tpu.utils.flatten import FlatParams


def pad_to_batches(x, y, batch_size):
    n = x.shape[0]
    n_batches = -(-n // batch_size)
    pad = n_batches * batch_size - n
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    xp = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
    yp = np.concatenate([y, np.zeros(pad, y.dtype)])
    shape = (n_batches, batch_size)
    return (xp.reshape(shape + x.shape[1:]), yp.reshape(shape),
            mask.reshape(shape))


def masked_nll_metrics(apply_fn, params, bx, by, bm):
    """Scan batched (nb, B, ...) data: returns (sum of per-batch masked-mean
    NLLs, masked correct count) — the reference's exact eval arithmetic
    (server.py:104-110), shared by server eval and the backdoor ASR check
    (backdoor.py:89-94)."""

    def batch_metrics(carry, batch):
        x, y, m = batch
        logp = apply_fn(params, x)
        per_ex = -jnp.take_along_axis(logp, y[:, None], axis=1).squeeze(1)
        batch_mean = jnp.sum(per_ex * m) / jnp.maximum(jnp.sum(m), 1.0)
        correct = jnp.sum((jnp.argmax(logp, axis=1) == y) * m)
        loss_sum, correct_sum = carry
        return (loss_sum + batch_mean, correct_sum + correct), None

    (loss_sum, correct_sum), _ = jax.lax.scan(
        batch_metrics, (jnp.zeros(()), jnp.zeros(())), (bx, by, bm))
    return loss_sum, correct_sum


def make_eval_fn(model: Model, flat: FlatParams, test_x, test_y, batch_size):
    """Returns jitted (flat_w) -> (test_loss, correct) on the full test set."""
    bx, by, bm = (jnp.asarray(a)
                  for a in pad_to_batches(test_x, test_y, batch_size))
    n_test = test_x.shape[0]

    @functools.partial(jax.jit, donate_argnums=())
    def evaluate(flat_w):
        params = flat.unravel(flat_w)
        loss_sum, correct_sum = masked_nll_metrics(model.apply, params,
                                                   bx, by, bm)
        return loss_sum / n_test, correct_sum

    return evaluate
