"""Experiment engine: the jitted round loop.

The reference's round is four host-side phases over one process
(reference main.py:64-71): dispatch_weights (N sequential client steps),
attacker.attack, collect_gradients, defend+update.  Here a round is:

    grads = vmap(grad(loss))(w, batches)      # all clients at once
    grads = attack.apply(grads, f, ctx)       # first-f-rows overwrite
    state = momentum_update(state, defense(grads, n, f))

For fusable attacks (none / ALIE / the baselines, and the backdoor by
default — its shadow train is itself pure jitted jax) the whole round is one
jitted function of ``(state, round_index)`` — batch gathers included — so
steady-state rounds are a single device program; ``backdoor_fused=False``
restores the reference's staged seam (main.py:66-71) with its per-round
host nan guard.

Evaluation, checkpointing and logging stay on the host at TEST_STEP cadence
(reference main.py:73-95).

Telemetry (cfg.telemetry): each round's defense diagnostics
(defenses/kernels.py telemetry seam), attack envelope stats
(attacks/base.py:envelope_stats) and per-client population stats ride out
of the jitted round as AUXILIARY OUTPUTS — fixed-shape device pytrees, no
host callbacks inside the jit.  When rounds fuse into spans, a
``lax.scan`` stacks the per-round pytrees along a leading round axis and
the host fetches the whole stack once per eval interval
(``_tele_span``); the per-round dispatch modes fetch per round.  Events
land in the run JSONL as 'defense'/'attack' records plus one end-of-run
'selection_hist' (utils/metrics.py schema).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from attacking_federate_learning_tpu.attacks.base import (
    Attack, AttackContext, NoAttack
)
from attacking_federate_learning_tpu.config import ExperimentConfig
from attacking_federate_learning_tpu.core.client import (
    make_client_update_fn, make_loss_fn
)
from attacking_federate_learning_tpu.core.evaluate import make_eval_fn
from attacking_federate_learning_tpu.core.server import (
    ServerState, faded_learning_rate, init_server_state, momentum_update
)
from attacking_federate_learning_tpu.data.datasets import load_dataset
from attacking_federate_learning_tpu.data.partition import (
    make_shards, round_batch_indices
)
from attacking_federate_learning_tpu.defenses import (
    DEFENSES, check_defense_args
)
from attacking_federate_learning_tpu.defenses.kernels import stage_wrapped
from attacking_federate_learning_tpu.models.base import get_model
from attacking_federate_learning_tpu.utils.costs import stage_scope
from attacking_federate_learning_tpu.utils.flatten import make_flattener
from attacking_federate_learning_tpu.utils.metrics import RunLogger
from attacking_federate_learning_tpu.utils.numerics import (
    nonfinite_count, norm_dynamic_range
)


def _jsonable(v):
    """Host telemetry leaf -> JSON value: 0-d arrays to float, vectors
    to lists, matrices — the hierarchical (S, m) per-shard stacks — to
    nested lists (the event schema stores fixed-shape arrays inline)."""
    a = np.asarray(v)
    if a.ndim == 0:
        return float(a)
    if a.ndim == 1:
        return [float(x) for x in a]
    return a.astype(float).tolist()


class FederatedExperiment:
    def __init__(self, cfg: ExperimentConfig, attacker: Optional[Attack] = None,
                 dataset=None, shardings=None):
        self.cfg = cfg
        self.attacker = attacker or NoAttack()
        self.dataset = dataset or load_dataset(cfg.dataset, cfg.data_dir,
                                               cfg.seed)
        self.model = get_model(cfg.model)
        self.n = cfg.users_count
        self.f = cfg.corrupted_count
        # Per-round cohort (config.participation): STATIC sizes — round(p·f)
        # malicious + honest remainder — with random identities per round,
        # so jit shapes never change and the rows-[0, m_mal) attack
        # invariant holds.  p=1 degenerates to the reference's
        # everyone-every-round cohort.
        if cfg.participation < 1.0:
            self.m = max(1, int(round(cfg.participation * self.n)))
            self.m_mal = min(int(round(cfg.participation * self.f)), self.m)
            if self.f > 0 and self.m_mal == 0:
                raise ValueError(
                    f"participation={cfg.participation} rounds the "
                    f"malicious cohort to 0 while f={self.f} — the attack "
                    f"would silently never run (static cohorts); raise "
                    f"participation or set mal_prop=0 explicitly")
            if self.m - self.m_mal > self.n - self.f:
                raise ValueError(
                    f"cohort needs {self.m - self.m_mal} honest clients "
                    f"but only {self.n - self.f} exist "
                    f"(n={self.n}, f={self.f}, "
                    f"participation={cfg.participation})")
        else:
            self.m, self.m_mal = self.n, self.f
        # Secure-aggregation protocol layer (protocols/secagg.py;
        # cfg.secagg): 'off' is the reference fiction and leaves the
        # compiled round byte-identical (pinned).  The structural
        # incompatibilities are rejected at config construction
        # (config.py); the one engine-level fact — a non-fusable
        # attacker handed in programmatically — is checked here.
        if cfg.secagg != "off":
            from attacking_federate_learning_tpu.protocols.secagg import (
                secagg_key
            )
            if not getattr(self.attacker, "fusable", True):
                raise ValueError(
                    "--secagg masks inside the fused round program and "
                    "needs a fusable attack (drop --backdoor-staged)")
            self._secagg = cfg.secagg
            self._secagg_key = secagg_key(cfg)
        else:
            self._secagg = None
        # Mesh plan first: the hierarchical init below decides between
        # the sequential megabatch scan and the SPMD client_map from
        # the clients-axis size (ISSUE 12), so the plan must exist
        # before the topology is planned.
        if shardings is None and cfg.mesh_shape is not None:
            from attacking_federate_learning_tpu.parallel.mesh import make_plan
            shardings = make_plan(tuple(cfg.mesh_shape))
        self.shardings = shardings  # parallel.MeshPlan or None (single device)
        # The defense only ever sees the round cohort (flat), one
        # megabatch / the shard-estimate matrix (hierarchical), or the
        # delivered sub-cohort (async).
        self._async = None
        self._hier_spmd = False
        if cfg.aggregation == "hierarchical":
            self._init_hierarchical()
        elif cfg.aggregation == "async":
            self._init_async()
        else:
            self._placement = None
            check_defense_args(cfg.defense, self.m, self.m_mal)
        if (getattr(self.attacker, "timed", False)
                and cfg.aggregation != "async"):
            raise ValueError(
                "a timed attack (attacks/backdoor.py "
                "TimedBackdoorAttack) games the async arrival schedule; "
                "it requires aggregation='async' — under synchronous "
                "topologies there is no arrival time to game")
        # Fault-injection subsystem (core/faults.py): None is the
        # zero-fault reference path — no fault state, no mask threading,
        # the compiled round program is bit-identical to the
        # pre-fault-subsystem one.
        if cfg.faults is not None and cfg.faults.enabled:
            from attacking_federate_learning_tpu.core.faults import (
                check_fault_support, fault_key
            )
            check_fault_support(cfg)
            self.faults = cfg.faults
            self._fault_key = fault_key(cfg)
        else:
            self.faults = None
        # Population & traffic engine (core/population.py): None is the
        # resident-cohort reference path — no registry, no schedule, no
        # arrival mask; the compiled round program is bit-identical to
        # the pre-population one.  The registry is LAZY: it holds
        # scalars only, so engine memory scales with the cohort m
        # however large cfg.traffic.population grows.
        if cfg.traffic is not None and cfg.traffic.enabled:
            from attacking_federate_learning_tpu.core.population import (
                PopulationRegistry, check_traffic_support, traffic_key
            )
            check_traffic_support(cfg)
            self.traffic = cfg.traffic
            self.registry = PopulationRegistry(cfg.traffic, self.n,
                                               self.f, cfg.seed)
            self._traffic_key = traffic_key(cfg)
            self._traffic_events = {}
            if cfg.aggregation not in ("hierarchical", "async"):
                # Ladder step 2: the bounds-valid fallback kernel,
                # ledgered as tier-1 like the configured defense.
                self._traffic_fallback_fn = stage_wrapped(
                    DEFENSES[cfg.traffic.fallback_defense],
                    "tier1_aggregate")
        else:
            self.traffic = None
            self.registry = None
        # Set by the flat _build_round_fns traffic branch only; its
        # None-ness is the run_span/run_round dispatch sentinel (hier
        # traffic is in-program slot resampling, no schedule operands).
        self._traffic_span = None
        self._part_key = jax.random.key(cfg.seed ^ 0x9A47)
        self._krum_select_fn = None  # set for Krum (selection telemetry)
        self.last_round_telemetry = None   # cfg.telemetry, per-round modes
        self.last_span_telemetry = None    # cfg.telemetry, fused spans
        self.defense_fn = DEFENSES[cfg.defense]
        if cfg.defense in ("Krum", "Bulyan"):
            self.defense_fn = self._wire_distance_defense(self.defense_fn)
        elif cfg.defense in ("TrimmedMean", "Median"):
            # Opt-in kernel routing (defenses/kernels.py:trimmed_mean
            # explains why the host kernel is not auto-dispatched; the
            # pallas suite is the same opt-in standard, ISSUE 11 —
            # config validation keeps the two exclusive).
            impl = (cfg.trimmed_mean_impl if cfg.defense == "TrimmedMean"
                    else cfg.median_impl)
            if cfg.aggregation_impl == "pallas":
                impl = "pallas"
            if impl != "xla":
                self.defense_fn = functools.partial(
                    self.defense_fn, impl=impl)
        elif cfg.defense == "DnC":
            # DnC's constants are config surface (the most constant-
            # sensitive defense), and its sketch keys flow from the
            # experiment seed so repeat runs with different seeds draw
            # different coordinate subsets (defenses/dnc.py).
            self.defense_fn = functools.partial(
                self.defense_fn, n_iters=cfg.dnc_iters,
                sketch_dim=cfg.dnc_sketch_dim,
                filter_frac=cfg.dnc_filter_frac, seed=cfg.seed)
            self.defense_fn.needs_round = True  # partial drops attributes
        elif cfg.defense == "GeoMedian":
            # Weiszfeld constants are config surface like the DnC knobs.
            self.defense_fn = functools.partial(
                self.defense_fn, iters=cfg.geomed_iters,
                eps=cfg.geomed_eps)
        elif cfg.defense == "CenteredClip":
            self.defense_fn = functools.partial(
                self.defense_fn, tau=cfg.cclip_tau,
                iters=cfg.cclip_iters)
        # Stage ledger (utils/costs.py): every op the tier-1 kernel
        # traces carries 'tier1_aggregate' metadata whatever the call
        # site (fused round, hier shard_fn, standalone cost entries).
        self.defense_fn = stage_wrapped(self.defense_fn,
                                        "tier1_aggregate")

        key = jax.random.key(cfg.seed)
        k_init, self.key_run = jax.random.split(key)
        params0 = self.model.init(k_init)
        self.flat = make_flattener(params0)
        self.state = init_server_state(self.flat.ravel(params0))
        if self.faults is not None and self._async is None:
            # Async rounds model stragglers as extra arrival delay
            # inside their own buffers (core/async_rounds.py) — the
            # sync fault ring never exists there.
            from attacking_federate_learning_tpu.core.faults import (
                init_fault_state, init_hier_fault_state
            )
            if self._placement is not None:
                # Hier ring: one (m, d) slab per shard per delay slot
                # (same total bytes as the flat full-participation
                # ring; empty pytree when stragglers are off).
                self._fault_state = init_hier_fault_state(
                    self.faults, self._placement.num_shards,
                    self._placement.megabatch, self.flat.dim)
            else:
                self._fault_state = init_fault_state(
                    self.faults, self.m, self.flat.dim)
        else:
            self._fault_state = None
        if self._async is not None:
            from attacking_federate_learning_tpu.core.async_rounds import (
                init_async_state
            )
            self._async_state = init_async_state(self._async, self.m,
                                                 self.flat.dim)
        else:
            self._async_state = None

        shards = make_shards(cfg.partition, self.dataset.train_y, self.n,
                             cfg.seed, cfg.dirichlet_alpha)
        self._streaming = cfg.data_placement == "host_stream"
        if self._streaming:
            # Beyond-HBM mode (SURVEY.md §7.3 #5): the training set stays
            # in host RAM; per-round batches are host-gathered and
            # double-buffered onto the device (data/stream.py).
            from attacking_federate_learning_tpu.data.stream import (
                HostStream
            )
            self.shards = shards                      # host numpy
            self.train_x = self.train_y = None
            self.stream = HostStream(self.dataset.train_x,
                                     self.dataset.train_y, shards,
                                     cfg.batch_size * cfg.local_steps,
                                     plan=shardings, n_rounds=cfg.epochs,
                                     participants_fn=self._participants_host,
                                     cohort_rows=self.m,
                                     prefetch=cfg.stream_prefetch,
                                     workers=cfg.stream_workers)
            if shardings is not None:
                self.state = shardings.place_state(self.state)
        else:
            self.shards = jnp.asarray(shards)
            self.train_x = jnp.asarray(self.dataset.train_x)
            self.train_y = jnp.asarray(self.dataset.train_y)
            if shardings is not None:
                self.shards, self.train_x, self.train_y, self.state = (
                    shardings.place(self.shards, self.train_x, self.train_y,
                                    self.state,
                                    replicate_shards=self._hier_spmd))

        # FEMNIST-style feature shift (SURVEY §7.2 M4): each client sees
        # the shared pool through its own affine transform a_i*x + b_i
        # (data/partition.py client_style_params).  Raw-data consumers,
        # deliberately: the global test set stays untransformed
        # (accuracy is measured on the common distribution) and the
        # backdoor attacker's shadow train reads the raw dataset (the
        # attacker controls its own pipeline).  Styled consumers: the
        # training batches below AND the metadata pool (collect_metadata
        # applies each contributor's transform — those samples model the
        # client's own view).
        if cfg.partition == "femnist_style":
            from attacking_federate_learning_tpu.data.partition import (
                client_style_params
            )
            a_sty, b_sty = client_style_params(self.n, cfg.style_strength,
                                               cfg.seed)
            self._style = (jnp.asarray(a_sty), jnp.asarray(b_sty))
        else:
            self._style = None

        # Reference parity: augmentation is part of the CIFAR100 train
        # pipeline only (reference data_sets.py:157-166); image-shaped
        # data required (the MNIST wire is flat).
        self._augment = (cfg.data_augment if cfg.data_augment is not None
                         else cfg.dataset == "CIFAR100")
        if self._augment and np.ndim(self.dataset.train_x) != 4:
            raise ValueError(
                f"data_augment needs (N, C, H, W) images, got "
                f"shape {np.shape(self.dataset.train_x)} for {cfg.dataset}")
        self._grad_dtype = jnp.dtype(cfg.grad_dtype)
        self._client_update = make_client_update_fn(self.model, self.flat,
                                                    cfg.local_steps,
                                                    remat=cfg.remat)
        self._needs_server_grad = getattr(self.defense_fn,
                                          "needs_server_grad", False)
        self.metadata = (self.collect_metadata()
                         if (cfg.collect_metadata
                             or self._needs_server_grad) else None)
        if self._needs_server_grad:
            # Validation-data defense (FLTrust): the server's own gradient
            # on the trusted metadata pool provides the trust anchor.
            self._meta_x = jnp.asarray(self.metadata[0])
            self._meta_y = jnp.asarray(self.metadata[1])
        self._build_round_fns()
        self.evaluate = make_eval_fn(self.model, self.flat,
                                     self.dataset.test_x, self.dataset.test_y,
                                     cfg.batch_size)

    # ------------------------------------------------------------------
    def _init_hierarchical(self):
        """Validate + plan the two-tier streaming round (ISSUE 6 /
        ROADMAP item 1; ops/federated.py, ARCHITECTURE.md "Hierarchical
        aggregation").

        The client axis lives inside a scanned device program, so every
        feature that needs the materialized (n, d) matrix — or a host
        hop per round — is rejected here rather than failing deep in a
        trace: partial participation (cohort sampling composes with
        placement in a follow-up), host streaming (one round per
        program by design), and the opt-in host kernels (a
        pure_callback per megabatch per scan step would marshal more
        than it saves).  Telemetry and round-stats are SUPPORTED
        (ISSUE 8): per-shard tier-1 diagnostics ride the scan as
        stacked fixed-shape pytrees — (S, m)-shaped, never
        (n,)-shaped, so the O(m·d) memory contract survives — and the
        tier-2 kernels emit their (S,)-shaped shard-selection record
        ('shard_selection' events, schema v6).  Fault injection is
        SUPPORTED (ISSUE 19): the per-client draw becomes a per-shard
        (m,) quarantine mask inside the scan step (mask-aware tier-1
        kernels unchanged), the straggler ring grows a shard axis
        ((delay, S, m, d) — sequential scan only,
        core/faults.py:check_fault_support rejects straggler ⊕ SPMD),
        and the correlated shard-DOMAIN axis (--fault-shard-dropout)
        kills whole megabatches at once, excluded at tier-2 via the
        alive_counts seam with a host-planned remask → fallback →
        hold ladder on the surviving-shard count."""
        cfg = self.cfg
        from attacking_federate_learning_tpu.defenses.kernels import (
            TIER2_DEFENSES, check_tier2_args
        )
        from attacking_federate_learning_tpu.ops.federated import (
            make_placement, tier1_assumed, tier2_assumed
        )

        if cfg.participation < 1.0:
            raise ValueError(
                "hierarchical aggregation requires full participation "
                "(placement assigns every client to a megabatch)")
        if cfg.data_placement != "device":
            raise ValueError(
                "hierarchical aggregation requires "
                "data_placement='device' (the scanned round gathers "
                "each megabatch's batch on device)")
        if cfg.backdoor and not cfg.backdoor_fused:
            raise ValueError(
                "hierarchical aggregation needs the fused backdoor "
                "path (drop --backdoor-staged)")
        if cfg.defense not in TIER2_DEFENSES:
            raise ValueError(
                f"hierarchical tier-1 defense must be one of "
                f"{sorted(TIER2_DEFENSES)} (the mask-aware kernel "
                f"set), got {cfg.defense!r}")
        if cfg.distance_impl in ("ring", "allgather", "host"):
            raise ValueError(
                f"hierarchical aggregation supports distance_impl in "
                f"auto/xla/pallas (got {cfg.distance_impl!r}): the "
                f"per-megabatch distance pass must stay inside the "
                f"scanned program")
        for knob in ("trimmed_mean_impl", "median_impl",
                     "bulyan_selection_impl", "bulyan_trim_impl"):
            if getattr(cfg, knob) == "host":
                # The pallas values stay INSIDE the scanned program
                # (ISSUE 11) and compose; only the host kernels would
                # pure_callback once per megabatch per scan step.
                raise ValueError(
                    f"hierarchical aggregation requires a device-"
                    f"resident {knob} ('xla' or 'pallas'; got 'host' — "
                    f"a host kernel would pure_callback once per "
                    f"megabatch per scan step)")

        self._placement = make_placement(self.n, self.f, cfg.megabatch,
                                         cfg.mal_placement)
        # SPMD tier-1 (ISSUE 12): a mesh whose clients axis holds > 1
        # device maps the megabatch axis onto it — each device scans
        # its own megabatches, tier-2 reads one explicit all_gather.
        # The schedule is validated NOW (S % clients axis, loudly)
        # rather than deep in a trace; a 1-device clients axis keeps
        # the sequential scan, byte-identical HLO included.
        if self.shardings is not None:
            from attacking_federate_learning_tpu.ops.federated import (
                spmd_schedule
            )
            from attacking_federate_learning_tpu.parallel.mesh import (
                CLIENTS
            )

            parts = self.shardings.mesh.shape[CLIENTS]
            if parts > 1:
                spmd_schedule(self._placement, parts)
                self._hier_spmd = True
        S = self._placement.num_shards
        self._tier1_f = (cfg.tier1_corrupted
                         if cfg.tier1_corrupted is not None
                         else tier1_assumed(self.f, S))
        self._tier2_f = (cfg.tier2_corrupted
                         if cfg.tier2_corrupted is not None
                         else tier2_assumed(self.f, cfg.megabatch))
        self._tier2_name = cfg.tier2_defense or cfg.defense
        # Same validity bounds per tier that the flat path checks once.
        check_tier2_args(cfg.defense, cfg.megabatch, self._tier1_f)
        check_tier2_args(self._tier2_name, S, self._tier2_f)
        # Stage ledger: the tier-2 shard reduction carries its own
        # taxonomy stage, distinct from the per-shard tier-1 kernel.
        self._tier2_fn = stage_wrapped(TIER2_DEFENSES[self._tier2_name],
                                       "tier2_aggregate")

    # ------------------------------------------------------------------
    def _init_async(self):
        """Validate + plan the FedBuff-style buffered round (ISSUE 9 /
        ROADMAP item 4; core/async_rounds.py, ARCHITECTURE.md
        "Asynchronous rounds").

        Arrival, buffering and staleness weighting all live inside the
        fused round program, so everything that needs a host hop per
        round — or a defense without the mask/weight seam — is
        rejected here, loudly, rather than failing deep in a trace:
        staged attacks, host kernels, partial participation (the ring
        and pending pool are indexed by cohort row), host streaming.
        secagg ⊕ async is structurally rejected at config time
        (vanilla requires flat, groupwise requires hierarchical).
        Faults COMPOSE: dropout = the update is never submitted,
        straggler = extra arrival delay, corrupt = damage in flight
        (core/async_rounds.py:draw_delays)."""
        cfg = self.cfg
        from attacking_federate_learning_tpu.core.async_rounds import (
            AsyncSpec, async_key, check_async_support
        )

        check_async_support(cfg)
        if not getattr(self.attacker, "fusable", True):
            raise ValueError(
                "--aggregation async needs a fusable attack: delivery, "
                "staleness weighting and the attack seam live inside "
                "the fused round program")
        self._placement = None
        if cfg.async_buffer > self.m:
            raise ValueError(
                f"--async-buffer {cfg.async_buffer} exceeds the cohort "
                f"(m={self.m}): the FedBuff trigger would never fire — "
                f"the pending pool holds at most one update per client")
        # A delivered async round aggregates EXACTLY k rows (the
        # FedBuff trigger), so the defense validity bounds apply at
        # n=k with the full f colluders assumed delivered — the
        # worst-case cohort a timed attack can arrange.
        try:
            check_defense_args(cfg.defense, cfg.async_buffer, self.m_mal)
        except ValueError as e:
            raise ValueError(
                f"--aggregation async aggregates exactly "
                f"k=--async-buffer rows per applied round, so the "
                f"defense bound applies at n=k: {e}") from e
        if (cfg.defense == "TrimmedMean"
                and cfg.async_buffer - self.m_mal - 1 < 1):
            raise ValueError(
                f"--aggregation async TrimmedMean keeps "
                f"k - f - 1 rows per applied round; got "
                f"k={cfg.async_buffer}, f={self.m_mal} — raise "
                f"--async-buffer")
        self._async = AsyncSpec(
            buffer=cfg.async_buffer,
            max_staleness=cfg.async_max_staleness,
            weighting=cfg.staleness_weight,
            timed=bool(getattr(self.attacker, "timed", False)))
        self._async_key = async_key(cfg)

    # ------------------------------------------------------------------
    def _wire_distance_defense(self, fn):
        """Bind scoring/distance-engine knobs onto a Krum/Bulyan kernel.

        'auto' stays UNRESOLVED in the wired partial: the kernels resolve
        it per call (defenses/kernels.py:resolve_distance_impl) — 'xla'
        for traced operands (a host round-trip inside the fused round
        program would pay a pure_callback marshal of the whole (n, d)
        matrix every round), and host BLAS for eager CPU-backend calls,
        which is exactly what the staged path's eager aggregation feeds
        it (_build_round_fns).  'ring'/'allgather' precompute the
        distance matrix with the blockwise shard_map kernels
        (parallel/distances.py) over the clients mesh axis and hand it
        to the kernel via its ``D=`` seam."""
        from attacking_federate_learning_tpu.defenses.kernels import (
            krum_select
        )

        cfg = self.cfg
        pallas_suite = cfg.aggregation_impl == "pallas"
        kw = {"method": cfg.krum_scoring_method}
        if cfg.krum_paper_scoring:
            kw["paper_scoring"] = True
        if cfg.distance_dtype != "float32":
            kw["distance_dtype"] = cfg.distance_dtype
        if cfg.defense == "Krum" and pallas_suite:
            # The fused distance->score kernel (ops/pallas_defense.py):
            # scores in one sweep, no (n, n) matrix, the topk-class
            # cancellation guard applied inside the dispatch.
            kw["scores_impl"] = "pallas"
        if cfg.defense == "Bulyan":
            if cfg.bulyan_batch_select != 1:
                kw["batch_select"] = cfg.bulyan_batch_select
            sel = cfg.bulyan_selection_impl
            if pallas_suite and sel == "xla":
                sel = "pallas"
            if sel != "xla":
                # 'host': hybrid exact selection — device distances, one
                # (n, n) D marshal, native host selection, device
                # trim-mean.  'pallas': the all-on-device exact route —
                # pallas D, traced selection loop, no marshal.
                kw["selection_impl"] = sel
            trim = "pallas" if pallas_suite else cfg.bulyan_trim_impl
            if trim != "xla":
                kw["trim_impl"] = trim
        impl = cfg.distance_impl
        if impl in ("ring", "allgather"):
            if self.shardings is None:
                raise ValueError(
                    f"distance_impl={impl!r} needs a device mesh — set "
                    f"mesh_shape (parallel/distances.py kernels are "
                    f"shard_map programs over the clients axis)")
            from attacking_federate_learning_tpu.parallel.distances import (
                pairwise_distances_allgather, pairwise_distances_ring
            )
            from attacking_federate_learning_tpu.parallel.mesh import CLIENTS
            dist_fn = {"ring": pairwise_distances_ring,
                       "allgather": pairwise_distances_allgather}[impl]
            mesh = self.shardings.mesh
            p = mesh.shape[CLIENTS]
            if self.m % p != 0:
                # shard_map's P('clients', None) in_spec needs even rows —
                # the kernels see the round cohort (m), not the population
                # (unlike the xla path, where GSPMD pads unevenly).
                raise ValueError(
                    f"distance_impl={impl!r} needs the round cohort "
                    f"divisible by the clients mesh axis (m={self.m}, "
                    f"axis={p})")

            # Blockwise tiles share cross_sq_distances, so bf16 operands
            # ride the MXU inside the shard_map too (f32 accumulation).
            dist_dtype = jnp.dtype(cfg.distance_dtype)

            def with_blockwise_D(grads, n, f, _fn=fn, **extra):
                extra.pop("distance_dtype", None)  # D is precomputed
                D = dist_fn(grads.astype(dist_dtype), mesh)
                return _fn(grads, n, f, D=D, **extra)

            if cfg.defense == "Krum":
                self._krum_select_fn = functools.partial(
                    with_blockwise_D, _fn=krum_select, **kw)
            return functools.partial(with_blockwise_D, **kw)
        kw["distance_impl"] = impl
        if cfg.defense == "Krum":
            # Selection telemetry shares the defense's exact knobs, so the
            # reported winner IS the aggregated client (round_diagnostics).
            self._krum_select_fn = functools.partial(krum_select, **kw)
        return functools.partial(fn, **kw)

    # ------------------------------------------------------------------
    def collect_metadata(self):
        """Metadata subsystem (reference C12, SURVEY.md §2 — vestigial
        there): every client contributes a stratified ~metadata_fraction
        sample of its first batch (reference user.py:63-66,
        train_test_split(test_size=0.11, stratify=y)); the server
        concatenates them (server.py:62-77).  Returns (meta_x, meta_y) —
        the validation pool a FLTrust/Zeno-style defense can consume."""
        cfg = self.cfg
        shards = np.asarray(self.shards)
        xs = np.asarray(self.dataset.train_x)
        ys = np.asarray(self.dataset.train_y)
        rng = np.random.default_rng(cfg.seed + 42)
        meta_x, meta_y = [], []
        for i in range(self.n):
            batch = shards[i, : cfg.batch_size]
            labels = ys[batch]
            take = max(1, int(round(cfg.metadata_fraction * len(batch))))
            # Stratified: sample each label proportionally.
            picked = []
            for c in np.unique(labels):
                pool = batch[labels == c]
                k = max(1, int(round(take * len(pool) / len(batch))))
                picked.extend(rng.choice(pool, size=min(k, len(pool)),
                                         replace=False).tolist())
            picked = np.asarray(picked[:take], np.int64)
            x_i = xs[picked]
            if self._style is not None:
                # Contributed samples are the client's OWN view of the
                # data: under femnist_style they carry that client's
                # a_i*x + b_i transform, exactly like its training
                # inputs — otherwise a FLTrust-style consumer would
                # score honest styled gradients against an unstyled
                # reference distribution no client actually has.
                a, b = self._style
                x_i = np.float32(a[i]) * x_i + np.float32(b[i])
            meta_x.append(x_i)
            meta_y.append(ys[picked])
        return np.concatenate(meta_x), np.concatenate(meta_y)

    def get_metadata(self):
        """Reference server.get_MetaData (server.py:58-59)."""
        return self.metadata

    # ------------------------------------------------------------------
    def _maybe_augment(self, xs, t):
        """In-program train-time augmentation where the reference pipeline
        has one (CIFAR100, data/augment.py)."""
        if self._augment:
            from attacking_federate_learning_tpu.data.augment import (
                reflect_crop_flip, round_augment_key
            )
            xs = reflect_crop_flip(xs, round_augment_key(self.cfg.seed, t))
        return xs

    def _apply_style(self, xs, participants):
        """Per-client affine style transform ('femnist_style' partition):
        row i of the cohort batch becomes a_i*xs_i + b_i — one fused
        broadcast multiply-add inside the round program, so the feature
        shift costs nothing extra on device."""
        if self._style is None:
            return xs
        a, b = self._style
        if participants is not None:
            a, b = a[participants], b[participants]
        shape = (xs.shape[0],) + (1,) * (xs.ndim - 1)
        return a.reshape(shape) * xs + b.reshape(shape)

    def _participants(self, t):
        """Round-t cohort ids, or None under full participation: the
        first m_mal entries are malicious ids (< f), the rest honest —
        random identities, static counts (config.participation).  The
        draw itself lives in core/population.py:legacy_cohort — the
        population sampler's uniform-reliability compat profile,
        relocated verbatim so it stays bit-compatible with every
        pre-population run (tests/test_traffic.py pins it)."""
        if self.cfg.participation >= 1.0:
            return None
        from attacking_federate_learning_tpu.core.population import (
            legacy_cohort
        )
        return legacy_cohort(self._part_key, t, self.n, self.f, self.m,
                             self.m_mal)

    def _participants_host(self, t):
        """Eager host-side cohort for the streaming prefetcher: jax's RNG
        is platform-invariant, so running the same derivation on the CPU
        backend yields exactly the traced path's ids without queueing a
        tiny program behind the accelerator's in-flight round."""
        if self.cfg.participation >= 1.0:
            return None
        try:
            cpu = jax.devices("cpu")[0]
        except RuntimeError:
            return np.asarray(self._participants(t))
        with jax.default_device(cpu):
            return np.asarray(self._participants(t))

    def _gather_batches(self, t, participants=None):
        """Round-t minibatches for the round cohort: one (m, k*B) gather
        from the device-resident dataset (replaces the reference's N
        host-side DataLoaders, user.py:52-55); k = local_steps (1 in the
        reference's FedSGD regime)."""
        shards = (self.shards if participants is None
                  else self.shards[participants])
        idx = round_batch_indices(
            shards, t, self.cfg.batch_size * self.cfg.local_steps)
        return self.train_x[idx], self.train_y[idx]

    def _compute_grads_impl(self, state: ServerState, t, batches=None,
                            part=None):
        """batches=None gathers from the device-resident dataset; the
        host-streaming mode (cfg.data_placement='host_stream') passes the
        round's pre-transferred (xs, ys) instead.  ``part`` pre-empts
        the participation draw with explicit (m,) cohort ids — the
        traffic engine's host-sampled shard archetypes (a population
        client materializes as its archetype's data shard + style;
        core/population.py).

        Stage ledger: everything here is the ``deliver`` stage — batch
        delivery + client update, the cohort's gradients arriving at
        tier 1 (utils/costs.py:STAGES; metadata-only annotation)."""
        cfg = self.cfg
        with stage_scope("deliver"):
            if batches is None:
                if part is None:
                    part = self._participants(t)
                xs, ys = self._gather_batches(t, part)
            else:
                xs, ys = batches
                # The streaming prefetcher derives the identical cohort
                # ids (platform-invariant RNG, _participants_host), so
                # re-deriving here keeps the style rows aligned with the
                # streamed batch.
                part = (self._participants(t) if self._style is not None
                        else None)
            xs = self._apply_style(xs, part)
            xs = self._maybe_augment(xs, t)
            # Split the flat (m, k*B) gather into k local-step
            # minibatches.
            k, B = cfg.local_steps, cfg.batch_size
            xs = xs.reshape((self.m, k, B) + xs.shape[2:])
            ys = ys.reshape((self.m, k, B))
            # Clients train at the faded lr the server dispatches
            # (reference server.py:50-52; inert at k=1, user.py:80); the
            # pseudo-gradient divides by the lr the server will multiply
            # back in so the FedAvg reduction is exact under the
            # constant-server-lr quirk.
            lr_train = faded_learning_rate(cfg.learning_rate,
                                           cfg.fading_rate, t)
            lr_report = (lr_train if cfg.server_uses_faded_lr
                         else cfg.learning_rate)
            grads = self._client_update(state.weights, xs, ys, lr_train,
                                        lr_report)
            grads = grads.astype(self._grad_dtype)  # bf16 halves HBM
            if self.shardings is not None:
                grads = self.shardings.constrain_grads(grads)
        return grads

    def _aggregate_impl(self, state: ServerState, grads, t, agg=None,
                        telemetry=False, margins=False, numerics=False,
                        mask=None, weights=None, action=None):
        """``agg`` pre-empts the defense call — the Krum-telemetry round
        computes the selection once and aggregates ``grads[sel]`` rather
        than running the O(n^2 d) distance engine twice.  ``telemetry``
        (static bool) asks the defense for its diagnostics pytree and
        returns ``(new_state, diag)`` instead of ``new_state``.
        ``mask``: the quarantine effective-cohort mask (core/faults.py),
        threaded into the mask-aware defense kernels; None (the
        no-fault path) leaves the defense call byte-identical.
        ``weights``: the async staleness weights riding the same seam
        (core/async_rounds.py; requires ``mask``).
        ``action``: the traffic watchdog's per-round ladder decision
        (core/population.py, () int32).  Both the configured defense and
        the bounds-valid fallback are always computed and jnp.where
        selects — identical pytree either way, and a NaN in the
        unselected branch cannot propagate through the select.  HOLD is
        applied at the state level after the update (FedBuff-style
        no-op, the async empty-delivery pattern)."""
        ddiag = {}
        if agg is None:
            # Stage ledger: the defense kernel (server_grad included —
            # FLTrust's trust anchor is part of the tier-1 decision) is
            # the ``tier1_aggregate`` stage.
            with stage_scope("tier1_aggregate"):
                kw = {}
                if mask is not None:
                    kw["mask"] = mask
                if weights is not None:
                    kw["weights"] = weights
                if getattr(self.defense_fn, "needs_round", False):
                    # Round-seeded defenses (DnC's fresh sketches) — the
                    # same attribute seam FLTrust uses for
                    # needs_server_grad.
                    kw["round"] = t
                if self._needs_server_grad:
                    server_grad = jax.grad(
                        make_loss_fn(self.model, self.flat))(
                        state.weights, self._meta_x, self._meta_y)
                    kw["server_grad"] = server_grad
                if telemetry:
                    if margins:
                        # Trace-time flag like telemetry itself; only
                        # the margin-bearing kernels accept it (config
                        # gates --margins to exactly those), so the
                        # kwarg is only ever passed when True.
                        kw["margins"] = True
                    if numerics:
                        # Kernel tie/cancellation counters ride the
                        # margin tensors (check_numerics_seam) — the
                        # engine passes margins=True alongside and
                        # filters margin fields back out when
                        # --margins itself is off.
                        kw["numerics"] = True
                    agg, ddiag = self.defense_fn(
                        grads, self.m, self.m_mal, telemetry=True, **kw)
                else:
                    agg = self.defense_fn(grads, self.m, self.m_mal, **kw)
                if action is not None:
                    from attacking_federate_learning_tpu.core.population \
                        import TRAFFIC_FALLBACK
                    fb_kw = {k: kw[k] for k in ("mask", "weights")
                             if k in kw}
                    fb = self._traffic_fallback_fn(
                        grads, self.m, self.m_mal, **fb_kw)
                    agg = jnp.where(action == TRAFFIC_FALLBACK, fb, agg)
        with stage_scope("apply"):
            agg = agg.astype(jnp.float32)
            if self.cfg.server_uses_faded_lr:
                lr = faded_learning_rate(self.cfg.learning_rate,
                                         self.cfg.fading_rate, t)
            else:
                # Reference parity: constant base lr on the server
                # (server.py:89, SURVEY.md §2.4 #7).
                lr = self.cfg.learning_rate
            new_state = momentum_update(state, agg, lr, self.cfg.momentum)
            if action is not None:
                from attacking_federate_learning_tpu.core.population \
                    import TRAFFIC_HOLD
                hold = action == TRAFFIC_HOLD
                new_state = ServerState(
                    weights=jnp.where(hold, state.weights,
                                      new_state.weights),
                    velocity=jnp.where(hold, state.velocity,
                                       new_state.velocity),
                    round=new_state.round)
        if telemetry:
            return new_state, ddiag
        return new_state

    def _build_round_fns(self):
        cfg = self.cfg
        if cfg.aggregation == "hierarchical":
            return self._build_hier_round_fns()
        if cfg.aggregation == "async":
            return self._build_async_round_fns()

        def ctx_for(state, t):
            return AttackContext(
                original_params=state.weights,
                learning_rate=faded_learning_rate(
                    cfg.learning_rate, cfg.fading_rate, t),
                round=t)

        self._ctx_for = ctx_for  # single construction site for the seam

        def round_diagnostics(grads, state_after, t, aux=None):
            """Per-round stats (SURVEY.md §5 rebuild item): client gradient
            norm spread, aggregate step norm, faded lr — plus, under Krum,
            which client won selection and whether it was malicious (the
            selection-histogram observability the reference lacks; ``aux``
            carries the selection the defense actually made).  Stage
            ledger: these riders observe the applied update — ``apply``."""
            with stage_scope("apply"):
                norms = jnp.linalg.norm(grads.astype(jnp.float32), axis=1)
                diag = {
                    "grad_norm_mean": jnp.mean(norms),
                    "grad_norm_max": jnp.max(norms),
                    "grad_norm_min": jnp.min(norms),
                    "update_norm": jnp.linalg.norm(state_after.velocity),
                    "faded_lr": faded_learning_rate(cfg.learning_rate,
                                                    cfg.fading_rate, t),
                }
                if aux and "krum_selected" in aux:
                    sel = aux["krum_selected"]
                    diag["krum_selected"] = sel
                    diag["malicious_selected"] = (sel < self.m_mal).astype(
                        jnp.int32)
            return diag

        self._round_diagnostics = round_diagnostics

        # In-program replacement for the reference's host-side shadow-train
        # nan guard (backdoor.py:145-152): track non-finiteness over the
        # crafted rows only (rows [0, f)) — matching the staged path's
        # isfinite check, which is strictly stronger than the reference's
        # isnan — so a diverging *server* update can't be misattributed to
        # the attack.  Skipped when no crafting happens (f == 0 or z == 0,
        # mirroring the reference's early returns, malicious.py:11, :21).
        # Fused spans surface the flag at the next host boundary (the
        # documented detection-latency trade, PARITY.md); --backdoor-staged
        # restores the per-round raise.
        self._check_attack_nan = (
            getattr(self.attacker, "checks_finite", False)
            and self.m_mal > 0
            and getattr(self.attacker, "num_std", 1) != 0)

        # Selection telemetry: compute the Krum winner ONCE and aggregate
        # grads[sel] (krum == grads[krum_select], defenses/kernels.py) —
        # the O(n^2 d) distance engine never runs twice per round.  With
        # full telemetry on, the defense itself returns its selection
        # mask from the same single distance computation, so the
        # pre-emption is unnecessary there.  Under fault injection the
        # pre-emption is off too: the selection depends on the
        # quarantine mask, and only the defense call carries it.
        diag_select = (self._krum_select_fn
                       if (cfg.log_round_stats and not cfg.telemetry
                           and not cfg.margins and not cfg.numerics
                           and self.faults is None
                           and self.traffic is None)
                       else None)

        # Kernel-side numerics (ISSUE 20): the tie/cancellation
        # counters band the margin tensors, so they exist only for the
        # margin-bearing defenses; the engine-level health counters
        # (nonfinite by stage, norm dynamic range) are defense-agnostic
        # and keyed off cfg.numerics alone.
        kernel_num = bool(cfg.numerics and cfg.defense in
                          ("Krum", "TrimmedMean", "Median", "Bulyan"))
        self._kernel_numerics = kernel_num

        def inject_and_quarantine(grads, t, fstate):
            """Fault seam (core/faults.py): inject the round-t faults
            into the submitted matrix, then mask/zero what the server
            can detect.  Returns the aggregable matrix, the effective-
            cohort mask, the new fault state and the per-round counts
            (fixed-shape scalars, keyed ``fault_*`` so they ride the
            telemetry plumbing into 'fault' events)."""
            from attacking_federate_learning_tpu.core.faults import (
                apply_faults, quarantine
            )
            with stage_scope("quarantine"):
                submitted, dropped, fstate2, fstats = apply_faults(
                    grads, t, self._fault_key, fstate, self.faults,
                    self.m_mal)
                clean, mask, qstats = quarantine(submitted, dropped)
            return clean, mask, fstate2, {**fstats, **qstats}

        self._inject_and_quarantine = inject_and_quarantine

        def attack_envelope(grads, state, t):
            """Pre-attack envelope stats (attacks/base.py seam), keyed
            ``attack_*`` into the telemetry pytree.  Stage ledger:
            observes the delivered/crafted matrix — ``deliver``."""
            with stage_scope("deliver"):
                stats = self.attacker.envelope_stats(grads, self.m_mal,
                                                     ctx_for(state, t))
            return {"attack_" + k: v for k, v in stats.items()}

        def attack_margins(pre, post, state, t):
            """Attack-side envelope utilization (attacks/base.py
            margin_stats; cfg.margins): computed on the PRE-attack
            matrix with the POST-attack (crafted) matrix riding along,
            keyed ``margin_attack_*`` so the emitter routes it into the
            'margin' event.  Stage ledger: ``deliver``."""
            with stage_scope("deliver"):
                stats = self.attacker.margin_stats(
                    pre, self.m_mal, ctx_for(state, t), crafted=post)
            return {"margin_attack_" + k: v for k, v in stats.items()}

        def finish_telemetry(tele, grads, ddiag):
            """Merge defense diagnostics + population stats into the
            round's telemetry pytree (all fixed-shape device arrays).
            Under margins-without-telemetry only the ``margin_*``
            defense fields ride out (``ddiag`` itself is untouched —
            the krum_selected aux still reads its selection mask).
            Stage ledger: defense forensics — ``tier1_aggregate``."""
            from attacking_federate_learning_tpu.defenses.kernels import (
                population_telemetry
            )
            with stage_scope("tier1_aggregate"):
                for k, v in ddiag.items():
                    # Three-way filter: margin fields ride iff
                    # --margins, num_ fields iff --numerics, the rest
                    # iff full telemetry — so a numerics-only run's
                    # margin carriers (check_numerics_seam forces the
                    # margins kwarg on) are dropped here and DCE'd out
                    # of the trace, and vice versa.
                    if k.startswith("margin_"):
                        if cfg.margins:
                            tele["defense_" + k] = v
                    elif k.startswith("num_"):
                        if cfg.numerics:
                            tele["defense_" + k] = v
                    elif cfg.telemetry:
                        tele["defense_" + k] = v
                if cfg.telemetry:
                    tele.update(population_telemetry(grads))
            return tele

        if self._secagg is not None:
            from attacking_federate_learning_tpu.protocols.secagg import (
                secagg_cohort
            )

            def secagg_step(agg_grads, mask, t):
                """Vanilla secure aggregation between the quarantine
                and the (NoDefense-only) aggregation: mask every
                submitted row in the uint32 bitcast domain, then
                recover + verify server-side (protocols/secagg.py).
                The recovered matrix is bit-identical to the clear one
                (dropped rows zeroed either way), so the downstream
                aggregate — and the whole run — is byte-for-byte the
                clear run's; the ``secagg_*`` stats ride the telemetry
                plumbing into per-round 'secagg' events."""
                return secagg_cohort(agg_grads, mask, self._secagg_key, t)

            self._secagg_step = secagg_step

        if getattr(self.attacker, "fusable", True):
            def fused_core(state, t, batches=None, fstate=None,
                           traffic=None):
                part = traffic[0] if traffic is not None else None
                grads = self._compute_grads_impl(state, t, batches,
                                                 part=part)
                tele = (attack_envelope(grads, state, t) if cfg.telemetry
                        else {})
                pre_attack = grads if cfg.margins else None
                with stage_scope("deliver"):
                    # Attack craft happens on the wire: what tier 1
                    # receives IS the crafted matrix.
                    grads = self.attacker.apply(grads, self.m_mal,
                                                ctx_for(state, t))
                if cfg.margins:
                    tele = {**tele,
                            **attack_margins(pre_attack, grads, state, t)}
                if cfg.numerics:
                    # Numeric health at the delivery seam: the crafted
                    # wire matrix, before any quarantine can mask a
                    # nonfinite row out of sight (utils/numerics.py).
                    with stage_scope("deliver"):
                        tele = {**tele,
                                "num_nonfinite_pre":
                                    nonfinite_count(grads),
                                "num_range_log2":
                                    norm_dynamic_range(grads)}
                # ``grads`` stays the post-attack, PRE-fault matrix from
                # here on (the nan guard must see what the attacker
                # crafted — a dropout zeroing a malicious row must not
                # hide a shadow-train nan); the defense aggregates the
                # quarantined ``agg_grads``.
                mask, agg_grads = None, grads
                if traffic is not None:
                    # Arrival quarantine: rows whose population client
                    # never arrived this round are zeroed and masked
                    # out of the defense (the same mask-aware seam the
                    # fault quarantine uses, core/population.py).
                    arrived = traffic[1]
                    with stage_scope("quarantine"):
                        agg_grads = jnp.where(
                            arrived[:, None], agg_grads,
                            jnp.zeros_like(agg_grads))
                    mask = arrived
                if self.faults is not None:
                    agg_grads, fmask, fstate, fstats = (
                        inject_and_quarantine(agg_grads, t, fstate))
                    mask = fmask if mask is None else (mask & fmask)
                    tele = {**tele, **fstats}
                if self._secagg is not None:
                    agg_grads, sstats = self._secagg_step(agg_grads,
                                                          mask, t)
                    tele = {**tele, **sstats}
                if cfg.numerics:
                    # Post-quarantine: what the defense actually
                    # aggregates (dead rows excluded by the mask).
                    with stage_scope("quarantine"):
                        tele = {**tele, "num_nonfinite_post":
                                nonfinite_count(agg_grads, mask=mask)}
                aux = {}
                act = traffic[2] if traffic is not None else None
                if cfg.telemetry or cfg.margins or kernel_num:
                    new_state, ddiag = self._aggregate_impl(
                        state, agg_grads, t, telemetry=True,
                        margins=cfg.margins or kernel_num,
                        numerics=kernel_num, mask=mask, action=act)
                    tele = finish_telemetry(tele, agg_grads, ddiag)
                    if (self._krum_select_fn is not None
                            and "selection_mask" in ddiag):
                        # Krum's mask is one-hot: its argmax IS the
                        # aggregated row (defenses/kernels.py:krum).
                        aux["krum_selected"] = jnp.argmax(
                            ddiag["selection_mask"]).astype(jnp.int32)
                else:
                    agg = None
                    if diag_select is not None:
                        sel = diag_select(grads, self.m, self.m_mal)
                        aux["krum_selected"] = sel
                        agg = grads[sel]
                    new_state = self._aggregate_impl(state, agg_grads, t,
                                                     agg=agg, mask=mask,
                                                     action=act)
                if cfg.numerics:
                    # Post-apply: a nonfinite velocity is the server
                    # update already poisoned, whatever the cohort
                    # counters said.
                    with stage_scope("apply"):
                        tele = {**tele, "num_nonfinite_agg":
                                nonfinite_count(new_state.velocity)}
                return new_state, grads, aux, tele, fstate

            def crafted_nonfinite(grads):
                with stage_scope("quarantine"):   # the fused nan guard
                    return (~jnp.isfinite(
                        grads[: self.m_mal].astype(jnp.float32))).any()

            if self.traffic is not None:
                def fused(state, t, sid, arrived, action, fstate=None):
                    """One traffic round: the host-sampled schedule row
                    (shard ids, arrival mask, ladder action) enters as
                    plain device operands — the compiled program never
                    sees the population, only the (m,) cohort."""
                    new_state, grads, aux, tele, fstate = fused_core(
                        state, t, None, fstate, (sid, arrived, action))
                    diag = (round_diagnostics(grads, new_state, t, aux)
                            if cfg.log_round_stats else {})
                    bad = (crafted_nonfinite(grads)
                           if self._check_attack_nan
                           else jnp.asarray(False))
                    return new_state, diag, bad, tele, fstate

                def traffic_span(state, t0, count, sids, arrs, acts,
                                 fstate=None):
                    # Traffic span: like fault_span (scan, static count)
                    # but each round consumes its row of the host-
                    # sampled schedule.  The carry threads only the
                    # fault state — the traffic schedule itself is
                    # stateless (pure in (traffic seed, t)), which is
                    # what makes preempt→resume bit-for-bit free.
                    def body(carry, xs):
                        s, bad, fs = carry
                        i, sid, arr, act = xs
                        s2, grads, _, tele, fs = fused_core(
                            s, t0 + i, None, fs, (sid, arr, act))
                        if self._check_attack_nan:
                            bad = bad | crafted_nonfinite(grads)
                        return (s2, bad, fs), tele

                    (s, bad, fs), stacked = jax.lax.scan(
                        body, (state, jnp.asarray(False), fstate),
                        (jnp.arange(count), sids, arrs, acts))
                    return s, bad, fs, stacked
            elif self.faults is None:
                def fused(state, t, batches=None):
                    new_state, grads, aux, tele, _ = fused_core(state, t,
                                                                batches)
                    diag = (round_diagnostics(grads, new_state, t, aux)
                            if cfg.log_round_stats else {})
                    bad = (crafted_nonfinite(grads)
                           if self._check_attack_nan
                           else jnp.asarray(False))
                    return new_state, diag, bad, tele
            else:
                def fused(state, t, fstate, batches=None):
                    new_state, grads, aux, tele, fstate = fused_core(
                        state, t, batches, fstate)
                    diag = (round_diagnostics(grads, new_state, t, aux)
                            if cfg.log_round_stats else {})
                    bad = (crafted_nonfinite(grads)
                           if self._check_attack_nan
                           else jnp.asarray(False))
                    return new_state, diag, bad, tele, fstate

            def fused_span(state, t0, count):
                # One device program for `count` rounds: steady-state
                # training between evals never returns to the host
                # (the reference makes 3N+2 host->object calls per round,
                # main.py:66-71).  count is a traced operand (fori_loop),
                # so every span length shares one compilation.
                def body(i, carry):
                    s, bad = carry
                    s2, grads, _, _, _ = fused_core(s, t0 + i)
                    if self._check_attack_nan:
                        bad = bad | crafted_nonfinite(grads)
                    return s2, bad

                return jax.lax.fori_loop(0, count, body,
                                         (state, jnp.asarray(False)))

            def tele_span(state, t0, count):
                # Telemetry span: lax.scan stacks each round's telemetry
                # pytree along a leading round axis, so `count` rounds
                # still run as ONE device program and the host fetches
                # the stack once per eval interval — no callbacks inside
                # the jit.  The stacked output's leading dim forces
                # `count` static (one compilation per distinct span
                # length; the eval cadence yields at most two).
                def body(carry, i):
                    s, bad = carry
                    s2, grads, _, tele, _ = fused_core(s, t0 + i)
                    if self._check_attack_nan:
                        bad = bad | crafted_nonfinite(grads)
                    return (s2, bad), tele

                (s, bad), stacked = jax.lax.scan(
                    body, (state, jnp.asarray(False)), jnp.arange(count))
                return s, bad, stacked

            def fault_span(state, t0, count, fstate):
                # Fault span: like tele_span (scan, static count, one
                # program per eval/checkpoint interval) but the carry
                # additionally threads the fault state (the straggler
                # ring buffer), and the stacked per-round pytree always
                # carries at least the 'fault_*' counts — fault events
                # are emitted per round whether or not cfg.telemetry.
                def body(carry, i):
                    s, bad, fs = carry
                    s2, grads, _, tele, fs = fused_core(s, t0 + i, None,
                                                        fs)
                    if self._check_attack_nan:
                        bad = bad | crafted_nonfinite(grads)
                    return (s2, bad, fs), tele

                (s, bad, fs), stacked = jax.lax.scan(
                    body, (state, jnp.asarray(False), fstate),
                    jnp.arange(count))
                return s, bad, fs, stacked

            donate = self._donate_kw()
            if self.traffic is not None:
                # Traffic paths never donate (the fault-path rationale:
                # stacked-scan outputs + schedule operands add aliasing
                # surface the CPU donation distrust already covers).
                self._fused_round = jax.jit(fused)
                self._traffic_span = jax.jit(traffic_span,
                                             static_argnums=2)
            elif self.faults is None:
                self._fused_round = jax.jit(fused, **donate)
                self._fused_span = jax.jit(fused_span, **donate)
                self._tele_span = jax.jit(tele_span, static_argnums=2,
                                          **donate)
            else:
                # The fault paths never donate (any backend): the fault
                # state rides the carry and the stacked-scan outputs add
                # aliasing surface beyond what _donate_kw's CPU rationale
                # already distrusts.
                self._fused_round = jax.jit(fused)
                self._fault_span = jax.jit(fault_span, static_argnums=2)
            self._staged = False
        else:
            if self.traffic is not None:
                # Config already rejects --backdoor-staged + traffic;
                # this catches a non-fusable attacker handed in
                # programmatically (same seam as the pallas check below).
                raise ValueError(
                    "the traffic engine requires a fusable attack (the "
                    "staged host-eager path has no arrival seam)")
            if (cfg.aggregation_impl == "pallas"
                    or cfg.bulyan_selection_impl == "pallas"):
                # Config already rejects --backdoor-staged ⊕ pallas;
                # this catches a non-fusable attacker handed in
                # programmatically (same seam as the secagg check).
                raise ValueError(
                    "the staged (host-eager) aggregation path does not "
                    "run the Pallas defense suite "
                    "(aggregation_impl/bulyan_selection_impl='pallas' "
                    "need a fusable attack)")
            self._compute_grads = jax.jit(self._compute_grads_impl)
            # Staged rounds already cross the host boundary every round,
            # so on the CPU backend a Krum/Bulyan aggregation runs EAGERLY:
            # the kernel then sees concrete arrays and 'auto' resolves to
            # the host BLAS engine zero-copy (defenses/host.py) instead of
            # paying XLA:CPU's ~2x gemm penalty inside jit (measured in
            # BASELINE.md).  Everything else keeps the jitted aggregate.
            # (Not under a device mesh: the jitted aggregate preserves the
            # MeshPlan state placement; the eager path would silently
            # un-place state and gather the sharded matrix every round.)
            eager_host_agg = (jax.default_backend() == "cpu"
                              and self.shardings is None
                              and cfg.defense in ("Krum", "Bulyan")
                              and cfg.distance_impl in ("auto", "host")
                              # The host engines have no mask seam
                              # (core/faults.py): under fault injection
                              # the jitted aggregate resolves 'auto' to
                              # 'xla' and threads the quarantine mask.
                              and self.faults is None
                              # Margins (and the numerics counters that
                              # band them) read the on-device scores;
                              # the eager host engines never return
                              # them.
                              and not (cfg.margins or kernel_num))
            self._aggregate = (self._aggregate_impl if eager_host_agg
                               else jax.jit(self._aggregate_impl,
                                            **self._donate_kw()))
            if self.faults is not None:
                # Staged rounds cross the host every round anyway; the
                # fault seam runs as its own small jitted step between
                # the (host) attack craft and the aggregation.
                self._fault_step = jax.jit(inject_and_quarantine)
            if cfg.telemetry or cfg.margins or kernel_num:
                # telemetry is a trace-time (static) flag, so the
                # telemetry aggregate is its own jitted function
                # (margins and the kernel numerics counters ride the
                # same diagnostics pytree).
                agg_tele = functools.partial(self._aggregate_impl,
                                             telemetry=True,
                                             margins=(cfg.margins
                                                      or kernel_num),
                                             numerics=kernel_num)
                self._aggregate_tele = (agg_tele if eager_host_agg
                                        else jax.jit(
                                            agg_tele,
                                            **self._donate_kw()))
            self._staged = True
        self._attack_envelope = attack_envelope
        self._attack_margins = attack_margins
        self._finish_telemetry = finish_telemetry

    # ------------------------------------------------------------------
    def _build_hier_round_fns(self):
        """Two-tier streaming round (cfg.aggregation='hierarchical').

        The round is the three federated primitives of ops/federated.py
        composed inside one jit: ``broadcast`` (the server weights ride
        the scan closure), ``client_map`` (a ``lax.scan`` over
        megabatches of ``cfg.megabatch`` clients — gather that
        megabatch's minibatch, compute its gradients, run the attack
        seam on ITS malicious rows, reduce it to one tier-1 robust
        estimate with the unchanged flat kernel), and ``shard_reduce``
        (the tier-2 shard_* kernel over the (n/m, d) estimate matrix).
        The full (n, d) gradient matrix and the (n, n) distance matrix
        never exist: XLA reuses one megabatch's buffers across scan
        steps, so peak round memory is O(m·d) (tools/perf_gate.py
        ``--memproof`` pins it at the 10k north star).

        ATTACK-SEAM SEMANTICS CHANGE (documented contract of the flag):
        ``Attack.craft`` runs once per megabatch and sees only that
        megabatch's malicious rows — ALIE-style cohort statistics are
        per-megabatch envelopes, and under ``mal_placement='spread'``
        each crafted vector is estimated from ~f/S rows instead of f.
        Augmentation keys are per-round (like the flat path), so crop/
        flip draws repeat across megabatches at equal row positions —
        an accepted, documented deviation (CIFAR100 only).

        Spans fuse exactly like the flat path: ``run_span`` drives the
        same ``_fused_round`` / ``_fused_span`` entry points (cost
        ledger names ``hier_round`` / ``hier_span``), and the nan guard
        ORs each megabatch's crafted-rows isfinite flag."""
        cfg = self.cfg
        from attacking_federate_learning_tpu.ops.federated import (
            client_map, shard_reduce
        )

        place = self._placement
        m = place.megabatch
        f1, f2, S = self._tier1_f, self._tier2_f, place.num_shards
        tier2_fn = self._tier2_fn

        def ctx_for(state, t):
            return AttackContext(
                original_params=state.weights,
                learning_rate=faded_learning_rate(
                    cfg.learning_rate, cfg.fading_rate, t),
                round=t)

        self._ctx_for = ctx_for
        if not getattr(self.attacker, "fusable", True):
            raise ValueError(
                "hierarchical aggregation needs a fusable attack: the "
                "client axis lives inside a scanned device program")
        # Same predicate as the flat path (the in-program shadow-train
        # nan guard), evaluated per megabatch over ITS crafted rows.
        self._check_attack_nan = (
            getattr(self.attacker, "checks_finite", False)
            and self.m_mal > 0
            and getattr(self.attacker, "num_std", 1) != 0)

        groupwise = self._secagg == "groupwise"
        if groupwise:
            from attacking_federate_learning_tpu.protocols.secagg import (
                secagg_group
            )
        if groupwise and cfg.telemetry:
            from attacking_federate_learning_tpu.protocols.secagg import (
                group_envelope_stats
            )
        tele_on = cfg.telemetry
        # Margins ride the same diagnostics seam at both tiers
        # (shard_fn asks the tier-1 kernel, hier_core the tier-2 one);
        # groupwise secagg is structurally margin-free (config pins
        # the defense to NoDefense there, which --margins rejects).
        marg_on = cfg.margins
        # Numerics ride the same two-tier seam (ISSUE 20): per-shard
        # kernel tie counters stack into shard_num_*, the tier-2
        # reduction's into tier2_num_*; groupwise secagg pins
        # NoDefense, whose kernels accept-and-ignore the flag.
        num_on = cfg.numerics
        # Per-client gradient norms are observable only in the CLEAR
        # hierarchical modes: under groupwise secagg the server sees
        # group sums, not rows, so the shard norm stack (and the
        # round-stats gradient-norm triple) would read a tensor the
        # threat model says the server never holds.
        want_norms = ((tele_on or cfg.log_round_stats)
                      and not groupwise)
        # Any extra per-shard output switches shard_fn to the dict
        # pytree; with everything off the return structure (and the
        # traced program) is byte-for-byte the pre-telemetry tuple.
        extras = tele_on or cfg.log_round_stats or marg_on or num_on

        def keep_diag(k):
            # The hier twin of the flat engine's three-way telemetry
            # filter: margin fields ride iff --margins, num_ fields
            # iff --numerics, everything else iff full telemetry.
            if k.startswith("margin_"):
                return marg_on
            if k.startswith("num_"):
                return num_on
            return tele_on

        def megabatch_grads(ids, c_mal, state, t):
            """Deliver + train + attack for one megabatch — the shared
            front half of the clear and faulted scan steps (a Python
            extraction, not a trace change: the fault seam only ever
            APPENDS ops after it, so the faults=None program is
            byte-identical).  Returns the crafted (m, d) matrix and
            the megabatch's nan flag."""
            if self.traffic is not None:
                # Hier traffic = in-program slot resampling only: each
                # megabatch slot re-draws its population archetype per
                # round (pure in (traffic key, t, shard identity) —
                # core/population.py).  Rounds stay full; the ladder
                # and churn accounting are flat/async-engine features
                # (composition matrix, ARCHITECTURE.md).
                from attacking_federate_learning_tpu.core.population \
                    import resample_slots
                ids = resample_slots(self._traffic_key, t, ids, c_mal,
                                     self.f, self.n)
            with stage_scope("deliver"):
                shard_rows = self.shards[ids]
                idx = round_batch_indices(
                    shard_rows, t, cfg.batch_size * cfg.local_steps)
                xs, ys = self.train_x[idx], self.train_y[idx]
                xs = self._apply_style(xs, ids)
                xs = self._maybe_augment(xs, t)
                k, B = cfg.local_steps, cfg.batch_size
                xs = xs.reshape((m, k, B) + xs.shape[2:])
                ys = ys.reshape((m, k, B))
                lr_train = faded_learning_rate(cfg.learning_rate,
                                               cfg.fading_rate, t)
                lr_report = (lr_train if cfg.server_uses_faded_lr
                             else cfg.learning_rate)
                grads = self._client_update(state.weights, xs, ys,
                                            lr_train, lr_report)
                grads = grads.astype(self._grad_dtype)
                if self.shardings is not None and not self._hier_spmd:
                    # Under the SPMD client_map the body is device-local
                    # code inside shard_map — a global sharding
                    # constraint has no meaning there (the megabatch
                    # grid IS the sharded operand).
                    grads = self.shardings.constrain_grads(grads)
                grads = self.attacker.apply(grads, c_mal,
                                            ctx_for(state, t))
            with stage_scope("quarantine"):   # the fused nan guard
                bad = (
                    (~jnp.isfinite(
                        grads[:c_mal].astype(jnp.float32))).any()
                    if (self._check_attack_nan and c_mal > 0)
                    else jnp.asarray(False))
            return grads, bad

        def shard_fn(ids, c_mal, state, t):
            """One megabatch: ids (m,) client ids (malicious first —
            the per-megabatch mirror of the rows-[0, f) invariant),
            c_mal its STATIC malicious count.  Returns the (d,) f32
            tier-1 estimate and the megabatch's nan flag (plus, under
            groupwise secagg, the group's bitwise sum-check verdict).
            With telemetry/round-stats on it returns a dict pytree
            carrying the tier-1 diagnostics (``diag`` — the flat
            kernel's telemetry on THIS shard's sub-matrix, stacked by
            client_map into the (S, ...) shard_selection record) and,
            in the clear modes, the per-row gradient norms."""
            grads, bad = megabatch_grads(ids, c_mal, state, t)
            if groupwise:
                # NET-SA composition: the group's rows are secure-
                # aggregated (masks keyed on these GLOBAL client ids,
                # protocols/secagg.py) and the server sees only the
                # group sum — the tier-1 "defense" is the masked mean
                # (cfg.defense is pinned to NoDefense at config time),
                # bit-identical to the clear tier-1 mean, so the
                # tier-2 robust pass over group sums is byte-for-byte
                # the plain hierarchical NoDefense tier's.
                grads, sum_ok = secagg_group(grads, self._secagg_key,
                                             t, ids)
                if not extras:
                    est = self.defense_fn(grads, m, f1)
                    return est.astype(jnp.float32), bad, sum_ok
                out = {"bad": bad, "sum_ok": sum_ok}
                if tele_on:
                    # NoDefense tier-1 (config-enforced under secagg)
                    # has an empty diagnostics pytree — nothing
                    # per-client ever leaves the group.
                    est, diag = self.defense_fn(grads, m, f1,
                                                telemetry=True)
                    out["diag"] = diag
                else:
                    est = self.defense_fn(grads, m, f1)
                out["est"] = est.astype(jnp.float32)
                return out
            if not extras:
                est = self.defense_fn(grads, m, f1)
                return est.astype(jnp.float32), bad
            out = {"bad": bad}
            if tele_on or marg_on or num_on:
                dkw = {}
                if marg_on or num_on:
                    dkw["margins"] = True
                if num_on:
                    dkw["numerics"] = True
                est, diag = self.defense_fn(grads, m, f1,
                                            telemetry=True, **dkw)
                # Margins/numerics-only: the full diagnostics never
                # leave the shard — just the flagged fields (the
                # stacked (S, ...) shard_margin_* / shard_num_*
                # records); a numerics-only run's forced margin
                # carriers are dropped here and DCE'd in-trace.
                diag = {k: v for k, v in diag.items() if keep_diag(k)}
                out["diag"] = diag
            else:
                est = self.defense_fn(grads, m, f1)
            out["est"] = est.astype(jnp.float32)
            if want_norms:
                with stage_scope("deliver"):   # delivered-matrix rider
                    out["norms"] = jnp.linalg.norm(
                        grads.astype(jnp.float32), axis=1)
            return out

        # SPMD: client_map runs the shard_map mapping (each device owns
        # its megabatches, one explicit all_gather of the estimates);
        # the gathered (S, ...) outputs come back REPLICATED, so the
        # tier-2 resharding constraint is skipped — re-annotating a
        # replicated matrix is exactly the GSPMD seam being retired.
        cm_plan = self.shardings if self._hier_spmd else None
        t2_plan = None if self._hier_spmd else self.shardings

        def hier_core(state, t):
            tele = {}
            # Outer scope: the megabatch scan's own plumbing (carry
            # writes, estimate stacking) books under tier1_aggregate;
            # the finer scopes inside shard_fn win for everything they
            # annotate (stage_attribution takes the innermost token).
            with stage_scope("tier1_aggregate"):
                out = client_map(shard_fn, place, state, t, plan=cm_plan)
            norms = diag1 = sum_oks = None
            if extras:
                ests, bads = out["est"], out["bad"]
                sum_oks = out.get("sum_ok")
                norms = out.get("norms")        # (S, m) clear modes
                diag1 = out.get("diag")         # stacked tier-1 pytree
            elif groupwise:
                ests, bads, sum_oks = out
            else:
                ests, bads = out
            if groupwise:
                # Per-group sum norms are server-visible under
                # group-wise secagg (each estimate is sum/m): the v5
                # 'secagg' event's observable quantity.  Stage ledger:
                # protocol-side riders — ``protect``.
                with stage_scope("protect"):
                    tele = {
                        "secagg_sum_check_ok":
                            jnp.all(sum_oks > 0).astype(jnp.int32),
                        "secagg_groups": jnp.asarray(S, jnp.int32),
                        "secagg_dropped": jnp.zeros((), jnp.int32),
                        "secagg_masks_reconstructed":
                            jnp.zeros((), jnp.int32),
                        "secagg_recovery": jnp.zeros((), jnp.int32),
                        "secagg_group_sum_norms":
                            jnp.linalg.norm(ests, axis=1) * m,
                    }
                    if tele_on:
                        # Group-sum envelope (protocols/secagg.py): the
                        # population view the server can still compute
                        # when groups, not clients, are the visible
                        # unit.
                        env = group_envelope_stats(ests, m)
                        tele["secagg_group_cos_to_mean"] = (
                            env["group_cos_to_mean"])
            if tele_on or marg_on or num_on:
                if diag1:
                    for dk, dv in diag1.items():
                        tele["shard_" + dk] = dv
                if norms is not None and tele_on:
                    tele["shard_grad_norms"] = norms
                t2kw = {}
                if marg_on or num_on:
                    t2kw["margins"] = True
                if num_on:
                    t2kw["numerics"] = True
                agg, diag2 = shard_reduce(tier2_fn, ests, S, f2,
                                          plan=t2_plan,
                                          telemetry=True, **t2kw)
                with stage_scope("tier2_aggregate"):
                    for dk, dv in diag2.items():
                        if keep_diag(dk):
                            tele["tier2_" + dk] = dv
                    if tele_on:
                        tele["tier2_est_norms"] = jnp.linalg.norm(
                            ests.astype(jnp.float32), axis=1)
                if num_on:
                    # Engine-level health at the tier boundary: the
                    # (S, d) estimate matrix the tier-2 reduction
                    # aggregates (per-shard wire health is in the
                    # stacked shard_num_* fields).
                    with stage_scope("tier2_aggregate"):
                        tele["num_nonfinite_post"] = nonfinite_count(
                            ests)
                        tele["num_range_log2"] = norm_dynamic_range(
                            ests)
            else:
                agg = shard_reduce(tier2_fn, ests, S, f2,
                                   plan=t2_plan)
            new_state = self._aggregate_impl(state, None, t, agg=agg)
            if num_on:
                with stage_scope("apply"):
                    tele["num_nonfinite_agg"] = nonfinite_count(
                        new_state.velocity)
            bad = (bads.any() if self._check_attack_nan
                   else jnp.asarray(False))
            diag = {}
            if cfg.log_round_stats:
                # The flat round_diagnostics re-read over what this
                # mode can observe: exact per-client norm stats in the
                # clear modes (the (S, m) stack holds the same n
                # values), group-sum norm stats under groupwise.
                with stage_scope("apply"):
                    diag = {
                        "update_norm": jnp.linalg.norm(
                            new_state.velocity),
                        "faded_lr": faded_learning_rate(
                            cfg.learning_rate, cfg.fading_rate, t),
                    }
                    if norms is not None:
                        diag.update(
                            grad_norm_mean=jnp.mean(norms),
                            grad_norm_max=jnp.max(norms),
                            grad_norm_min=jnp.min(norms))
                    else:
                        gs = jnp.linalg.norm(
                            ests.astype(jnp.float32), axis=1) * m
                        diag.update(
                            group_sum_norm_mean=jnp.mean(gs),
                            group_sum_norm_max=jnp.max(gs),
                            group_sum_norm_min=jnp.min(gs))
            return new_state, diag, bad, tele

        def fused(state, t, batches=None):
            # `batches` mirrors the flat signature (run_round always
            # passes it); hierarchical is device-resident-only, so it
            # is always None (validated at init).
            new_state, diag, bad, tele = hier_core(state, t)
            return new_state, diag, bad, tele

        def fused_span(state, t0, count):
            # Same traced-count fori_loop as the flat span: one
            # compilation covers every span length.
            def body(i, carry):
                s, bad = carry
                s2, _, b, _ = hier_core(s, t0 + i)
                if self._check_attack_nan:
                    bad = bad | b
                return s2, bad

            return jax.lax.fori_loop(0, count, body,
                                     (state, jnp.asarray(False)))

        def tele_span(state, t0, count):
            # Per-round telemetry pytrees (and groupwise secagg's
            # protocol stats) come back stacked, exactly like the flat
            # engine's telemetry span (static count: one compilation
            # per distinct span length).
            def body(carry, i):
                s, bad = carry
                s2, _, b, tele = hier_core(s, t0 + i)
                if self._check_attack_nan:
                    bad = bad | b
                return (s2, bad), tele

            (s, bad), stacked = jax.lax.scan(
                body, (state, jnp.asarray(False)), jnp.arange(count))
            return s, bad, stacked

        if self.faults is not None:
            # Faulted hierarchical round (ISSUE 19): two fault
            # granularities compose inside the same scanned program —
            # per-CLIENT faults become a per-shard (m,) quarantine mask
            # into the unchanged mask-aware tier-1 kernel, and the
            # correlated shard-DOMAIN axis kills whole megabatches at
            # once, excluded at tier-2 through the alive_counts seam.
            # The tier-2 graceful-degradation ladder is the traffic
            # engine's (core/population.py plan_action over the
            # SURVIVING-shard count vs f2): planned on host per round
            # (pure in (fault key, t) — resume regenerates it),
            # selected on device, no data-dependent shapes.
            from attacking_federate_learning_tpu.core.faults import (
                TIER2_FALLBACK, apply_shard_faults, domain_alive_row,
                quarantine
            )
            from attacking_federate_learning_tpu.core.population import (
                TRAFFIC_FALLBACK
            )
            from attacking_federate_learning_tpu.defenses.kernels import (
                TIER2_DEFENSES
            )

            faults = self.faults
            fkey = self._fault_key
            straggler = faults.straggler > 0
            # Ladder step: the masked shard-median fallback kernel
            # (core/faults.py TIER2_FALLBACK — the widest-validity
            # tier-2 kernel, f-free over survivors).
            self._tier2_fallback_fn = stage_wrapped(
                TIER2_DEFENSES[TIER2_FALLBACK], "tier2_aggregate")

            def fault_shard_fn(sid, ids, c_mal, state, t, ring):
                """Faulted megabatch step: the clear front half
                (megabatch_grads — byte-identical trace) plus the
                fault seam.  ``sid`` is the shard id threaded by
                client_map(with_sid=True) — the fault draw is pure in
                (fault key, t, sid), so the host schedule
                (core/faults.py hier_fault_schedule) replays every
                count exactly.  ``ring`` is the (delay, S, m, d) stale
                slab (a unit f32 dummy when straggler is off).
                Returns a dict pytree; client_map stacks it (S, ...)"""
                grads, bad = megabatch_grads(ids, c_mal, state, t)
                with stage_scope("quarantine"):
                    old = (ring[jnp.mod(t, faults.straggler_delay), sid]
                           if straggler else None)
                    faulted, drop, fstats, fresh = apply_shard_faults(
                        grads, t, sid, fkey, old, faults, c_mal)
                    # Full (S,) domain row indexed at sid: every shard
                    # computes the same row (XLA CSEs the copies under
                    # the sequential scan; under shard_map each device
                    # derives it locally — no cross-shard operand).
                    dom = domain_alive_row(fkey, t, S, faults)[sid]
                out = {"bad": bad}
                for sk, sv in fstats.items():
                    out["f_" + sk] = sv
                if straggler:
                    out["fresh"] = fresh
                if groupwise:
                    # Groupwise secagg ⊕ dropout (config admits only
                    # dropout-style faults here): the dropped members'
                    # pairwise masks are reconstructed over the group's
                    # GLOBAL client ids (recovery_residue), the group
                    # sum excludes them, and the masked NoDefense mean
                    # divides by the survivor count — exactly the clear
                    # quarantine semantics, behind the protocol.
                    qmask = ~drop
                    recovered, sstats = secagg_group(
                        faulted, self._secagg_key, t, ids, alive=qmask)
                    out["secagg"] = sstats
                    with stage_scope("quarantine"):
                        out["f_quarantined"] = (
                            m - jnp.sum(qmask)).astype(jnp.int32)
                    if tele_on:
                        est, diag = self.defense_fn(
                            recovered, m, f1, mask=qmask, telemetry=True)
                        out["diag"] = diag
                    else:
                        est = self.defense_fn(recovered, m, f1,
                                              mask=qmask)
                else:
                    with stage_scope("quarantine"):
                        clean, qmask, qstats = quarantine(faulted, drop)
                    out["f_quarantined"] = qstats["fault_quarantined"]
                    if tele_on or marg_on or num_on:
                        dkw = {}
                        if marg_on or num_on:
                            dkw["margins"] = True
                        if num_on:
                            dkw["numerics"] = True
                        est, diag = self.defense_fn(
                            clean, m, f1, mask=qmask, telemetry=True,
                            **dkw)
                        diag = {k: v for k, v in diag.items()
                                if keep_diag(k)}
                        out["diag"] = diag
                    else:
                        est = self.defense_fn(clean, m, f1, mask=qmask)
                    if want_norms:
                        with stage_scope("deliver"):
                            # Norms of the QUARANTINED matrix — what
                            # the server actually aggregates.
                            out["norms"] = jnp.linalg.norm(
                                clean.astype(jnp.float32), axis=1)
                # Effective cohort: quarantine survivors, zeroed whole
                # when the shard's DOMAIN is dead this round — the
                # tier-2 alive_counts seam excludes alive == 0 shards.
                with stage_scope("quarantine"):
                    out["alive"] = (jnp.sum(qmask)
                                    * dom).astype(jnp.int32)
                out["est"] = est.astype(jnp.float32)
                return out

            def fault_hier_core(state, t, action, fstate):
                ring = (fstate["stale"] if straggler
                        else jnp.ones((), jnp.float32))
                with stage_scope("tier1_aggregate"):
                    out = client_map(fault_shard_fn, place, state, t,
                                     ring, plan=cm_plan, with_sid=True)
                ests, bads, alive = out["est"], out["bad"], out["alive"]
                fstate2 = fstate
                if straggler:
                    with stage_scope("quarantine"):
                        # One ring write per round, outside the scan:
                        # client_map stacks ``fresh`` (S, m, d) in sid
                        # order — exactly the ring's shard axis.
                        fstate2 = {"stale":
                                   jax.lax.dynamic_update_index_in_dim(
                                       ring, out["fresh"],
                                       jnp.mod(t,
                                               faults.straggler_delay),
                                       0)}
                with stage_scope("quarantine"):
                    dom = domain_alive_row(fkey, t, S, faults)
                    # NaN-safety: a shard with zero aggregable rows has
                    # an undefined tier-1 estimate (0/0 mean); zero it
                    # before tier-2 (whose mask already excludes it) so
                    # nothing non-finite can leak through an unselected
                    # lane.
                    ests = jnp.where(alive[:, None] > 0, ests,
                                     jnp.zeros((), ests.dtype))
                    tele = {
                        "fault_injected_dropout": jnp.sum(
                            out["f_injected_dropout"]).astype(jnp.int32),
                        "fault_injected_straggler": jnp.sum(
                            out["f_injected_straggler"]).astype(
                                jnp.int32),
                        "fault_injected_corrupt": jnp.sum(
                            out["f_injected_corrupt"]).astype(jnp.int32),
                        "fault_quarantined": jnp.sum(
                            out["f_quarantined"]).astype(jnp.int32),
                        "fault_shards_dead": (
                            S - jnp.sum(dom)).astype(jnp.int32),
                        "fault_shard_alive": alive.astype(jnp.int32),
                        "fault_shards_alive": jnp.sum(
                            alive > 0).astype(jnp.int32),
                        "fault_tier2_action": jnp.asarray(action,
                                                          jnp.int32),
                    }
                if groupwise:
                    sa = out["secagg"]
                    with stage_scope("protect"):
                        tele.update({
                            "secagg_sum_check_ok": jnp.all(
                                sa["secagg_sum_check_ok"] > 0).astype(
                                    jnp.int32),
                            "secagg_groups": jnp.asarray(S, jnp.int32),
                            "secagg_dropped": jnp.sum(
                                sa["secagg_dropped"]).astype(jnp.int32),
                            "secagg_masks_reconstructed": jnp.sum(
                                sa["secagg_masks_reconstructed"]
                            ).astype(jnp.int32),
                            "secagg_recovery": jnp.any(
                                sa["secagg_recovery"] > 0).astype(
                                    jnp.int32),
                            "secagg_group_sum_norms":
                                jnp.linalg.norm(ests, axis=1) * m,
                        })
                        if tele_on:
                            env = group_envelope_stats(ests, m)
                            tele["secagg_group_cos_to_mean"] = (
                                env["group_cos_to_mean"])
                norms = out.get("norms")
                if tele_on or marg_on or num_on:
                    diag1 = out.get("diag")
                    if diag1:
                        for dk, dv in diag1.items():
                            tele["shard_" + dk] = dv
                    if norms is not None and tele_on:
                        tele["shard_grad_norms"] = norms
                    t2kw = {}
                    if marg_on or num_on:
                        t2kw["margins"] = True
                    if num_on:
                        t2kw["numerics"] = True
                    agg, diag2 = shard_reduce(tier2_fn, ests, S, f2,
                                              alive_counts=alive,
                                              plan=t2_plan,
                                              telemetry=True, **t2kw)
                    with stage_scope("tier2_aggregate"):
                        for dk, dv in diag2.items():
                            if keep_diag(dk):
                                tele["tier2_" + dk] = dv
                        if tele_on:
                            tele["tier2_est_norms"] = jnp.linalg.norm(
                                ests.astype(jnp.float32), axis=1)
                    if num_on:
                        with stage_scope("tier2_aggregate"):
                            tele["num_nonfinite_post"] = (
                                nonfinite_count(ests))
                            tele["num_range_log2"] = (
                                norm_dynamic_range(ests))
                else:
                    agg = shard_reduce(tier2_fn, ests, S, f2,
                                       alive_counts=alive, plan=t2_plan)
                # Ladder on device: the fallback estimate is always
                # computed (fixed shapes), the host-planned action
                # selects.  Telemetry/margins diagnostics above always
                # read the CONFIGURED tier-2 kernel — under FALLBACK
                # only the aggregate switches (documented,
                # ARCHITECTURE.md "Faults & recovery").
                fb = shard_reduce(self._tier2_fallback_fn, ests, S, f2,
                                  alive_counts=alive, plan=t2_plan)
                agg = jnp.where(action == TRAFFIC_FALLBACK, fb, agg)
                # HOLD rides _aggregate_impl's action seam (state-level
                # jnp.where after the momentum update).
                new_state = self._aggregate_impl(state, None, t, agg=agg,
                                                 action=action)
                if num_on:
                    with stage_scope("apply"):
                        tele["num_nonfinite_agg"] = nonfinite_count(
                            new_state.velocity)
                bad = (bads.any() if self._check_attack_nan
                       else jnp.asarray(False))
                diag = {}
                if cfg.log_round_stats:
                    with stage_scope("apply"):
                        diag = {
                            "update_norm": jnp.linalg.norm(
                                new_state.velocity),
                            "faded_lr": faded_learning_rate(
                                cfg.learning_rate, cfg.fading_rate, t),
                        }
                        if norms is not None:
                            diag.update(
                                grad_norm_mean=jnp.mean(norms),
                                grad_norm_max=jnp.max(norms),
                                grad_norm_min=jnp.min(norms))
                        else:
                            gs = jnp.linalg.norm(
                                ests.astype(jnp.float32), axis=1) * m
                            diag.update(
                                group_sum_norm_mean=jnp.mean(gs),
                                group_sum_norm_max=jnp.max(gs),
                                group_sum_norm_min=jnp.min(gs))
                return new_state, diag, bad, tele, fstate2

            def fault_fused(state, t, action, fstate, batches=None):
                # `batches` mirrors the flat faulted signature
                # (run_round always passes it); hierarchical is
                # device-resident-only, so it is always None.
                return fault_hier_core(state, t, action, fstate)

            def fault_span(state, t0, count, fstate, actions):
                # Hier fault span: the flat fault_span's shape (scan,
                # static count, stacked 'fault_*' pytree, fault state
                # in the carry) plus the host-planned (count,) ladder
                # actions as a scanned operand.
                def body(carry, xs):
                    s, bad, fs = carry
                    i, act = xs
                    s2, _, b, tele, fs = fault_hier_core(
                        s, t0 + i, act, fs)
                    if self._check_attack_nan:
                        bad = bad | b
                    return (s2, bad, fs), tele

                (s, bad, fs), stacked = jax.lax.scan(
                    body, (state, jnp.asarray(False), fstate),
                    (jnp.arange(count), actions))
                return s, bad, fs, stacked

            # The fault paths never donate (flat rationale: the fault
            # state rides the carry and the stacked-scan outputs add
            # aliasing surface).
            self._fused_round = jax.jit(fault_fused)
            self._fault_span = jax.jit(fault_span, static_argnums=2)
            self._staged = False
            return

        donate = self._donate_kw()
        self._fused_round = jax.jit(fused, **donate)
        self._fused_span = jax.jit(fused_span, **donate)
        if groupwise or cfg.telemetry or cfg.margins or cfg.numerics:
            self._tele_span = jax.jit(tele_span, static_argnums=2,
                                      **donate)
        self._staged = False

    # ------------------------------------------------------------------
    def _build_async_round_fns(self):
        """FedBuff-style buffered round (cfg.aggregation='async';
        core/async_rounds.py, ARCHITECTURE.md "Asynchronous rounds").

        The round is the sync compute pipeline plus the asynchrony
        machinery, all inside one jit: every client computes a FRESH
        update against the current broadcast weights (exactly the flat
        path's ``_compute_grads_impl``), the update is submitted into
        the in-flight ring at its PRNG-drawn arrival slot, round-t
        arrivals merge into the pending pool, and the server consumes
        the first ``async_buffer`` pending updates FIFO — delivered
        rows masked into the mask-aware defense kernels with their
        staleness weights threaded as a fixed-shape ``(m,)`` vector
        through the ``weights=`` seam.

        ATTACK-SEAM SEMANTICS CHANGE (documented contract of the
        flag): ``Attack.craft`` runs at DELIVERY time over the
        delivered matrix — the colluders coordinate at the aggregation
        boundary, their crafting statistics come from the DELIVERED
        malicious sub-cohort (``AttackContext.staleness``,
        attacks/base.py:delivered_cohort_stats), and a ``timed``
        attacker additionally forces its own emission delay to 0.  The
        attacker controls content and emission time; arrival
        timestamps (hence staleness weights) are the server's.

        A round with NO deliveries is a server no-op: weights and
        velocity hold (the round counter still advances) — a real
        async server does nothing until updates arrive.

        Spans always scan (``_async_span``): the stacked per-round
        pytree carries the ``async_*`` counts (and ``fault_*`` under
        composed faults) whether or not cfg.telemetry, exactly like
        the fault span — v7 'async' events are emitted per round.  The
        async state (ring + pending) rides the carry and checkpoints
        through the Checkpointer ``extra=`` seam
        (:meth:`carry_state_host`)."""
        cfg = self.cfg
        from attacking_federate_learning_tpu.core.async_rounds import (
            async_step, staleness_weights
        )
        from attacking_federate_learning_tpu.defenses.kernels import (
            population_telemetry
        )
        # Same predicate as the flat builder (ISSUE 20): kernel
        # tie/cancellation counters exist only for the margin-bearing
        # defenses.
        kernel_num = bool(cfg.numerics and cfg.defense in
                          ("Krum", "TrimmedMean", "Median", "Bulyan"))
        self._kernel_numerics = kernel_num

        spec = self._async
        D = spec.depth
        if self.traffic is not None:
            # Async traffic = latency-profile delivery: per-cohort-slot
            # heavy-tail Pareto scales (materialized lazily from the
            # population registry, never a (P,) tensor) replace the
            # uniform 0..D arrival draw inside the ring
            # (core/async_rounds.py:draw_delays).
            from attacking_federate_learning_tpu.core.population import (
                async_latency_for_cfg
            )
            self._traffic_latency = async_latency_for_cfg(cfg, self.m)
        else:
            self._traffic_latency = None

        def ctx_for(state, t, staleness=None):
            return AttackContext(
                original_params=state.weights,
                learning_rate=faded_learning_rate(
                    cfg.learning_rate, cfg.fading_rate, t),
                round=t, staleness=staleness)

        self._ctx_for = ctx_for
        # Same predicate as the flat path (the in-program shadow-train
        # nan guard), evaluated over the crafted delivered rows.
        self._check_attack_nan = (
            getattr(self.attacker, "checks_finite", False)
            and self.m_mal > 0
            and getattr(self.attacker, "num_std", 1) != 0)

        def crafted_nonfinite(grads):
            return (~jnp.isfinite(
                grads[: self.m_mal].astype(jnp.float32))).any()

        def async_core(state, t, astate):
            grads = self._compute_grads_impl(state, t)
            # Stage ledger: the delivery ring (submit/merge/evict/
            # deliver) is how updates ARRIVE — ``deliver``.
            with stage_scope("deliver"):
                (delivered_grads, delivered, staleness, astate,
                 stats) = async_step(
                    grads, t, self._async_key, spec, astate, self.m_mal,
                    faults=self.faults,
                    fkey=self._fault_key if self.faults is not None
                    else None,
                    latency=self._traffic_latency)
            ctx = ctx_for(state, t, staleness)
            tele = dict(stats)
            if cfg.telemetry:
                with stage_scope("deliver"):
                    env = self.attacker.envelope_stats(delivered_grads,
                                                       self.m_mal, ctx)
                tele.update({"attack_" + k: v for k, v in env.items()})
            with stage_scope("deliver"):
                # Attack at delivery; undelivered rows [0, f) get
                # overwritten too, so re-mask before aggregation (the
                # quarantine zero convention — distance engines
                # NaN-free).
                crafted = self.attacker.apply(delivered_grads,
                                              self.m_mal, ctx)
            if cfg.margins:
                # Attack margins at the delivery seam: pre-attack =
                # the delivered matrix, crafted = the post-attack one
                # (attacks/base.py margin_stats).
                with stage_scope("deliver"):
                    ms = self.attacker.margin_stats(
                        delivered_grads, self.m_mal, ctx, crafted=crafted)
                tele.update(
                    {"margin_attack_" + k: v for k, v in ms.items()})
            bad = (crafted_nonfinite(crafted)
                   if self._check_attack_nan else jnp.asarray(False))
            if cfg.numerics:
                with stage_scope("deliver"):
                    tele.update(
                        num_nonfinite_pre=nonfinite_count(crafted),
                        num_range_log2=norm_dynamic_range(
                            crafted, mask=delivered))
            with stage_scope("quarantine"):
                agg_grads = jnp.where(delivered[:, None], crafted, 0.0)
            if cfg.numerics:
                with stage_scope("quarantine"):
                    tele["num_nonfinite_post"] = nonfinite_count(
                        agg_grads, mask=delivered)
            with stage_scope("deliver"):
                weights = staleness_weights(staleness, delivered,
                                            spec.weighting)
                # Weight mass by staleness bucket — the science surface
                # ('async' events; weighting='none' reports unit
                # weights).
                w_eff = (weights if weights is not None
                         else jnp.where(delivered, 1.0, 0.0))
                bucket = staleness[None, :] == jnp.arange(D)[:, None]
                tele["async_weight_mass"] = jnp.sum(
                    bucket * w_eff[None, :], axis=1).astype(jnp.float32)
            if cfg.telemetry or cfg.margins or kernel_num:
                upd, ddiag = self._aggregate_impl(
                    state, agg_grads, t, telemetry=True,
                    margins=cfg.margins or kernel_num,
                    numerics=kernel_num, mask=delivered,
                    weights=weights)
                with stage_scope("tier1_aggregate"):
                    for dk, dv in ddiag.items():
                        # Same three-way filter as the flat engine's
                        # finish_telemetry (margin_ iff --margins,
                        # num_ iff --numerics, rest iff telemetry).
                        if dk.startswith("margin_"):
                            if cfg.margins:
                                tele["defense_" + dk] = dv
                        elif dk.startswith("num_"):
                            if cfg.numerics:
                                tele["defense_" + dk] = dv
                        elif cfg.telemetry:
                            tele["defense_" + dk] = dv
                    if cfg.telemetry:
                        tele.update(population_telemetry(agg_grads))
            else:
                upd = self._aggregate_impl(state, agg_grads, t,
                                           mask=delivered,
                                           weights=weights)
            with stage_scope("apply"):
                # Empty delivery = server no-op (weights/velocity hold,
                # the round counter still advances).
                any_del = jnp.any(delivered)
                new_state = ServerState(
                    weights=jnp.where(any_del, upd.weights,
                                      state.weights),
                    velocity=jnp.where(any_del, upd.velocity,
                                       state.velocity),
                    round=upd.round)
            if cfg.numerics:
                with stage_scope("apply"):
                    tele["num_nonfinite_agg"] = nonfinite_count(
                        new_state.velocity)
            diag = {}
            if cfg.log_round_stats:
                # Norm stats over the COMPUTED cohort (what clients
                # submitted this round — comparable to the flat
                # fields); the delivered view lives in async_* stats.
                with stage_scope("apply"):
                    norms = jnp.linalg.norm(grads.astype(jnp.float32),
                                            axis=1)
                    diag = {
                        "grad_norm_mean": jnp.mean(norms),
                        "grad_norm_max": jnp.max(norms),
                        "grad_norm_min": jnp.min(norms),
                        "update_norm": jnp.linalg.norm(
                            new_state.velocity),
                        "faded_lr": faded_learning_rate(
                            cfg.learning_rate, cfg.fading_rate, t),
                    }
            return new_state, diag, bad, tele, astate

        def fused(state, t, astate, batches=None):
            # `batches` mirrors the flat faulted signature (run_round
            # always passes it); async is device-resident-only, so it
            # is always None (validated at init).
            return async_core(state, t, astate)

        def async_span(state, t0, count, astate):
            # Always a scan (static count): the stacked per-round
            # pytree carries the async_* counts with or without
            # telemetry — 'async' events are per-round, like 'fault'.
            def body(carry, i):
                s, bad, a = carry
                s2, _, b, tele, a = async_core(s, t0 + i, a)
                if self._check_attack_nan:
                    bad = bad | b
                return (s2, bad, a), tele

            (s, bad, a), stacked = jax.lax.scan(
                body, (state, jnp.asarray(False), astate),
                jnp.arange(count))
            return s, bad, a, stacked

        # Like the fault paths, async never donates: the buffer state
        # rides the carry and the stacked-scan outputs add aliasing
        # surface beyond what _donate_kw's CPU rationale distrusts.
        self._fused_round = jax.jit(fused)
        self._async_span = jax.jit(async_span, static_argnums=2)
        self._staged = False

    # ------------------------------------------------------------------
    def wire_ledger(self):
        """Per-seam wire ledger for THIS engine's topology
        (utils/costs.py:wire_ledger): the bytes each logical network
        seam moves per round, derived statically from the config — no
        execution, no HLO.  Seams that the topology doesn't exercise
        carry 0 bytes, so one schema covers flat, hierarchical and
        async runs (and their secagg compositions) uniformly.

        The hierarchical tier1_to_tier2 seam doubles as the SPMD
        cross-check: under a >1-device clients axis it equals the
        measured all_gather ``collective_bytes`` that
        tools/perf_gate.py --shardproof pins to S*d*4 (ISSUE 12)."""
        cfg = self.cfg
        spmd_parts = 1
        num_shards = None
        if cfg.aggregation == "hierarchical":
            num_shards = self._placement.num_shards
            if self._hier_spmd:
                from attacking_federate_learning_tpu.parallel.mesh import (
                    CLIENTS
                )
                spmd_parts = int(self.shardings.mesh.shape[CLIENTS])
        dropped = 0
        if cfg.secagg != "off" and self.faults is not None:
            # Expected mask-reconstruction load: the dropout fault rate
            # over the cohort (secagg only composes with dropout faults,
            # config.py enforces).
            dropped = int(round(self.faults.dropout * self.m))
        from attacking_federate_learning_tpu.utils.costs import wire_ledger
        return wire_ledger(
            cohort=self.m,
            dim=self.flat.dim,
            grad_bytes=self._grad_dtype.itemsize,
            topology=cfg.aggregation,
            num_shards=num_shards,
            megabatch=cfg.megabatch if num_shards is not None else None,
            spmd_parts=spmd_parts,
            secagg=cfg.secagg,
            dropped=dropped,
            async_buffer=(cfg.async_buffer
                          if cfg.aggregation == "async" else None),
        )

    # ------------------------------------------------------------------
    def cost_report(self, logger=None, span: Optional[int] = None):
        """Static compile-and-cost facts for every jitted entry point
        this engine built (utils/costs.py): each is lowered and
        compiled ONCE — AOT, no execution — and its deterministic HLO
        facts (cost_analysis FLOPs / bytes-accessed, memory_analysis
        buffer sizes) plus compile wall time and persistent-cache
        attribution are collected into a CompileLedger.  With a
        ``logger``, one 'compile' + one 'cost' event (schema v2) lands
        per entry point; tools/perf_gate.py diffs the same facts
        against PERF_BASELINE.json.

        The report is an observer: it never touches the round
        functions themselves (their HLO is pinned byte-identical with
        the report on or off — tests/test_costs.py), and the compiles
        it pays are exactly the ones the run would pay anyway, warmed
        through the persistent cache.

        ``span``: the span length to analyze for the static-length span
        programs (default: the eval interval, the length the run
        compiles first)."""
        import jax

        from attacking_federate_learning_tpu.utils.costs import (
            CompileLedger
        )

        cfg = self.cfg
        ledger = CompileLedger()
        t0 = jnp.asarray(0, jnp.int32)
        span_len = int(span or max(1, min(cfg.test_step, cfg.epochs)))
        d = self.flat.dim
        if self._streaming:
            # Streamed rounds take the round batch as an argument;
            # abstract shapes suffice for lowering.
            kB = cfg.batch_size * cfg.local_steps
            batches = (jax.ShapeDtypeStruct(
                           (self.m, kB) + self.dataset.train_x.shape[1:],
                           jnp.float32),
                       jax.ShapeDtypeStruct((self.m, kB), jnp.int32))
        else:
            batches = None

        entries = []
        # Hierarchical engines expose the same two jitted entry points
        # under their own ledger names — the perf gate pins hier_round's
        # peak-proxy bytes to the megabatch, not the cohort.
        hier = cfg.aggregation == "hierarchical"
        round_name, span_name = (("hier_round", "hier_span") if hier
                                 else ("fused_round", "fused_span"))
        if not self._staged:
            if self._async is not None:
                # Async engines expose their two jitted entry points
                # under their own ledger names (the buffer state rides
                # the signatures).
                entries.append(("async_round", lambda: self._fused_round
                                .lower(self.state, t0,
                                       self._async_state, batches)))
                entries.append(
                    ("async_span", lambda: self._async_span.lower(
                        self.state, t0, span_len, self._async_state)))
            elif self.traffic is not None:
                # Traffic engines expose their two jitted entry points
                # under their own ledger names; the schedule operands
                # are abstract (m,)-shaped rows — the lowered program
                # proves memory scales with the cohort, never the
                # population (tests/test_traffic.py pins this).
                sid_sds = jax.ShapeDtypeStruct((self.m,), jnp.int32)
                arr_sds = jax.ShapeDtypeStruct((self.m,), jnp.bool_)
                act_sds = jax.ShapeDtypeStruct((), jnp.int32)
                entries.append(("traffic_round", lambda:
                                self._fused_round.lower(
                                    self.state, t0, sid_sds, arr_sds,
                                    act_sds, self._fault_state)))
                sids_sds = jax.ShapeDtypeStruct((span_len, self.m),
                                                jnp.int32)
                arrs_sds = jax.ShapeDtypeStruct((span_len, self.m),
                                                jnp.bool_)
                acts_sds = jax.ShapeDtypeStruct((span_len,), jnp.int32)
                entries.append(("traffic_span", lambda:
                                self._traffic_span.lower(
                                    self.state, t0, span_len, sids_sds,
                                    arrs_sds, acts_sds,
                                    self._fault_state)))
            elif self.faults is None:
                entries.append((round_name, lambda: self._fused_round
                                .lower(self.state, t0, batches)))
                if not self._streaming:
                    # Span length is a traced operand: one compilation
                    # covers every span, so one analysis does too.
                    entries.append(
                        (span_name, lambda: self._fused_span.lower(
                            self.state, t0,
                            jnp.asarray(span_len, jnp.int32))))
                    if cfg.telemetry or cfg.margins or cfg.numerics:
                        # Hierarchical engines ledger their telemetry
                        # span under their own name so the perf gate
                        # can pin the hier-tele cost cells separately
                        # (margins and numerics ride the same span
                        # entry point).
                        entries.append(
                            ("hier_tele_span" if hier else "tele_span",
                             lambda: self._tele_span.lower(
                                 self.state, t0, span_len)))
            else:
                entries.append(("fused_round", lambda: self._fused_round
                                .lower(self.state, t0, self._fault_state,
                                       batches)))
                entries.append(
                    ("fault_span", lambda: self._fault_span.lower(
                        self.state, t0, span_len, self._fault_state)))
        else:
            entries.append(("compute_grads", lambda: self._compute_grads
                            .lower(self.state, t0, batches)))
            grads_sds = jax.ShapeDtypeStruct((self.m, d), self._grad_dtype)
            if hasattr(self._aggregate, "lower"):
                # The staged CPU Krum/Bulyan aggregation runs EAGERLY
                # (host BLAS) — nothing compiled to analyze there.
                entries.append(("aggregate", lambda: self._aggregate.lower(
                    self.state, grads_sds, t0)))
            if ((cfg.telemetry or cfg.margins
                    or getattr(self, "_kernel_numerics", False))
                    and hasattr(self._aggregate_tele, "lower")):
                entries.append(
                    ("aggregate_tele", lambda: self._aggregate_tele.lower(
                        self.state, grads_sds, t0)))

        # The wired defense kernel in isolation: the per-cell
        # defense-cost row of the attack x defense grid (ALIE vs Bulyan
        # cells differ by orders of magnitude in O(n^2 d) kernel cost —
        # this is where that becomes a recorded number).
        kw = {}
        if getattr(self.defense_fn, "needs_round", False):
            kw["round"] = t0
        if self._needs_server_grad:
            kw["server_grad"] = jax.ShapeDtypeStruct((d,), jnp.float32)
        # Hierarchical: the tier-1 kernel only ever sees one (m, d)
        # megabatch with the assumed per-shard bound; tier-2 gets its
        # own ledger row over the (S, d) estimate matrix.
        du_n, du_f = ((self._placement.megabatch, self._tier1_f) if hier
                      else (self.m, self.m_mal))
        grads_sds = jax.ShapeDtypeStruct((du_n, d), self._grad_dtype)
        defense_fn = self.defense_fn

        def defense_lowered():
            jitted = jax.jit(lambda G, **kws: defense_fn(
                G, du_n, du_f, **kws))
            return jitted.lower(grads_sds, **kw)

        entries.append((f"defense_{cfg.defense}", defense_lowered))
        if hier:
            S = self._placement.num_shards
            est_sds = jax.ShapeDtypeStruct((S, d), jnp.float32)
            tier2_fn, f2 = self._tier2_fn, self._tier2_f

            def tier2_lowered():
                jitted = jax.jit(lambda E: tier2_fn(E, S, f2))
                return jitted.lower(est_sds)

            entries.append((f"tier2_{self._tier2_name}", tier2_lowered))
        entries.append(("eval", lambda: self.evaluate.lower(
            jax.ShapeDtypeStruct((d,), jnp.float32))))

        for name, thunk in entries:
            try:
                ledger.analyze(name, thunk())
            except Exception as e:        # noqa: BLE001 — one entry
                # failing to lower must not lose the rest of the table
                ledger.errors.append((name, f"{type(e).__name__}: {e}"))
        # Wire ledger rides the same report: one versioned wire_bytes
        # event per cost_report, next to the per-entry stage_cost rows.
        try:
            ledger.wire = self.wire_ledger()
        except Exception:             # noqa: BLE001 — observability
            ledger.wire = None        # must never sink a run
        if logger is not None:
            ledger.emit(logger)
        self.cost_ledger = ledger
        return ledger

    # ------------------------------------------------------------------
    @staticmethod
    def _donate_kw():
        """Server-state donation policy: donate on accelerators (HBM
        reuse matters there), never on the CPU backend.  This box's
        jaxlib honors CPU donation with full input/output buffer
        aliasing, and the combination with zero-copy ``np.asarray``
        views has produced dangling reads and flaky heap corruption
        (segfaults/aborts mid-test-suite, clobbered snapshot restores —
        the seed's recoverable-state failure).  A (d,)-state copy per
        round is noise on CPU; correctness isn't."""
        if jax.default_backend() == "cpu":
            return {}
        return {"donate_argnums": 0}

    @staticmethod
    def _host_copy(tree):
        """Owned host snapshot of a device pytree.  ``np.asarray`` on a
        CPU-backend jax array can be a zero-copy VIEW of the device
        buffer; snapshots taken before a donating call must own their
        memory or the donation clobbers them."""
        return jax.tree.map(lambda a: np.array(a, copy=True), tree)

    def carry_state_host(self):
        """Host copy of the engine's cross-round carry state for the
        Checkpointer ``extra=`` seam: the async ring + pending pool
        (six ``async_*``-keyed arrays — f32 buffers, bool occupancy
        masks, int32 birth counters) under aggregation='async', or the
        straggler ring buffer (``stale``) under sync fault injection.
        None when the engine carries nothing beyond the ServerState."""
        if self._async is not None and self._async_state:
            host = self._host_copy(self._async_state)
            return {"async_" + k: v for k, v in host.items()}
        if self.faults is None or not self._fault_state:
            return None
        return self._host_copy(self._fault_state)

    def restore_carry_state(self, extra):
        """Re-install checkpointed carry state (the fault ring buffer
        or the async buffers) after a resume (cli.py --resume /
        Checkpointer ``extra``) so a resumed run continues
        bit-for-bit.  Dtypes are restored per array (npz round-trips
        bool occupancy and int32 birth counters faithfully, but a
        foreign writer may widen — coerce to the engine's layout)."""
        if not extra:
            return
        if self._async is not None:
            if any(k.startswith("async_") for k in extra):
                ref = self._async_state
                self._async_state = {
                    k: jnp.asarray(extra["async_" + k]).astype(v.dtype)
                    for k, v in ref.items()}
            return
        if self.faults is not None and "stale" in extra:
            self._fault_state = {"stale": jnp.asarray(extra["stale"])}

    def restore_fault_state(self, extra):
        """Back-compat alias (pre-async spelling; cli.py --resume and
        older callers)."""
        self.restore_carry_state(extra)

    def fault_state_host(self):
        """Back-compat alias for :meth:`carry_state_host` (pre-async
        spelling — it now also returns the async buffers)."""
        return self.carry_state_host()

    def _diverged(self) -> bool:
        """Divergence watchdog predicate, evaluated at span boundaries
        (host side, one fetch): non-finite server weights, or a weight
        norm beyond FaultConfig.watchdog_norm — the signature of
        unquarantinable garbage (e.g. bit-scaled finite rows) making it
        through aggregation."""
        w = np.asarray(self.state.weights)
        if not np.isfinite(w).all():
            return True
        return float(np.linalg.norm(w)) > self.faults.watchdog_norm

    def _rollback(self, logger, epoch, checkpointer):
        """Roll the engine back to the last good auto-checkpointed state
        instead of aborting.  Emits a 'fault' event, re-persists the
        restored state as an on-failure auto-checkpoint, and raises
        FloatingPointError only once max_rollbacks is exhausted (state
        still restored first, so catch-and-continue callers hold a
        finite state)."""
        self._rollbacks += 1
        st, fs = self._last_good
        restored_round = int(st.round)
        logger.record(kind="fault", round=int(epoch), rolled_back=1,
                      restored_round=restored_round,
                      rollbacks_total=self._rollbacks)
        logger.print(
            f"!! server state diverged after round {epoch}; rolling "
            f"back to round {restored_round} "
            f"(rollback {self._rollbacks}/{self.faults.max_rollbacks})")
        self.state = (self.shardings.place_state(st)
                      if self.shardings is not None
                      else jax.tree.map(jnp.asarray, st))
        if fs is not None:
            # fs is the carry_state_host() form (async_* keys or the
            # fault ring), so the restore path is shared with --resume.
            self.restore_carry_state(fs)
        if checkpointer is not None:
            # On-failure checkpoint: persist the state we rolled back
            # to, so an external --resume lands on the same round.
            checkpointer.save_auto(self.state, extra=fs)
        if self._rollbacks > self.faults.max_rollbacks:
            raise FloatingPointError(
                f"server state diverged after round {epoch} and "
                f"exhausted {self.faults.max_rollbacks} rollbacks "
                f"(restored to round {restored_round})")

    def _raise_if_attack_nan(self, bad):
        """Host side of the crafted-rows nan flag — reference-equivalent
        guard, not message parity: the reference raises
        ``Exception('Got nan dist loss')`` / ``Exception('Got nan loss')``
        (backdoor.py:145-152); this raises FloatingPointError with one
        message for both, and checks isfinite (strictly stronger than the
        reference's isnan)."""
        if self._check_attack_nan and bool(bad):
            raise FloatingPointError("Got nan in backdoor shadow training")

    # --- measured walls (utils/walls.py; cfg.profile_every) -----------
    def _span_entry_name(self) -> str:
        """The ledger name of the span program run_span dispatches —
        the same name cost_report records its stage_cost under, so the
        measured 'wall' event joins the modeled row by name."""
        hier = self.cfg.aggregation == "hierarchical"
        if self._async is not None:
            return "async_span"
        if self.traffic is not None and not hier:
            return "traffic_span"
        if self.faults is not None:
            return "fault_span"
        if (self.cfg.telemetry or self.cfg.margins or self.cfg.numerics
                or self._secagg is not None):
            return "hier_tele_span" if hier else "tele_span"
        return "hier_span" if hier else "fused_span"

    def _span_hlo_text(self, count: int) -> str:
        """Compiled HLO text of the span program for ``count`` rounds —
        the static side of the walls join (instruction name -> stage
        token).  AOT lower+compile, exactly the program run_span's jit
        call builds (warm through the persistent cache); memoized per
        (entry, count) since the scanned spans specialize on length."""
        name = self._span_entry_name()
        key = (name, 1 if name == "fused_span" else int(count))
        cache = getattr(self, "_wall_hlo_cache", None)
        if cache is None:
            cache = self._wall_hlo_cache = {}
        if key not in cache:
            t0 = jnp.asarray(0, jnp.int32)
            if self._async is not None:
                low = self._async_span.lower(
                    self.state, t0, int(count), self._async_state)
            elif self.traffic is not None and name == "traffic_span":
                c = int(count)
                low = self._traffic_span.lower(
                    self.state, t0, c,
                    jax.ShapeDtypeStruct((c, self.m), jnp.int32),
                    jax.ShapeDtypeStruct((c, self.m), jnp.bool_),
                    jax.ShapeDtypeStruct((c,), jnp.int32),
                    self._fault_state)
            elif self.faults is not None:
                if self._placement is not None:
                    low = self._fault_span.lower(
                        self.state, t0, int(count), self._fault_state,
                        jax.ShapeDtypeStruct((int(count),), jnp.int32))
                else:
                    low = self._fault_span.lower(
                        self.state, t0, int(count), self._fault_state)
            elif (self.cfg.telemetry or self.cfg.margins
                    or self.cfg.numerics or self._secagg is not None):
                low = self._tele_span.lower(self.state, t0, int(count))
            else:
                # Span length is a traced operand: one compilation
                # covers every span length, so one text does too.
                low = self._fused_span.lower(
                    self.state, t0, jnp.asarray(count, jnp.int32))
            cache[key] = low.compile().as_text()
        return cache[key]

    def _book_span_walls(self, logger, trace_dir: str, count: int):
        """Book one profiled span capture onto the stage taxonomy and
        emit the schema-v10 'wall' event (source='trace').  Returns the
        WallRecord, or None when the capture produced no trace (the
        device_trace no-op path on an un-gated accelerator) — walls
        observability must never sink the run it measures."""
        from attacking_federate_learning_tpu.utils.walls import (
            book_trace
        )

        try:
            rec = book_trace(
                trace_dir, self._span_hlo_text(count),
                name=self._span_entry_name(),
                platform=jax.devices()[0].platform, rounds=count)
        except Exception as e:          # noqa: BLE001 — observability
            logger.print(f"[walls] booking failed: "
                         f"{type(e).__name__}: {e}")
            return None
        if rec is not None and logger is not None:
            logger.record(**rec.wall_event())
        return rec

    def _traffic_plan(self, start: int, count: int):
        """Host-sampled traffic schedule for rounds [start, start+count):
        cohort shard ids, arrival masks and ladder actions (one device
        operand row per round), plus the v11 'traffic' events the run
        loop emits at the next journal-fresh boundary.  Pure in the
        traffic seed and the round index (core/population.py), so a
        resumed run regenerates the identical schedule — no carry
        state."""
        from attacking_federate_learning_tpu.core.population import (
            traffic_schedule
        )
        return traffic_schedule(
            self.registry, start, count, self.m, self.m_mal,
            self.cfg.defense, self.traffic.fallback_defense,
            self.traffic.min_cohort)

    def _fault_plan(self, start: int, count: int):
        """Host-planned tier-2 ladder actions for the faulted
        hierarchical rounds [start, start+count): replay the fault
        schedule (core/faults.py hier_fault_schedule — pure in the
        fault key and the round index, so a resumed run regenerates
        the identical plan), then run the traffic engine's
        plan_action on each round's SURVIVING-shard count vs the
        tier-2 kernel's validity bound (f2).  Returns a (count,)
        int32 np array of TRAFFIC_* codes — one scanned device
        operand row per round."""
        from attacking_federate_learning_tpu.core.faults import (
            hier_fault_schedule, plan_tier2_actions
        )
        rows = hier_fault_schedule(self._fault_key, start, count,
                                   self._placement, self.faults)
        return plan_tier2_actions([r["shards_alive"] for r in rows],
                                  self._tier2_name, self._tier2_f)

    def run_span(self, start: int, count: int) -> ServerState:
        """Run ``count`` rounds [start, start+count) as one scanned device
        program when the attack is fusable; falls back to per-round calls
        otherwise (staged attacks need host crafting; round diagnostics
        need every intermediate gradient matrix; host-streamed data feeds
        one round's batch per program, overlapped with the previous
        round's compute).  Under cfg.telemetry the span still runs as one
        program — per-round telemetry pytrees come back STACKED
        (``_tele_span``) and land in ``self.last_span_telemetry`` as
        ``(start, stacked_pytree)`` for the caller to fetch once."""
        if count <= 0:
            return self.state
        if self._staged or self.cfg.log_round_stats or self._streaming:
            for t in range(start, start + count):
                self.run_round(t)
        else:
            self.last_round_stats = None
            self.last_span_telemetry = None
            pre_span = pre_fstate = pre_astate = None
            if self._check_attack_nan:
                # The span donates self.state, so when the in-program nan
                # flag fires the post-nan state is all a caller would have
                # left — unlike the staged/reference path, where the raise
                # leaves the last good round behind.  A host snapshot of
                # the pre-span state (~2 vectors of d) keeps catch-and-
                # continue callers (benchmarks.py) recoverable.
                # np.array(copy=True), NOT np.asarray: asarray can be a
                # zero-copy view of the very buffer the span donates,
                # and a clobbered snapshot restores garbage.
                pre_span = self._host_copy(self.state)
                if self._fault_state is not None:
                    pre_fstate = self._host_copy(self._fault_state)
                if self._async_state is not None:
                    pre_astate = self._host_copy(self._async_state)
            if self._async is not None:
                # Async spans always scan: the stacked per-round pytree
                # carries the 'async_*' counts (v7 'async' events are
                # per-round, telemetry on or off) and the buffer state
                # rides the carry.
                (self.state, bad, self._async_state, stacked) = (
                    self._async_span(self.state,
                                     jnp.asarray(start, jnp.int32),
                                     int(count), self._async_state))
                self.last_span_telemetry = (int(start), stacked)
            elif self._traffic_span is not None:
                # Traffic spans always scan: the host samples the span's
                # schedule (stateless, pure in (traffic seed, t)) and
                # each round consumes its row; the watchdog's ladder
                # decisions land as per-round v11 'traffic' events at
                # the next host boundary.  Composed faults thread their
                # state through the same carry.
                sched = self._traffic_plan(int(start), int(count))
                self._traffic_events.update(
                    {e["round"]: e for e in sched.events})
                (self.state, bad, self._fault_state, stacked) = (
                    self._traffic_span(
                        self.state, jnp.asarray(start, jnp.int32),
                        int(count), jnp.asarray(sched.shard_ids),
                        jnp.asarray(sched.arrived),
                        jnp.asarray(sched.action), self._fault_state))
                # Without telemetry/faults the stacked pytree is empty —
                # nothing for the emission loop to fetch.
                self.last_span_telemetry = (
                    (int(start), stacked)
                    if jax.tree_util.tree_leaves(stacked) else None)
            elif self.faults is not None:
                # Fault spans always scan (the stacked per-round pytree
                # carries the 'fault_*' counts even without telemetry).
                # Hierarchical fault spans additionally consume the
                # host-planned tier-2 ladder actions (one row per
                # round; _fault_plan is pure in (fault key, t)).
                if self._placement is not None:
                    acts = self._fault_plan(int(start), int(count))
                    self.state, bad, self._fault_state, stacked = (
                        self._fault_span(self.state,
                                         jnp.asarray(start, jnp.int32),
                                         int(count), self._fault_state,
                                         jnp.asarray(acts)))
                else:
                    self.state, bad, self._fault_state, stacked = (
                        self._fault_span(self.state,
                                         jnp.asarray(start, jnp.int32),
                                         int(count), self._fault_state))
                self.last_span_telemetry = (int(start), stacked)
            elif (self.cfg.telemetry or self.cfg.margins
                    or self.cfg.numerics or self._secagg is not None):
                # secagg, margins and numerics ride the telemetry span
                # too: their per-round stats (sum-check verdicts /
                # margin fields / numeric-health counters) must come
                # back stacked even with cfg.telemetry off, exactly
                # like the fault counts do under faults.
                self.state, bad, stacked = self._tele_span(
                    self.state, jnp.asarray(start, jnp.int32), int(count))
                self.last_span_telemetry = (int(start), stacked)
            else:
                self.state, bad = self._fused_span(
                    self.state, jnp.asarray(start, jnp.int32),
                    jnp.asarray(count, jnp.int32))
            if self._check_attack_nan and bool(bad):
                self.state = (self.shardings.place_state(pre_span)
                              if self.shardings is not None
                              else jax.tree.map(jnp.asarray, pre_span))
                if pre_fstate is not None:
                    self._fault_state = jax.tree.map(jnp.asarray,
                                                     pre_fstate)
                if pre_astate is not None:
                    self._async_state = jax.tree.map(jnp.asarray,
                                                     pre_astate)
                self._raise_if_attack_nan(bad)
        return self.state

    def run_round(self, t: int) -> ServerState:
        batches = self.stream.get(int(t)) if self._streaming else None
        t_host = int(t)
        t = jnp.asarray(t, jnp.int32)
        self.last_round_stats = None
        self.last_round_telemetry = None
        if not self._staged:
            if self._async is not None:
                (self.state, diag, bad, tele,
                 self._async_state) = self._fused_round(
                    self.state, t, self._async_state, batches)
            elif self._traffic_span is not None:
                sched = self._traffic_plan(t_host, 1)
                self._traffic_events.update(
                    {e["round"]: e for e in sched.events})
                (self.state, diag, bad, tele,
                 self._fault_state) = self._fused_round(
                    self.state, t, jnp.asarray(sched.shard_ids[0]),
                    jnp.asarray(sched.arrived[0]),
                    jnp.asarray(sched.action[0]), self._fault_state)
            elif self.faults is not None:
                if self._placement is not None:
                    act = self._fault_plan(t_host, 1)[0]
                    (self.state, diag, bad, tele,
                     self._fault_state) = self._fused_round(
                        self.state, t, jnp.asarray(act, jnp.int32),
                        self._fault_state, batches)
                else:
                    (self.state, diag, bad, tele,
                     self._fault_state) = self._fused_round(
                        self.state, t, self._fault_state, batches)
            else:
                self.state, diag, bad, tele = self._fused_round(
                    self.state, t, batches)
            if diag:
                self.last_round_stats = diag
            if tele:
                self.last_round_telemetry = tele
            self._raise_if_attack_nan(bad)
        else:
            grads = self._compute_grads(self.state, t, batches)
            tele = (self._attack_envelope(grads, self.state, t)
                    if self.cfg.telemetry else {})
            pre_attack = grads if self.cfg.margins else None
            grads = self.attacker.apply(grads, self.m_mal,
                                        self._ctx_for(self.state, t))
            if self.cfg.margins:
                tele = {**tele, **self._attack_margins(
                    pre_attack, grads, self.state, t)}
            if self.cfg.numerics:
                # Staged twin of the fused engine counters (eager —
                # the staged path crosses the host every round anyway).
                tele = {**tele,
                        "num_nonfinite_pre": nonfinite_count(grads),
                        "num_range_log2": norm_dynamic_range(grads)}
            mask = None
            if self.faults is not None:
                grads, mask, self._fault_state, fstats = self._fault_step(
                    grads, t, self._fault_state)
                tele = {**tele, **fstats}
            if self.cfg.numerics:
                tele = {**tele, "num_nonfinite_post":
                        nonfinite_count(grads, mask=mask)}
            aux = {}
            if (self.cfg.telemetry or self.cfg.margins
                    or getattr(self, "_kernel_numerics", False)):
                # The defense returns its own diagnostics (single
                # distance computation; the Krum mask marks the
                # aggregated row by construction).
                self.state, ddiag = self._aggregate_tele(self.state,
                                                         grads, t,
                                                         mask=mask)
                tele = self._finish_telemetry(tele, grads, ddiag)
                if (self._krum_select_fn is not None
                        and "selection_mask" in ddiag):
                    aux["krum_selected"] = jnp.argmax(
                        ddiag["selection_mask"]).astype(jnp.int32)
                self.last_round_telemetry = tele
            else:
                agg = None
                if (self.cfg.log_round_stats
                        and self._krum_select_fn is not None
                        and self.faults is None):
                    # Eager selection (same knobs as the defense),
                    # aggregate the selected row directly — single
                    # distance computation, same as the fused path.
                    sel = self._krum_select_fn(grads, self.m, self.m_mal)
                    aux["krum_selected"] = sel
                    agg = grads[sel]
                self.state = self._aggregate(self.state, grads, t, agg,
                                             mask=mask)
                if tele:
                    self.last_round_telemetry = tele
            if self.cfg.numerics:
                tele = {**tele, "num_nonfinite_agg":
                        nonfinite_count(self.state.velocity)}
                self.last_round_telemetry = tele
            if self.cfg.log_round_stats:
                self.last_round_stats = self._round_diagnostics(
                    grads, self.state, t, aux)
        return self.state

    def _shard_static_fields(self):
        """The placement ground truth every 'shard_selection' event
        carries (host-side statics): which defenses ran per tier, the
        megabatch size, and each shard's malicious-row count — what
        the forensics layer (report.py) attributes tier-2 rejections
        against.  Shared with tools/science_gate.py so the gate's
        replayed cells see exactly what a logged run records."""
        pl = self._placement
        return {"defense": self.cfg.defense,
                "tier2_defense": self._tier2_name,
                "megabatch": pl.megabatch,
                "mal_counts": list(pl.mal_counts),
                "mal_placement": self.cfg.mal_placement,
                "tier1_corrupted": self._tier1_f,
                "tier2_corrupted": self._tier2_f}

    def _emit_round_telemetry(self, logger, t, tele):
        """Write one round's telemetry (host values) as 'defense' and
        'attack' events (cfg.telemetry), its 'fault_*' counts as a
        'fault' event, its 'secagg_*' protocol stats as a 'secagg'
        event (both emitted with or without telemetry), its margin
        fields as one schema-v12 'margin' event (cfg.margins — also
        with or without telemetry), its numeric-health counters as one
        schema-v14 'numerics' event (cfg.numerics — likewise
        independent of telemetry), and — for hierarchical rounds —
        its 'shard_*'/'tier2_*' stacks as one schema-v6
        'shard_selection' event; track Krum winners for the
        end-of-run selection histogram."""
        defense_fields, attack_fields = {}, {}
        fault_fields, secagg_fields, shard_fields = {}, {}, {}
        async_fields = {}
        margin_fields, margin_attack, hier_margin = {}, {}, {}
        numerics_fields = {}
        for k, v in tele.items():
            val = _jsonable(v)
            # Margin/numerics prefixes are checked FIRST:
            # 'defense_margin_*' / 'shard_margin_*' / 'tier2_margin_*'
            # (and the num_ twins) would otherwise be swallowed by the
            # defense/shard branches below.
            if k.startswith("defense_margin_"):
                margin_fields[k[len("defense_"):]] = val
            elif k.startswith("margin_attack_"):
                margin_attack[k[len("margin_attack_"):]] = val
            elif k.startswith(("shard_margin_", "tier2_margin_")):
                hier_margin[k] = val
            elif k.startswith("defense_num_"):
                # Kernel tie/cancellation counters: 'defense_num_x'
                # lands as bare 'x' in the v14 'numerics' event.
                numerics_fields[k[len("defense_num_"):]] = val
            elif k.startswith(("shard_num_", "tier2_num_")):
                # Hier stacks keep their tier prefix, drop 'num_':
                # 'shard_num_tie_rows' -> 'shard_tie_rows'.
                tier, rest = k.split("num_", 1)
                numerics_fields[tier + rest] = val
            elif k.startswith("num_"):
                # Engine-level health counters.
                numerics_fields[k[len("num_"):]] = val
            elif k.startswith("attack_"):
                attack_fields[k[len("attack_"):]] = val
            elif k.startswith("async_"):
                # v7 'async' record: scalar counts land as ints, the
                # staleness histogram / weight-mass vectors as lists.
                async_fields[k[len("async_"):]] = (
                    int(val) if isinstance(val, float)
                    and float(val).is_integer() else val)
            elif k.startswith("fault_"):
                # Scalar counts land as ints; the hierarchical
                # per-shard survivor vector ('fault_shard_alive',
                # (S,)) as an int list.
                fault_fields[k[len("fault_"):]] = (
                    [int(x) for x in val] if isinstance(val, list)
                    else int(val))
            elif k.startswith("secagg_"):
                # Scalar counts/flags land as ints, the groupwise
                # sum-norm vector as a float list.
                secagg_fields[k[len("secagg_"):]] = (
                    int(val) if isinstance(val, float)
                    and float(val).is_integer() else val)
            elif k.startswith(("shard_", "tier2_")):
                # Hierarchical forensics stacks keep their tier prefix
                # — 'shard_selection_mask' (S, m) and
                # 'tier2_selection_mask' (S,) are different axes of
                # the same round and land in one event.
                shard_fields[k] = val
            elif k.startswith("defense_"):
                defense_fields[k[len("defense_"):]] = val
            else:
                defense_fields[k] = val  # population stats
        if fault_fields:
            logger.record(kind="fault", round=int(t), **fault_fields)
        if async_fields:
            logger.record(kind="async", round=int(t), **async_fields)
        if secagg_fields:
            logger.record(kind="secagg", round=int(t), **secagg_fields)
        if self.cfg.margins and (margin_fields or margin_attack
                                 or hier_margin):
            # One schema-v12 'margin' event per round: the bare defense
            # margin fields + the colluder-survival rollups
            # (utils/margins.py), the attack's envelope utilization
            # ('attack_*'), the hierarchical stacks with their own
            # rollups, and — when a traffic schedule rides along — the
            # round's effective-f (the traffic event itself is popped
            # AFTER this emission in both run loops, so the join reads
            # it in place).
            from attacking_federate_learning_tpu.utils.margins import (
                margin_rollups, hier_margin_rollups, tier2_margin_rollups
            )
            ev = dict(margin_fields)
            ev.update(margin_rollups(margin_fields, self.m_mal))
            for mk, mv in margin_attack.items():
                ev["attack_" + mk] = mv
            if hier_margin:
                ev.update(hier_margin)
                shard_stacks = {k[len("shard_"):]: v
                                for k, v in hier_margin.items()
                                if k.startswith("shard_margin_")}
                tier2_fields = {k[len("tier2_"):]: v
                                for k, v in hier_margin.items()
                                if k.startswith("tier2_margin_")}
                if shard_stacks:
                    mal_counts = list(self._placement.mal_counts)
                    for rk, rv in hier_margin_rollups(
                            shard_stacks, mal_counts).items():
                        ev["shard_" + rk] = rv
                if tier2_fields:
                    colluder_shards = [c > 0 for c in
                                       self._placement.mal_counts]
                    for rk, rv in tier2_margin_rollups(
                            tier2_fields, colluder_shards).items():
                        ev["tier2_" + rk] = rv
            if self.traffic is not None:
                tr = self._traffic_events.get(int(t))
                if tr is not None and "f_eff" in tr:
                    ev["f_eff"] = int(tr["f_eff"])
            logger.record(kind="margin", round=int(t),
                          defense=self.cfg.defense,
                          malicious_count=self.m_mal, **ev)
        if self.cfg.numerics and numerics_fields:
            # One schema-v14 'numerics' event per round: engine-level
            # health counters (nonfinite by stage, norm dynamic range),
            # the kernel tie/cancellation counters (flat or as hier
            # shard_/tier2_ stacks), and the host rollups
            # (utils/numerics.py — nonfinite_total, tie_locked), all
            # stamped with the tie band they were measured at.
            from attacking_federate_learning_tpu.utils.numerics import (
                TIE_BAND_ULPS, numerics_rollups
            )
            nev = dict(numerics_fields)
            nev.update(numerics_rollups(numerics_fields))
            logger.record(kind="numerics", round=int(t),
                          defense=self.cfg.defense,
                          tie_band_ulps=TIE_BAND_ULPS, **nev)
        if not self.cfg.telemetry:
            return
        if shard_fields:
            logger.record(kind="shard_selection", round=int(t),
                          **self._shard_static_fields(), **shard_fields)
        if defense_fields:
            logger.record(kind="defense", round=int(t),
                          defense=self.cfg.defense,
                          malicious_count=self.m_mal, **defense_fields)
        if attack_fields:
            logger.record(kind="attack", round=int(t),
                          attack=self.attacker.name, **attack_fields)
        mask = defense_fields.get("selection_mask")
        if mask is not None and self._krum_select_fn is not None:
            # Krum: one-hot mask -> winner id for the selection histogram.
            self._telemetry_winners.append(
                int(max(range(len(mask)), key=mask.__getitem__)))

    def _emit_selection_hist(self, logger):
        """End-of-run 'selection_hist' event: the GRID_RESULTS top-1-
        share analysis, emitted by the engine instead of hand-rolled
        drivers (tools/femnist_style_study.py pre-telemetry)."""
        import collections

        wins = self._telemetry_winners
        if not wins:
            return
        counts = collections.Counter(wins)
        top1_client, top1 = counts.most_common(1)[0]
        logger.record(
            kind="selection_hist", defense=self.cfg.defense,
            counts={str(k): v for k, v in sorted(counts.items())},
            rounds=len(wins), distinct_winners=len(counts),
            top1_share=round(top1 / len(wins), 4),
            top1_client=top1_client,
            malicious_picks=sum(1 for w in wins if w < self.m_mal))

    def run(self, logger: Optional[RunLogger] = None,
            checkpointer=None, timer=None, journal=None,
            shutdown=None) -> dict:
        """Full experiment loop (reference main.py:64-95).

        ``timer``: an optional utils.profiling.PhaseTimer; per-phase
        wall-clock (round / eval, device-synchronized) is accumulated and
        written as a structured record at the end (the reference's only
        timing artifact is one timestamp, main.py:97).

        ``journal``: an optional utils.lifecycle.RunJournal — rounds and
        evals are committed at host boundaries with exactly-once
        semantics across restarts, and per-round event emission is
        gated by the journal's high-water mark so a resumed run never
        re-emits what a previous attempt already recorded.  None (the
        default) leaves every pre-lifecycle caller untouched.

        ``shutdown``: an optional utils.lifecycle.GracefulShutdown; its
        request flag is polled at each span boundary — when set, the
        engine auto-checkpoints, records a 'lifecycle' preempt event,
        marks the journal 'preempted' and raises
        utils.lifecycle.Preempted (the CLI maps it to exit code 75).

        Logger ownership: a logger the engine creates itself is managed
        with ``with`` (crash-safe close — JSONL handle closed, accuracy
        CSV written even if the loop raises); a caller-provided logger is
        ``finish()``ed on success as before, and the caller's own
        ``with`` (cli.py) covers the crash path."""
        import contextlib

        cfg = self.cfg
        own_logger = logger is None
        logger = logger or RunLogger(cfg, cfg.output, cfg.log_dir)
        test_size = len(self.dataset.test_y)
        self._telemetry_winners = []

        def phase(name, sync=None):
            if timer is None:
                return contextlib.nullcontext()
            return timer.phase(name,
                               sync_on=sync or (lambda: self.state.weights))

        with contextlib.ExitStack() as stack:
            if own_logger:
                stack.enter_context(logger)
            return self._run_body(logger, checkpointer, timer, phase,
                                  test_size, journal, shutdown)

    def _preempt(self, logger, checkpointer, epoch, journal, shutdown):
        """Honor a graceful-shutdown request at a span boundary: persist
        an auto-checkpoint (creating a Checkpointer if the caller runs
        without one — a preempt that loses the run would defeat the
        point), flush a 'lifecycle' preempt event, mark the journal and
        raise Preempted (utils/lifecycle.py)."""
        from attacking_federate_learning_tpu.utils.checkpoint import (
            Checkpointer
        )
        from attacking_federate_learning_tpu.utils.lifecycle import (
            EXIT_PREEMPTED, Preempted
        )

        ck = checkpointer or Checkpointer(
            self.cfg,
            auto_dir=journal.dir if journal is not None else None)
        path = ck.save_auto(self.state, extra=self.fault_state_host())
        source = shutdown.source or "signal"
        logger.record(kind="lifecycle", phase="preempt", round=int(epoch),
                      source=source, checkpoint=path,
                      attempt=journal.attempt if journal is not None else 1)
        logger.print(f"!! preempted ({source}) after round {epoch}; "
                     f"state checkpointed to {path}; "
                     f"exiting {EXIT_PREEMPTED} (resumable)")
        if journal is not None:
            journal.finish("preempted", EXIT_PREEMPTED, checkpoint=path)
            journal.close()
        raise Preempted(epoch, source)

    def _run_body(self, logger, checkpointer, timer, phase, test_size,
                  journal=None, shutdown=None):
        cfg = self.cfg
        if cfg.backdoor:
            # Pre-training accuracy line (reference main.py:45-51).
            loss0, correct0 = self.evaluate(self.state.weights)
            logger.print(
                "\nBEFORE: Test set. Average loss: {:.4f}, Accuracy: {}/{} "
                "({:.2f}%)".format(float(loss0), int(correct0), test_size,
                                   100.0 * float(correct0) / test_size))
        else:
            logger.print("\nStarting Training...")

        # Resume-aware: a restored ServerState carries its round counter
        # (utils/checkpoint.py), so the loop continues where it stopped.
        # When the attack is fusable and no per-round observability is
        # requested, all rounds between eval points run as ONE scanned
        # device program (run_span); eval cadence is identical either way.
        use_spans = (not self._staged and not cfg.log_round_stats
                     and timer is None and not self._streaming)
        ckpt_every = cfg.checkpoint_every
        watchdog_on = self.faults is not None and self.faults.watchdog
        self._rollbacks = 0
        if watchdog_on or ckpt_every:
            # Last-good snapshot: the rollback target until the first
            # auto-checkpoint boundary replaces it.
            self._last_good = (self._host_copy(self.state),
                               self.fault_state_host())
        epoch = int(self.state.round)
        start_epoch = epoch
        last_asr = None
        if journal is not None:
            attempt = journal.start_attempt(epoch)
            phase_name = ("start" if attempt == 1 and epoch == 0
                          else "resume")
            logger.record(kind="lifecycle", phase=phase_name,
                          round=epoch, attempt=attempt,
                          replay_high=journal.high)
            if phase_name == "resume":
                logger.print(
                    f"[lifecycle] attempt {attempt} resumes at round "
                    f"{epoch} (journal high-water {journal.high}: "
                    f"replayed rounds/evals are not re-recorded)")

        def fresh(t):
            # Exactly-once event emission across restarts: a round at or
            # below the journal's high-water mark was already recorded
            # by the attempt that committed it (deterministic replay
            # recomputes the identical values — re-emitting would
            # double-count them downstream).
            return journal is None or journal.fresh_round(t)

        # Measured-walls observatory (cfg.profile_every > 0, span paths
        # only — the per-round paths already carry --profile's
        # PhaseTimer): every span is timed on the host clock at its
        # existing boundary, and every K-th eval interval additionally
        # runs under a profiler capture booked onto the stage taxonomy
        # (utils/walls.py).  Off (the default), none of this executes —
        # no extra syncs, no events, and the compiled programs are
        # pinned byte-identical either way (tests/test_walls.py).
        prof_k = int(cfg.profile_every or 0)
        walls_interval = 0
        loop_t0 = time.perf_counter()

        while epoch < cfg.epochs:
            if use_spans:
                # Advance to the next eval boundary in one device
                # program; auto-checkpoint boundaries clip the span too
                # (a span must not run past its own checkpoint cadence).
                if epoch % cfg.test_step == 0:
                    boundary = epoch
                else:
                    boundary = min((epoch // cfg.test_step + 1)
                                   * cfg.test_step, cfg.epochs - 1)
                if ckpt_every:
                    # Same boundary quirk as the eval cadence above: at
                    # a checkpoint epoch the span is one round, so the
                    # save below runs right after it.
                    boundary = min(boundary,
                                   epoch if epoch % ckpt_every == 0
                                   else (epoch // ckpt_every + 1)
                                   * ckpt_every)
                count = boundary - epoch + 1
                if prof_k > 0:
                    from attacking_federate_learning_tpu.utils import (
                        profiling as _prof
                    )

                    profiled = walls_interval % prof_k == 0
                    walls_interval += 1
                    trace_dir = (os.path.join(logger.log_dir,
                                              "walltrace", f"r{epoch}")
                                 if profiled else None)
                    t_span = time.perf_counter()
                    with _prof.device_trace(trace_dir):
                        self.run_span(epoch, count)
                        # The sync the host wall needs; the span paths
                        # fetch at this boundary anyway, so nothing new
                        # crosses in-jit.
                        jax.block_until_ready(self.state.weights)
                    span_wall = time.perf_counter() - t_span
                    logger.record(
                        kind="wall", source="host",
                        name=self._span_entry_name(), round=int(epoch),
                        rounds=int(count), wall_s=round(span_wall, 6),
                        rounds_per_s=(round(count / span_wall, 4)
                                      if span_wall > 0 else 0.0))
                    if trace_dir is not None:
                        self._book_span_walls(logger, trace_dir, count)
                else:
                    self.run_span(epoch, count)
                if ((cfg.telemetry or cfg.margins or cfg.numerics
                        or self.faults is not None
                        or self._secagg is not None
                        or self._async is not None)
                        and self.last_span_telemetry is not None):
                    # ONE host fetch per eval interval: the whole stacked
                    # telemetry pytree comes over at the eval boundary.
                    t0, stacked = self.last_span_telemetry
                    host = jax.tree.map(np.asarray, stacked)
                    for i in range(boundary - epoch + 1):
                        if fresh(t0 + i):
                            self._emit_round_telemetry(
                                logger, t0 + i,
                                jax.tree.map(lambda a: a[i], host))
                    self.last_span_telemetry = None
                if self.traffic is not None and self._traffic_events:
                    # Traffic events are host-born (the schedule knows
                    # arrivals and ladder actions before the device
                    # runs) — emitted at the same exactly-once boundary
                    # as the fetched telemetry.
                    for tt in range(epoch, boundary + 1):
                        ev = self._traffic_events.pop(tt, None)
                        if ev is not None and fresh(tt):
                            logger.record(kind="traffic", **ev)
                if journal is not None:
                    journal.commit_rounds(epoch, boundary)
                epoch = boundary
            else:
                with phase("round"):
                    self.run_round(epoch)
                if (cfg.log_round_stats and fresh(epoch)
                        and self.last_round_stats is not None):
                    logger.record(kind="round", round=epoch,
                                  **{k: float(v) for k, v in
                                     self.last_round_stats.items()})
                if ((cfg.telemetry or cfg.margins or cfg.numerics
                        or self.faults is not None
                        or self._secagg is not None
                        or self._async is not None)
                        and fresh(epoch)
                        and self.last_round_telemetry is not None):
                    self._emit_round_telemetry(
                        logger, epoch,
                        jax.tree.map(np.asarray,
                                     self.last_round_telemetry))
                if self.traffic is not None and self._traffic_events:
                    ev = self._traffic_events.pop(epoch, None)
                    if ev is not None and fresh(epoch):
                        logger.record(kind="traffic", **ev)
                if journal is not None:
                    journal.commit_rounds(epoch, epoch)

            if watchdog_on and self._diverged():
                # Graceful degradation: restore the last good state and
                # re-run from there instead of aborting (bounded by
                # max_rollbacks); the eval below never sees the
                # diverged weights.
                self._rollback(logger, epoch, checkpointer)
                epoch = int(self.state.round)
                continue

            if ((epoch % cfg.test_step == 0 or epoch == cfg.epochs - 1)
                    and (journal is None or journal.fresh_eval(epoch))):
                # Replayed evals (journal) are skipped entirely: eval is
                # pure observation of the deterministically-recomputed
                # state, so re-running it would only duplicate 'eval'
                # events and burn the resume window.
                # The lambda reads `correct` after the block assigns it, so
                # the timer blocks on the eval outputs, not stale state.
                t_eval = time.perf_counter()
                with phase("eval", lambda: correct):
                    test_loss, correct = self.evaluate(self.state.weights)
                if prof_k > 0:
                    # Host eval wall (source='host'); the block the
                    # clock needs is the one record_eval below pays
                    # anyway when it converts the outputs.
                    jax.block_until_ready((test_loss, correct))
                    logger.record(kind="wall", source="host",
                                  name="eval", round=int(epoch),
                                  wall_s=round(
                                      time.perf_counter() - t_eval, 6))
                accuracy = logger.record_eval(epoch, test_loss, correct,
                                              test_size)
                if (accuracy > cfg.checkpoint_acc_threshold
                        and checkpointer is not None):
                    # Carry state rides EVERY checkpoint (not just the
                    # autos): --resume picks the newest by round, and a
                    # best-accuracy save that tied an auto would
                    # otherwise silently drop the async buffers / fault
                    # ring on resume.
                    checkpointer.save(self.state, accuracy,
                                      extra=self.carry_state_host())
                if cfg.backdoor and hasattr(self.attacker, "test_asr"):
                    # Post-aggregation backdoor check, printed after the
                    # accuracy line as in the reference (main.py:91-95).
                    asr = self.attacker.test_asr(self.state.weights,
                                                 logger=logger, tag="POST")
                    last_asr = float(asr)
                    logger.record(kind="asr", round=epoch,
                                  attack_success_rate=last_asr)
                if journal is not None:
                    journal.commit_eval(epoch)
            if ckpt_every and epoch % ckpt_every == 0:
                # Periodic auto-checkpoint (atomic + rotated,
                # utils/checkpoint.py) — the watchdog above has already
                # certified this state, so it also becomes the new
                # in-memory last-good rollback target.
                self._last_good = (self._host_copy(self.state),
                                   self.fault_state_host())
                if checkpointer is not None:
                    checkpointer.save_auto(self.state,
                                           extra=self._last_good[1])
            if (shutdown is not None
                    and shutdown.should_preempt(start_epoch, epoch)):
                # Span boundary = the only place a checkpoint is
                # coherent (state.round == epoch + 1, fault ring buffer
                # at the matching phase); a signal that landed mid-span
                # waited here.
                self._preempt(logger, checkpointer, epoch, journal,
                              shutdown)
            epoch += 1

        if self.cfg.telemetry:
            self._emit_selection_hist(logger)
        if timer is not None:
            logger.record(kind="profile", phases=timer.summary())
        if self._streaming:
            # Did the host gather/transfer sit on the round path?
            # (VERDICT r2 #3's stream-stall measurement; near-zero stall
            # per get means the prefetch pipeline kept up.)
            logger.record(kind="stream", **self.stream.stall_stats())
        if journal is not None:
            logger.record(kind="lifecycle", phase="complete",
                          round=int(self.state.round) - 1,
                          attempt=journal.attempt)
            # Registry stamp (PR 5, utils/registry.py): the manifest
            # becomes the run's queryable summary — trajectory
            # endpoints, the event-log join path, and the full config
            # (what 'runs diff' reads for config deltas) — and one
            # index line is appended so the finished run is resolvable
            # without a rescan.  A v4 'registry' event mirrors the
            # stamp into the event log itself.
            import dataclasses as _dc

            from attacking_federate_learning_tpu.utils.lifecycle import (
                run_id_for
            )
            from attacking_federate_learning_tpu.utils.registry import (
                RunRegistry
            )

            summary = {"events": os.path.abspath(logger.jsonl_path)}
            # Headline wall summary (always-on, sync-free: total loop
            # wall over committed rounds) — the campaign table's time
            # column reads this off the registry entry.
            rounds_done = int(self.state.round) - start_epoch
            loop_wall = time.perf_counter() - loop_t0
            if rounds_done > 0 and loop_wall > 0:
                summary["rounds_per_s"] = round(rounds_done / loop_wall,
                                                4)
            if logger.accuracies:
                summary["final_accuracy"] = round(
                    float(logger.accuracies[-1]), 4)
                summary["max_accuracy"] = round(
                    float(max(logger.accuracies)), 4)
            if last_asr is not None:
                summary["final_asr"] = round(last_asr, 4)
            logger.record(kind="registry", run_id=journal.run_id,
                          rounds=int(self.state.round), **summary)
            journal.finish("done",
                           config=_dc.asdict(cfg),
                           config_hash=run_id_for(cfg).rsplit("_", 1)[-1],
                           **summary)
            journal.close()
            try:
                reg = RunRegistry(cfg.run_dir)
                reg.stamp(reg._entry_for_run(journal.run_id,
                                             migrate=False))
            except OSError as e:       # an unwritable index must not
                logger.print(f"[registry] stamp failed: {e}")  # fail a
                #                                           finished run
        logger.finish()
        return {"accuracies": logger.accuracies,
                "epochs": logger.accuracies_epochs,
                "final_weights": self.state.weights}
