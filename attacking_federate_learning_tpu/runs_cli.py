"""The ``runs`` subcommand: query and compare the cross-run registry.

``cli.py`` dispatches ``... cli runs <verb>`` here (before argparse, so
the experiment flag surface stays reference-verbatim).  Verbs:

- ``runs list``     — refresh + print the index (``key=value`` filters)
- ``runs show Q``   — one resolved run's full entry + journal audit
- ``runs diff A B`` — field-by-field diff of two runs: config deltas,
  final accuracy/ASR, fault/lifecycle/cache counts, and the per-round
  trajectory divergence point (bit-identity when the shared rounds
  match exactly — the determinism witness two same-seed runs must
  pass).  ``--band N`` relaxes the float comparison to an N-ulp band
  in the float32 domain: cross-ENGINE twins (sharded vs single-device,
  flat vs hierarchical tier-1) legally differ by ~1-ulp reduction
  reorders that cascade through selection-mediated metrics (the PR 4
  adjudication rationale, tests/test_distance_impl.py) — exact-float
  compare makes those diffs all-noise, the band names only the real
  divergences
- ``runs compare Q...`` — side-by-side metric table over N runs
- ``runs tag Q TAG``    — attach a resolvable human tag
- ``runs trace Q``      — export the run's event log as Chrome/Perfetto
  trace JSON (utils/trace_export.py; hierarchical runs get the tier-2
  rejection counter + forensics instants as their own track)
- ``runs forensics Q``  — tier-2 selection forensics + the colluder-
  localization verdict over a hierarchical run's schema-v6
  shard_selection stream (report.py:forensics_summary)
- ``runs campaign [Q]`` — list campaigns, or render one campaign's
  defense x attack table (report.py:campaign_table) with metric values
  resolved through the registry — the values match the per-run
  manifests bit-exactly, and skipped cells show their composition-
  rejection reason.  Refreshes the registry first (campaign cells
  finish out-of-band, so a cold index would lie)
- ``runs attribution Q [B]`` — per-stage cost table (the ISSUE-15
  taxonomy: deliver/quarantine/protect/tier1_aggregate/
  tier2_aggregate/apply) and per-seam wire-bytes table from a run's
  schema-v9 ``stage_cost``/``wire_bytes`` events (any --cost-report
  run carries them; campaign cells do automatically).  A second query
  renders the two runs' stage/seam diff instead
- ``runs walls Q [B]`` — measured per-stage wall tables from a run's
  schema-v10 ``wall`` events (any --profile-every run carries them):
  per-entry stage-wall medians over the run's trace captures, joined
  to the entry's stage_cost twin for measured-vs-modeled ratios, plus
  the host-clock span/eval rollup.  A second query renders the two
  runs' stage-wall diff instead (delta marks fire above 25% — walls
  are measured, so exact-equality marks would flag noise)
- ``runs margins Q [B]`` — per-defense margin trajectories from a
  run's schema-v12 ``margin`` events (any --margins run carries them):
  the colluder-survival ledger (defense-sign colluder margin,
  selected-colluder count, kept mass) plus the Krum winner/runner-up
  gap and traffic f_eff per round.  A second query renders the two
  runs' colluder-margin drift instead — per-round deltas with
  sign-flip marks (a flip is a defense decision REVERSAL between the
  runs, the signal the margin-drift gate watches)
- ``runs selfcheck``    — CI leg: refresh idempotence + resolvability
  over the current run store (tools/smoke.sh leg 6)

Resolution (utils/registry.py): exact run_id, unique prefix, tag, with
``key=value`` filters narrowing first.  Pure log/JSON reading — no jax.
Stale-index guard: verbs that read without refreshing warn LOUDLY when
``runs/index.jsonl`` is older than the newest run manifest/journal
(utils/registry.py:stale_run_ids) instead of silently reporting
outdated summaries.
"""

from __future__ import annotations

import argparse
import json
import os

from attacking_federate_learning_tpu.utils.metrics import iter_events
from attacking_federate_learning_tpu.utils.registry import RunRegistry


# Entry fields shown by `runs list` / `runs compare`.
_LIST_FIELDS = ("status", "dataset", "defense", "seed", "rounds_committed",
                "final_accuracy", "final_asr", "tag")
_COMPARE_FIELDS = ("source", "status", "attempts", "rounds_committed",
                   "evals_committed", "final_accuracy", "max_accuracy",
                   "final_asr", "cache_hits", "fault_rounds", "torn_lines")

# Per-round event kinds whose payloads witness the trajectory; 't'
# (wall clock) and 'v' (schema stamp) are not trajectory.
_TRAJ_KINDS = ("round", "eval", "asr", "defense", "attack", "fault",
               "margin", "numerics")
_NON_TRAJ_FIELDS = {"t", "v"}


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.2f}"
    return str(v)


def _load_run_events(entry):
    """The run's event stream (torn-tolerant), or [] when the entry has
    no readable log."""
    path = entry.get("events")
    if not isinstance(path, str) or not os.path.exists(path):
        return []
    return list(iter_events(path, validate=False, skip_bad=True))


def _trajectory(events):
    """{round: {kind: payload}} over the per-round kinds — the
    comparable fingerprint of one run's behavior."""
    out = {}
    for e in events:
        kind = e.get("kind")
        r = e.get("round")
        if kind not in _TRAJ_KINDS or not isinstance(r, (int, float)):
            continue
        payload = {k: v for k, v in e.items()
                   if k not in _NON_TRAJ_FIELDS}
        out.setdefault(int(r), {})[kind] = payload
    return out


def _f32_ord(x: float) -> int:
    """Monotonic integer ordinal of a float in the float32 domain:
    adjacent representable f32 values differ by exactly 1.  Event
    floats are f32 measurements serialized through JSON f64, so the
    f32 lattice is the native resolution of an event-log ulp."""
    import struct

    (u,) = struct.unpack("<I", struct.pack("<f", float(x)))
    return u if u < 0x80000000 else 0x80000000 - u


def _values_match(a, b, band: int) -> bool:
    """Payload-field equality under an optional N-ulp float band.
    ``band == 0`` is exact compare (the same-seed determinism bar);
    ``band > 0`` admits numeric values within ``band`` f32 ulps
    (NaN matches only NaN; lists compare elementwise)."""
    if a == b:
        return True
    if band <= 0:
        return False
    num = (int, float)
    if (isinstance(a, num) and isinstance(b, num)
            and not isinstance(a, bool) and not isinstance(b, bool)):
        if a != a or b != b:            # NaN never passes a == b above:
            return a != a and b != b    # equal only when BOTH are NaN
        try:
            return abs(_f32_ord(a) - _f32_ord(b)) <= band
        except (OverflowError, ValueError):
            return False
    if (isinstance(a, list) and isinstance(b, list)
            and len(a) == len(b)):
        return all(_values_match(x, y, band) for x, y in zip(a, b))
    return False


def diff_trajectories(events_a, events_b, band: int = 0) -> dict:
    """First-divergence analysis over two runs' per-round records.

    Compares the payloads of every shared (round, kind) pair in round
    order; the first mismatch names the round, the kind and the fields
    that differ.  ``bit_identical`` is True when every shared pair
    matches exactly — floats included, which is the right bar: the
    engine is deterministic, so two same-seed runs must reproduce to
    the bit and any ulp wiggle is a real (if legal) program change.
    ``band`` (f32 ulps, ``runs diff --band N``) relaxes the float
    compare for cross-engine twins whose metrics legally sit on 1-ulp
    reduction-reorder flips; a clean banded compare reports
    ``identical_within_band`` instead of bit-identity."""
    ta, tb = _trajectory(events_a), _trajectory(events_b)
    shared = sorted(set(ta) & set(tb))
    out = {"rounds_a": len(ta), "rounds_b": len(tb),
           "rounds_compared": len(shared), "band_ulps": band,
           "divergence_round": None, "bit_identical": False}
    for r in shared:
        kinds = sorted(set(ta[r]) & set(tb[r]))
        for kind in kinds:
            pa, pb = ta[r][kind], tb[r][kind]
            bad = sorted(k for k in set(pa) | set(pb)
                         if not _values_match(pa.get(k), pb.get(k),
                                              band))
            if bad:
                out["divergence_round"] = r
                out["divergence_kind"] = kind
                out["divergence_fields"] = {
                    k: [pa.get(k), pb.get(k)] for k in bad[:5]}
                if kind in ("margin", "numerics"):
                    # The observatory events carry their own stage
                    # attribution: name WHERE in the round pipeline
                    # the first mismatch sits and how big it is in
                    # f32 ulp (utils/numerics.py:FIELD_STAGE).
                    from attacking_federate_learning_tpu.utils import (
                        numerics as N
                    )
                    stage, ulp, anchor = N.divergence_attribution(
                        out["divergence_fields"], kind=kind)
                    out["divergence_stage"] = stage
                    out["divergence_ulp"] = ulp
                    out["divergence_anchor"] = anchor
                return out
    if shared and band == 0:
        out["bit_identical"] = True
    elif shared:
        out["identical_within_band"] = True
    return out


def diff_runs(reg: RunRegistry, ea: dict, eb: dict,
              band: int = 0) -> dict:
    """Field-by-field run diff: config deltas (from the stamped
    manifests), summary-field deltas, and the trajectory divergence
    point from the two event logs (``band``: f32-ulp tolerance for the
    trajectory floats — see :func:`diff_trajectories`)."""
    out = {"a": ea.get("run_id"), "b": eb.get("run_id")}
    ca, cb = reg.load_config(ea), reg.load_config(eb)
    if ca is not None and cb is not None:
        out["config_deltas"] = {
            k: [ca.get(k), cb.get(k)]
            for k in sorted(set(ca) | set(cb)) if ca.get(k) != cb.get(k)}
    out["field_deltas"] = {
        k: [ea.get(k), eb.get(k)]
        for k in _COMPARE_FIELDS if ea.get(k) != eb.get(k)}
    out["trajectory"] = diff_trajectories(_load_run_events(ea),
                                          _load_run_events(eb),
                                          band=band)
    return out


def _print_diff(d, out=print):
    out(f"== runs diff: {d['a']}  vs  {d['b']} ==")
    cd = d.get("config_deltas")
    if cd is None:
        out("  config: no stamped configs (pre-registry manifests)")
    elif not cd:
        out("  config: identical")
    else:
        out(f"  config deltas ({len(cd)}):")
        for k, (va, vb) in cd.items():
            out(f"    {k}: {va!r} -> {vb!r}")
    fd = d["field_deltas"]
    if fd:
        out("  summary deltas:")
        for k, (va, vb) in fd.items():
            out(f"    {k}: {_fmt(va)} vs {_fmt(vb)}")
    else:
        out("  summary: identical")
    tr = d["trajectory"]
    if not tr["rounds_compared"]:
        out("  trajectory: no shared per-round records to compare")
    elif tr["bit_identical"]:
        out(f"  trajectory: BIT-IDENTICAL over {tr['rounds_compared']} "
            f"shared rounds")
    elif tr.get("identical_within_band"):
        out(f"  trajectory: identical within {tr['band_ulps']}-ulp band "
            f"over {tr['rounds_compared']} shared rounds")
    elif tr["divergence_round"] is not None:
        fields = ", ".join(
            f"{k} ({_fmt(v[0])} vs {_fmt(v[1])})"
            for k, v in tr["divergence_fields"].items())
        out(f"  trajectory: first divergence at round "
            f"{tr['divergence_round']} in '{tr['divergence_kind']}' "
            f"[{fields}]")
        if tr.get("divergence_stage") is not None:
            ulp = tr.get("divergence_ulp")
            size = f"{ulp} ulp" if ulp is not None else "non-numeric"
            out(f"    stage: {tr['divergence_stage']} via field "
                f"'{tr['divergence_anchor']}' ({size})")


def _refresh(reg, args):
    summary = reg.refresh(bench=args.bench, progress=args.progress)
    return summary


def _warn_if_stale(reg):
    """The stale-index footgun: reading without refresh must be LOUD
    when the store moved under the index (utils/registry.py)."""
    stale = reg.stale_run_ids()
    if stale:
        show = ", ".join(str(s) for s in stale[:4])
        more = f" (+{len(stale) - 4} more)" if len(stale) > 4 else ""
        print(f"[registry] WARNING: {reg.index_path} is older than "
              f"{len(stale)} run journal(s)/manifest(s): {show}{more} "
              f"— summaries below may be stale; drop --no-refresh or "
              f"run 'runs list' to rebuild")
    return stale


def cmd_list(reg, args):
    if not args.no_refresh:
        s = _refresh(reg, args)
        print(f"[registry] {s['entries']} entries "
              f"({s['built']} rebuilt, {s['reused']} reused"
              + (f", {s['migrated']} checkpoint(s) migrated"
                 if s.get("migrated") else "") + ")")
    else:
        _warn_if_stale(reg)
    ents = reg.entries(args.filter)
    if args.json:
        print(json.dumps(ents, default=str))
        return 0
    if not ents:
        print("no runs in the index (run something with --journal, or "
              "check --run-dir)")
        return 0
    for e in ents:
        cols = "  ".join(f"{k}={_fmt(e.get(k))}" for k in _LIST_FIELDS
                         if e.get(k) is not None)
        print(f"{e['run_id']}  [{e.get('source', '?')}]  {cols}")
    return 0


def cmd_show(reg, args):
    e = reg.resolve(args.query, args.filter)
    if args.json:
        print(json.dumps(e, default=str))
        return 0
    print(f"== {e['run_id']} ==")
    for k in sorted(e):
        if k in ("run_id", "sig"):
            continue
        print(f"  {k}: {e[k]}")
    if e.get("source") == "run":
        from attacking_federate_learning_tpu.utils.lifecycle import (
            RunJournal
        )
        j = RunJournal(os.path.dirname(e["dir"]), e["run_id"])
        problems = j.verify()
        j.close()
        print("  journal audit: " + ("clean" if not problems
                                     else "; ".join(problems)))
    return 0


def cmd_diff(reg, args):
    d = diff_runs(reg, reg.resolve(args.a, args.filter),
                  reg.resolve(args.b, args.filter), band=args.band)
    if args.json:
        print(json.dumps(d, default=str))
    else:
        _print_diff(d)
    return 0


def cmd_compare(reg, args):
    ents = [reg.resolve(q, args.filter) for q in args.queries]
    if args.json:
        print(json.dumps(ents, default=str))
        return 0
    width = max(len(str(e["run_id"])) for e in ents)
    header = f"{'run_id':<{width}}  " + "  ".join(
        f"{k:>14s}" for k in _COMPARE_FIELDS)
    print(header)
    for e in ents:
        print(f"{e['run_id']:<{width}}  " + "  ".join(
            f"{_fmt(e.get(k)):>14s}" for k in _COMPARE_FIELDS))
    return 0


def cmd_tag(reg, args):
    e = reg.tag(args.query, args.tag)
    print(f"tagged {e['run_id']} as {args.tag!r}")
    return 0


def cmd_trace(reg, args):
    from attacking_federate_learning_tpu.utils.trace_export import (
        export_trace
    )

    e = reg.resolve(args.query, args.filter)
    events = e.get("events")
    if not isinstance(events, str) or not os.path.exists(events):
        print(f"run {e['run_id']} has no readable event log "
              f"(events={events!r})")
        return 1
    out = export_trace(events, args.out, name=e["run_id"])
    print(f"wrote {out} (load in chrome://tracing or ui.perfetto.dev)")
    return 0


def cmd_forensics(reg, args):
    """Registry-resolved 'report forensics' (report.py): the tier-2
    rejection attribution + colluder-localization verdict over a
    hierarchical run's schema-v6 shard_selection stream."""
    from attacking_federate_learning_tpu.report import forensics_main

    e = reg.resolve(args.query, args.filter)
    events = e.get("events")
    if not isinstance(events, str) or not os.path.exists(events):
        print(f"run {e['run_id']} has no readable event log "
              f"(events={events!r})")
        return 1
    fargs = [events]
    if args.json:
        fargs.append("--json")
    if args.events:
        fargs += ["--events", args.events]
    return forensics_main(fargs)


def cmd_async(reg, args):
    """Registry-resolved staleness table (report.py:async_summary):
    per-round delivered counts, the aggregate staleness histogram and
    the weight mass per staleness bucket from a run's v7 'async'
    stream.  Exit 1 when the run carries no async events (a
    synchronous run)."""
    import json as _json

    from attacking_federate_learning_tpu.report import (
        async_summary, load_events
    )

    e = reg.resolve(args.query, args.filter)
    events = e.get("events")
    if not isinstance(events, str) or not os.path.exists(events):
        print(f"run {e['run_id']} has no readable event log "
              f"(events={events!r})")
        return 1
    asy = async_summary(load_events([events], skip_bad=True))
    if asy is None:
        print(f"run {e['run_id']}: no 'async' events — the staleness "
              f"table needs an --aggregation async run")
        return 1
    if args.json:
        print(_json.dumps({e["run_id"]: asy}))
        return 0
    print(f"== {e['run_id']} ==")
    print(f"  async rounds {asy['rounds']}: delivered "
          f"{asy['delivered_total']} ({asy['delivered_mean']}/round, "
          f"{asy['empty_rounds']} empty), evicted "
          f"{asy['evicted_total']}, superseded "
          f"{asy['superseded_total']}, quarantined "
          f"{asy['quarantined_total']}")
    print("  delivered per round: "
          + "  ".join(str(d) for d in asy["delivered_per_round"]))
    if "staleness_hist" in asy:
        mass = asy.get("weight_mass",
                       [None] * len(asy["staleness_hist"]))
        print("  staleness   rows   weight mass")
        for s, (h, w) in enumerate(zip(asy["staleness_hist"], mass)):
            wtxt = f"{w:11.3f}" if w is not None else "          -"
            print(f"    s={s}     {h:5d}  {wtxt}")
    return 0


def cmd_traffic(reg, args):
    """Registry-resolved population-traffic table
    (report.py:traffic_summary): per-round arrived counts and
    effective-f, the degradation-ladder action histogram
    (remask/fallback/hold), which defenses actually aggregated, and
    the degraded rounds from a run's v11 'traffic' stream.  Exit 1
    when the run carries no traffic events (a static-cohort run)."""
    import json as _json

    from attacking_federate_learning_tpu.report import (
        load_events, traffic_summary
    )

    e = reg.resolve(args.query, args.filter)
    events = e.get("events")
    if not isinstance(events, str) or not os.path.exists(events):
        print(f"run {e['run_id']} has no readable event log "
              f"(events={events!r})")
        return 1
    tr = traffic_summary(load_events([events], skip_bad=True))
    if tr is None:
        print(f"run {e['run_id']}: no 'traffic' events — the traffic "
              f"table needs a --traffic-population run")
        return 1
    if args.json:
        print(_json.dumps({e["run_id"]: tr}))
        return 0
    print(f"== {e['run_id']} ==")
    print(f"  traffic rounds {tr['rounds']}: arrived "
          f"{tr['arrived_mean']}/round (min {tr['arrived_min']}), "
          f"f_eff {tr['f_eff_mean']}/round (max {tr['f_eff_max']})")
    print("  arrived per round: "
          + "  ".join(str(a) for a in tr["arrived_per_round"]))
    print("  f_eff   per round: "
          + "  ".join(str(f) for f in tr["f_eff_per_round"]))
    print("  action      rounds")
    for a in ("remask", "fallback", "hold"):
        if a in tr["actions"]:
            print(f"    {a:<9} {tr['actions'][a]:5d}")
    for a, n in sorted(tr["actions"].items()):
        if a not in ("remask", "fallback", "hold"):
            print(f"    {a:<9} {n:5d}")
    print("  aggregated by: "
          + ", ".join(f"{d} x{n}"
                      for d, n in sorted(tr["defenses"].items())))
    if tr["degraded_rounds"]:
        print("  degraded rounds: "
              + " ".join(str(r) for r in tr["degraded_rounds"]))
    return 0


def cmd_campaign(reg, args):
    """List campaigns, or render one campaign's defense x attack table
    from the registry (report.py:campaign_table).  The registry is
    refreshed first unless --no-refresh — campaign cells finish in
    child processes, so a cold index would render stale numbers (and
    with --no-refresh the staleness guard warns loudly instead)."""
    from attacking_federate_learning_tpu.report import (
        _print_campaign_table, campaign_table
    )

    camp_root = os.path.join(args.run_dir, "campaigns")
    try:
        names = sorted(
            n for n in os.listdir(camp_root)
            if os.path.exists(os.path.join(camp_root, n,
                                           "manifest.json")))
    except OSError:
        names = []
    if args.query is None:
        if not names:
            print(f"no campaigns under {camp_root} (run one with "
                  f"'campaign spec.json' or 'grid --journal')")
            return 0
        for n in names:
            with open(os.path.join(camp_root, n, "manifest.json")) as f:
                man = json.load(f)
            counts = "  ".join(
                f"{k}={v}" for k, v in sorted(
                    (man.get("counts") or {}).items()))
            print(f"{n}  [{man.get('status', '?')}]  "
                  f"order={man.get('order')}  {counts}")
        return 0
    matches = ([args.query] if args.query in names
               else [n for n in names if n.startswith(args.query)])
    if len(matches) != 1:
        print(f"campaign {args.query!r} "
              + (f"is ambiguous: {matches}" if matches
                 else f"not found under {camp_root} "
                      f"({len(names)} campaigns)"))
        return 2
    with open(os.path.join(camp_root, matches[0],
                           "manifest.json")) as f:
        man = json.load(f)
    if args.no_refresh:
        _warn_if_stale(reg)
    else:
        _refresh(reg, args)
    entries = {str(e.get("run_id")): e for e in reg.entries()}
    table = campaign_table(man, entries)
    if args.json:
        print(json.dumps({"manifest": man, "table": table},
                         default=str))
        return 0
    _print_campaign_table(table)
    counts = man.get("counts") or {}
    print("  cells: " + "  ".join(f"{k}={v}" for k, v in
                                  sorted(counts.items()))
          + f"   cache: {man.get('cache')}")
    return 0


def _attribution_data(events):
    """The run's v9 observability payloads: {entry: stage_cost event}
    (last writer wins — one cost_report per run in practice) plus the
    run's wire_bytes event, or None when the run predates schema v9 /
    ran without --cost-report."""
    stages, wire = {}, None
    for e in events:
        if e.get("kind") == "stage_cost" and isinstance(
                e.get("name"), str):
            stages[e["name"]] = e
        elif e.get("kind") == "wire_bytes":
            wire = e
    if not stages and wire is None:
        return None
    return {"stages": stages, "wire": wire}


def _print_attribution(att):
    from attacking_federate_learning_tpu.utils.costs import STAGES

    for name in sorted(att["stages"]):
        ev = att["stages"][name]
        cov = ev.get("coverage") or {}
        cf, cb = cov.get("flops"), cov.get("bytes_accessed")
        covtxt = ("" if cf is None else
                  f"   coverage: flops {cf:.1%}, bytes {cb:.1%}")
        print(f"  entry {name}{covtxt}")
        print(f"    {'stage':<17}{'MFLOPs':>10}{'MB read+write':>15}"
              f"{'MB temp':>10}")
        rows = dict(ev.get("stages") or {})
        rows["unattributed"] = ev.get("unattributed") or {}
        for stage in tuple(STAGES) + ("unattributed",):
            r = rows.get(stage)
            if r is None:
                continue
            print(f"    {stage:<17}"
                  f"{r.get('flops', 0) / 1e6:>10.2f}"
                  f"{r.get('bytes_accessed', 0) / 1e6:>15.2f}"
                  f"{r.get('temp_bytes', 0) / 1e6:>10.2f}")
    wire = att["wire"]
    if wire:
        print(f"  wire seams ({wire.get('topology')}, cohort "
              f"{wire.get('cohort')}, d={wire.get('dim')}):")
        for seam, rec in (wire.get("seams") or {}).items():
            extra = "  [collective]" if rec.get("collective") else ""
            print(f"    {seam:<22}{rec.get('bytes', 0):>14,} B{extra}")
        print(f"    {'total':<22}{wire.get('total_bytes', 0):>14,} B")


def cmd_attribution(reg, args):
    """Per-stage cost and per-seam wire tables from a run's schema-v9
    ``stage_cost`` / ``wire_bytes`` events (emitted by --cost-report;
    campaign cells carry them automatically).  With a second query,
    diff the two runs' attributions instead — the observability
    counterpart of ``runs diff``'s trajectory compare.  Exit 1 when a
    run carries no attribution events."""
    ents = [reg.resolve(args.query, args.filter)]
    if args.b is not None:
        ents.append(reg.resolve(args.b, args.filter))
    atts = []
    for e in ents:
        att = _attribution_data(_load_run_events(e))
        if att is None:
            print(f"run {e['run_id']}: no stage_cost/wire_bytes "
                  f"events — rerun with --cost-report (schema v9+)")
            return 1
        atts.append(att)
    if args.json:
        print(json.dumps({e["run_id"]: a
                          for e, a in zip(ents, atts)}, default=str))
        return 0
    if len(ents) == 1:
        print(f"== {ents[0]['run_id']} ==")
        _print_attribution(atts[0])
        return 0
    from attacking_federate_learning_tpu.utils.costs import STAGES

    a, b = atts
    ida, idb = ents[0]["run_id"], ents[1]["run_id"]
    print(f"== attribution diff: {ida} vs {idb} ==")
    for name in sorted(set(a["stages"]) | set(b["stages"])):
        ea, eb = a["stages"].get(name), b["stages"].get(name)
        if ea is None or eb is None:
            print(f"  entry {name}: only in "
                  f"{ida if eb is None else idb}")
            continue
        print(f"  entry {name}  (MFLOPs: A, B, delta)")
        ra = dict(ea.get("stages") or {})
        ra["unattributed"] = ea.get("unattributed") or {}
        rb = dict(eb.get("stages") or {})
        rb["unattributed"] = eb.get("unattributed") or {}
        for stage in tuple(STAGES) + ("unattributed",):
            fa = (ra.get(stage) or {}).get("flops", 0.0)
            fb = (rb.get(stage) or {}).get("flops", 0.0)
            if fa == fb == 0:
                continue
            mark = "" if fa == fb else "   <-- differs"
            print(f"    {stage:<17}{fa / 1e6:>10.2f}{fb / 1e6:>10.2f}"
                  f"{(fb - fa) / 1e6:>+10.2f}{mark}")
    wa, wb = a["wire"], b["wire"]
    if wa or wb:
        sa = (wa or {}).get("seams") or {}
        sb = (wb or {}).get("seams") or {}
        print("  wire seams (bytes: A, B, delta)")
        for seam in sorted(set(sa) | set(sb)):
            ba = (sa.get(seam) or {}).get("bytes", 0)
            bb = (sb.get(seam) or {}).get("bytes", 0)
            mark = "" if ba == bb else "   <-- differs"
            print(f"    {seam:<22}{ba:>14,}{bb:>14,}{bb - ba:>+12,}"
                  f"{mark}")
    return 0


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2] if vals else None


def _walls_data(events):
    """The run's v10 measured-walls payloads, summarized: per-entry
    stage-wall medians over its trace captures (joined to the entry's
    v9 stage_cost for measured-vs-modeled ratios when present), plus
    the host-clock span/eval rollup.  None when the run predates
    schema v10 / ran without --profile-every."""
    from attacking_federate_learning_tpu.utils.costs import STAGES
    from attacking_federate_learning_tpu.utils.walls import (
        measured_vs_modeled
    )

    spans, evals, traces, costs = [], [], {}, {}
    for e in events:
        if e.get("kind") == "wall":
            if e.get("source") == "trace":
                traces.setdefault(str(e.get("name")), []).append(e)
            elif e.get("name") == "eval":
                evals.append(e)
            else:
                spans.append(e)
        elif e.get("kind") == "stage_cost" and isinstance(
                e.get("name"), str):
            costs[e["name"]] = e
    if not spans and not evals and not traces:
        return None
    out = {"host": {}, "entries": {}}
    if spans:
        rps = [e["rounds_per_s"] for e in spans
               if isinstance(e.get("rounds_per_s"), (int, float))]
        out["host"]["spans"] = {
            "count": len(spans),
            "rounds": sum(int(e.get("rounds", 0) or 0) for e in spans),
            "total_wall_s": round(sum(float(e.get("wall_s", 0.0))
                                      for e in spans), 4),
            "median_rounds_per_s": _median(rps)}
    if evals:
        out["host"]["evals"] = {
            "count": len(evals),
            "median_wall_ms": round(1e3 * _median(
                [float(e.get("wall_s", 0.0)) for e in evals]), 3)}
    for name, evs in traces.items():
        agg = {"captures": len(evs),
               "stages": {}, "unattributed_us": _median(
                   [float(e.get("unattributed_us", 0.0))
                    for e in evs])}
        for s in STAGES:
            vals = [float((e.get("stages") or {}).get(s, 0.0))
                    for e in evs]
            if any(v > 0 for v in vals):
                agg["stages"][s] = _median(vals)
        covs = [(e.get("coverage") or {}).get("op_time_fraction")
                for e in evs]
        covs = [c for c in covs if isinstance(c, (int, float))]
        if covs:
            agg["op_time_fraction"] = _median(covs)
        if name in costs:
            agg["vs_modeled"] = measured_vs_modeled(agg, costs[name])
        out["entries"][name] = agg
    return out


def _print_walls(w):
    from attacking_federate_learning_tpu.utils.costs import STAGES

    hs = w["host"].get("spans")
    if hs:
        rps = hs.get("median_rounds_per_s")
        print(f"  host walls: {hs['count']} spans / {hs['rounds']} "
              f"rounds in {hs['total_wall_s']:.2f} s"
              + (f", median {rps:.2f} rounds/s" if rps else ""))
    he = w["host"].get("evals")
    if he:
        print(f"  evals: {he['count']}, median "
              f"{he['median_wall_ms']:.1f} ms")
    for name in sorted(w["entries"]):
        agg = w["entries"][name]
        cov = agg.get("op_time_fraction")
        covtxt = (f"   op-time coverage {cov:.1%}"
                  if cov is not None else "")
        print(f"  entry {name}  ({agg['captures']} capture(s)){covtxt}")
        ratios = agg.get("vs_modeled") or {}
        print(f"    {'stage':<17}{'measured ms':>13}{'share':>8}"
              f"{'modeled':>9}{'ratio':>8}")
        rows = dict(agg.get("stages") or {})
        rows["unattributed"] = agg.get("unattributed_us") or 0.0
        for stage in tuple(STAGES) + ("unattributed",):
            us = rows.get(stage)
            if us is None or (us == 0.0 and stage not in ratios):
                continue
            r = ratios.get(stage) or {}
            share = r.get("measured_share")
            modeled = r.get("modeled_share")
            ratio = r.get("ratio")
            print(f"    {stage:<17}{us / 1e3:>13.3f}"
                  + (f"{share:>8.1%}" if share is not None
                     else f"{'':>8}")
                  + (f"{modeled:>9.1%}" if modeled is not None
                     else f"{'-':>9}")
                  + (f"{ratio:>8.2f}" if ratio is not None
                     else f"{'-':>8}"))


def cmd_walls(reg, args):
    """Measured per-stage wall tables from a run's schema-v10 'wall'
    events (emitted by --profile-every), with measured-vs-modeled
    ratios wherever the run also carries the v9 stage_cost twin.  With
    a second query, diff the two runs' stage walls instead — delta
    marks flag stages whose medians moved by more than 25% (walls are
    measured, so exact-equality marks would fire on noise).  Exit 1
    when a run carries no wall events."""
    ents = [reg.resolve(args.query, args.filter)]
    if args.b is not None:
        ents.append(reg.resolve(args.b, args.filter))
    walls = []
    for e in ents:
        w = _walls_data(_load_run_events(e))
        if w is None:
            print(f"run {e['run_id']}: no wall events — rerun with "
                  f"--profile-every K (schema v10+)")
            return 1
        walls.append(w)
    if args.json:
        print(json.dumps({e["run_id"]: w
                          for e, w in zip(ents, walls)}, default=str))
        return 0
    if len(ents) == 1:
        print(f"== {ents[0]['run_id']} ==")
        _print_walls(walls[0])
        return 0
    from attacking_federate_learning_tpu.utils.costs import STAGES

    a, b = walls
    ida, idb = ents[0]["run_id"], ents[1]["run_id"]
    print(f"== walls diff: {ida} vs {idb} ==")
    ha = (a["host"].get("spans") or {}).get("median_rounds_per_s")
    hb = (b["host"].get("spans") or {}).get("median_rounds_per_s")
    if ha and hb is not None:
        print(f"  rounds/s: {ha:.2f} vs {hb:.2f} "
              f"({(hb - ha) / ha:+.1%})")
    for name in sorted(set(a["entries"]) | set(b["entries"])):
        ea, eb = a["entries"].get(name), b["entries"].get(name)
        if ea is None or eb is None:
            print(f"  entry {name}: only in "
                  f"{ida if eb is None else idb}")
            continue
        print(f"  entry {name}  (measured ms: A, B, delta)")
        ra = dict(ea.get("stages") or {})
        ra["unattributed"] = ea.get("unattributed_us") or 0.0
        rb = dict(eb.get("stages") or {})
        rb["unattributed"] = eb.get("unattributed_us") or 0.0
        for stage in tuple(STAGES) + ("unattributed",):
            ua = float(ra.get(stage, 0.0))
            ub = float(rb.get(stage, 0.0))
            if ua == ub == 0.0:
                continue
            moved = abs(ub - ua) > 0.25 * max(ua, ub)
            mark = "   <-- differs" if moved else ""
            print(f"    {stage:<17}{ua / 1e3:>13.3f}{ub / 1e3:>13.3f}"
                  f"{(ub - ua) / 1e3:>+13.3f}{mark}")
    return 0


def _margin_series_data(events):
    """The run's v12 margin series, or None when the run carries no
    margin events (ran without --margins / predates schema v12)."""
    from attacking_federate_learning_tpu.utils.margins import (
        margin_series
    )

    ser = margin_series(events)
    return ser or None


def cmd_margins(reg, args):
    """Per-defense margin trajectories from a run's schema-v12
    'margin' events (--margins runs; utils/margins.py:margin_series):
    the colluder-survival ledger (defense-sign colluder margin,
    selected-colluder count, kept mass) plus the winner/runner-up gap
    and traffic f_eff per round.  With a second query, render the
    cross-run drift instead — per-round colluder-margin deltas with
    sign-flip marks (a flip is a defense decision reversal, not
    noise).  Exit 1 when a run carries no margin events."""
    ents = [reg.resolve(args.query, args.filter)]
    if args.b is not None:
        ents.append(reg.resolve(args.b, args.filter))
    series = []
    for e in ents:
        s = _margin_series_data(_load_run_events(e))
        if s is None:
            print(f"run {e['run_id']}: no margin events — rerun with "
                  f"--margins (schema v12+)")
            return 1
        series.append(s)
    if args.json:
        print(json.dumps({e["run_id"]: s
                          for e, s in zip(ents, series)}))
        return 0
    from attacking_federate_learning_tpu.utils.margins import (
        SERIES_FIELDS, margin_drift
    )

    def _cell(v):
        if v is None:
            return f"{'-':>10}"
        if isinstance(v, bool) or isinstance(v, int):
            return f"{v:>10d}"
        return f"{float(v):>10.4f}"

    if len(ents) == 1:
        print(f"== {ents[0]['run_id']} ==")
        for d, ser in sorted(series[0].items()):
            fields = [f for f in SERIES_FIELDS
                      if any(v is not None for v in ser[f])]
            print(f"  defense {d} ({len(ser['round'])} rounds)")
            print("    round " + "".join(f"{f:>22}"[-22:] for f in fields))
            for i, r in enumerate(ser["round"]):
                print(f"    {r:>5} " + "".join(
                    f"{'':>12}" + _cell(ser[f][i]) for f in fields))
            cm = [v for v in ser.get("colluder_margin", [])
                  if v is not None]
            if cm:
                neg = sum(1 for v in cm if v <= 0)
                print(f"    colluder margin: min {min(cm):+.4f}, "
                      f"final {cm[-1]:+.4f}, breached (<=0) "
                      f"{neg}/{len(cm)} rounds")
        return 0
    a, b = series
    ida, idb = ents[0]["run_id"], ents[1]["run_id"]
    print(f"== margin drift: {ida} vs {idb} ==")
    for d in sorted(set(a) | set(b)):
        if d not in a or d not in b:
            print(f"  defense {d}: only in {ida if d in a else idb}")
            continue
        dr = margin_drift(a[d], b[d])
        if not dr["rounds"]:
            print(f"  defense {d}: no shared rounds")
            continue
        print(f"  defense {d}  (colluder_margin: A, B, delta)")
        a_by_r = dict(zip(a[d]["round"], a[d]["colluder_margin"]))
        b_by_r = dict(zip(b[d]["round"], b[d]["colluder_margin"]))
        for r, delta in zip(dr["rounds"], dr["delta"]):
            va, vb = a_by_r.get(r), b_by_r.get(r)
            mark = "   <-- sign flip" if r in dr["sign_flips"] else ""
            dtxt = f"{delta:>+13.4f}" if delta is not None else f"{'-':>13}"
            print(f"    round {r:>4}{_cell(va):>13}{_cell(vb):>13}"
                  f"{dtxt}{mark}")
        if dr["sign_flips"]:
            print(f"    sign flips at rounds: "
                  + " ".join(str(r) for r in dr["sign_flips"]))
        else:
            print("    no sign flips (defense decisions stable "
                  "across runs)")
    return 0


def cmd_numerics(reg, args):
    """Numeric-health trajectories from a run's schema-v14 'numerics'
    events (--numerics runs; utils/numerics.py:numerics_series):
    per-round nonfinite counts by stage, gradient-norm dynamic range,
    tie-proximity and cancellation-depth counters, plus the tie-lock
    rollup.  With a second query, report per-field determinism drift
    instead — the first round where the two runs' series differ
    (utils/numerics.py:numerics_drift; same-seed twins must report
    none).  Exit 1 when a run carries no numerics events."""
    from attacking_federate_learning_tpu.utils.numerics import (
        numerics_drift, numerics_series
    )

    ents = [reg.resolve(args.query, args.filter)]
    if args.b is not None:
        ents.append(reg.resolve(args.b, args.filter))
    series = []
    for e in ents:
        s = numerics_series(_load_run_events(e))
        if not s:
            print(f"run {e['run_id']}: no numerics events — rerun "
                  f"with --numerics (schema v14+)")
            return 1
        series.append(s)
    if args.json:
        print(json.dumps({e["run_id"]: {f: list(map(list, v))
                                        for f, v in s.items()}
                          for e, s in zip(ents, series)}))
        return 0

    def _cell(v):
        if isinstance(v, float) and not v.is_integer():
            return f"{v:>12.4f}"
        return f"{int(v):>12d}"

    if len(ents) == 1:
        s = series[0]
        fields = sorted(s)
        rounds = sorted({r for v in s.values() for r, _ in v})
        print(f"== {ents[0]['run_id']} ==")
        print("  round " + "".join(f"{f:>16}"[-16:] for f in fields))
        by_f = {f: dict(s[f]) for f in fields}
        for r in rounds:
            print(f"  {r:>5} " + "".join(
                f"{'':>4}" + (_cell(by_f[f][r]) if r in by_f[f]
                              else f"{'-':>12}") for f in fields))
        nf = [v for _, v in s.get("nonfinite_total", [])]
        locked = [r for r, v in s.get("tie_locked", []) if v]
        ties = [v for _, v in s.get("tie_rows", [])]
        print(f"  health: nonfinite_total sum {int(sum(nf))}, "
              f"tie-locked {len(locked)}/{len(rounds)} rounds"
              + (f" (rounds {' '.join(map(str, locked[:8]))}"
                 + ("..." if len(locked) > 8 else "") + ")"
                 if locked else "")
              + (f", max tie_rows {int(max(ties))}" if ties else ""))
        return 0

    a, b = series
    ida, idb = ents[0]["run_id"], ents[1]["run_id"]
    print(f"== numerics drift: {ida} vs {idb} ==")
    drifted = False
    for f in sorted(set(a) | set(b)):
        if f not in a or f not in b:
            print(f"  {f}: only in {ida if f in a else idb}")
            drifted = True
            continue
        hit = numerics_drift(a, b, field=f)
        if hit is None:
            continue
        r, va, vb = hit
        drifted = True
        print(f"  {f}: first drift at round {r} "
              f"({_fmt(va)} vs {_fmt(vb)})")
    if not drifted:
        shared = len({r for v in a.values() for r, _ in v}
                     & {r for v in b.values() for r, _ in v})
        print(f"  deterministic twins: every shared field agrees over "
              f"{shared} shared rounds")
    return 0


def cmd_selfcheck(reg, args):
    """CI self-check (tools/smoke.sh leg 6): two refreshes must agree
    (incremental refresh is idempotent over an unchanged store), every
    run entry must resolve by its own id, and the index must survive
    its own round trip."""
    problems = []
    s1 = _refresh(reg, args)
    e1 = reg.entries()
    s2 = _refresh(reg, args)
    e2 = reg.entries()
    if e1 != e2:
        changed = [a.get("run_id") for a, b in zip(e1, e2) if a != b]
        problems.append(f"refresh not idempotent (changed: {changed})")
    if s2["built"] != 0:
        problems.append(f"second refresh rebuilt {s2['built']} "
                        f"entries over an unchanged store")
    for e in e2:
        try:
            got = reg.resolve(str(e["run_id"]))
            if got != e:
                problems.append(f"{e['run_id']}: resolve returned a "
                                f"different entry")
        except ValueError as err:
            problems.append(f"{e['run_id']}: unresolvable: {err}")
    torn = [e["run_id"] for e in e2
            if e.get("problems") or e.get("torn_lines")]
    print(f"[selfcheck] {len(e2)} entries, {s1['built']} rebuilt on "
          f"first refresh, 0 expected on second"
          + (f"; tolerated torn artifacts in {torn}" if torn else ""))
    if problems:
        for p in problems:
            print(f"FAIL selfcheck: {p}")
        return 1
    print("ok   selfcheck: index refresh idempotent, all entries "
          "resolvable")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="attacking_federate_learning_tpu runs",
        description="Query the cross-run registry (utils/registry.py: "
                    "runs/index.jsonl over journal dirs + BENCH/"
                    "PROGRESS artifacts).")
    p.add_argument("--run-dir", default="runs",
                   help="the run store to index (cfg.run_dir)")
    p.add_argument("--bench", action="append", default=None,
                   metavar="GLOB",
                   help="bench JSON glob to ingest on refresh "
                        "(repeatable; default BENCH_*.json; pass '' "
                        "to disable)")
    p.add_argument("--progress", action="append", default=None,
                   metavar="GLOB",
                   help="progress JSONL glob to ingest on refresh "
                        "(repeatable; default PROGRESS.jsonl; pass '' "
                        "to disable)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--filter", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="restrict to entries whose field matches "
                        "(repeatable; e.g. --filter defense=Krum)")
    sub = p.add_subparsers(dest="verb", required=True)
    sp = sub.add_parser("list", help="refresh + list the index")
    sp.add_argument("--no-refresh", action="store_true",
                    help="read the existing index without rescanning")
    sp.set_defaults(fn=cmd_list)
    sp = sub.add_parser("show", help="one run's full entry")
    sp.add_argument("query")
    sp.set_defaults(fn=cmd_show)
    sp = sub.add_parser("diff", help="field-by-field diff of two runs")
    sp.add_argument("a")
    sp.add_argument("b")
    sp.add_argument("--band", type=int, default=0, metavar="N",
                    help="f32-ulp tolerance for trajectory floats "
                         "(0 = exact bit compare; N > 0 admits legal "
                         "reduction-reorder wiggle when diffing "
                         "cross-engine twins)")
    sp.set_defaults(fn=cmd_diff)
    sp = sub.add_parser("compare", help="side-by-side metric table")
    sp.add_argument("queries", nargs="+")
    sp.set_defaults(fn=cmd_compare)
    sp = sub.add_parser("tag", help="attach a resolvable tag")
    sp.add_argument("query")
    sp.add_argument("tag")
    sp.set_defaults(fn=cmd_tag)
    sp = sub.add_parser("trace", help="export Chrome/Perfetto trace JSON")
    sp.add_argument("query")
    sp.add_argument("-o", "--out", default=None)
    sp.set_defaults(fn=cmd_trace)
    sp = sub.add_parser("forensics",
                        help="tier-2 selection forensics + colluder "
                             "localization (hierarchical runs with "
                             "--telemetry; report.py)")
    sp.add_argument("query")
    sp.add_argument("--events", default=None, metavar="JSONL",
                    help="append the v6 'forensics' verdict event to "
                         "this run log")
    sp.set_defaults(fn=cmd_forensics)
    sp = sub.add_parser("async",
                        help="staleness table from v7 'async' events "
                             "(--aggregation async runs; report.py "
                             "async_summary)")
    sp.add_argument("query")
    sp.set_defaults(fn=cmd_async)
    sp = sub.add_parser("traffic",
                        help="population-traffic table from v11 "
                             "'traffic' events (--traffic-population "
                             "runs; report.py traffic_summary)")
    sp.add_argument("query")
    sp.set_defaults(fn=cmd_traffic)
    sp = sub.add_parser("campaign",
                        help="list campaigns, or render one campaign's "
                             "defense x attack table from the registry "
                             "(campaigns/, report.py:campaign_table)")
    sp.add_argument("query", nargs="?", default=None,
                    help="campaign id or unique prefix (omit to list)")
    sp.add_argument("--no-refresh", action="store_true",
                    help="skip the registry refresh (the staleness "
                         "guard warns loudly if the store moved)")
    sp.set_defaults(fn=cmd_campaign)
    sp = sub.add_parser("attribution",
                        help="per-stage cost + per-seam wire tables "
                             "from v9 stage_cost/wire_bytes events "
                             "(--cost-report runs); a second query "
                             "diffs two runs")
    sp.add_argument("query")
    sp.add_argument("b", nargs="?", default=None,
                    help="second run: diff B against the first")
    sp.set_defaults(fn=cmd_attribution)
    sp = sub.add_parser("walls",
                        help="measured per-stage wall tables from v10 "
                             "'wall' events (--profile-every runs), "
                             "with measured-vs-modeled ratios; a "
                             "second query diffs two runs")
    sp.add_argument("query")
    sp.add_argument("b", nargs="?", default=None,
                    help="second run: diff B against the first")
    sp.set_defaults(fn=cmd_walls)
    sp = sub.add_parser("margins",
                        help="per-defense margin trajectories from v12 "
                             "'margin' events (--margins runs); a "
                             "second query renders the cross-run "
                             "colluder-margin drift with sign-flip "
                             "marks")
    sp.add_argument("query")
    sp.add_argument("b", nargs="?", default=None,
                    help="second run: drift of B against the first")
    sp.set_defaults(fn=cmd_margins)
    sp = sub.add_parser("numerics",
                        help="numeric-health trajectories from v14 "
                             "'numerics' events (--numerics runs); a "
                             "second query reports per-field "
                             "determinism drift (first differing "
                             "round)")
    sp.add_argument("query")
    sp.add_argument("b", nargs="?", default=None,
                    help="second run: drift of B against the first")
    sp.set_defaults(fn=cmd_numerics)
    sp = sub.add_parser("selfcheck",
                        help="CI: refresh idempotence + resolvability")
    sp.set_defaults(fn=cmd_selfcheck)
    args = p.parse_args(argv)
    if args.bench is None:
        args.bench = ["BENCH_*.json"]
    if args.progress is None:
        args.progress = ["PROGRESS.jsonl"]

    reg = RunRegistry(args.run_dir)
    if args.verb != "list" and not os.path.exists(reg.index_path):
        # Verbs that read the index build it on first use.
        reg.refresh(bench=args.bench, progress=args.progress)
    try:
        return args.fn(reg, args)
    except ValueError as e:
        print(f"runs {args.verb}: {e}")
        return 2


if __name__ == "__main__":
    raise SystemExit(main())
