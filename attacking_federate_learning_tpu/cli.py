"""Experiment CLI.

Flag-compatible with the reference driver (reference main.py:103-153),
including short flags and defaults (-m 0.24, -z 1.5, -d NoDefense, -s MNIST,
-b No, -c 128, -e 300, -l 0.1), minus its typo'd ``-dispatch_weightsn`` alias
for --users-count (main.py:118) and plus the TPU-era knobs: --backend,
--partition, --seed, --server-uses-faded-lr.  CIFAR100 is intentionally not
offered yet, mirroring the reference CLI's own exclusion (main.py:114).

Run:  python -m attacking_federate_learning_tpu.cli -d Krum -s MNIST

Heavy imports happen inside main() so --backend can select the JAX platform
before jax initializes.
"""

from __future__ import annotations

import argparse
import os

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.config import ExperimentConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native federated-learning attack/defense simulator")
    p.add_argument("-m", "--mal-prop", default=0.24, type=float,
                   help="proportion of malicious users")
    p.add_argument("-z", "--num_std", default=1.5, type=float,
                   help="how many standard deviations the attacker shifts")
    p.add_argument("-d", "--defense", default="NoDefense",
                   choices=["NoDefense", "Bulyan", "TrimmedMean", "Krum",
                            "FLTrust"])
    p.add_argument("-s", "--dataset", default=C.MNIST,
                   choices=[C.MNIST, C.CIFAR10, C.SYNTH_MNIST,
                            C.SYNTH_CIFAR10, C.SYNTH_MNIST_HARD])
    p.add_argument("-b", "--backdoor", default="No",
                   choices=["No", "pattern", "1", "2", "3"],
                   help="no backdoor, pattern trigger, or single-sample "
                        "backdoor with the given training index")
    p.add_argument("-n", "--users-count", default=10, type=int)
    p.add_argument("-c", "--batch_size", default=128, type=int)
    p.add_argument("-e", "--epochs", default=300, type=int)
    p.add_argument("-l", "--learning_rate", default=0.1, type=float)
    p.add_argument("-o", "--output", type=str,
                   help="output file for results (tee)")
    p.add_argument("--partition", default="iid",
                   choices=["iid", "dirichlet"])
    p.add_argument("--dirichlet-alpha", default=0.5, type=float)
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--data-dir", default="data", type=str)
    p.add_argument("--backend", default="auto",
                   choices=["auto", "cpu", "tpu"],
                   help="JAX platform; must be chosen before jax initializes")
    p.add_argument("--mesh-shape", default=None, type=str,
                   help="'clients,model' device split, e.g. 8,1")
    p.add_argument("--no-checkpoint", action="store_true",
                   help="disable the acc>70%% checkpoint (reference "
                        "main.py:84-89 behavior is on by default)")
    p.add_argument("--krum-paper-scoring", action="store_true",
                   help="paper-faithful Krum scoring (n-f-2 closest) instead "
                        "of the reference's n-f (defences.py:26)")
    p.add_argument("--server-uses-faded-lr", action="store_true",
                   help="paper-faithful mode: faded lr on the server step "
                        "(the reference uses the constant base lr, "
                        "server.py:89)")
    p.add_argument("--profile", action="store_true",
                   help="accumulate per-phase (round/eval) wall-clock and "
                        "record it in the JSONL log")
    p.add_argument("--trace-dir", type=str, default=None,
                   help="capture a jax.profiler XLA trace into this dir")
    return p


def config_from_args(args) -> ExperimentConfig:
    mesh_shape = (tuple(int(x) for x in args.mesh_shape.split(","))
                  if args.mesh_shape else None)
    return ExperimentConfig(
        users_count=args.users_count,
        mal_prop=args.mal_prop,
        dataset=args.dataset,
        learning_rate=args.learning_rate,
        batch_size=args.batch_size,
        epochs=args.epochs,
        num_std=args.num_std,
        backdoor=args.backdoor,
        defense=args.defense,
        output=args.output,
        seed=args.seed,
        partition=args.partition,
        dirichlet_alpha=args.dirichlet_alpha,
        data_dir=args.data_dir,
        backend=args.backend,
        mesh_shape=mesh_shape,
        krum_paper_scoring=args.krum_paper_scoring,
        server_uses_faded_lr=args.server_uses_faded_lr,
    )


def apply_backend(backend: str):
    """Select the JAX platform before jax is imported (cfg.backend)."""
    if backend == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        # Disable this image's TPU-relay site hook for CPU-only runs.
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
    elif backend == "tpu":
        os.environ.setdefault("JAX_PLATFORMS", "tpu,axon")


def main(argv=None):
    args = build_parser().parse_args(argv)
    apply_backend(args.backend)
    cfg = config_from_args(args)

    # Imported here so apply_backend ran before jax initialization.
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
    from attacking_federate_learning_tpu.utils.metrics import RunLogger
    from attacking_federate_learning_tpu.utils.profiling import (
        PhaseTimer, xla_trace
    )

    logger = RunLogger(cfg, cfg.output, cfg.log_dir)
    logger.dump_config()

    dataset = load_dataset(cfg.dataset, cfg.data_dir, cfg.seed)
    attacker = make_attacker(cfg, dataset=dataset)
    exp = FederatedExperiment(cfg, attacker=attacker, dataset=dataset)
    checkpointer = None if args.no_checkpoint else Checkpointer(cfg)
    timer = PhaseTimer() if args.profile else None
    with xla_trace(args.trace_dir):
        result = exp.run(logger, checkpointer=checkpointer, timer=timer)
    if timer is not None:
        logger.print({"phase_timing": timer.summary()})
    return result


if __name__ == "__main__":
    main()
