"""Experiment CLI.

Flag-compatible with the reference driver (reference main.py:103-153),
including short flags and defaults (-m 0.24, -z 1.5, -d NoDefense, -s MNIST,
-b No, -c 128, -e 300, -l 0.1) and even its typo'd ``-dispatch_weightsn``
alias for --users-count (main.py:118), plus the TPU-era knobs: --backend,
--partition, --seed, --server-uses-faded-lr.  Unlike the reference CLI
(main.py:114), CIFAR100/WRN-40-4 is selectable here.

Run:  python -m attacking_federate_learning_tpu.cli -d Krum -s MNIST

Subcommand: ``... cli report logs/run.jsonl [more.jsonl]`` summarizes
structured run logs (selection concentration, phase timing, trajectories
— report.py).  Dispatched before argparse so the experiment flag surface
stays reference-verbatim.

Heavy imports happen inside main() so --backend can select the JAX platform
before jax initializes.
"""

from __future__ import annotations

import argparse
import os

from attacking_federate_learning_tpu import config as C
from attacking_federate_learning_tpu.config import ExperimentConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native federated-learning attack/defense simulator")
    p.add_argument("-m", "--mal-prop", default=0.24, type=float,
                   help="proportion of malicious users")
    p.add_argument("-z", "--num_std", default=1.5,
                   type=lambda s: s if s == "auto" else float(s),
                   help="how many standard deviations the attacker "
                        "shifts; 'auto' computes the ALIE paper's z_max "
                        "from (n, f) (beyond-reference)")
    p.add_argument("-d", "--defense", default="NoDefense",
                   choices=["NoDefense", "Bulyan", "TrimmedMean", "Krum",
                            "FLTrust", "Median", "GeoMedian", "NormBound",
                            "DnC", "CenteredClip"])
    p.add_argument("--attack", default="auto",
                   choices=["auto", "none", "alie", "backdoor",
                            "backdoor_timed", "signflip", "noise",
                            "minmax", "minsum"],
                   help="'auto' = reference behavior (backdoor if -b set, "
                        "else ALIE, reference main.py:44-54); the rest are "
                        "beyond-reference baselines (attacks/); "
                        "'backdoor_timed' is the async timing-channel "
                        "variant (emits with delay 0 so its rows always "
                        "arrive fresh; needs --aggregation async)")
    p.add_argument("--attack-direction", default="std",
                   choices=["std", "sign", "unit"],
                   help="min-max/min-sum perturbation direction "
                        "(attacks/minmax.py): cohort -std (the NDSS'21 "
                        "paper's best), -sign(mean), or -unit mean")
    p.add_argument("--dnc-iters", default=ExperimentConfig.dnc_iters,
                   type=int, help="DnC filtering iterations")
    p.add_argument("--dnc-sketch-dim",
                   default=ExperimentConfig.dnc_sketch_dim, type=int,
                   help="DnC coordinate-sketch size per iteration")
    p.add_argument("--dnc-filter-frac",
                   default=ExperimentConfig.dnc_filter_frac, type=float,
                   help="DnC outliers removed per iteration, as a "
                        "fraction of f")
    p.add_argument("--geomed-iters", default=ExperimentConfig.geomed_iters,
                   type=int, help="GeoMedian Weiszfeld iterations")
    p.add_argument("--geomed-eps", default=ExperimentConfig.geomed_eps,
                   type=float,
                   help="GeoMedian distance-smoothing floor")
    p.add_argument("--cclip-tau", default=ExperimentConfig.cclip_tau,
                   type=float,
                   help="CenteredClip L2 clip radius (ICML'21)")
    p.add_argument("--cclip-iters", default=ExperimentConfig.cclip_iters,
                   type=int, help="CenteredClip re-centering trips")
    p.add_argument("--trimmed-mean-impl",
                   default=ExperimentConfig.trimmed_mean_impl,
                   choices=["xla", "host"],
                   help="TrimmedMean kernel: traced XLA (default) or the "
                        "opt-in native host kernel (fast at 10k clients "
                        "on the CPU backend)")
    p.add_argument("--median-impl",
                   default=ExperimentConfig.median_impl,
                   choices=["xla", "host"],
                   help="Median kernel: traced XLA (default) or the "
                        "opt-in native host kernel")
    p.add_argument("-s", "--dataset", default=C.MNIST,
                   choices=[C.MNIST, C.CIFAR10, C.CIFAR100, C.SYNTH_MNIST,
                            C.SYNTH_CIFAR10, C.SYNTH_MNIST_HARD,
                            C.SYNTH_CIFAR10_HARD],
                   help="CIFAR100 runs the WRN-40-4 the reference defines "
                        "but never exposes (reference main.py:114 excludes "
                        "it; data_sets.py:108-173 defines it)")
    p.add_argument("--model", default=None,
                   choices=["mnist_mlp", "mnist_cnn", "cifar10_cnn",
                            "resnet20", "wideresnet40_4"],
                   help="override the dataset's canonical model "
                        "(default: MLP for MNIST, CNN for CIFAR10, "
                        "WRN-40-4 for CIFAR100)")
    p.add_argument("-b", "--backdoor", default="No",
                   choices=["No", "pattern", "1", "2", "3"],
                   help="no backdoor, pattern trigger, or single-sample "
                        "backdoor with the given training index")
    # '-dispatch_weightsn' mirrors the reference CLI's typo'd alias for
    # --users-count (reference main.py:118) so reference invocations work
    # verbatim.
    p.add_argument("-n", "-dispatch_weightsn", "--users-count", default=10,
                   type=int)
    p.add_argument("-c", "--batch_size", default=128, type=int)
    p.add_argument("-e", "--epochs", default=300, type=int)
    p.add_argument("--participation", default=1.0, type=float,
                   help="fraction of clients sampled each round (static "
                        "cohort sizes, random identities; 1.0 = the "
                        "reference's everyone-every-round)")
    p.add_argument("--local-steps", default=1, type=int,
                   help="FedAvg-style local SGD steps per round (1 = the "
                        "reference's FedSGD; k>1 reports (w0-w_k)/lr as "
                        "the wire gradient)")
    p.add_argument("-l", "--learning_rate", default=0.1, type=float)
    p.add_argument("-o", "--output", type=str,
                   help="output file for results (tee)")
    p.add_argument("--partition", default="iid",
                   choices=["iid", "dirichlet", "femnist_style"])
    p.add_argument("--dirichlet-alpha", default=0.5, type=float)
    p.add_argument("--style-strength", default=0.25, type=float,
                   help="femnist_style per-client contrast/brightness "
                        "spread (data/partition.py client_style_params)")
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--data-dir", default="data", type=str)
    p.add_argument("--log-dir", default="logs", type=str,
                   help="CSV/JSONL output dir (reference logs/, main.py:100)")
    p.add_argument("--run-dir", default="runs", type=str,
                   help="checkpoint dir (reference runs/, server.py:44)")
    p.add_argument("--synth-train", default=ExperimentConfig.synth_train,
                   type=int,
                   help="training examples for SYNTH_* / fallback datasets")
    p.add_argument("--synth-test", default=ExperimentConfig.synth_test,
                   type=int,
                   help="test examples for SYNTH_* / fallback datasets")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "cpu", "tpu"],
                   help="JAX platform; must be chosen before jax initializes")
    p.add_argument("--mesh-shape", default=None, type=str,
                   help="'clients,model' device split, e.g. 8,1; "
                        "'none' clears an earlier --mesh-shape (argparse "
                        "last-wins — the supervisor's OOM degradation "
                        "appends it to relax the MeshPlan).  Under "
                        "--aggregation hierarchical a clients axis > 1 "
                        "runs tier-1 as one SPMD shard_map program "
                        "(each device scans its own megabatches; "
                        "n/megabatch must divide the clients axis)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize client activations in the backward "
                        "pass (jax.checkpoint) — trades FLOPs for HBM at "
                        "WRN/large-cohort scale")
    p.add_argument("--data-placement", default="device",
                   choices=["device", "host_stream"],
                   help="'device' holds the training set in HBM; "
                        "'host_stream' keeps it in host RAM and "
                        "double-buffers per-round batches (beyond-HBM "
                        "datasets)")
    p.add_argument("--stream-prefetch",
                   default=ExperimentConfig.stream_prefetch, type=int,
                   help="host_stream pipeline depth: rounds of batches "
                        "kept in flight (data/stream.py)")
    p.add_argument("--stream-workers",
                   default=ExperimentConfig.stream_workers, type=int,
                   choices=[0, 1],
                   help="1 = run the host gather + transfer on a "
                        "background thread so it overlaps device compute")
    p.add_argument("--no-checkpoint", action="store_true",
                   help="disable the acc>70%% checkpoint (reference "
                        "main.py:84-89 behavior is on by default)")
    p.add_argument("--krum-scoring-method", default="sort",
                   choices=["sort", "topk", "auto"],
                   help="Krum/Bulyan score evaluation: cancellation-free "
                        "'sort' (default), complement-'topk' (cheaper at "
                        "large n / small f; a runtime guard falls back to "
                        "sort when the subtraction would cancel), or "
                        "'auto' to pick by shape")
    p.add_argument("--bulyan-batch-select",
                   default=ExperimentConfig.bulyan_batch_select, type=int,
                   help="Bulyan selection batch size: q>1 selects the q "
                        "lowest-scoring clients per trip against the same "
                        "scores (a flagged relaxation of the reference's "
                        "sequential selection for the 10k regime); 1 = "
                        "reference-exact")
    p.add_argument("--bulyan-selection-impl",
                   default=ExperimentConfig.bulyan_selection_impl,
                   choices=["xla", "host", "pallas"],
                   help="Bulyan selection engine: traced XLA loop "
                        "(default), the hybrid exact path — device "
                        "distances, one (n, n) host marshal, native "
                        "incremental selection, device trim-mean — or "
                        "'pallas': the same exact loop over the fused "
                        "pallas distance kernel's on-device D (no "
                        "marshal at all; ops/pallas_defense.py)")
    p.add_argument("--aggregation-impl",
                   default=ExperimentConfig.aggregation_impl,
                   choices=["xla", "pallas"],
                   help="Defense-kernel suite (ops/pallas_defense.py): "
                        "'pallas' runs the tier-1 pipeline on-device — "
                        "fused distance->Krum-score kernel, tiled "
                        "trimmed-mean/median, all-on-device Bulyan — "
                        "with interpret-mode fallback off-TPU; 'xla' "
                        "(default) leaves every path unchanged")
    p.add_argument("--bulyan-trim-impl",
                   default=ExperimentConfig.bulyan_trim_impl,
                   choices=["xla", "host"],
                   help="Bulyan trimmed-mean tail: traced XLA kernel "
                        "(default) or the native host kernel (the "
                        "CPU-backend 10k opt-in; same standard as "
                        "--trimmed-mean-impl)")
    p.add_argument("--aggregation", default="flat",
                   choices=["flat", "hierarchical", "async"],
                   help="'flat' = reference path (one (n, d) matrix, one "
                        "defense call); 'hierarchical' streams the client "
                        "axis through --megabatch-sized scan shards with "
                        "per-shard tier-1 robust estimates and a tier-2 "
                        "cross-shard reduction — the (n, d)/(n, n) arrays "
                        "never materialize (ops/federated.py); 'async' = "
                        "FedBuff-style buffered rounds — updates arrive "
                        "PRNG-drawn rounds late, the server aggregates "
                        "the first --async-buffer pending arrivals with "
                        "staleness-weighted contributions "
                        "(core/async_rounds.py)")
    p.add_argument("--async-buffer", default=0, type=int, metavar="K",
                   help="async mode's FedBuff buffer size: pending "
                        "updates consumed per round, FIFO (required "
                        ">= 1 under --aggregation async)")
    p.add_argument("--async-max-staleness",
                   default=ExperimentConfig.async_max_staleness,
                   type=int, metavar="S",
                   help="async staleness bound: arrival delays draw "
                        "from [0, S], a pending update older than S "
                        "rounds is evicted (masked, never aggregated)")
    p.add_argument("--staleness-weight", default="none",
                   choices=["none", "poly", "const"],
                   help="async contribution discount by staleness s: "
                        "'none' (pure first-k), 'poly' (1/sqrt(1+s), "
                        "the FedBuff paper), 'const' (0.5 for any "
                        "stale row) — threaded into the mask-aware "
                        "kernels' weights= seam")
    p.add_argument("--megabatch", default=0, type=int, metavar="M",
                   help="hierarchical tier-1 shard size m (must divide "
                        "--users-count, >= 2 shards); round peak memory "
                        "scales with m*d instead of n*d")
    p.add_argument("--tier2-defense", default=None,
                   choices=["NoDefense", "Krum", "TrimmedMean", "Bulyan",
                            "Median"],
                   help="tier-2 reducer over the (n/m, d) shard-estimate "
                        "matrix (defenses/kernels.py shard_* entries); "
                        "default: same family as -d/--defense")
    p.add_argument("--mal-placement", default="spread",
                   choices=["spread", "concentrated"],
                   help="colluder placement across megabatches: 'spread' "
                        "deals the malicious ids round-robin, "
                        "'concentrated' packs them into the fewest shards "
                        "(the colluders-own-a-shard scenario; only "
                        "meaningful under --aggregation hierarchical)")
    p.add_argument("--tier1-corrupted", default=None, type=int,
                   metavar="F1",
                   help="assumed per-shard corrupted bound for tier-1 "
                        "(default: ceil(f / num_shards), the spread "
                        "worst case)")
    p.add_argument("--tier2-corrupted", default=None, type=int,
                   metavar="F2",
                   help="assumed corrupted-shard bound for tier-2 "
                        "(default: ceil(f / megabatch))")
    p.add_argument("--secagg", default="off",
                   choices=["off", "vanilla", "groupwise"],
                   help="secure-aggregation protocol layer "
                        "(protocols/secagg.py): 'vanilla' = Bonawitz-"
                        "style pairwise-masked cohort sum (requires -d "
                        "NoDefense — the server sees no per-client "
                        "rows; --fault-dropout becomes a mask-"
                        "reconstruction round), 'groupwise' = NET-SA-"
                        "style per-megabatch sums composed with "
                        "--aggregation hierarchical (tier-2 robust "
                        "kernels run over group sums via "
                        "--tier2-defense)")
    p.add_argument("--distance-impl", default="auto",
                   choices=["auto", "xla", "pallas", "host", "ring",
                            "allgather"],
                   help="Krum/Bulyan distance engine (defenses/kernels.py): "
                        "XLA Gram matmul, fused pallas TPU kernel, host "
                        "BLAS (CPU backend), or the blockwise shard_map "
                        "schedules over the clients mesh axis "
                        "(ring/allgather need --mesh-shape)")
    p.add_argument("--distance-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="dtype for the Krum/Bulyan distance computation "
                        "only (training stays f32): bfloat16 rides the "
                        "MXU at native throughput with f32 accumulation "
                        "— a flagged deviation for the 10k regime")
    p.add_argument("--krum-paper-scoring", action="store_true",
                   help="paper-faithful Krum scoring (n-f-2 closest) instead "
                        "of the reference's n-f (defences.py:26)")
    p.add_argument("--server-uses-faded-lr", action="store_true",
                   help="paper-faithful mode: faded lr on the server step "
                        "(the reference uses the constant base lr, "
                        "server.py:89)")
    p.add_argument("--backdoor-staged", action="store_true",
                   help="run the backdoor via the staged per-round path "
                        "(the reference's host nan guard every round, "
                        "backdoor.py:145-152) instead of fusing the "
                        "shadow train into the round program")
    p.add_argument("--augment", default="auto",
                   choices=["auto", "on", "off"],
                   help="train-time reflect-pad-4 + random-crop + h-flip "
                        "(reference data_sets.py:157-166); 'auto' follows "
                        "the reference (CIFAR100 only)")
    p.add_argument("--resume", nargs="?", const="auto", default=None,
                   metavar="CKPT",
                   help="resume from a checkpoint (.npz path, or no value "
                        "to use the newest checkpoint in runs/<dataset>/ — "
                        "auto-checkpoints included); continues from the "
                        "saved round, fault state included")
    p.add_argument("--checkpoint-every", default=0, type=int,
                   metavar="N",
                   help="write a rotated, atomically-replaced auto-"
                        "checkpoint every N rounds (0 = off) — the "
                        "--resume target after a kill and the rollback "
                        "target for the fault watchdog")
    p.add_argument("--fault-dropout", default=0.0, type=float,
                   metavar="P",
                   help="per-client per-round dropout probability: the "
                        "client returns no update; its row is "
                        "quarantined out of the aggregation "
                        "(core/faults.py)")
    p.add_argument("--fault-straggler", default=0.0, type=float,
                   metavar="P",
                   help="per-client per-round straggler probability: the "
                        "client submits its gradient from "
                        "--fault-straggler-delay rounds ago (stale ring "
                        "buffer inside the fused round)")
    p.add_argument("--fault-straggler-delay", default=1, type=int,
                   metavar="K", help="straggler staleness in rounds")
    p.add_argument("--fault-corrupt", default=0.0, type=float,
                   metavar="P",
                   help="per-HONEST-client per-round corruption "
                        "probability (distinct from the attack seam, "
                        "which owns rows [0, f)); see "
                        "--fault-corrupt-mode")
    p.add_argument("--fault-corrupt-mode", default="nan",
                   choices=["nan", "inf", "scale"],
                   help="corruption flavor: non-finite rows ('nan'/'inf' "
                        "— caught by the pre-aggregation quarantine) or "
                        "finite bit-scaled rows ('scale' — what the "
                        "robust defense / divergence watchdog must "
                        "absorb)")
    p.add_argument("--fault-shard-dropout", default=0.0, type=float,
                   metavar="P",
                   help="per-SHARD-DOMAIN per-round failure onset "
                        "probability (hierarchical only): a dead domain "
                        "loses its whole megabatch for "
                        "--fault-shard-dropout-dwell rounds, its tier-1 "
                        "estimate is excluded at tier-2 (alive_counts "
                        "seam) and the host-planned remask -> fallback "
                        "-> hold ladder degrades the tier-2 kernel when "
                        "too few shards survive (core/faults.py)")
    p.add_argument("--fault-shard-dropout-dwell", default=1, type=int,
                   metavar="K",
                   help="rounds a dead shard domain stays dead after "
                        "each failure onset (correlated outage width)")
    p.add_argument("--traffic-population", default=0, type=int,
                   metavar="P",
                   help="population & traffic engine (core/population.py): "
                        "sample each round's cohort from a registry of P "
                        "clients (P >> cohort; per-client state is lazy — "
                        "no (P,)-sized tensor ever exists) with diurnal "
                        "arrival, correlated on/off churn, heavy-tail "
                        "async latencies, and a defense-validity watchdog "
                        "that degrades under-filled rounds through "
                        "remask -> fallback defense -> hold, each "
                        "decision a v11 'traffic' event; 0 = off (the "
                        "legacy --participation draw)")
    p.add_argument("--traffic-rate", default=0.9, type=float, metavar="R",
                   help="base per-round arrival rate (scaled per client "
                        "by its reliability profile)")
    p.add_argument("--traffic-diurnal-amp", default=0.0, type=float,
                   metavar="A",
                   help="diurnal modulation amplitude in [0,1]: rate(t) = "
                        "R*(1 + A*sin(2*pi*t/period))")
    p.add_argument("--traffic-diurnal-period", default=24, type=int,
                   metavar="T", help="diurnal period in rounds")
    p.add_argument("--traffic-churn-dwell", default=4, type=int,
                   metavar="K",
                   help="mean on/off churn episode length in rounds "
                        "(per-client Markov-style alternating renewal: "
                        "one availability draw per K-round block)")
    p.add_argument("--traffic-latency-scale", default=1.0, type=float,
                   metavar="S",
                   help="heavy-tail straggler latency scale (async "
                        "engine: Pareto arrival delay replaces the "
                        "uniform 0..D draw)")
    p.add_argument("--traffic-latency-tail", default=1.5, type=float,
                   metavar="A", help="Pareto tail exponent (smaller = "
                                     "heavier straggler tail)")
    p.add_argument("--traffic-sybil-period", default=0, type=int,
                   metavar="T",
                   help="time-correlated colluder arrival: colluders "
                        "arrive only in a window of --traffic-sybil-width "
                        "rounds every T rounds, boosted so their AVERAGE "
                        "arrival mass matches uniform (fixed average f — "
                        "participation as an attack axis); 0 = uniform "
                        "colluder arrival")
    p.add_argument("--traffic-sybil-width", default=1, type=int,
                   metavar="W", help="sybil burst window width in rounds")
    p.add_argument("--traffic-fallback", default="Median",
                   choices=["Median", "TrimmedMean", "NoDefense"],
                   help="ladder step 2: the bounds-valid defense an "
                        "under-filled round falls back to when the "
                        "configured defense's validity bound breaks")
    p.add_argument("--traffic-min-cohort", default=1, type=int,
                   metavar="M",
                   help="floor on arrived clients below which the round "
                        "degrades regardless of defense bounds")
    p.add_argument("--traffic-seed", default=None, type=int,
                   metavar="SEED",
                   help="traffic schedule seed override (default: derived "
                        "from the experiment seed) — lets a campaign "
                        "sweep traffic realizations without moving the "
                        "data/init/attack draws")
    p.add_argument("--profile", action="store_true",
                   help="accumulate per-phase (round/eval) wall-clock and "
                        "record it in the JSONL log")
    p.add_argument("--round-stats", action="store_true",
                   help="record per-round gradient/update norm diagnostics "
                        "in the JSONL log")
    p.add_argument("--telemetry", action="store_true",
                   help="per-round aggregation forensics: defense "
                        "selection masks/scores, trim/clip/trust "
                        "diagnostics, attack envelope stats, per-client "
                        "norms — device-side aux outputs of the jitted "
                        "round, written as 'defense'/'attack'/"
                        "'selection_hist' events (read with the 'report' "
                        "subcommand).  Under --aggregation hierarchical "
                        "(and --secagg groupwise) the same flag emits "
                        "per-shard tier-1 + tier-2 'shard_selection' "
                        "events — read with 'report forensics'")
    p.add_argument("--margins", action="store_true",
                   help="robustness-margin observatory (utils/margins.py): "
                        "the defense's in-jit decision margins (Krum "
                        "winner/runner-up gap + per-row distance to the "
                        "selection threshold, trim boundary distances + "
                        "kept fractions, Bulyan selection slack) and the "
                        "attack's envelope utilization, rolled up into "
                        "one schema-v12 'margin' event per round — the "
                        "colluder-survival ledger (read with 'runs "
                        "margins').  Requires a margin-bearing defense "
                        "(Krum/TrimmedMean/Median/Bulyan) on an "
                        "on-device impl")
    p.add_argument("--numerics", action="store_true",
                   help="numerics & determinism observatory "
                        "(utils/numerics.py): in-jit numeric health "
                        "counters — per-stage nonfinite counts, "
                        "gradient-norm dynamic range, distance-Gram "
                        "cancellation depth, and tie-proximity counters "
                        "banded at k ulp of the margin decision "
                        "boundaries — one schema-v14 'numerics' event "
                        "per round (read with 'runs numerics'; "
                        "cross-impl envelopes in NUMERICS_BASELINE.json)."
                        "  Works with any defense; tie/cancellation "
                        "counters need a margin-bearing one on an "
                        "on-device impl")
    p.add_argument("--trace-dir", type=str, default=None,
                   help="capture a jax.profiler XLA trace into this dir")
    p.add_argument("--profile-every", default=0, type=int, metavar="K",
                   help="measured-walls observatory (utils/walls.py): "
                        "time every span/eval on the host clock and "
                        "capture + stage-book one profiler trace per K "
                        "eval intervals, recorded as schema-v10 'wall' "
                        "events (read with 'runs walls'); 0 disables")
    p.add_argument("--cost-report", action="store_true",
                   help="before training, lower+compile every jitted "
                        "entry point once and record its static HLO "
                        "cost facts (FLOPs, bytes accessed, memory "
                        "sizes) and compile/cache attribution as "
                        "'compile'/'cost' events (utils/costs.py; read "
                        "with the 'report' subcommand)")
    p.add_argument("--heartbeat", default=0.0, type=float, metavar="SECS",
                   help="append a 'heartbeat' event every SECS seconds "
                        "(round, rounds/s EMA, rss, last-event age) so "
                        "a stalled run is distinguishable from a long "
                        "compile by tailing the events file; 0 = off")
    p.add_argument("--journal", action="store_true",
                   help="keep an append-only per-run journal + resume "
                        "manifest under runs/<run-id>/ "
                        "(utils/lifecycle.py): rounds and evals are "
                        "committed exactly once across any number of "
                        "restarts, and a resumed run never re-emits "
                        "events a previous attempt already recorded")
    p.add_argument("--run-id", default=None, metavar="ID",
                   help="journal identity override (implies --journal); "
                        "default derives from the config hash.  The "
                        "supervisor pins this so degraded restarts "
                        "(halved batch, CPU fallback) still share one "
                        "journal")
    return p


def config_from_args(args) -> ExperimentConfig:
    mesh_shape = None
    if args.mesh_shape and args.mesh_shape.lower() != "none":
        mesh_shape = tuple(int(x) for x in args.mesh_shape.split(","))
    faults = None
    if (args.fault_dropout or args.fault_straggler or args.fault_corrupt
            or args.fault_shard_dropout):
        faults = C.FaultConfig(
            dropout=args.fault_dropout,
            straggler=args.fault_straggler,
            corrupt=args.fault_corrupt,
            straggler_delay=args.fault_straggler_delay,
            corrupt_mode=args.fault_corrupt_mode,
            shard_dropout=args.fault_shard_dropout,
            shard_dropout_dwell=args.fault_shard_dropout_dwell)
    traffic = None
    if args.traffic_population > 0:
        traffic = C.TrafficConfig(
            population=args.traffic_population,
            rate=args.traffic_rate,
            diurnal_amp=args.traffic_diurnal_amp,
            diurnal_period=args.traffic_diurnal_period,
            churn_dwell=args.traffic_churn_dwell,
            latency_scale=args.traffic_latency_scale,
            latency_tail=args.traffic_latency_tail,
            sybil_burst_period=args.traffic_sybil_period,
            sybil_burst_width=args.traffic_sybil_width,
            fallback_defense=args.traffic_fallback,
            min_cohort=args.traffic_min_cohort,
            seed=args.traffic_seed)
    return ExperimentConfig(
        faults=faults,
        traffic=traffic,
        checkpoint_every=args.checkpoint_every,
        users_count=args.users_count,
        mal_prop=args.mal_prop,
        dataset=args.dataset,
        model=args.model,
        learning_rate=args.learning_rate,
        batch_size=args.batch_size,
        epochs=args.epochs,
        local_steps=args.local_steps,
        participation=args.participation,
        num_std=args.num_std,
        backdoor=args.backdoor,
        defense=args.defense,
        output=args.output,
        seed=args.seed,
        partition=args.partition,
        dirichlet_alpha=args.dirichlet_alpha,
        style_strength=args.style_strength,
        data_dir=args.data_dir,
        log_dir=args.log_dir,
        run_dir=args.run_dir,
        backend=args.backend,
        mesh_shape=mesh_shape,
        data_placement=args.data_placement,
        stream_prefetch=args.stream_prefetch,
        stream_workers=args.stream_workers,
        remat=args.remat,
        krum_paper_scoring=args.krum_paper_scoring,
        krum_scoring_method=args.krum_scoring_method,
        distance_impl=args.distance_impl,
        distance_dtype=args.distance_dtype,
        bulyan_batch_select=args.bulyan_batch_select,
        bulyan_selection_impl=args.bulyan_selection_impl,
        bulyan_trim_impl=args.bulyan_trim_impl,
        aggregation_impl=args.aggregation_impl,
        server_uses_faded_lr=args.server_uses_faded_lr,
        log_round_stats=args.round_stats,
        telemetry=args.telemetry,
        margins=args.margins,
        numerics=args.numerics,
        synth_train=args.synth_train,
        synth_test=args.synth_test,
        data_augment={"auto": None, "on": True, "off": False}[args.augment],
        backdoor_fused=not args.backdoor_staged,
        attack_direction=args.attack_direction,
        dnc_iters=args.dnc_iters,
        dnc_sketch_dim=args.dnc_sketch_dim,
        dnc_filter_frac=args.dnc_filter_frac,
        geomed_iters=args.geomed_iters,
        geomed_eps=args.geomed_eps,
        cclip_tau=args.cclip_tau,
        cclip_iters=args.cclip_iters,
        trimmed_mean_impl=args.trimmed_mean_impl,
        median_impl=args.median_impl,
        secagg=args.secagg,
        aggregation=args.aggregation,
        megabatch=args.megabatch,
        tier2_defense=args.tier2_defense,
        mal_placement=args.mal_placement,
        tier1_corrupted=args.tier1_corrupted,
        tier2_corrupted=args.tier2_corrupted,
        async_buffer=args.async_buffer,
        async_max_staleness=args.async_max_staleness,
        staleness_weight=args.staleness_weight,
        profile_every=args.profile_every,
    )


def apply_backend(backend: str):
    """Select the JAX platform (cfg.backend).

    Env vars cover the normal case; on images whose sitecustomize imports
    jax at interpreter start the platform config is already frozen, so the
    live config is updated too (backend init is lazy, so this is still in
    time as long as no jax op has run)."""
    if backend == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        # Keep subprocesses off this image's TPU-relay site hook.
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif backend == "tpu":
        os.environ.setdefault("JAX_PLATFORMS", "tpu,axon")


def main(argv=None):
    if argv is None:
        import sys

        argv = sys.argv[1:]
    if argv and argv[0] == "report":
        # Run-report subcommand (report.py): pure log reading, no jax —
        # dispatched before argparse so the experiment flag surface
        # stays reference-verbatim.
        from attacking_federate_learning_tpu.report import main as report_main

        return report_main(argv[1:])
    if argv and argv[0] == "campaign":
        # Campaign scheduler subcommand (campaigns/cli.py): run a
        # declarative sweep spec as resumable, cache-aware cells.
        # Heavy imports stay lazy so --dry-run/plan paths touch no jax.
        from attacking_federate_learning_tpu.campaigns.cli import (
            main as campaign_main
        )

        return campaign_main(argv[1:])
    if argv and argv[0] == "runs":
        # Cross-run registry subcommand (runs_cli.py): list/show/diff/
        # compare/tag/trace/forensics/selfcheck over runs/index.jsonl
        # (utils/registry.py).  Pure log/JSON reading, no jax; same
        # pre-argparse dispatch as 'report'.
        from attacking_federate_learning_tpu.runs_cli import (
            main as runs_main
        )

        return runs_main(argv[1:])
    parser = build_parser()
    args = parser.parse_args(argv)
    if (args.attack in ("backdoor", "backdoor_timed")
            and args.backdoor == "No"):
        # BackdoorAttack's poison set is derived from the -b trigger; an
        # explicit --attack backdoor without one would build an empty set.
        parser.error(f"--attack {args.attack} requires a trigger: "
                     f"-b pattern|1|2|3")
    if args.attack == "backdoor_timed" and args.aggregation != "async":
        # The timing channel only exists where arrival time matters.
        parser.error("--attack backdoor_timed games the async arrival "
                     "schedule (delay-0 emission); it requires "
                     "--aggregation async")
    apply_backend(args.backend)
    cfg = config_from_args(args)
    if cfg.profile_every > 0:
        # Arm per-op CPU trace events BEFORE the first compile (XLA
        # parses XLA_FLAGS once); without this a CPU capture carries
        # runtime spans only and every wall books to 'unattributed'.
        from attacking_federate_learning_tpu.utils.profiling import (
            ensure_op_profiling
        )

        ensure_op_profiling()

    from attacking_federate_learning_tpu.utils.backend import (
        enable_compile_cache
    )

    enable_compile_cache()

    # Imported here so apply_backend ran before jax initialization.
    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.utils.checkpoint import Checkpointer
    from attacking_federate_learning_tpu.utils.lifecycle import (
        EXIT_DIVERGED, EXIT_PREEMPTED, GracefulShutdown, Preempted,
        RunJournal, run_id_for
    )
    from attacking_federate_learning_tpu.utils.metrics import RunLogger
    from attacking_federate_learning_tpu.utils.profiling import (
        PhaseTimer, xla_trace
    )

    # A journaled run gets a PRIVATE event log named by its run id: the
    # reference CSV filename schema (config.csv_name) encodes no seed,
    # so two runs differing only by seed would interleave into one
    # JSONL — unusable for the registry's per-run rollups and 'runs
    # diff' trajectory comparison.  Unjournaled runs keep the
    # reference-schema name.
    run_id = (args.run_id or run_id_for(cfg)
              if (args.journal or args.run_id) else None)

    # Context-managed: the JSONL handle is closed and the accuracy CSV
    # written even when the run raises (utils/metrics.py:RunLogger).
    with RunLogger(cfg, cfg.output, cfg.log_dir, jsonl_name=run_id,
                   heartbeat_every=args.heartbeat) as logger:
        logger.dump_config()

        dataset = load_dataset(cfg.dataset, cfg.data_dir, cfg.seed,
                               synth_train=cfg.synth_train,
                               synth_test=cfg.synth_test)
        attacker = make_attacker(cfg, dataset=dataset,
                                 name=None if args.attack == "auto"
                                 else args.attack)
        exp = FederatedExperiment(cfg, attacker=attacker, dataset=dataset)
        # Run-lifecycle journal (utils/lifecycle.py), created BEFORE the
        # checkpointer: a journaled run's rotated auto-checkpoints live
        # under its own runs/<run_id>/ (PR 5 layout — the shared
        # runs/<dataset>/ dir made two runs' resume points collide),
        # so the Checkpointer needs the journal dir.
        journal = None
        if run_id is not None:
            journal = RunJournal(cfg.run_dir, run_id)
            logger.print(f"[lifecycle] journal {journal.dir} "
                         f"(attempts so far: {journal.attempt})")
        auto_dir = journal.dir if journal is not None else None
        checkpointer = (None if args.no_checkpoint
                        else Checkpointer(cfg, auto_dir=auto_dir))
        if args.resume is not None:
            import numpy as np

            ckpt = checkpointer or Checkpointer(cfg, auto_dir=auto_dir)
            # 'auto' resumes from the newest checkpoint by round —
            # rotated auto-checkpoints compete with the best-accuracy
            # one, so a killed run continues from where it actually got.
            path = (args.resume if args.resume != "auto"
                    else (ckpt.latest() or ckpt.path))
            if not os.path.exists(path):
                raise SystemExit(f"--resume: no checkpoint at {path}")
            if path.endswith((".pth.tar", ".pth", ".pt")):
                # Reference-produced torch checkpoint (reference
                # server.py:40-48).
                from attacking_federate_learning_tpu.utils.checkpoint import (
                    import_reference_checkpoint
                )
                exp.state, ref_acc = import_reference_checkpoint(
                    path, expected_dim=exp.flat.dim)
                if checkpointer is not None:
                    checkpointer.best_acc = ref_acc
                logger.print(f"Imported reference checkpoint (acc {ref_acc})")
            else:
                exp.state, extra = ckpt.resume(path, with_extra=True)
                # Checkpointed fault state (the straggler ring buffer)
                # comes back too, so a resumed faulted run continues
                # bit-for-bit.
                exp.restore_fault_state(extra)
                if checkpointer is not None:
                    # Don't let the first post-resume eval overwrite a
                    # better checkpoint (keep_best seeding; auto
                    # checkpoints record accuracy -1, so the best
                    # checkpoint's own accuracy still wins).
                    checkpointer.best_acc = max(
                        float(np.load(path)["accuracy"]),
                        checkpointer.load_best_acc())
            if exp.shardings is not None:
                # Restore the planned state sharding the engine set at init
                # (state only — data placement was already decided at init,
                # incl. the host-streaming keep-on-host contract).
                exp.state = exp.shardings.place_state(exp.state)
            logger.print(f"Resumed from round {int(exp.state.round)}")
        if args.cost_report:
            # Static compile-and-cost facts, BEFORE training: the same
            # compiles the run pays anyway (persistent-cache-warmed),
            # analyzed once and recorded as 'compile'/'cost' events.
            ledger = exp.cost_report(logger)
            for rec in ledger.records:
                logger.print(
                    f"[cost] {rec.name:16s} flops={rec.flops:.3e}  "
                    f"bytes={rec.bytes_accessed:.3e}  "
                    f"peak={rec.peak_bytes / 1e6:.1f} MB  "
                    f"compile={rec.compile_s:.2f}s ({rec.cache})")
            for name, msg in ledger.errors:
                logger.print(f"[cost] {name}: analysis failed: {msg}")
        timer = PhaseTimer() if args.profile else None
        # Graceful SIGTERM/SIGINT handling is always on for a CLI-driven
        # run — a signal lands as a checkpoint + 'preempted' exit (75)
        # at the next span boundary instead of a lost run.
        # FL_PREEMPT_AT_ROUND is the deterministic injection seam
        # (tests, tools/crash_matrix.py, the capture rehearsal drill).
        pre_at = os.environ.get("FL_PREEMPT_AT_ROUND")
        shutdown = GracefulShutdown(
            preempt_at_round=int(pre_at) if pre_at else None)
        try:
            with xla_trace(args.trace_dir), shutdown:
                result = exp.run(logger, checkpointer=checkpointer,
                                 timer=timer, journal=journal,
                                 shutdown=shutdown)
        except Preempted as e:
            # Graceful shutdown honored: state checkpointed, journal
            # marked; EX_TEMPFAIL tells the supervisor "resume me".
            logger.print(f"[lifecycle] {e}")
            raise SystemExit(EXIT_PREEMPTED)
        except FloatingPointError as e:
            # Deterministic numeric failure (watchdog rollbacks
            # exhausted, or the backdoor shadow-train nan guard):
            # retrying the identical config reproduces it, so the exit
            # code tells the supervisor NOT to retry.
            logger.record(kind="lifecycle", phase="fatal",
                          failure="divergence", error=str(e))
            logger.print(f"[lifecycle] fatal (divergence): {e}")
            if journal is not None:
                journal.finish("diverged", EXIT_DIVERGED, error=str(e))
                journal.close()
            raise SystemExit(EXIT_DIVERGED)
        if timer is not None:
            # finish() (run's success path) leaves the tee open for
            # exactly this trailing summary; __exit__ closes it.
            logger.print({"phase_timing": timer.summary()})
    return result


if __name__ == "__main__":
    main()
