"""Decision-margin reductions and rollups — the margin observatory.

ALIE and Bulyan are *margin* arguments: the attack works exactly when
the crafted rows sit inside the defense's acceptance region, so the
per-round observable that explains GRID_RESULTS' accuracy cells (the
Bulyan IID z=1.5 collapse, the femnist_style rescue) is each row's
signed distance to the decision boundary.  This module owns both
halves of that measurement:

- **Device-side reductions** (jit-traceable, fixed shapes, no host
  callbacks): the rank/score algebra shared by the defense kernels'
  ``margins=`` seam (defenses/kernels.py, defenses/median.py).  Each
  helper mirrors its kernel's exact sort/selection semantics so the
  margins carry exactness identities instead of approximations:

  * a row is Krum/Bulyan-selected **iff** its selection margin > 0
    (one-sided at exact f32 score ties, where a winner's margin
    degrades to 0 — measure-zero on continuous inputs);
  * a row's trim survival mass equals the telemetry kept-fraction
    bit for bit (same keep set, same sum/d reduction).

- **Host-side rollups** (plain NumPy over event fields): the
  colluder-survival ledger — per-round scalars in DEFENSE sign
  (``colluder_margin`` > 0 means every malicious row sits strictly
  outside the acceptance region; <= 0 means at least one colluder is
  inside) — plus the series/drift helpers behind ``runs margins``.

Sign conventions.  Per-row ``margin_selection`` is ATTACK-side:
positive means the row was selected (it beat the acceptance
threshold), negative means rejected — so "selected iff margin > 0"
reads naturally.  The rollup ``colluder_margin`` flips the sign of
the worst (= most-inside) malicious row, giving the DEFENSE-side
robustness margin: ``colluder_margin = -max(margin_selection[:f])``
is the minimum distance any colluder still has to cover; <= 0 means
at least one colluder is inside the acceptance region.  Boundary
distances (``margin_boundary_dist``) are inside-positive the same
way.

What the observatory actually measures in the pinned GRID round-5
pair (tools/science_gate.py, BEHAVIOR_BASELINE): identical crafted
colluder rows are score-degenerate — a selected colluder's runner-up
is its identical twin, so equal f32 scores subtract to EXACTLY 0.0
and the margin tie-locks at the decision boundary.  The IID z=1.5
collapse stays tie-locked 28/30 rounds (colluders selected at margin
0, accuracy 10%); the femnist_style rescue is NOT a sign flip to
positive margins — colluders are still selected, but the tie-lock
breaks from ~round 19 (19/30 tie rounds, 11 strict-selection events)
while the wider honest cohort sigma neutralizes the drift and
training converges at 99%.  The discriminators the gate pins are
``margin_tie_rounds`` and ``colluder_selected_total``, whose bands
do not overlap — not the margin's sign.

This module never imports defense kernels (the kernels import it),
and the device helpers never touch the host (the engine threads them
out of the fused round program as auxiliary jit outputs).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np
from jax import lax


# Margin field names a defense diagnostics pytree may carry; the engine
# routes exactly these keys out of the telemetry dict into the schema
# v12 ``margin`` event (core/engine.py:_emit_round_telemetry).
MARGIN_KEYS = ("margin_selection", "margin_gap", "margin_slack",
               "margin_kept_frac", "margin_boundary_dist",
               "margin_trim_kept")


# --- device-side reductions (jit-traceable, fixed shapes) --------------


def krum_margins(scores, selected_idx, mask=None):
    """Selection margins from a Krum score vector.

    ``margin_selection[i]``: signed distance of row ``i``'s score to
    the selection threshold — for the winner, runner-up score minus
    its own (>= 0, > 0 off ties); for everyone else, the winning
    score minus its own (<= 0).  ``margin_gap`` is the winner/runner-up
    score gap (the same number the winner's margin reports).  Dead
    rows under ``mask`` are forced to -inf (their +inf scores would
    otherwise produce inf/nan arithmetic)."""
    n = scores.shape[0]
    kk = min(2, n)
    neg, _ = lax.top_k(-scores, kk)
    s1 = -neg[0]
    s2 = -neg[kk - 1]
    rows = jnp.arange(n)
    margin = jnp.where(rows == selected_idx, s2, s1) - scores
    if mask is not None:
        margin = jnp.where(mask, margin, -jnp.inf)
    return {"margin_selection": margin.astype(jnp.float32),
            "margin_gap": (s2 - s1).astype(jnp.float32)}


def rank_keep_margins(key, number_to_consider, order=None):
    """Trim-envelope margins from a per-coordinate sort key.

    ``key`` is the (n, d) matrix the trimmed mean ranks rows by per
    coordinate (|deviation from the anchor median|, dead rows already
    at +inf); ``number_to_consider`` (static or traced) is the keep
    count.  Returns

    - ``margin_kept_frac`` (n,): per row, the fraction of coordinates
      where it survived the trim — computed from rank membership, so
      it is bit-equal to the scatter-based telemetry ``kept_fraction``
      (same stable sort, same keep set, same sum/d) and holds for
      every impl that shares the key (the pallas tiles replicate the
      XLA ranks op for op);
    - ``margin_boundary_dist`` (n,): per row, the mean over
      coordinates of (trim boundary - key) — inside-positive distance
      to the envelope edge, where the boundary is the midpoint of the
      last-kept and first-trimmed key values (falling back to the
      last-kept value when the first-trimmed is a +inf sentinel).

    ``order``: the kernel's already-computed stable argsort of
    ``key`` along axis 0, to avoid a second sort."""
    n = key.shape[0]
    if order is None:
        order = jnp.argsort(key, axis=0, stable=True)
    ranks = jnp.argsort(order, axis=0, stable=True)
    k = jnp.asarray(number_to_consider, jnp.int32)
    keep = ranks < k
    # sum-then-divide, NOT jnp.mean (which multiplies by the
    # reciprocal): bit-equality with the kernels' scatter-based
    # ``.at[...].add(1.0) / d`` kept_fraction depends on the division.
    kept_frac = jnp.sum(keep.astype(jnp.float32), axis=1) / key.shape[1]
    srt = jnp.take_along_axis(key, order, axis=0)
    lo = jnp.take(srt, jnp.maximum(k - 1, 0), axis=0, mode="clip")
    hi = jnp.take(srt, jnp.minimum(k, n - 1), axis=0, mode="clip")
    boundary = jnp.where(jnp.isfinite(hi), 0.5 * (lo + hi), lo)
    dist = jnp.mean(boundary[None, :] - key, axis=1)
    return {"margin_kept_frac": kept_frac.astype(jnp.float32),
            "margin_boundary_dist": dist.astype(jnp.float32)}


def median_pick_margins(users_grads, mask=None, weights=None):
    """Pick-mass margins for the coordinate-wise median.

    Re-derives the exact rank membership of kernels.masked_median /
    ``jnp.median`` (same +inf-sentinel sort, same middle-rank picks,
    same weighted lower-median crossing) and reports

    - ``margin_kept_frac`` (n,): per row, the mean over coordinates of
      its pick weight (0.5/0.5 on the two middles at even alive
      counts, 1.0 on the single middle / weighted pick) — the mass
      the row contributes to the aggregate; summing over rows gives
      1.0 per coordinate, and the picked values reconstruct the
      aggregate (pinned test-side);
    - ``margin_boundary_dist`` (n,): minus the mean |distance to the
      rank-derived median| per coordinate — inside-positive proximity
      to the decision point (the median itself), dead rows -inf."""
    n = users_grads.shape[0]
    alive = (jnp.ones((n,), bool) if mask is None
             else mask.astype(bool))
    vals = jnp.where(alive[:, None], users_grads, jnp.inf)
    order = jnp.argsort(vals, axis=0)
    ranks = jnp.argsort(order, axis=0)
    if weights is not None:
        w = jnp.where(alive, weights, 0.0)
        w_srt = jnp.take_along_axis(
            jnp.broadcast_to(w[:, None], vals.shape), order, axis=0)
        cum = jnp.cumsum(w_srt, axis=0)
        half = jnp.sum(w) / 2.0
        pick_rank = jnp.argmax(cum >= half, axis=0)
        pick = (ranks == pick_rank[None, :]).astype(jnp.float32)
    else:
        e = jnp.sum(alive).astype(jnp.int32)
        lo_r, hi_r = (e - 1) // 2, e // 2
        pick = (0.5 * (ranks == lo_r).astype(jnp.float32)
                + 0.5 * (ranks == hi_r).astype(jnp.float32))
    kept_frac = jnp.mean(pick, axis=1)
    med = jnp.sum(jnp.where(alive[:, None], users_grads, 0.0) * pick,
                  axis=0)
    dist = -jnp.mean(jnp.abs(users_grads - med[None, :]), axis=1)
    dist = jnp.where(alive, dist, -jnp.inf)
    return {"margin_kept_frac": kept_frac.astype(jnp.float32),
            "margin_boundary_dist": dist.astype(jnp.float32)}


# --- host-side rollups (NumPy over event fields) -----------------------


def _finite(a):
    a = np.asarray(a, np.float64)
    return a[np.isfinite(a)]


def margin_rollups(fields, mal_count):
    """Colluder-survival scalars from one round's per-row margin fields.

    ``fields``: margin_* arrays/lists as the kernel returned them (rows
    [0, mal_count) are the malicious clients — the attack-seam
    contract).  Returns DEFENSE-sign scalars:

    - ``colluder_margin``: -max over finite malicious selection
      margins (boundary distances when the defense has no selection) —
      the minimum distance any colluder still has to cover; <= 0 means
      at least one colluder is inside the acceptance region.
    - ``colluder_selected``: how many malicious rows were selected
      (selection margin > 0).
    - ``colluder_kept_mass`` / ``honest_kept_mass``: mean surviving
      coordinate mass over malicious / honest rows (trim kept-fraction;
      Bulyan uses its trim-stage survival).
    """
    out = {}
    f = int(mal_count)
    sel = fields.get("margin_selection")
    bd = fields.get("margin_boundary_dist")
    basis = sel if sel is not None else bd
    if basis is not None and f > 0:
        mal = _finite(np.asarray(basis, np.float64)[:f])
        if mal.size:
            out["colluder_margin"] = float(-np.max(mal))
    if sel is not None and f > 0:
        out["colluder_selected"] = int(
            np.sum(np.asarray(sel, np.float64)[:f] > 0))
    kept = fields.get("margin_trim_kept", fields.get("margin_kept_frac"))
    if kept is not None:
        kept = np.asarray(kept, np.float64)
        if f > 0:
            out["colluder_kept_mass"] = float(np.mean(kept[:f]))
        if kept.size > f:
            out["honest_kept_mass"] = float(np.mean(kept[f:]))
    gap = fields.get("margin_gap")
    if gap is not None and np.ndim(gap) == 0:
        out["margin_gap"] = float(gap)
    return out


def hier_margin_rollups(stacks, mal_counts):
    """Rollups over a hierarchical round's (S, n) margin stacks.

    ``stacks``: margin_* fields stacked over the shard axis (the
    client_map output); ``mal_counts``: (S,) per-shard malicious-row
    counts (rows [0, mal_counts[s]) of shard s are malicious — the
    placement contract).  Aggregates the per-shard rollups the way the
    ledger reads them: the WORST shard margin (min), the TOTAL
    selected-colluder count, the mean kept masses."""
    mal_counts = [int(c) for c in mal_counts]
    margins, selected = [], 0
    kept_c, kept_h = [], []
    any_sel = False
    for s, f_s in enumerate(mal_counts):
        row_fields = {k: np.asarray(v)[s] for k, v in stacks.items()
                      if np.ndim(v) >= 2 or k == "margin_gap"}
        r = margin_rollups(row_fields, f_s)
        if "colluder_margin" in r:
            margins.append(r["colluder_margin"])
        if "colluder_selected" in r:
            any_sel = True
            selected += r["colluder_selected"]
        if "colluder_kept_mass" in r:
            kept_c.append(r["colluder_kept_mass"])
        if "honest_kept_mass" in r:
            kept_h.append(r["honest_kept_mass"])
    out = {}
    if margins:
        out["colluder_margin"] = float(min(margins))
    if any_sel:
        out["colluder_selected"] = int(selected)
    if kept_c:
        out["colluder_kept_mass"] = float(np.mean(kept_c))
    if kept_h:
        out["honest_kept_mass"] = float(np.mean(kept_h))
    return out


def tier2_margin_rollups(fields, colluder_shards):
    """Rollups over the tier-2 (cross-shard) margin fields.

    ``fields``: margin_* vectors over the (S,) SHARD axis;
    ``colluder_shards``: boolean/int mask of shards holding malicious
    clients.  Tier-2's "colluders" are those shards' estimates; the
    same defense-sign scalars as :func:`margin_rollups`, prefixed
    ``tier2_`` by the caller."""
    cs = np.asarray(colluder_shards, bool)
    idx = np.flatnonzero(cs)
    out = {}
    sel = fields.get("margin_selection")
    bd = fields.get("margin_boundary_dist")
    basis = sel if sel is not None else bd
    if basis is not None and idx.size:
        mal = _finite(np.asarray(basis, np.float64)[idx])
        if mal.size:
            out["colluder_margin"] = float(-np.max(mal))
    if sel is not None and idx.size:
        out["colluder_selected"] = int(
            np.sum(np.asarray(sel, np.float64)[idx] > 0))
    kept = fields.get("margin_trim_kept", fields.get("margin_kept_frac"))
    if kept is not None and idx.size:
        out["colluder_kept_mass"] = float(
            np.mean(np.asarray(kept, np.float64)[idx]))
    return out


# --- run-level series / drift (the ``runs margins`` backend) -----------

# Scalar fields a margin event carries that trajectories plot; order is
# the render order.
SERIES_FIELDS = ("colluder_margin", "colluder_selected",
                 "colluder_kept_mass", "honest_kept_mass", "margin_gap",
                 "f_eff")


def margin_series(events):
    """Margin events (dicts, any order) -> per-defense round series:
    ``{defense: {"round": [...], "<field>": [...]}}`` with rounds
    ascending and missing scalars as None (a defense without a
    selection has no colluder_selected — the series keeps alignment)."""
    by_def = {}
    for e in events:
        if e.get("kind") != "margin":
            continue
        d = str(e.get("defense", "?"))
        rows = by_def.setdefault(d, [])
        rows.append(e)
    out = {}
    for d, rows in by_def.items():
        rows.sort(key=lambda e: int(e.get("round", 0)))
        ser = {"round": [int(e.get("round", 0)) for e in rows]}
        for fld in SERIES_FIELDS:
            ser[fld] = [e.get(fld) for e in rows]
        out[d] = ser
    return out


def margin_drift(series_a, series_b, field="colluder_margin",
                 tol=1e-6):
    """Cross-run drift on one margin field: align two
    :func:`margin_series` entries by round and report per-round deltas
    plus the rounds where the DEFENSE-sign margin flips sign between
    runs (the drift marks ``runs margins <a> <b>`` renders).  Returns
    ``{"rounds": [...], "delta": [...], "sign_flips": [...]}``."""
    a_by_r = dict(zip(series_a.get("round", []),
                      series_a.get(field, [])))
    b_by_r = dict(zip(series_b.get("round", []),
                      series_b.get(field, [])))
    rounds = sorted(set(a_by_r) & set(b_by_r))
    deltas, flips = [], []
    for r in rounds:
        va, vb = a_by_r[r], b_by_r[r]
        if va is None or vb is None:
            deltas.append(None)
            continue
        deltas.append(float(vb) - float(va))
        if (math.copysign(1.0, va) != math.copysign(1.0, vb)
                and (abs(va) > tol or abs(vb) > tol)):
            flips.append(r)
    return {"rounds": rounds, "delta": deltas, "sign_flips": flips}
