"""Preemption-safe run lifecycle: graceful shutdown, the per-run
journal, and the failure taxonomy.

The reference (and this repo through PR 3) treats the *process* as
immortal: a SIGTERM mid-run loses everything since the last
auto-checkpoint, and a naive ``--resume`` re-emits (and re-counts) every
event between the checkpoint and the kill.  Real FL stacks are built
around exactly this failure mode (Bonawitz et al.'s dropout-tolerant
secure aggregation; straggler-resilient execution) — and on this box a
wasted SIGTERM during a rare TPU relay window is a wasted *window*.

Three cooperating pieces (all host-side; nothing here touches a jax op):

- :class:`GracefulShutdown` — SIGTERM/SIGINT set a flag; the engine
  polls it at span boundaries (``core/engine.py:_run_body``), writes an
  auto-checkpoint + resume manifest, flushes the event stream, and
  raises :class:`Preempted`, which the CLI maps to
  :data:`EXIT_PREEMPTED` (75, ``EX_TEMPFAIL`` — "resumable, try
  again").  A second signal while the first is being honored restores
  the default disposition and re-delivers — the hard-kill escape hatch.

- :class:`RunJournal` — an append-only ``journal.jsonl`` plus an
  atomically-rewritten ``manifest.json`` under ``runs/<run_id>/``.
  Round and eval records are committed at host boundaries with a
  monotonic high-water mark, so re-executed rounds (after ``--resume``
  OR after a watchdog rollback) are never double-counted and their
  events never double-emitted: the journal gives exactly-once
  round/eval accounting across any number of restarts.
  ``verify()`` checks the invariant mechanically (tools/crash_matrix.py
  and the supervisor call it after every supervised run).

- :func:`classify_failure` — the supervisor's failure taxonomy
  (preempted / divergence / oom / backend / stall / crash), shared here
  so tests pin it without spawning processes.

Durability contract: journal appends are flushed + fsync'd (they happen
at span boundaries — eval/checkpoint cadence — not per round, so the
fsync is off the hot path); the manifest is written same-dir-tmp +
``os.replace`` like every checkpoint (utils/checkpoint.py).  A SIGKILL
mid-append leaves at most one torn line, which the next attempt seals
(newline) and the reader skips — the exactly-once invariant survives
arbitrary kill points because records are committed *after* the work
they describe and gated by the high-water mark on replay.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import time
from typing import Optional


# Process exit codes (the supervisor's first classification key).
EXIT_OK = 0
EXIT_PREEMPTED = 75   # EX_TEMPFAIL: checkpointed + resumable, retry now
EXIT_DIVERGED = 76    # watchdog exhausted max_rollbacks: deterministic,
#                       retrying the same config would diverge again


class Preempted(Exception):
    """A graceful-shutdown request was honored at a span boundary: the
    state is checkpointed, the manifest says 'preempted', and the
    process should exit EXIT_PREEMPTED."""

    def __init__(self, round_: int, source: str):
        self.round = int(round_)
        self.source = source
        super().__init__(
            f"preempted by {source} at round boundary {round_} "
            f"(state checkpointed; resume with --resume)")


class GracefulShutdown:
    """Signal-driven shutdown request, polled at span boundaries.

    A handler can't interrupt an in-flight device program (nor should
    it: a torn round is worthless), so SIGTERM/SIGINT only *request*:
    the engine honors the request at the next host boundary — the same
    boundary where checkpoints and eval already live — by
    checkpointing and raising :class:`Preempted`.

    ``preempt_at_round``: deterministic injection seam for tests, the
    crash matrix and the capture rehearsal (env ``FL_PREEMPT_AT_ROUND``
    via the CLI): the request fires at the first boundary at or past
    that round, but only when the attempt *started* at or before it —
    so the resumed attempt (which starts past the injection point)
    runs to completion instead of re-preempting forever.
    """

    def __init__(self, preempt_at_round: Optional[int] = None,
                 signals=(signal.SIGTERM, signal.SIGINT)):
        self.preempt_at_round = preempt_at_round
        self.signals = tuple(signals)
        self.requested = False
        self.source = None
        self._old = {}

    # --- installation ---------------------------------------------------
    def install(self):
        for s in self.signals:
            self._old[s] = signal.signal(s, self._on_signal)
        return self

    def restore(self):
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old = {}

    __enter__ = install

    def __exit__(self, exc_type, exc, tb):
        self.restore()
        return False

    def _on_signal(self, signum, frame):
        if self.requested:
            # Second signal: the user means NOW.  Restore the default
            # disposition and re-deliver — no graceful anything.
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        self.requested = True
        self.source = signal.Signals(signum).name

    # --- the boundary poll ----------------------------------------------
    def should_preempt(self, start_round: int, round_: int) -> bool:
        """True when the engine should checkpoint-and-exit at this
        boundary (``round_`` just finished; the attempt resumed from
        ``start_round``)."""
        if self.requested:
            return True
        pa = self.preempt_at_round
        if pa is not None and start_round <= pa <= round_:
            self.source = self.source or "injected"
            return True
        return False


# ---------------------------------------------------------------------------
# run identity

# Config fields that do not shape the trajectory or the run's identity —
# two runs differing only here are the SAME run to the journal.
_IDENTITY_EXCLUDED = ("output", "log_dir", "run_dir")


def run_id_for(cfg) -> str:
    """Deterministic run id: a restarted process (same config) finds the
    same journal.  Supervised runs override this with an explicit
    ``--run-id`` so the journal stays unified across *degraded*
    restarts (a halved batch or a CPU fallback changes the config hash
    on purpose — the supervisor owns the identity then)."""
    d = dataclasses.asdict(cfg)
    for k in _IDENTITY_EXCLUDED:
        d.pop(k, None)
    digest = hashlib.sha1(
        json.dumps(d, sort_keys=True, default=str).encode()).hexdigest()
    return f"{cfg.dataset}_{cfg.defense}_s{cfg.seed}_{digest[:10]}"


# ---------------------------------------------------------------------------
# the per-run journal


class RunJournal:
    """Append-only per-run journal + atomic resume manifest.

    Layout (``<run_dir>/<run_id>/``):

    - ``journal.jsonl`` — one record per committed unit, append-only:
      ``{"kind": "attempt", "attempt": k, "from_round": r}``,
      ``{"kind": "rounds", "start": s, "end": e}`` (inclusive),
      ``{"kind": "eval", "round": t}``,
      ``{"kind": "finish", "status": ..., "exit_code": ...}``.
    - ``manifest.json`` — the current lifecycle summary, atomically
      replaced at every transition (what the supervisor reads).

    Exactly-once semantics: ``commit_rounds`` clamps below the
    monotonic high-water mark, so a round enters the journal at most
    once no matter how many times it is re-executed (resume replay and
    watchdog rollback both re-execute); ``fresh_round``/``fresh_eval``
    gate event emission and eval work with the same mark, so the event
    stream matches.  Records are committed *after* the work they
    describe: a kill between execution and commit re-executes (and
    then commits) on resume — never double-commits.
    """

    def __init__(self, run_dir: str, run_id: str):
        self.run_id = run_id
        self.dir = os.path.join(run_dir, run_id)
        os.makedirs(self.dir, exist_ok=True)
        self.journal_path = os.path.join(self.dir, "journal.jsonl")
        self.manifest_path = os.path.join(self.dir, "manifest.json")
        self._fh = None
        self.high = -1          # highest committed round
        self.evals = set()      # committed eval rounds
        self.attempt = 0        # attempts so far (this one after start_attempt)
        self.torn_lines = 0
        self._replay()

    # --- replay ----------------------------------------------------------
    def records(self) -> list:
        """All parseable journal records (torn lines skipped)."""
        if not os.path.exists(self.journal_path):
            return []
        out, torn = [], 0
        with open(self.journal_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    # A SIGKILL mid-append leaves one torn line; the
                    # append path seals it with a newline so it can
                    # never swallow a later record.
                    torn += 1
        self.torn_lines = torn
        return out

    def _replay(self):
        for rec in self.records():
            k = rec.get("kind")
            if k == "rounds":
                self.high = max(self.high, int(rec["end"]))
            elif k == "eval":
                self.evals.add(int(rec["round"]))
            elif k == "attempt":
                self.attempt = max(self.attempt, int(rec["attempt"]))

    # --- append path ------------------------------------------------------
    def _append(self, rec: dict):
        if self._fh is None:
            # Seal a torn tail before appending: without the newline a
            # new record would concatenate onto the partial line and
            # both would be unreadable.
            if (os.path.exists(self.journal_path)
                    and os.path.getsize(self.journal_path) > 0):
                with open(self.journal_path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    needs_seal = f.read(1) != b"\n"
                if needs_seal:
                    with open(self.journal_path, "a") as f:
                        f.write("\n")
            self._fh = open(self.journal_path, "a")
        rec.setdefault("t", round(time.time(), 3))
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    # --- lifecycle transitions -------------------------------------------
    def start_attempt(self, resume_round: int) -> int:
        """Record the start of one process attempt; returns the attempt
        number (1-based)."""
        self.attempt += 1
        self._append({"kind": "attempt", "attempt": self.attempt,
                      "from_round": int(resume_round)})
        self.write_manifest("running")
        return self.attempt

    def finish(self, status: str, exit_code: int = EXIT_OK, **extra):
        self._append({"kind": "finish", "status": status,
                      "exit_code": int(exit_code)})
        self.write_manifest(status, exit_code=int(exit_code), **extra)

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # --- exactly-once accounting -----------------------------------------
    def fresh_round(self, t: int) -> bool:
        """True when round ``t`` has not been committed yet — the gate
        for per-round event emission (a replayed round's events were
        already written by the attempt that committed it)."""
        return int(t) > self.high

    def commit_rounds(self, start: int, end: int):
        """Commit rounds [start, end] (inclusive), clamped to the fresh
        suffix; re-executions below the high-water mark are no-ops."""
        start = max(int(start), self.high + 1)
        if int(end) < start:
            return
        self._append({"kind": "rounds", "start": start, "end": int(end)})
        self.high = int(end)

    def fresh_eval(self, t: int) -> bool:
        return int(t) not in self.evals

    def commit_eval(self, t: int):
        if not self.fresh_eval(t):
            return
        self._append({"kind": "eval", "round": int(t)})
        self.evals.add(int(t))

    # --- manifest ---------------------------------------------------------
    def write_manifest(self, status: str, **extra):
        man = {"run_id": self.run_id, "status": status,
               "attempt": self.attempt, "last_round": self.high,
               "rounds_committed": self.high + 1,
               "evals_committed": len(self.evals),
               "torn_lines": self.torn_lines,
               "updated": round(time.time(), 3)}
        man.update(extra)
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(man, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.manifest_path)

    def read_manifest(self) -> Optional[dict]:
        if not os.path.exists(self.manifest_path):
            return None
        with open(self.manifest_path) as f:
            return json.load(f)

    # --- the invariant, checked mechanically ------------------------------
    def verify(self, epochs: Optional[int] = None,
               test_step: Optional[int] = None) -> list:
        """Exactly-once audit; returns a list of problem strings (empty
        = clean).  With ``epochs``, coverage of [0, epochs) is required;
        with ``test_step`` too, the eval set must be exactly the eval
        cadence (every test_step-th round plus the final one)."""
        problems = []
        seen_rounds = {}
        evals = {}
        for rec in self.records():
            if rec.get("kind") == "rounds":
                for t in range(int(rec["start"]), int(rec["end"]) + 1):
                    seen_rounds[t] = seen_rounds.get(t, 0) + 1
            elif rec.get("kind") == "eval":
                t = int(rec["round"])
                evals[t] = evals.get(t, 0) + 1
        dup_r = sorted(t for t, c in seen_rounds.items() if c > 1)
        if dup_r:
            problems.append(f"rounds committed more than once: {dup_r}")
        dup_e = sorted(t for t, c in evals.items() if c > 1)
        if dup_e:
            problems.append(f"evals committed more than once: {dup_e}")
        if epochs is not None:
            missing = [t for t in range(epochs) if t not in seen_rounds]
            if missing:
                problems.append(f"rounds never committed: {missing}")
            stray = sorted(t for t in seen_rounds if not 0 <= t < epochs)
            if stray:
                problems.append(f"rounds outside [0, {epochs}): {stray}")
            if test_step is not None:
                want = {t for t in range(epochs)
                        if t % test_step == 0 or t == epochs - 1}
                if set(evals) != want:
                    problems.append(
                        f"eval set mismatch: got {sorted(evals)}, "
                        f"want {sorted(want)}")
        return problems


# ---------------------------------------------------------------------------
# failure taxonomy (shared by tools/supervisor.py and its tests)

# Classes, in the order the supervisor reports them.  'done' and the
# fatal classes terminate supervision; the rest retry (with per-class
# backoff and degradation, tools/supervisor.py).
FAILURE_CLASSES = ("done", "preempted", "divergence", "oom", "backend",
                   "stall", "crash")

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "MemoryError", "std::bad_alloc", "OOM when allocating")
_BACKEND_MARKERS = ("Unable to initialize backend",
                    "failed to connect", "Connection refused",
                    "DEADLINE_EXCEEDED", "UNAVAILABLE",
                    "relay", "socket closed",
                    "TPU initialization failed")
_DIVERGENCE_MARKERS = ("diverged", "exhausted", "FloatingPointError")


def classify_failure(returncode: int, stderr_tail: str = "",
                     stalled: bool = False) -> str:
    """Map one child run's outcome to a failure class.

    Precedence: a supervisor-detected stall (heartbeat age beyond the
    stall timeout — the child was killed BY the supervisor, so its exit
    code describes the kill, not the disease) wins over everything;
    then the explicit lifecycle exit codes; then stderr markers (OOM
    before backend: an OOM abort often drags connection noise behind
    it); anything else is a plain crash."""
    if returncode == EXIT_OK:
        return "done"
    if stalled:
        return "stall"
    if returncode == EXIT_PREEMPTED:
        return "preempted"
    if returncode == EXIT_DIVERGED:
        return "divergence"
    tail = stderr_tail or ""
    if any(m in tail for m in _OOM_MARKERS):
        return "oom"
    if any(m in tail for m in _BACKEND_MARKERS):
        return "backend"
    if any(m in tail for m in _DIVERGENCE_MARKERS):
        return "divergence"
    return "crash"
