"""The wire format: params pytree <-> flat vector.

The reference's load-bearing abstraction is a flat float vector of all model
parameters (``flatten_params`` reference user.py:17-18, ``row_into_parameters``
user.py:21-28): server state, the (n_users, d) gradient matrix, defense inputs
and attack perturbations all live in that format.

Here the pytree is the primary representation (models run on pytrees) and the
flat vector appears only at the defense/attack boundary, via a pair of jitted
bijections built once per model with ``jax.flatten_util.ravel_pytree``.
Because model pytrees are ordered dicts in torch ``.parameters()`` order and
weights keep torch's (out, in) / (O, I, H, W) layouts, the flat vector is
bit-layout-compatible with the reference's wire format: a flat vector produced
by the reference loads into these models unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.flatten_util
import jax.numpy as jnp


class FlatParams(NamedTuple):
    """Bijection between a model's params pytree and the flat wire vector."""
    ravel: Callable[[Any], jax.Array]     # pytree -> (d,)
    unravel: Callable[[jax.Array], Any]   # (d,) -> pytree
    dim: int                              # d


def make_flattener(example_params) -> FlatParams:
    flat, unravel = jax.flatten_util.ravel_pytree(example_params)

    def ravel(tree):
        return jax.flatten_util.ravel_pytree(tree)[0]

    return FlatParams(ravel=ravel, unravel=unravel, dim=int(flat.shape[0]))


def ravel_batch(trees) -> jax.Array:
    """Stacked pytrees (leading client axis) -> (n, d) matrix."""
    return jax.vmap(lambda t: jax.flatten_util.ravel_pytree(t)[0])(trees)


def tree_zeros_like(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)
