"""Measured stage walls: book a ``jax.profiler`` trace onto the stage
taxonomy (the runtime twin of utils/costs.py:stage_attribution).

PR 15 priced every compiled op statically (modeled FLOPs/bytes split
across the six-stage taxonomy).  This module measures where the *wall
clock* actually goes: it parses the Chrome-trace JSON a
``jax.profiler.trace(dir)`` capture writes under
``<dir>/plugins/profile/<ts>/*.trace.json.gz`` and books every op
event's duration to the innermost stage token of that op's ``op_name``
metadata, with the same exact-partition discipline as
``stage_attribution`` — stage sums + the ``unattributed`` residual
equal the booked total *by construction* (one bucket per op, total =
sum of buckets), and coverage is reported instead of hidden.

The join that makes this work on this box (measured, not assumed):

- On the TFRT CPU backend the profiler emits **no** op-level events by
  default — only runtime spans (``TfrtCpuExecutable::Execute``,
  ``PjitFunction(f)``) with empty args.  With
  ``--xla_cpu_enable_xprof_traceme=true`` in ``XLA_FLAGS`` (set before
  the FIRST compile of the process — XLA parses the env once;
  :func:`attacking_federate_learning_tpu.utils.profiling.
  ensure_op_profiling` owns the mechanics) each thunk execution
  appears as one X event **named by its HLO instruction**
  (``dot.4``, ``iota_reduce_fusion``) — with no scope path and no
  args.
- The stage tokens therefore never ride the trace itself; they live in
  the compiled program's ``op_name`` metadata.  Booking is a join:
  instruction name (trace event) -> ``op_name`` (HLO text) -> innermost
  stage token (``stage_attribution``'s rule, verbatim).  On TPU the
  op events carry full metadata already; the same join degrades to a
  name lookup and books identically.

The op universe is defined by the HLO map: an X event whose name is a
known instruction of one of the supplied programs is an op event;
everything else (python tracer rows, threadpool listeners, executable
wrappers) is runtime noise, counted in ``coverage`` but never booked —
so a host-heavy capture cannot smear the device partition.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
import re
from typing import Optional

from attacking_federate_learning_tpu.utils.costs import STAGES, _STAGE_SET

_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')

# Trace-event names that are runtime machinery, never HLO ops; counted
# as runtime (not "unknown") in coverage diagnostics.
_RUNTIME_PREFIXES = ("TfrtCpu", "PjitFunction", "ThreadpoolListener",
                     "ParseArguments", "ThunkExecutor", "$", "Xla",
                     "ExecuteShardedOnLocalDevices", "copy_to_host")


@dataclasses.dataclass
class WallRecord:
    """Measured per-stage wall time for one entry point / capture.

    ``stages`` maps each canonical stage to booked microseconds;
    ``unattributed_us`` holds op time whose ``op_name`` carries no
    stage token (scopes off, XLA-invented fusions with no metadata).
    ``total_us`` is defined as ``sum(stages.values()) +
    unattributed_us`` — the partition is exact by construction, which
    :func:`WallRecord.check` re-asserts.  ``coverage`` reports what the
    partition does NOT cover: trace op events never matched to the
    supplied HLO and the runtime/host share of the capture."""

    name: str
    platform: str = "unknown"
    rounds: Optional[int] = None
    stages: dict = dataclasses.field(default_factory=dict)
    unattributed_us: float = 0.0
    coverage: dict = dataclasses.field(default_factory=dict)
    trace_dir: Optional[str] = None

    @property
    def total_us(self) -> float:
        return sum(self.stages.values()) + self.unattributed_us

    def check(self) -> None:
        """Partition invariant: stage sums + unattributed == total,
        exactly (same floats, same order — not within a tolerance)."""
        total = sum(self.stages.values()) + self.unattributed_us
        if total != self.total_us:
            raise AssertionError(
                f"wall partition broken for {self.name}: "
                f"{total} != {self.total_us}")

    def wall_event(self) -> dict:
        """Schema-v10 'wall' event payload (source='trace')."""
        ev = dict(kind="wall", source="trace", name=self.name,
                  wall_s=round(self.total_us / 1e6, 6),
                  stages={s: round(v, 3)
                          for s, v in self.stages.items()},
                  unattributed_us=round(self.unattributed_us, 3),
                  coverage=self.coverage, platform=self.platform)
        if self.rounds is not None:
            ev["rounds"] = int(self.rounds)
        if self.trace_dir:
            ev["trace_dir"] = self.trace_dir
        return ev


def hlo_stage_map(text: str) -> dict:
    """Instruction name -> innermost stage token (or None) for one
    compiled HLO text — the static side of the trace join.  The token
    rule is stage_attribution's, verbatim: the LAST taxonomy token in
    the ``op_name`` scope path wins (an outer engine scope must not
    clobber the finer scopes inside)."""
    out = {}
    for line in text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        nm = _OPNAME_RE.search(line)
        stage = None
        if nm is not None:
            toks = [t for t in nm.group(1).split("/") if t in _STAGE_SET]
            if toks:
                stage = toks[-1]
        out[m.group(1)] = stage
    return out


def find_trace_file(trace_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under a ``jax.profiler.trace`` output
    dir (``<dir>/plugins/profile/<timestamp>/<host>.trace.json.gz``),
    or None when the capture produced nothing (dead relay, no-op
    device_trace)."""
    hits = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                     recursive=True)
    hits += glob.glob(os.path.join(trace_dir, "**", "*.trace.json"),
                      recursive=True)
    return max(hits, key=os.path.getmtime) if hits else None


def load_trace_events(path: str) -> list:
    """The X (complete) events of one Chrome-trace JSON (.gz or
    plain)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        obj = json.load(f)
    return [e for e in obj.get("traceEvents", [])
            if isinstance(e, dict) and e.get("ph") == "X"]


def book_events(events, stage_map: dict, name: str = "trace",
                platform: str = "unknown",
                rounds: Optional[int] = None,
                trace_dir: Optional[str] = None) -> WallRecord:
    """Book trace X events onto the stage taxonomy via the instruction
    name -> stage join.  Every op event (name present in ``stage_map``)
    lands in exactly one bucket — its innermost stage, or
    ``unattributed`` when its ``op_name`` carries no taxonomy token —
    so the partition is exact by construction.  Non-op events are
    classified (runtime machinery vs unknown) and reported in
    coverage, never booked."""
    stages = {s: 0.0 for s in STAGES}
    unattributed = 0.0
    op_events = 0
    runtime_us = 0.0
    unknown_us = 0.0
    unknown_events = 0
    for e in events:
        nm = e.get("name")
        dur = float(e.get("dur", 0.0) or 0.0)
        if not isinstance(nm, str):
            continue
        if nm in stage_map:
            op_events += 1
            stage = stage_map[nm]
            if stage is None:
                unattributed += dur
            else:
                stages[stage] += dur
        elif nm.startswith(_RUNTIME_PREFIXES) or "::" in nm:
            runtime_us += dur
        else:
            unknown_events += 1
            unknown_us += dur
    booked = sum(stages.values()) + unattributed
    rec = WallRecord(
        name=name, platform=platform, rounds=rounds,
        stages={s: v for s, v in stages.items() if v > 0.0},
        unattributed_us=unattributed, trace_dir=trace_dir)
    rec.coverage = {
        "op_events": op_events,
        "trace_events": len(events),
        "booked_us": round(booked, 3),
        "runtime_us": round(runtime_us, 3),
        "unknown_us": round(unknown_us, 3),
        "unknown_events": unknown_events,
        # Fraction of non-runtime X-event time the partition explains;
        # 0.0 on a capture with no op events (flag unset / TPU-gated
        # no-op trace) — loud, not wrong.
        "op_time_fraction": round(
            booked / (booked + unknown_us), 4)
        if (booked + unknown_us) > 0 else 0.0,
    }
    rec.check()
    return rec


def book_trace(trace_dir: str, hlo_texts, name: str = "trace",
               platform: str = "unknown",
               rounds: Optional[int] = None) -> Optional[WallRecord]:
    """Parse the newest capture under ``trace_dir`` and book it against
    one HLO text or an iterable of texts (their instruction maps are
    unioned — a span capture may interleave several executables).
    Returns None when the dir holds no trace (the device_trace no-op
    path), never raises on an empty capture."""
    path = find_trace_file(trace_dir)
    if path is None:
        return None
    if isinstance(hlo_texts, str):
        hlo_texts = [hlo_texts]
    stage_map: dict = {}
    for text in hlo_texts:
        stage_map.update(hlo_stage_map(text))
    events = load_trace_events(path)
    return book_events(events, stage_map, name=name, platform=platform,
                       rounds=rounds, trace_dir=trace_dir)


def measured_vs_modeled(wall_rec: dict, stage_cost: dict) -> dict:
    """Per-stage measured-vs-modeled shares for one entry point: joins
    a 'wall' event (source='trace') with its 'stage_cost' twin by
    stage.  Shares are fractions of each record's own attributed total
    (measured us vs modeled flops), so the ratio is scale-free:
    ratio > 1 means the stage costs more wall time than its modeled
    flop share predicts (memory-bound, host-marshal, launch overhead),
    ratio < 1 the reverse.  Stages absent from either side carry None
    ratios instead of fabricated zeros."""
    meas = dict(wall_rec.get("stages") or {})
    meas["unattributed"] = float(wall_rec.get("unattributed_us", 0.0))
    modeled = {s: float((v or {}).get("flops", 0.0))
               for s, v in (stage_cost.get("stages") or {}).items()}
    modeled["unattributed"] = float(
        (stage_cost.get("unattributed") or {}).get("flops", 0.0))
    mt = sum(meas.values())
    ct = sum(modeled.values())
    out = {}
    for stage in tuple(STAGES) + ("unattributed",):
        m_us = float(meas.get(stage, 0.0))
        flops = modeled.get(stage)
        m_share = (m_us / mt) if mt > 0 else 0.0
        c_share = (flops / ct) if (flops is not None and ct > 0) else None
        row = {"measured_us": round(m_us, 3),
               "measured_share": round(m_share, 4),
               "modeled_share": (round(c_share, 4)
                                 if c_share is not None else None)}
        row["ratio"] = (round(m_share / c_share, 3)
                        if c_share else None)
        if m_us > 0 or (c_share or 0) > 0:
            out[stage] = row
    return out
