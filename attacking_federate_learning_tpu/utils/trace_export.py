"""Render a structured run log as Chrome/Perfetto trace-event JSON.

The event schema (utils/metrics.py) already carries everything a
timeline viewer needs — per-round telemetry with relative timestamps,
compile wall times, lifecycle transitions, fault injections, heartbeat
liveness — but until PR 5 the only timeline view was ``tail -f``.  This
module converts any run JSONL (all schema versions) into the Trace
Event Format that ``chrome://tracing`` and https://ui.perfetto.dev load
directly:

- **rounds** become complete ("X") spans on one track: each round's
  span opens at the earliest event carrying that round number and
  closes at the next round's open (the last round closes at the last
  event timestamp) — so the round cadence, eval stalls and fused-span
  bursts are visible at a glance;
- **compiles** become "X" spans of their measured ``compile_s`` on a
  compile track (cache attribution in args);
- **evals / asr / lifecycle / faults / stream / registry / gate**
  become instant ("i") events with their payload in args;
- **heartbeats** become counter ("C") tracks (rss_mb, rounds_per_s) —
  a stalled run is a flat-lining counter;
- **shard_selection** rounds (schema v6, hierarchical forensics)
  become a ``tier2_rejected`` counter (how many shard estimates the
  cross-shard reduction rejected that round) plus per-round instants
  on a "tier-2 forensics" track naming the rejected set, and a
  **forensics** verdict becomes an instant on the same track — the
  colluder-localization story as a timeline;
- **margin** rounds (schema v12, --margins) become a
  ``colluder_margin`` counter track next to ``tier2_rejected`` — the
  defense-sign colluder margin per round, so a robustness collapse is
  literally the counter crossing zero on the timeline (rounds without
  a finite margin draw no point);
- the end-of-run **profile** summary (PhaseTimer) is laid out as
  sequential "X" spans on a phases track (aggregates, not real
  intervals — count/mean ride in args);
- **wall** events (schema v10, --profile-every): each source='trace'
  capture's measured stage walls become sequential "X" spans on a
  "measured stages" track (aggregates over the profiled span, same
  convention as the phases track — the relative widths are the
  runtime attribution utils/walls.py booked), and the host-clock
  span/eval walls become instants on the same track.

``device_trace`` is the opt-in REAL capture hook: under ``FL_TEST_TPU=1``
it wraps ``jax.profiler`` start/stop trace (XLA-level, TensorBoard/
Perfetto-loadable) around a region; anywhere else it is a no-op, so
harness code can always use it without risking a TPU touch on a box
where the relay may be dead (CLAUDE.md).

``validate_trace`` checks the exported object against the trace-event
schema rules a viewer relies on (tests pin a real 5-round export).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
from typing import Optional

from attacking_federate_learning_tpu.utils.metrics import iter_events


# Track (tid) layout inside the single "run" process.
_TID_ROUNDS = 1
_TID_EVALS = 2
_TID_COMPILES = 3
_TID_LIFECYCLE = 4
_TID_FAULTS = 5
_TID_PHASES = 6
_TID_FORENSICS = 7
_TID_WALLS = 8

_TID_NAMES = {_TID_ROUNDS: "rounds", _TID_EVALS: "evals",
              _TID_COMPILES: "compiles", _TID_LIFECYCLE: "lifecycle",
              _TID_FAULTS: "faults", _TID_PHASES: "phases (aggregate)",
              _TID_FORENSICS: "tier-2 forensics",
              _TID_WALLS: "measured stages (aggregate)"}

_INSTANT_KINDS = {"eval": _TID_EVALS, "asr": _TID_EVALS,
                  "lifecycle": _TID_LIFECYCLE, "fault": _TID_FAULTS,
                  "stream": _TID_LIFECYCLE, "registry": _TID_LIFECYCLE,
                  "gate": _TID_LIFECYCLE, "forensics": _TID_FORENSICS}

# Event-record fields that are bookkeeping, not payload.
_META_FIELDS = {"kind", "t", "v"}


def _us(t_seconds) -> int:
    """Trace-event timestamps are integer microseconds."""
    return int(round(1e6 * float(t_seconds)))


def _args_of(rec) -> dict:
    """JSON-safe payload args: scalars kept, vectors summarized by
    length (a 79k-entry selection mask has no business in a tooltip)."""
    out = {}
    for k, v in rec.items():
        if k in _META_FIELDS:
            continue
        if isinstance(v, (list, tuple)):
            out[k] = f"<{len(v)} values>"
        elif isinstance(v, (dict,)):
            out[k] = f"<{len(v)} fields>"
        else:
            out[k] = v
    return out


def events_to_trace(events, name: str = "run") -> dict:
    """One run's events (dicts, any schema version) -> a Chrome
    trace-event JSON object ``{"traceEvents": [...]}``."""
    pid = 1
    trace = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
              "args": {"name": name}}]
    for tid, tname in _TID_NAMES.items():
        trace.append({"name": "thread_name", "ph": "M", "pid": pid,
                      "tid": tid, "args": {"name": tname}})

    # Pass 1: per-round open timestamps (earliest event naming the
    # round) and the overall clock extent.
    round_open = {}
    t_max = 0.0
    for e in events:
        t = e.get("t")
        if not isinstance(t, (int, float)):
            continue
        t_max = max(t_max, float(t))
        r = e.get("round")
        if isinstance(r, (int, float)) and e.get("kind") != "heartbeat":
            r = int(r)
            round_open[r] = min(round_open.get(r, float(t)), float(t))

    # Round spans: close each at the next round's open (fused spans
    # surface as a burst of zero-ish-width rounds at the fetch
    # boundary — faithful: that IS when the host learned about them).
    opens = sorted(round_open.items())
    for i, (r, t0) in enumerate(opens):
        t1 = opens[i + 1][1] if i + 1 < len(opens) else max(t_max, t0)
        trace.append({"name": f"round {r}", "ph": "X", "pid": pid,
                      "tid": _TID_ROUNDS, "ts": _us(t0),
                      "dur": max(_us(t1) - _us(t0), 1),
                      "args": {"round": r}})

    for e in events:
        kind = e.get("kind")
        t = e.get("t")
        if kind is None or not isinstance(t, (int, float)):
            continue
        if kind == "compile":
            dur_s = float(e.get("compile_s", 0.0) or 0.0)
            ts = max(float(t) - dur_s, 0.0)   # t stamps the tail
            trace.append({"name": f"compile {e.get('name', '?')}",
                          "ph": "X", "pid": pid, "tid": _TID_COMPILES,
                          "ts": _us(ts), "dur": max(_us(dur_s), 1),
                          "args": _args_of(e)})
        elif kind == "heartbeat":
            for field in ("rss_mb", "rounds_per_s"):
                if isinstance(e.get(field), (int, float)):
                    trace.append({"name": field, "ph": "C", "pid": pid,
                                  "tid": 0, "ts": _us(t),
                                  "args": {field: float(e[field])}})
        elif kind == "profile":
            # Aggregate phase totals laid end to end from t=0: not real
            # intervals (count/mean in args say so), but the relative
            # widths ARE the timing attribution.
            cursor = 0.0
            for pname, row in (e.get("phases") or {}).items():
                total = float(row.get("total_s", 0.0))
                trace.append({"name": pname, "ph": "X", "pid": pid,
                              "tid": _TID_PHASES, "ts": _us(cursor),
                              "dur": max(_us(total), 1),
                              "args": {"count": row.get("count"),
                                       "mean_ms": row.get("mean_ms"),
                                       "aggregate": True}})
                cursor += total
        elif kind == "wall":
            if e.get("source") == "trace":
                # Measured stage walls (schema v10): laid end to end
                # from the event's own timestamp — aggregates over the
                # profiled span, not real intervals (args say so), but
                # the relative widths ARE the measured attribution,
                # the runtime twin of the phases track above.
                cursor = float(t)
                rows = dict(e.get("stages") or {})
                ua = float(e.get("unattributed_us", 0.0) or 0.0)
                if ua > 0:
                    rows["unattributed"] = ua
                for sname, us in rows.items():
                    dur_s = float(us) / 1e6
                    trace.append({"name": f"{e.get('name', '?')}:"
                                          f"{sname}",
                                  "ph": "X", "pid": pid,
                                  "tid": _TID_WALLS, "ts": _us(cursor),
                                  "dur": max(_us(dur_s), 1),
                                  "args": {"measured_us": float(us),
                                           "entry": e.get("name"),
                                           "aggregate": True}})
                    cursor += dur_s
            else:
                # Host-clock span/eval walls: instants on the same
                # track (the payload carries wall_s / rounds_per_s).
                trace.append({"name": f"wall:{e.get('name', '?')}",
                              "ph": "i", "pid": pid, "tid": _TID_WALLS,
                              "ts": _us(t), "s": "t",
                              "args": _args_of(e)})
        elif kind == "shard_selection":
            # Hierarchical forensics (schema v6): the tier-2 rejection
            # attribution as a timeline — a counter of how many shard
            # estimates the cross-shard reduction rejected this round,
            # plus an instant naming the rejected set (report.py owns
            # the attribution rule; mean/median tier-2 kernels expose
            # no selection and draw no point).
            from attacking_federate_learning_tpu.report import (
                tier2_attribution
            )
            mass, rejected = tier2_attribution(e)
            if mass is not None:
                trace.append({"name": "tier2_rejected", "ph": "C",
                              "pid": pid, "tid": 0, "ts": _us(t),
                              "args": {"tier2_rejected":
                                       float(len(rejected))}})
                args = _args_of(e)
                args["rejected_shards"] = ",".join(
                    str(s) for s in sorted(rejected)) or "none"
                trace.append({"name": f"tier2 reject "
                                      f"{sorted(rejected)}",
                              "ph": "i", "pid": pid,
                              "tid": _TID_FORENSICS, "ts": _us(t),
                              "s": "t", "args": args})
        elif kind == "margin":
            # Robustness-margin ledger (schema v12, --margins): the
            # defense-sign colluder margin as a counter track next to
            # tier2_rejected — a collapse is the counter crossing zero.
            # Rounds without a finite margin (an async empty delivery
            # makes no decision) draw no point rather than a NaN the
            # viewer can't parse.
            cm = e.get("colluder_margin")
            if isinstance(cm, (int, float)) and math.isfinite(cm):
                trace.append({"name": "colluder_margin", "ph": "C",
                              "pid": pid, "tid": 0, "ts": _us(t),
                              "args": {"colluder_margin": float(cm)}})
        elif kind == "numerics":
            # Numeric-health ledger (schema v14, --numerics): one
            # counter track per round for the health scalars a viewer
            # can eyeball — nonfinite total, tie-proximity count, and
            # cancellation depth.  Hier stacks are lists; only finite
            # scalars draw points (same NaN rule as the margin track).
            vals = {}
            for f in ("nonfinite_total", "tie_rows", "cancel_bits"):
                v = e.get(f)
                if isinstance(v, (int, float)) and math.isfinite(v):
                    vals[f] = float(v)
            if vals:
                trace.append({"name": "numerics", "ph": "C",
                              "pid": pid, "tid": 0, "ts": _us(t),
                              "args": vals})
        elif kind in _INSTANT_KINDS:
            label = kind if kind != "lifecycle" else (
                f"lifecycle:{e.get('phase', '?')}")
            if kind == "forensics":
                label = f"forensics:{e.get('verdict', '?')}"
            trace.append({"name": label, "ph": "i", "pid": pid,
                          "tid": _INSTANT_KINDS[kind], "ts": _us(t),
                          "s": "t", "args": _args_of(e)})
        # round/defense/attack/cost/etc. are covered by the round spans
        # and would only duplicate tooltips.
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def export_trace(jsonl_path: str, out_path: Optional[str] = None,
                 name: Optional[str] = None, validate: bool = False) -> str:
    """Read one run JSONL (torn tails tolerated — a crashed run's trace
    is exactly the interesting one) and write the trace JSON next to it
    (``<log>.trace.json``) or to ``out_path``.  Returns the path."""
    events = list(iter_events(jsonl_path, validate=validate,
                              skip_bad=True))
    trace = events_to_trace(
        events, name=name or os.path.basename(jsonl_path))
    problems = validate_trace(trace)
    if problems:     # the exporter must never emit an unloadable trace
        raise ValueError(f"exporter bug: {problems[:3]}")
    out_path = out_path or jsonl_path + ".trace.json"
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return out_path


# Phase types this exporter emits; validation is over these (a viewer
# accepts more, but anything else coming out of events_to_trace is a
# bug).
_KNOWN_PH = {"X", "i", "C", "M"}


def validate_trace(obj) -> list:
    """Check a trace object against the Chrome trace-event schema rules
    the viewers rely on; returns a list of problem strings (empty =
    loadable)."""
    problems = []
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return ["trace must be a JSON object with a 'traceEvents' list"]
    evs = obj["traceEvents"]
    if not isinstance(evs, list):
        return ["'traceEvents' must be a list"]
    for i, e in enumerate(evs):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = e.get("ph")
        if ph not in _KNOWN_PH:
            problems.append(f"{where}: unknown ph {ph!r}")
            continue
        if not isinstance(e.get("name"), str) or not e["name"]:
            problems.append(f"{where}: missing/empty name")
        for field in ("pid", "tid"):
            if not isinstance(e.get(field), int):
                problems.append(f"{where}: {field} must be an int")
        if ph != "M":
            ts = e.get("ts")
            if not isinstance(ts, int) or ts < 0:
                problems.append(f"{where}: ts must be a non-negative "
                                f"integer (microseconds), got {ts!r}")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, int) or dur <= 0:
                problems.append(f"{where}: 'X' event needs integer "
                                f"dur > 0, got {dur!r}")
        if ph == "C":
            args = e.get("args")
            if (not isinstance(args, dict) or not args or not all(
                    isinstance(v, (int, float)) for v in args.values())):
                problems.append(f"{where}: 'C' event needs numeric args")
        if ph == "M":
            if not isinstance(e.get("args", {}).get("name"), str):
                problems.append(f"{where}: metadata event needs "
                                f"args.name")
        if ph == "i" and e.get("s") not in (None, "g", "p", "t"):
            problems.append(f"{where}: instant scope must be g/p/t")
    return problems


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]):
    """Opt-in REAL profiler capture: under ``FL_TEST_TPU=1`` (the same
    gate the hardware-bound tests use) this wraps ``jax.profiler``
    start/stop trace around the block, producing an XLA-level
    TensorBoard/Perfetto capture in ``log_dir``.  Anywhere else — no
    log_dir, or no FL_TEST_TPU — it is a no-op, so callers can wrap
    capture regions unconditionally without ever touching a backend
    whose relay may be dead (CLAUDE.md).  The measured-walls layer
    uses the CPU-safe variant (utils/profiling.py:device_trace); this
    strictly-gated spelling is kept for its pre-walls callers."""
    from attacking_federate_learning_tpu.utils.profiling import (
        device_trace as _dt
    )
    with _dt(log_dir, require_gate=True):
        yield
