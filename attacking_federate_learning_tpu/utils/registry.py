"""Cross-run registry: a queryable index over ``runs/``.

Through PR 4 every run writes rich artifacts — the exactly-once journal
and manifest (utils/lifecycle.py), the versioned event log
(utils/metrics.py), compile/cost ledgers (utils/costs.py) — but each is
consumed exactly once and never compared across runs: PARITY.md and
GRID_RESULTS.md are maintained by hand.  This module turns the run
store into the queryable substrate those comparisons need (DrJAX,
arXiv:2403.07128, makes the same argument for FL-in-JAX at scale:
experimentation lives or dies on run-level instrumentation, not ad-hoc
logs):

- :class:`RunRegistry` indexes every ``runs/<run_id>/`` journal dir
  (manifest + journal high-water mark + event-log rollups) plus
  BENCH_*.json / PROGRESS.jsonl sidecar artifacts into a single
  ``runs/index.jsonl``;
- ``refresh()`` is incremental (a per-source ``sig`` of mtime+size
  skips unchanged runs) and tolerant of torn artifacts (a SIGKILL
  mid-write leaves at most one unparseable line/file; it is counted,
  never fatal);
- ``resolve()`` finds a run by exact id, unique id prefix, tag, or
  ``key=value`` config filter — the CLI's ``runs list/show/diff/
  compare`` and ``report --run-id`` all resolve through it;
- ``stamp()`` is the engine's run-finish hook (core/engine.py): one
  appended index line, so a finished run is queryable immediately
  without a full rescan.

The index is append-friendly: readers take the LAST entry per run_id,
and ``refresh()`` compacts.  One-shot migration (the PR 5 layout fix):
a manifest whose ``checkpoint`` points at a rotated auto-checkpoint
still sitting in the shared legacy ``runs/<dataset>/`` dir gets that
checkpoint moved under the owning ``runs/<run_id>/`` — the collision
that forced PR 4's supervisor to gate resume on run-id progress.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Optional


INDEX_NAME = "index.jsonl"

# Manifest/journal filenames (utils/lifecycle.py layout).
_MANIFEST = "manifest.json"
_JOURNAL = "journal.jsonl"

# Entry fields promoted out of the stored config for filtering without
# opening the manifest.
_CONFIG_KEYS = ("dataset", "defense", "seed", "epochs", "batch_size",
                "partition")


def _stat_sig(*paths) -> str:
    """mtime+size signature over the artifacts backing one entry; a
    changed file changes the sig, so refresh re-ingests exactly the
    runs that moved."""
    parts = []
    for p in paths:
        try:
            st = os.stat(p)
            parts.append(f"{st.st_mtime_ns}:{st.st_size}")
        except OSError:
            parts.append("-")
    return ";".join(parts)


def _read_json(path) -> Optional[dict]:
    """Tolerant JSON read: a torn/absent file is None, never a crash
    (the registry must index a run store that a SIGKILL is actively
    mutating)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _iter_jsonl(path):
    """Yield (record, None) per parseable line and (None, lineno) per
    torn one."""
    try:
        f = open(path)
    except OSError:
        return
    with f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line), None
            except json.JSONDecodeError:
                yield None, lineno


class RunRegistry:
    """Queryable index over one ``run_dir`` (default ``runs/``)."""

    def __init__(self, run_dir: str = "runs"):
        self.run_dir = run_dir
        self.index_path = os.path.join(run_dir, INDEX_NAME)
        self._migrations = 0    # moves performed by the current refresh

    # --- index io ---------------------------------------------------------
    def _load_index(self) -> dict:
        """{run_id: entry}, last entry per run_id wins (stamp() appends;
        refresh() compacts); torn lines skipped."""
        out = {}
        for rec, torn in _iter_jsonl(self.index_path):
            if rec is not None and isinstance(rec, dict) and "run_id" in rec:
                out[rec["run_id"]] = rec
        return out

    def _write_index(self, entries: dict):
        os.makedirs(self.run_dir, exist_ok=True)
        tmp = self.index_path + ".tmp"
        with open(tmp, "w") as f:
            for rid in sorted(entries):
                f.write(json.dumps(entries[rid], default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.index_path)

    def stamp(self, entry: dict):
        """Append one entry (engine run-finish hook).  Append-only so
        concurrent finishers can't lose each other's stamps; readers
        take the last entry per run_id and refresh() compacts."""
        if "run_id" not in entry:
            raise ValueError("registry entry needs a run_id")
        os.makedirs(self.run_dir, exist_ok=True)
        with open(self.index_path, "a") as f:
            f.write(json.dumps(entry, default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())

    # --- ingestion --------------------------------------------------------
    def _run_dirs(self):
        """Journal dirs under run_dir: anything carrying a manifest or a
        journal.  Dataset checkpoint dirs (runs/<dataset>/ — the
        reference layout, checkpoint files only) are not runs."""
        try:
            names = sorted(os.listdir(self.run_dir))
        except OSError:
            return []
        out = []
        for n in names:
            d = os.path.join(self.run_dir, n)
            if not os.path.isdir(d):
                continue
            if (os.path.exists(os.path.join(d, _MANIFEST))
                    or os.path.exists(os.path.join(d, _JOURNAL))):
                out.append(n)
        return out

    def _journal_rollup(self, d: str) -> dict:
        """High-water mark + eval/attempt counts straight from the raw
        journal (the manifest may be stale or torn)."""
        high, evals, attempts, torn = -1, set(), 0, 0
        for rec, bad in _iter_jsonl(os.path.join(d, _JOURNAL)):
            if rec is None:
                torn += 1
                continue
            k = rec.get("kind")
            if k == "rounds":
                try:
                    high = max(high, int(rec["end"]))
                except (KeyError, TypeError, ValueError):
                    torn += 1
            elif k == "eval":
                evals.add(rec.get("round"))
            elif k == "attempt":
                attempts = max(attempts, int(rec.get("attempt", 0)))
        return {"journal_high": high, "evals_committed": len(evals),
                "attempts": attempts, "torn_lines": torn}

    def _events_rollup(self, events_path: str) -> dict:
        """Per-kind counts + trajectory endpoints + compile-cache and
        fault/lifecycle tallies from a run's event log (tolerant: a torn
        line is counted, not fatal — the registry indexes logs that a
        crash truncated)."""
        kinds = {}
        final_acc = max_acc = final_asr = None
        cache_hits = cache_misses = fault_rounds = 0
        torn = 0
        for rec, bad in _iter_jsonl(events_path):
            if rec is None:
                torn += 1
                continue
            k = rec.get("kind")
            if k is None:
                continue
            kinds[k] = kinds.get(k, 0) + 1
            if k == "eval":
                acc = rec.get("accuracy")
                if isinstance(acc, (int, float)):
                    final_acc = acc
                    max_acc = acc if max_acc is None else max(max_acc, acc)
            elif k == "asr":
                asr = rec.get("attack_success_rate")
                if isinstance(asr, (int, float)):
                    final_asr = asr
            elif k == "compile":
                cache = rec.get("cache")
                cache_hits += cache == "hit"
                cache_misses += cache == "miss"
            elif k == "fault":
                fault_rounds += 1
        out = {"event_kinds": kinds, "event_torn_lines": torn}
        if final_acc is not None:
            out["final_accuracy"] = round(final_acc, 4)
            out["max_accuracy"] = round(max_acc, 4)
        if final_asr is not None:
            out["final_asr"] = round(final_asr, 4)
        if cache_hits or cache_misses:
            out["cache_hits"] = cache_hits
            out["cache_misses"] = cache_misses
        if fault_rounds:
            out["fault_rounds"] = fault_rounds
        return out

    def _migrate_checkpoint(self, run_id: str, d: str,
                            manifest: dict) -> Optional[str]:
        """One-shot layout migration: a manifest-referenced auto-
        checkpoint still in the shared legacy runs/<dataset>/ dir moves
        under the owning runs/<run_id>/ (npz + json sidecar), and the
        manifest is rewritten to point there.  Only the file the
        manifest itself names is touched — that file is this run's by
        construction, so no other run's resume can lose it."""
        ck = manifest.get("checkpoint")
        if not isinstance(ck, str) or not os.path.basename(ck).startswith(
                "checkpoint-auto-"):
            return None
        src_dir = os.path.dirname(os.path.abspath(ck))
        if src_dir == os.path.abspath(d):
            return None                   # already owned
        dst = os.path.join(d, os.path.basename(ck))
        if not os.path.exists(ck) or os.path.exists(dst):
            return None
        os.replace(ck, dst)
        side = ck.replace(".npz", ".json")
        if os.path.exists(side):
            os.replace(side, dst.replace(".npz", ".json"))
        manifest["checkpoint"] = dst
        tmp = os.path.join(d, _MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, default=str)
        os.replace(tmp, os.path.join(d, _MANIFEST))
        return dst

    def _entry_for_run(self, run_id: str, migrate: bool) -> dict:
        d = os.path.join(self.run_dir, run_id)
        manifest = _read_json(os.path.join(d, _MANIFEST)) or {}
        entry = {"run_id": run_id, "source": "run", "dir": d}
        if migrate and manifest:
            moved = self._migrate_checkpoint(run_id, d, manifest)
            if moved:
                # Historical record (kept on reuse); the refresh
                # summary counts only moves performed in that pass.
                entry["migrated_checkpoint"] = moved
                self._migrations += 1
        for k in ("status", "attempt", "last_round", "rounds_committed",
                  "updated", "exit_code", "checkpoint", "events",
                  "final_accuracy", "max_accuracy", "final_asr",
                  "rounds_per_s", "config_hash", "tag"):
            if k in manifest:
                entry[k] = manifest[k]
        cfg = manifest.get("config")
        if isinstance(cfg, dict):
            for k in _CONFIG_KEYS:
                if k in cfg:
                    entry[k] = cfg[k]
        if not manifest:
            entry["problems"] = ["manifest missing or torn"]
        entry.update(self._journal_rollup(d))
        ev = entry.get("events")
        if isinstance(ev, str) and os.path.exists(ev):
            entry.update(self._events_rollup(ev))
        entry["sig"] = _stat_sig(os.path.join(d, _MANIFEST),
                                 os.path.join(d, _JOURNAL))
        return entry

    def _entry_for_bench(self, path: str) -> dict:
        blob = _read_json(path) or {}
        # The driver wraps bench stdout as {"parsed": RESULT}; a raw
        # RESULT dump at the root is accepted too.
        parsed = blob.get("parsed") if isinstance(
            blob.get("parsed"), dict) else blob
        stem = os.path.splitext(os.path.basename(path))[0]
        entry = {"run_id": f"bench:{stem}", "source": "bench",
                 "path": path, "sig": _stat_sig(path)}
        if not blob:
            entry["problems"] = ["bench JSON missing or torn"]
            return entry
        for k in ("metric", "value", "unit", "valid", "env",
                  "phases_completed", "window_s", "run_ids"):
            if k in parsed:
                entry[k] = parsed[k]
        return entry

    def _entry_for_progress(self, path: str) -> dict:
        entry = {"run_id": f"progress:{os.path.basename(path)}",
                 "source": "progress", "path": path,
                 "sig": _stat_sig(path)}
        last, n, torn = None, 0, 0
        for rec, bad in _iter_jsonl(path):
            if rec is None:
                torn += 1
                continue
            last, n = rec, n + 1
        entry["lines"] = n
        entry["torn_lines"] = torn
        if last:
            entry["last"] = last
        return entry

    # --- refresh ----------------------------------------------------------
    def refresh(self, bench: Optional[list] = None,
                progress: Optional[list] = None,
                migrate: bool = True) -> dict:
        """Rebuild ``runs/index.jsonl`` incrementally.  ``bench`` /
        ``progress``: explicit sidecar artifact paths (globs accepted);
        unchanged sources (same sig) keep their previous entry without
        re-reading logs.  Returns a summary dict."""
        old = self._load_index()
        fresh, reused = {}, 0
        self._migrations = 0

        def take(key, build):
            prev = old.get(key)
            sig = build["sig_probe"]()
            if prev is not None and prev.get("sig") == sig:
                # Migration already ran when the entry was first built
                # (a moved checkpoint changes the manifest => the sig).
                fresh[key] = prev
                return False
            fresh[key] = build["make"]()
            return True

        built = 0
        for rid in self._run_dirs():
            d = os.path.join(self.run_dir, rid)
            built += take(rid, {
                "sig_probe": lambda d=d: _stat_sig(
                    os.path.join(d, _MANIFEST), os.path.join(d, _JOURNAL)),
                "make": lambda rid=rid: self._entry_for_run(rid, migrate)})
        for pat in (bench or []):
            for p in sorted(_glob.glob(pat)) or []:
                key = f"bench:{os.path.splitext(os.path.basename(p))[0]}"
                built += take(key, {
                    "sig_probe": lambda p=p: _stat_sig(p),
                    "make": lambda p=p: self._entry_for_bench(p)})
        for pat in (progress or []):
            for p in sorted(_glob.glob(pat)) or []:
                key = f"progress:{os.path.basename(p)}"
                built += take(key, {
                    "sig_probe": lambda p=p: _stat_sig(p),
                    "make": lambda p=p: self._entry_for_progress(p)})
        reused = len(fresh) - built
        self._write_index(fresh)
        return {"entries": len(fresh), "built": built, "reused": reused,
                "dropped": len(set(old) - set(fresh)),
                "migrated": self._migrations}

    # --- staleness --------------------------------------------------------
    def stale_run_ids(self) -> list:
        """Run ids whose manifest/journal changed AFTER the index was
        last written — the stale-index footgun: a reader that skips
        refresh() ('runs list --no-refresh', a cold 'runs campaign')
        would silently report outdated summaries.  Returns every run
        dir when the index does not exist yet."""
        try:
            idx_mtime = os.path.getmtime(self.index_path)
        except OSError:
            return self._run_dirs()
        stale = []
        for rid in self._run_dirs():
            d = os.path.join(self.run_dir, rid)
            for name in (_MANIFEST, _JOURNAL):
                try:
                    if os.path.getmtime(os.path.join(d, name)) > idx_mtime:
                        stale.append(rid)
                        break
                except OSError:
                    continue
        return stale

    # --- queries ----------------------------------------------------------
    def entries(self, filters=()) -> list:
        """Index entries (stable run_id order), optionally filtered by
        ``key=value`` strings compared against the stringified entry
        field (so ``seed=1`` and ``defense=Krum`` both work)."""
        out = list(self._load_index().values())
        out.sort(key=lambda e: str(e.get("run_id")))
        for flt in filters:
            if "=" not in flt:
                raise ValueError(f"filter must be key=value, got {flt!r}")
            k, v = flt.split("=", 1)
            out = [e for e in out if str(e.get(k)) == v]
        return out

    def resolve(self, query: str, filters=()) -> dict:
        """One entry by exact run_id, unique id prefix, or tag; raises
        ValueError naming the candidates on a miss or an ambiguity."""
        ents = self.entries(filters)
        by_id = {e["run_id"]: e for e in ents}
        if query in by_id:
            return by_id[query]
        pref = [e for e in ents if str(e["run_id"]).startswith(query)]
        if len(pref) == 1:
            return pref[0]
        tagged = [e for e in ents if e.get("tag") == query]
        if len(tagged) == 1:
            return tagged[0]
        cands = sorted(str(e["run_id"]) for e in (pref or tagged))
        if cands:
            raise ValueError(
                f"run {query!r} is ambiguous: {cands}")
        raise ValueError(
            f"no run matching {query!r} in {self.index_path} "
            f"({len(ents)} entries; refresh with 'runs list --refresh'?)")

    def tag(self, query: str, tag: str) -> dict:
        """Attach a human tag to a run (resolvable via resolve());
        persisted in both the index and the manifest so a refresh keeps
        it."""
        entry = self.resolve(query)
        entry["tag"] = tag
        man_path = os.path.join(entry.get("dir", ""), _MANIFEST)
        man = _read_json(man_path)
        if man is not None:
            man["tag"] = tag
            tmp = man_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(man, f, indent=1, default=str)
            os.replace(tmp, man_path)
            # The manifest changed: refresh the sig so the next
            # refresh() keeps this entry instead of rebuilding a
            # tagless one.
            entry["sig"] = _stat_sig(
                man_path, os.path.join(entry.get("dir", ""), _JOURNAL))
        self.stamp(entry)
        return entry

    def load_config(self, entry: dict) -> Optional[dict]:
        """The stored config dict for a run entry (None for sidecar
        sources or pre-registry manifests)."""
        if entry.get("source") != "run":
            return None
        man = _read_json(os.path.join(entry.get("dir", ""), _MANIFEST))
        cfg = (man or {}).get("config")
        return cfg if isinstance(cfg, dict) else None
