"""Numerics & determinism observatory (ISSUE 20).

Every hard triage in this repo's history has been a floating-point one
(the PR 4 bulyan-blockwise 1-ulp cascade, the test_native.py 3/1000
tie band, the PR 18 tie-lock at margin 0.0) — this module makes f32
behavior a first-class observable, in the same three layers the
margins observatory uses (utils/margins.py):

- **Device helpers** (pure jnp, fixed shapes, safe inside jit):
  nonfinite counters by stage, gradient-norm dynamic range,
  cancellation-depth estimates on the distance Gram, and
  tie-proximity counters that REUSE the PR 18 margin tensors (no new
  O(n^2 d) reductions — the margins are already the signed distance
  to each decision boundary; we only band them at k ulp of the
  boundary's own scale).  The engine threads them like margins and
  emits one schema-v14 'numerics' event per round (core/engine.py).

- **Host ulp machinery** (NumPy): the monotone f32 ordinal (shared
  semantics with runs_cli._f32_ord), elementwise/max ulp distance,
  and the f64-adjudicated verdict for an impl pair — the referee the
  cross-implementation divergence ledger (tools/impl_drift.py) and
  its gate (tools/numerics_gate.py) persist into
  NUMERICS_BASELINE.json.

- **Reader helpers**: per-round series extraction for the
  ``runs numerics`` verb, field->stage attribution for the upgraded
  ``runs diff --band`` divergence report, and host rollups for the
  event emitter.

This module never imports defenses/kernels.py (the kernels import it).
"""

from __future__ import annotations

import math

import numpy as np

try:  # the host-side half works without a jax runtime (tools/)
    import jax.numpy as jnp
except Exception:  # pragma: no cover - jax is baked into this image
    jnp = None

# Default tie band: a decision whose margin sits within this many ulp
# (at the boundary's own magnitude) of zero is one a legal 1-ulp
# evaluation-order difference could plausibly flip — 8 ulp covers the
# measured cross-engine envelopes (tests/test_native.py's <=1-ulp tie
# swaps, tests/test_pallas.py's reduction-order bands) with headroom.
TIE_BAND_ULPS = 8

_EPS32 = 2.0 ** -23           # f32 machine epsilon (ulp at 1.0)
_TINY32 = 2.0 ** -126         # smallest normal f32

# ---------------------------------------------------------------------------
# Device-side health counters (fixed-shape, jit-safe)
# ---------------------------------------------------------------------------


def nonfinite_count(x, mask=None):
    """() int32 count of non-finite entries of ``x`` (f32 view).

    ``mask`` (n,) bool restricts a (n, d) matrix to its alive rows —
    the post-quarantine counter must not re-count what quarantine
    already zeroed out of the aggregable cohort."""
    bad = ~jnp.isfinite(x.astype(jnp.float32))
    if mask is not None:
        keep = mask
        if bad.ndim == 2:
            keep = mask[:, None]
        bad = bad & keep
    return jnp.sum(bad).astype(jnp.int32)


def norm_dynamic_range(x, mask=None):
    """() f32 log2(max/min) over the finite nonzero row norms of the
    (n, d) matrix — the gradient-norm dynamic range.  0.0 when fewer
    than two usable rows exist (degenerate, not an error)."""
    norms = jnp.linalg.norm(x.astype(jnp.float32), axis=-1)
    ok = jnp.isfinite(norms) & (norms > 0)
    if mask is not None:
        ok = ok & mask
    hi = jnp.max(jnp.where(ok, norms, -jnp.inf))
    lo = jnp.min(jnp.where(ok, norms, jnp.inf))
    usable = jnp.isfinite(hi) & jnp.isfinite(lo) & (lo > 0)
    rng = jnp.where(usable,
                    jnp.log2(jnp.maximum(hi, _TINY32))
                    - jnp.log2(jnp.maximum(lo, _TINY32)),
                    jnp.float32(0.0))
    return rng.astype(jnp.float32)


def max_finite_abs(x):
    """() f32 largest finite |entry| of ``x`` — the boundary scale the
    trim-stage tie band is measured at (dead-row +inf sentinels and
    nonfinite inputs are excluded).  0.0 when nothing finite remains."""
    a = jnp.abs(jnp.asarray(x, jnp.float32))
    m = jnp.max(jnp.where(jnp.isfinite(a), a, -jnp.inf))
    return jnp.where(jnp.isfinite(m), m,
                     jnp.float32(0.0)).astype(jnp.float32)


def ulp_at(scale):
    """f32 spacing at magnitude ``|scale|`` (eps * |scale|, floored at
    the smallest normal so a zero-scale boundary still has a band)."""
    s = jnp.abs(jnp.asarray(scale, jnp.float32))
    return jnp.maximum(s * jnp.float32(_EPS32), jnp.float32(_TINY32))


def tie_proximity(margin, scale, k=TIE_BAND_ULPS):
    """() int32 count of finite margin entries within ``k`` ulp (at
    the boundary scale) of zero — decisions a k-ulp evaluation
    perturbation could flip.  ``margin`` is a PR 18 margin tensor
    (signed distance to the decision boundary, utils/margins.py), so
    this costs one (n,)-sized reduction and no new distance work."""
    band = jnp.float32(k) * ulp_at(scale)
    m = jnp.asarray(margin, jnp.float32)
    near = jnp.isfinite(m) & (jnp.abs(m) <= band)
    return jnp.sum(near).astype(jnp.int32)


def cancellation_bits(max_term, min_positive):
    """() f32 log2(max accumulated term / min positive result): the
    bits a ||a||^2 + ||b||^2 - 2ab Gram subtraction cancelled to
    produce its smallest surviving value — the measured tie-band
    driver (ops/distances.py; PR 4's adjudicated failure mode)."""
    mt = jnp.maximum(jnp.abs(jnp.asarray(max_term, jnp.float32)),
                     jnp.float32(_TINY32))
    mp = jnp.maximum(jnp.abs(jnp.asarray(min_positive, jnp.float32)),
                     jnp.float32(_TINY32))
    return jnp.maximum(jnp.log2(mt) - jnp.log2(mp),
                       jnp.float32(0.0)).astype(jnp.float32)


def gram_cancellation_bits(Dm, mask=None):
    """Cancellation-depth estimate over an (n, n) squared-distance
    matrix (+inf diagonal convention, defenses/kernels.py): the
    largest finite entry against the smallest positive one.  Rows
    masked dead are excluded pairwise.  0.0 when no positive finite
    off-diagonal distance exists (identical cohort)."""
    Df = jnp.asarray(Dm, jnp.float32)
    finite = jnp.isfinite(Df)
    if mask is not None:
        finite = finite & (mask[:, None] & mask[None, :])
    pos = finite & (Df > 0)
    any_pos = jnp.any(pos)
    min_pos = jnp.min(jnp.where(pos, Df, jnp.inf))
    max_fin = jnp.max(jnp.where(finite, Df, -jnp.inf))
    bits = cancellation_bits(
        jnp.where(any_pos, max_fin, jnp.float32(1.0)),
        jnp.where(any_pos, min_pos, jnp.float32(1.0)))
    return jnp.where(any_pos, bits, jnp.float32(0.0))


# ---------------------------------------------------------------------------
# Host-side ulp machinery (NumPy; shared semantics with runs_cli._f32_ord)
# ---------------------------------------------------------------------------


def f32_ords(a):
    """Monotone int64 ordinal of each value in the f32 domain:
    adjacent representable f32s differ by exactly 1 (the vectorized
    twin of runs_cli._f32_ord — one lattice, two spellings)."""
    bits = np.ascontiguousarray(
        np.asarray(a, np.float32)).view(np.uint32).astype(np.int64)
    return np.where(bits < 0x80000000, bits, 0x80000000 - bits)


def ulp_diff(a, b):
    """Elementwise f32 ulp distance (int64).  NaN-vs-NaN is 0 ulp
    (same non-value); NaN-vs-number is the +inf sentinel 2**31 (no
    finite band admits it)."""
    af = np.asarray(a, np.float32).ravel()
    bf = np.asarray(b, np.float32).ravel()
    d = np.abs(f32_ords(af) - f32_ords(bf))
    na, nb = np.isnan(af), np.isnan(bf)
    d = np.where(na & nb, 0, d)
    d = np.where(na ^ nb, np.int64(2) ** 31, d)
    return d


def max_ulp(a, b):
    """(max ulp distance, argmax flat coordinate) between two arrays;
    (0, -1) for empty or bit-identical inputs."""
    d = ulp_diff(a, b)
    if d.size == 0 or not d.any():
        return 0, -1
    i = int(np.argmax(d))
    return int(d[i]), i


def adjudicate(a, b, oracle64, band_ulps=TIE_BAND_ULPS):
    """f64-refereed verdict for one impl pair on identical inputs.

    ``oracle64`` is the f64 reference result (defenses/oracle.py run
    in double); both f32 outputs are measured against its f32
    rounding.  Returns a JSON-ready record:

    - ``max_ulp`` / ``n_mismatch`` / ``argmax_coord``: the pair's raw
      divergence envelope;
    - ``in_tie_band``: every divergent coordinate sits within
      ``band_ulps`` of BOTH the other impl and the oracle — the PR 4
      "legal reduction-order flip" class;
    - ``verdict``: 'exact' (bit-identical), 'tie_band', 'a_closer' /
      'b_closer' (one impl is strictly nearer the f64 truth over the
      divergent coordinates — an accuracy asymmetry worth keeping),
      or 'split' (neither dominates and the band is exceeded)."""
    a32 = np.asarray(a, np.float32).ravel()
    b32 = np.asarray(b, np.float32).ravel()
    oc = np.asarray(oracle64, np.float64).ravel().astype(np.float32)
    d = ulp_diff(a32, b32)
    mis = np.nonzero(d)[0]
    rec = {"max_ulp": 0, "n_mismatch": 0, "argmax_coord": -1,
           "in_tie_band": True, "verdict": "exact",
           "band_ulps": int(band_ulps)}
    if mis.size == 0:
        return rec
    i = int(np.argmax(d))
    da = ulp_diff(a32, oc)[mis]
    db = ulp_diff(b32, oc)[mis]
    in_band = bool(int(d.max()) <= band_ulps
                   and int(max(da.max(), db.max())) <= band_ulps)
    if in_band:
        verdict = "tie_band"
    elif int(np.sum(da < db)) and not int(np.sum(db < da)):
        verdict = "a_closer"
    elif int(np.sum(db < da)) and not int(np.sum(da < db)):
        verdict = "b_closer"
    else:
        verdict = "split"
    rec.update(max_ulp=int(d[i]), n_mismatch=int(mis.size),
               argmax_coord=i, in_tie_band=in_band, verdict=verdict)
    return rec


# ---------------------------------------------------------------------------
# Event-side helpers (emitter rollups, series, stage attribution)
# ---------------------------------------------------------------------------

# Per-round 'numerics' event fields a reader can series (host scalars;
# hier stacks carry shard_/tier2_ prefixes on the same names).
SERIES_FIELDS = ("nonfinite_pre", "nonfinite_post", "nonfinite_agg",
                 "range_log2", "tie_rows", "cancel_bits",
                 "nonfinite_total", "tie_locked")

# Which pipeline stage (utils/costs.py STAGES taxonomy) each numerics
# counter observes — the attribution `runs diff --band` names when two
# runs first diverge in a margin/numerics record.
FIELD_STAGE = {
    "nonfinite_pre": "deliver",          # post-attack wire matrix
    "range_log2": "deliver",
    "nonfinite_post": "quarantine",      # post-quarantine aggregable
    "tie_rows": "tier1_aggregate",       # selection/trim boundary
    "cancel_bits": "tier1_aggregate",    # distance Gram
    "nonfinite_agg": "apply",            # applied update
    "nonfinite_total": "apply",
    "tie_locked": "tier1_aggregate",
}

# Margin-event fields attribute by construction (utils/margins.py):
# attack-side envelope utilization observes the delivery seam, every
# defense-side margin the tier-1 decision.
_MARGIN_STAGE_DEFAULT = "tier1_aggregate"


def stage_of(field, kind="numerics"):
    """Stage token a diverging margin/numerics event field observes."""
    f = str(field)
    if f.startswith("tier2_"):
        return "tier2_aggregate"
    if f.startswith("shard_"):
        f = f[len("shard_"):]
    if kind == "margin":
        return "deliver" if f.startswith("attack_") \
            else _MARGIN_STAGE_DEFAULT
    return FIELD_STAGE.get(f, "tier1_aggregate")


def field_ulp(a, b):
    """Event-log ulp distance between two JSON payload values (floats
    or flat numeric lists); None when not comparable that way."""
    num = (int, float)
    if (isinstance(a, num) and isinstance(b, num)
            and not isinstance(a, bool) and not isinstance(b, bool)):
        return int(ulp_diff([a], [b])[0])
    if (isinstance(a, list) and isinstance(b, list)
            and len(a) == len(b) and a
            and all(isinstance(x, num) for x in a)
            and all(isinstance(x, num) for x in b)):
        return int(ulp_diff(a, b).max())
    return None


def divergence_attribution(fields, kind="numerics"):
    """For a ``runs diff`` divergence record's ``{field: [va, vb]}``
    map on a margin/numerics event: (stage, max ulp over the
    attributable fields, the field that carries it).  Ulp is None when
    no differing field is numerically comparable."""
    best_field, best_ulp = None, None
    for k in sorted(fields):
        va, vb = fields[k]
        u = field_ulp(va, vb)
        if u is not None and (best_ulp is None or u > best_ulp):
            best_field, best_ulp = k, u
    anchor = best_field if best_field is not None else sorted(fields)[0]
    return stage_of(anchor, kind=kind), best_ulp, anchor


def numerics_rollups(fields):
    """Host-side derived summary merged into the per-round 'numerics'
    event: total nonfinite count across stages and the tie-lock flag
    (any decision within the tie band this round — the PR 18 Bulyan
    collapse signature is this flag pinned at 1)."""
    total = 0
    for k, v in fields.items():
        base = k[len("shard_"):] if k.startswith("shard_") else (
            k[len("tier2_"):] if k.startswith("tier2_") else k)
        if base.startswith("nonfinite"):
            if isinstance(v, list):
                total += int(sum(x for x in v
                                 if isinstance(x, (int, float))
                                 and math.isfinite(x)))
            elif isinstance(v, (int, float)) and math.isfinite(v):
                total += int(v)
    locked = 0
    for k, v in fields.items():
        base = k[len("shard_"):] if k.startswith("shard_") else (
            k[len("tier2_"):] if k.startswith("tier2_") else k)
        if base == "tie_rows":
            vs = v if isinstance(v, list) else [v]
            if any(isinstance(x, (int, float)) and x > 0 for x in vs):
                locked = 1
    return {"nonfinite_total": total, "tie_locked": locked}


def numerics_series(events):
    """{field: [(round, value), ...]} over a run's 'numerics' events,
    rounds ascending — the `runs numerics` trajectory (hier stacks are
    reduced to their max, the conservative health view)."""
    rows = sorted((e for e in events if e.get("kind") == "numerics"),
                  key=lambda e: e.get("round", 0))
    out = {}
    for e in rows:
        r = e.get("round")
        if not isinstance(r, (int, float)):
            continue
        for f in SERIES_FIELDS:
            for key in (f, "shard_" + f, "tier2_" + f):
                v = e.get(key)
                if isinstance(v, list):
                    vs = [x for x in v if isinstance(x, (int, float))
                          and math.isfinite(x)]
                    v = max(vs) if vs else None
                if isinstance(v, (int, float)) and math.isfinite(v):
                    out.setdefault(key, []).append((int(r), v))
    return out


def numerics_drift(series_a, series_b, field="tie_rows"):
    """First round where two runs' numerics series for ``field``
    differ: (round, value_a, value_b), or None when they agree over
    every shared round (the determinism bar for same-seed twins)."""
    da = dict(series_a.get(field, ()))
    db = dict(series_b.get(field, ()))
    for r in sorted(set(da) & set(db)):
        if da[r] != db[r]:
            return int(r), da[r], db[r]
    return None
