"""Structured run metrics, the event schema, and logging.

The reference logs via a print/file tee closure (reference main.py:13-18), a
``locals()`` config dump (main.py:19), accuracy lines every TEST_STEP rounds
(main.py:77-80) and a CSV of the accuracy trajectory whose filename encodes
every hyperparameter (main.py:100).  This module keeps all of those outputs
(tee, config dump, CSV with the same filename schema) and adds what the
reference lacks (SURVEY.md §5): a versioned schema of structured JSONL
events — per-round diagnostics, eval/ASR trajectories, phase timings,
stream stall stats, and the telemetry pipeline's per-round defense/attack
forensics (core/engine.py) — validated at the emitter so malformed events
fail the producing run, not a downstream reader.

Event contract (schema v1): every event is one JSON object per line with a
``kind`` from :data:`EVENT_KINDS`, that kind's required fields, a schema
version ``v`` and a relative timestamp ``t``.  Extra fields are always
allowed (they're how diagnostics grow without a version bump); missing
required fields or unknown kinds are errors.  ``tools/check_events.py`` is
the standalone validator; ``report.py`` is the reader.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

import numpy as np


SCHEMA_VERSION = 1

# kind -> required fields.  Producers: core/engine.py (round, eval, asr,
# profile, stream, defense, attack, selection_hist via RunLogger).
EVENT_KINDS = {
    # per-round scalar diagnostics (--round-stats)
    "round": {"round"},
    # eval-cadence accuracy line (reference main.py:77-80, structured)
    "eval": {"round", "test_loss", "accuracy", "correct", "test_size"},
    # backdoor attack-success rate at eval cadence
    "asr": {"round", "attack_success_rate"},
    # PhaseTimer summary written once at run end (--profile)
    "profile": {"phases"},
    # host-stream stall accounting (data/stream.py stall_stats)
    "stream": {"stream_stall_s", "stream_gets"},
    # per-round defense forensics (--telemetry): selection masks/scores,
    # trim/clip/trust diagnostics, per-client norms + cosine-to-mean
    "defense": {"round", "defense"},
    # per-round attack envelope stats (--telemetry): ALIE z/sigma/drift
    # norms, backdoor shadow loss
    "attack": {"round", "attack"},
    # end-of-run selection histogram (the GRID_RESULTS top-1 analysis)
    "selection_hist": {"defense", "counts"},
    # fault-injection / recovery accounting (core/faults.py + the
    # engine's divergence watchdog): per-round injected/quarantined
    # counts, and rollback records (rolled_back, restored_round)
    "fault": {"round"},
}


def validate_event(rec) -> dict:
    """Validate one event against the schema; returns it or raises
    ValueError.  Unknown kinds and missing required fields are errors;
    extra fields are not (diagnostics grow without a version bump)."""
    if not isinstance(rec, dict):
        raise ValueError(
            f"event must be a JSON object, got {type(rec).__name__}")
    kind = rec.get("kind")
    if kind not in EVENT_KINDS:
        raise ValueError(
            f"unknown event kind {kind!r} (schema v{SCHEMA_VERSION}; "
            f"known: {sorted(EVENT_KINDS)})")
    missing = EVENT_KINDS[kind] - rec.keys()
    if missing:
        raise ValueError(
            f"{kind!r} event missing required fields {sorted(missing)}")
    v = rec.get("v", SCHEMA_VERSION)
    if v != SCHEMA_VERSION:
        raise ValueError(f"unsupported event schema version {v!r} "
                         f"(this reader speaks v{SCHEMA_VERSION})")
    if "round" in EVENT_KINDS[kind] and not isinstance(
            rec["round"], (int, float)):
        raise ValueError(
            f"{kind!r} event field 'round' must be numeric, "
            f"got {rec['round']!r}")
    return rec


def iter_events(path, validate: bool = True):
    """Yield events from a run JSONL, optionally schema-validated.
    Raises ValueError (with the line number) on a malformed line so a
    reader never silently consumes drifted events."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not JSON: {e}") from e
            if validate:
                try:
                    validate_event(rec)
                except ValueError as e:
                    raise ValueError(f"{path}:{lineno}: {e}") from e
            yield rec


class RunLogger:
    """Tee + CSV + structured JSONL sink; a context manager.

    ``with RunLogger(cfg) as logger:`` guarantees the JSONL handle is
    closed and the accuracy CSV is written even when the run raises
    (crash-safe ``close``).  ``finish()`` (CSV + JSONL close) is
    idempotent and leaves the tee handle open so callers can still
    ``print`` a trailing summary line; ``close()`` / ``__exit__`` shut
    everything."""

    def __init__(self, config, output: Optional[str] = None,
                 log_dir: str = "logs", jsonl_name: Optional[str] = None):
        self.config = config
        self.output = output
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)  # the reference crashes when
        # logs/ is missing (main.py:100, readme.md:25); we create it.
        base = jsonl_name or config.csv_name().replace(".csv", "")
        self.jsonl_path = os.path.join(log_dir, base + ".jsonl")
        self._jsonl = open(self.jsonl_path, "a")
        # Reference-style tee (main.py:13-18): append semantics, but the
        # handle is opened ONCE and kept — the reference reopened the
        # file on every print.
        self._tee = open(self.output, "a") if self.output else None
        self._finished = False
        self.accuracies: list = []
        self.accuracies_epochs: list = []
        self._t0 = time.time()

    # --- context manager ------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # --- reference-style tee (main.py:13-18) ---------------------------
    def print(self, s, end="\n"):
        if self._tee is not None:
            self._tee.write(str(s) + end)
            self._tee.flush()  # per-call reopen flushed implicitly
        else:
            print(s, end=end, flush=True)

    def dump_config(self):
        self.print(dataclasses.asdict(self.config))

    # --- structured records --------------------------------------------
    def record(self, **fields):
        fields.setdefault("t", round(time.time() - self._t0, 3))
        if "kind" in fields:
            # Validate at the emitter: a malformed event fails the run
            # that produced it, not a later reader.
            fields.setdefault("v", SCHEMA_VERSION)
            validate_event(fields)
        self._jsonl.write(json.dumps(fields, default=float) + "\n")
        self._jsonl.flush()

    def record_eval(self, epoch, test_loss, correct, test_size, asr=None,
                    **extra):
        accuracy = 100.0 * float(correct) / test_size
        self.accuracies.append(accuracy)
        self.accuracies_epochs.append(epoch)
        # Line format mirrors reference main.py:77-80.
        self.print("Test set: [{:3d}] Average loss: {:.4f}, "
                   "Accuracy: {}/{} ({:.2f}%)".format(
                       epoch, float(test_loss), int(correct), test_size,
                       accuracy))
        rec = dict(kind="eval", round=epoch, test_loss=float(test_loss),
                   accuracy=accuracy, correct=int(correct),
                   test_size=test_size, **extra)
        if asr is not None:
            rec["attack_success_rate"] = float(asr)
        self.record(**rec)
        return accuracy

    def finish(self):
        """Write the CSV and close the JSONL.  Idempotent; the tee stays
        open (trailing summary prints still tee) until close()."""
        if self._finished:
            return
        self._finished = True
        if self.accuracies:
            self.print("Max accuracy: {}".format(max(self.accuracies)))
            # CSV with the reference's filename schema (main.py:100).
            np.savetxt(os.path.join(self.log_dir, self.config.csv_name()),
                       np.asarray(self.accuracies), delimiter=",")
        self._jsonl.close()

    def close(self):
        self.finish()
        if self._tee is not None and not self._tee.closed:
            self._tee.close()
