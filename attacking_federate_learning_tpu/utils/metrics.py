"""Structured run metrics, the event schema, and logging.

The reference logs via a print/file tee closure (reference main.py:13-18), a
``locals()`` config dump (main.py:19), accuracy lines every TEST_STEP rounds
(main.py:77-80) and a CSV of the accuracy trajectory whose filename encodes
every hyperparameter (main.py:100).  This module keeps all of those outputs
(tee, config dump, CSV with the same filename schema) and adds what the
reference lacks (SURVEY.md §5): a versioned schema of structured JSONL
events — per-round diagnostics, eval/ASR trajectories, phase timings,
stream stall stats, and the telemetry pipeline's per-round defense/attack
forensics (core/engine.py) — validated at the emitter so malformed events
fail the producing run, not a downstream reader.

Event contract (schema v2): every event is one JSON object per line with a
``kind`` from :data:`EVENT_KINDS`, that kind's required fields, a schema
version ``v`` and a relative timestamp ``t``.  Extra fields are always
allowed (they're how diagnostics grow without a version bump); missing
required fields or unknown kinds are errors.  ``tools/check_events.py`` is
the standalone validator; ``report.py`` is the reader.

Version history: v1 introduced the structured kinds (round/eval/asr/
profile/stream/defense/attack/selection_hist, later fault); v2 adds the
compile-and-cost observatory kinds — ``compile`` (per-entry-point
compile wall time + persistent-cache attribution), ``cost`` (static HLO
FLOPs / bytes-accessed / memory facts, utils/costs.py) and
``heartbeat`` (the RunLogger liveness thread); v3 adds ``lifecycle``
(run-lifecycle transitions — start/resume/preempt/complete from the
engine, retry/degrade/exhausted from tools/supervisor.py;
utils/lifecycle.py); v4 adds the cross-run observatory rollups —
``registry`` (the engine's run-finish stamp that joins the event log to
``runs/index.jsonl``, utils/registry.py) and ``gate`` (one behavioral-
drift verdict per pinned cell, tools/science_gate.py); v5 adds
``secagg`` — one secure-aggregation protocol record per round
(protocols/secagg.py: masks reconstructed, dropout-recovery flag,
bitwise sum-check verdict, per-group sum norms under groupwise); v6
adds the hierarchical forensics kinds — ``shard_selection`` (one
record per hierarchical round under --telemetry: the stacked per-shard
tier-1 diagnostics and the tier-2 cross-shard selection/trim
diagnostics, with the static placement ground truth riding along) and
``forensics`` (the colluder-localization verdict `report forensics`
computes from a run's shard_selection stream); v7 adds ``async`` —
one asynchronous-round record per round under
``aggregation='async'`` (core/async_rounds.py: delivered / pending /
in-flight counts, evictions, supersessions, the delivered staleness
histogram and the weight mass per staleness bucket — emitted with or
without --telemetry, like 'fault'); v8 adds ``campaign`` — one
campaign-scheduler transition per record
(attacking_federate_learning_tpu/campaigns/: campaign start/done,
cell start and the cell's terminal verdict done/failed/skipped/
adopted, deadline checkpoints — written to the campaign's own
``runs/campaigns/<id>/events.jsonl``, never into a run's log by the
engine); v9 adds the stage & wire ledger kinds (utils/costs.py,
emitted by CompileLedger.emit under --cost-report) — ``stage_cost``
(one per compiled entry point: the whole-program FLOPs/bytes/temp
partitioned across the canonical stage taxonomy ``deliver →
quarantine → protect → tier1_aggregate → tier2_aggregate → apply``
plus the unattributed residual and the modeled coverage) and
``wire_bytes`` (one per run: bytes-per-round on every protocol seam —
broadcast, client_update, tier1_to_tier2, secagg mask exchange /
recovery, async delivery); v10 adds ``wall`` — the measured-walls
observatory (utils/walls.py, ``--profile-every``): one record per
measured wall, either host-clock span/eval timing at the engine's
eval-boundary fetch (``source='host'``: wall_s, rounds, rounds/s —
no new host callbacks in-jit) or a profiler-trace capture booked
onto the stage taxonomy (``source='trace'``: per-stage microseconds
+ unattributed residual summing exactly to wall_s, with op-event
coverage riding along) — the runtime twin of v9's modeled
``stage_cost``; v11 adds ``traffic`` — one population-traffic record
per round under a ``--traffic-population`` run (core/population.py):
the arrived-count / effective-f accounting of the sampled cohort and
the defense-validity watchdog's ladder decision
(action='remask'/'fallback'/'hold', with the cohort pids, f_eff and
the defense actually applied riding along) — host-born from the
PRNG-replayable schedule, so ``replay_traffic`` diffs the emitted
stream against an independent regeneration; v12 adds ``margin`` —
one robustness-margin record per round under ``--margins``
(core/engine.py + utils/margins.py): the defenses' in-jit decision
margins (Krum winner/runner-up gap and per-row signed distance to the
selection threshold, trim-boundary distances and kept-coordinate
fractions, Bulyan per-iteration selection slack) rolled up host-side
into the colluder-survival ledger (colluder_margin /
colluder_selected / colluder_kept_mass), with the attack's envelope
utilization and traffic's f_eff riding along; v13 extends ``fault``
with the hierarchical shard-domain fields (core/faults.py ISSUE 19:
``shard_alive`` — the per-shard survivor-count vector after quarantine
and domain death, ``shards_dead`` / ``shards_alive`` — the correlated
shard-DOMAIN accounting, and ``tier2_action`` — the host-planned
remask/fallback/hold ladder decision at tier-2), all host-replayable
from the fault key (tools/fault_matrix.py diffs them exactly); v14
adds ``numerics`` — one numeric-health record per round under
``--numerics`` (core/engine.py + utils/numerics.py): per-stage
nonfinite counts (pre/post quarantine, post-aggregate), the
gradient-norm dynamic range, the distance-Gram cancellation-depth
estimate, and the tie-proximity counters that band the PR 18 margin
tensors at k ulp of their decision boundary, rolled up host-side into
nonfinite_total / tie_locked (read with ``runs numerics``; the
cross-implementation envelopes live in NUMERICS_BASELINE.json, gated
by tools/numerics_gate.py).
Readers accept every version; older logs simply never carry the newer
kinds, and a newer-only kind stamped with an older version is an
emitter bug, rejected (``KIND_MIN_VERSION``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Optional

import numpy as np


SCHEMA_VERSION = 14
SUPPORTED_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14)

# kind -> required fields.  Producers: core/engine.py (round, eval, asr,
# profile, stream, defense, attack, selection_hist via RunLogger).
EVENT_KINDS = {
    # per-round scalar diagnostics (--round-stats)
    "round": {"round"},
    # eval-cadence accuracy line (reference main.py:77-80, structured)
    "eval": {"round", "test_loss", "accuracy", "correct", "test_size"},
    # backdoor attack-success rate at eval cadence
    "asr": {"round", "attack_success_rate"},
    # PhaseTimer summary written once at run end (--profile)
    "profile": {"phases"},
    # host-stream stall accounting (data/stream.py stall_stats)
    "stream": {"stream_stall_s", "stream_gets"},
    # per-round defense forensics (--telemetry): selection masks/scores,
    # trim/clip/trust diagnostics, per-client norms + cosine-to-mean
    "defense": {"round", "defense"},
    # per-round attack envelope stats (--telemetry): ALIE z/sigma/drift
    # norms, backdoor shadow loss
    "attack": {"round", "attack"},
    # end-of-run selection histogram (the GRID_RESULTS top-1 analysis)
    "selection_hist": {"defense", "counts"},
    # fault-injection / recovery accounting (core/faults.py + the
    # engine's divergence watchdog): per-round injected/quarantined
    # counts, and rollback records (rolled_back, restored_round)
    "fault": {"round"},
    # --- v2: the compile-and-cost observatory (utils/costs.py) ---------
    # per-entry-point compile record: wall time + persistent-cache
    # attribution ('hit'/'miss'/'uncached') + backend platform
    "compile": {"name", "compile_s", "cache"},
    # static HLO facts for the same entry point: exact FLOPs and
    # bytes-accessed (cost_analysis), memory sizes (memory_analysis)
    "cost": {"name", "flops", "bytes_accessed", "peak_bytes"},
    # RunLogger liveness thread: emitted every N seconds so a stalled
    # capture is distinguishable from a long compile by tailing the
    # events file (round / rounds-per-sec EMA ride along when known)
    "heartbeat": {"rss_mb", "last_event_age_s"},
    # --- v3: the run-lifecycle layer (utils/lifecycle.py) --------------
    # one transition of the preemption-safe run lifecycle.  'phase' is
    # the transition name: the engine emits start/resume/preempt/
    # complete (core/engine.py), the supervisor retry/degrade/
    # stall_kill/exhausted/fatal (tools/supervisor.py).  Extra fields
    # (round, attempt, signal, failure class, degradation applied) ride
    # along as diagnostics.
    "lifecycle": {"phase"},
    # --- v4: the cross-run observatory (utils/registry.py) -------------
    # the engine's run-finish registry stamp: the run_id this event log
    # belongs to, with the final-trajectory summary riding along
    # (final/max accuracy, ASR, rounds) — the join key between a log
    # and runs/index.jsonl
    "registry": {"run_id"},
    # one behavioral-drift gate verdict (tools/science_gate.py): the
    # pinned cell's name and its pass/fail/skip status, with the
    # compared metrics as extra fields
    "gate": {"cell", "status"},
    # --- v5: the secure-aggregation protocol layer (protocols/secagg.py)
    # one protocol record per round (emitted with or without
    # --telemetry, like 'fault'): bitwise sum-check verdict
    # (sum_check_ok), dropped-client count, masks reconstructed in the
    # simulated seed-reveal (recovery), and under groupwise the
    # per-group sum norms — the server-visible quantities
    "secagg": {"round"},
    # --- v6: hierarchical forensics (core/engine.py, report.py) ---------
    # one record per hierarchical round under --telemetry: the stacked
    # per-shard tier-1 diagnostics ('shard_*' fields — (S, m) selection
    # masks/scores, kept fractions) and the tier-2 cross-shard
    # diagnostics ('tier2_*' fields — (S,) selection mask/scores over
    # the shard-estimate matrix), plus the static placement ground
    # truth (mal_counts, megabatch) the forensics layer attributes
    # against.  Under groupwise secagg only the tier-2 (group-sum-
    # level) fields appear — per-client rows are not server-visible.
    "shard_selection": {"round", "defense"},
    # the colluder-localization verdict 'report forensics' computes
    # from a run's shard_selection stream (tier-2 rejection
    # attribution: which shards were rejected, when localization
    # stabilized, whether the malicious shards were isolated)
    "forensics": {"verdict"},
    # --- v7: asynchronous buffered rounds (core/async_rounds.py) --------
    # one record per async round (emitted with or without --telemetry,
    # like 'fault'): delivered / pending / in-flight counts, over-stale
    # evictions, supersessions, quarantined non-finite arrivals, the
    # delivered staleness histogram and the per-bucket weight mass —
    # the staleness-rollup raw material ('report' staleness table)
    "async": {"round", "delivered"},
    # --- v8: the campaign scheduler (campaigns/scheduler.py) ------------
    # one scheduler transition: 'phase' is campaign_start/cell_start/
    # cell_done/cell_failed/cell_skipped/deadline/campaign_done, with
    # the cell id, rejection reason, cache hit/miss evidence and
    # summary metrics riding along as diagnostics
    "campaign": {"campaign", "phase"},
    # --- v9: the stage & wire ledger (utils/costs.py) -------------------
    # one per compiled entry point (CompileLedger.emit): the program's
    # actual totals partitioned per canonical stage ('stages': stage ->
    # {flops, bytes_accessed, temp_bytes}), the unattributed residual
    # (partition sums equal the 'cost' event's totals exactly) and the
    # modeled coverage fractions the perf gate's --stageproof bars
    "stage_cost": {"name", "stages", "coverage"},
    # one per run: bytes-per-round on every protocol seam the topology
    # crosses ('seams': seam -> {bytes, ...}; the hierarchical
    # tier1_to_tier2 seam reproduces the measured SPMD all_gather
    # collective_bytes == S·d·4)
    "wire_bytes": {"topology", "seams", "total_bytes"},
    # --- v10: the measured-walls observatory (utils/walls.py) -----------
    # one measured wall per record, emitted under --profile-every.
    # source='host': host-clock timing at the engine's existing eval-
    # boundary fetch (span wall + rounds + rounds/s, eval wall) — cheap,
    # every span.  source='trace': one profiled span per K eval
    # intervals, booked onto the stage taxonomy ('stages': stage -> us,
    # plus 'unattributed_us'; the partition sums to wall_s exactly) with
    # op-event 'coverage' riding along — the runtime twin of
    # 'stage_cost', joined by 'name' for measured-vs-modeled ratios
    # ('runs walls').
    "wall": {"name", "source", "wall_s"},
    # --- v11: the population & traffic engine (core/population.py) ------
    # one record per traffic round (emitted with or without --telemetry,
    # like 'fault'): the arrived count of the sampled cohort, the
    # arrived-malicious count f_eff, and the defense-validity watchdog's
    # ladder decision ('action': remask/fallback/hold) with the defense
    # actually applied and the cohort pids riding along — host-born
    # from the PRNG-replayable schedule (replay_traffic diffs the
    # emitted stream against an independent regeneration)
    "traffic": {"round", "arrived", "action"},
    # --- v12: the robustness-margin observatory (utils/margins.py) ------
    # one record per round under --margins: the defense's in-jit
    # decision margins stripped to bare names (selection margins, gap,
    # trim kept fractions / boundary distances, Bulyan slack), the
    # host-side colluder-survival rollups (colluder_margin — the
    # DEFENSE-side worst margin over the malicious rows, <= 0 when a
    # colluder survives selection — colluder_selected, kept-mass
    # splits), the attack's envelope-utilization stats ('attack_*'),
    # the hierarchical per-shard/tier-2 stacks ('shard_margin_*' /
    # 'tier2_margin_*' with their own rollups) and traffic's f_eff
    # when a --traffic-population schedule rides along
    "margin": {"round", "defense"},
    # --- v14: the numerics & determinism observatory (utils/numerics.py)
    # one record per round under --numerics: per-stage nonfinite counts
    # (nonfinite_pre / nonfinite_post / nonfinite_agg), the gradient-
    # norm dynamic range (range_log2), the tie-proximity counters read
    # off the PR 18 margin tensors (tie_rows, banded at tie_band_ulps
    # of the decision boundary's own f32 spacing), the distance-Gram
    # cancellation-depth estimate (cancel_bits), the hierarchical
    # per-shard/tier-2 stacks on the same names ('shard_*'/'tier2_*'),
    # and the host rollups (nonfinite_total, tie_locked)
    "numerics": {"round", "defense"},
}

# Minimum schema version per kind introduced after v1; an event carrying
# one of these but stamped with an older version is an emitter bug (an
# older writer cannot know these kinds).
KIND_MIN_VERSION = {"compile": 2, "cost": 2, "heartbeat": 2,
                    "lifecycle": 3, "registry": 4, "gate": 4,
                    "secagg": 5, "shard_selection": 6, "forensics": 6,
                    "async": 7, "campaign": 8,
                    "stage_cost": 9, "wire_bytes": 9,
                    "wall": 10, "traffic": 11, "margin": 12,
                    "numerics": 14}

# Back-compat alias (pre-v3 spelling used by external readers).
V2_KINDS = {k for k, v in KIND_MIN_VERSION.items() if v == 2}


def validate_event(rec) -> dict:
    """Validate one event against the schema; returns it or raises
    ValueError.  Unknown kinds, unknown schema versions and missing
    required fields are errors; extra fields are not (diagnostics grow
    without a version bump)."""
    if not isinstance(rec, dict):
        raise ValueError(
            f"event must be a JSON object, got {type(rec).__name__}")
    v = rec.get("v", SCHEMA_VERSION)
    if v not in SUPPORTED_VERSIONS:
        # Version first: an event from a NEWER writer may carry kinds
        # this reader has never heard of — "unknown kind" would
        # misdiagnose that as emitter corruption.
        raise ValueError(
            f"unsupported event schema version {v!r} (this reader "
            f"speaks v{min(SUPPORTED_VERSIONS)}..v{max(SUPPORTED_VERSIONS)}"
            f"; a newer writer's logs need a newer reader)")
    kind = rec.get("kind")
    if kind not in EVENT_KINDS:
        raise ValueError(
            f"unknown event kind {kind!r} (schema v{SCHEMA_VERSION}; "
            f"known: {sorted(EVENT_KINDS)})")
    min_v = KIND_MIN_VERSION.get(kind, 1)
    if v < min_v:
        raise ValueError(
            f"{kind!r} events need schema v{min_v}, but this one is "
            f"stamped v{v} (emitter bug: a v{v} writer cannot produce "
            f"this kind)")
    missing = EVENT_KINDS[kind] - rec.keys()
    if missing:
        raise ValueError(
            f"{kind!r} event missing required fields {sorted(missing)}")
    if "round" in EVENT_KINDS[kind] and not isinstance(
            rec["round"], (int, float)):
        raise ValueError(
            f"{kind!r} event field 'round' must be numeric, "
            f"got {rec['round']!r}")
    return rec


def iter_events(path, validate: bool = True, skip_bad: bool = False,
                bad_lines: Optional[list] = None):
    """Yield events from a run JSONL, optionally schema-validated.
    Raises ValueError (with the line number) on a malformed line so a
    reader never silently consumes drifted events — unless ``skip_bad``
    (the cross-run readers: a crash-truncated log's torn tail must not
    make the whole run store unreadable), in which case bad lines are
    skipped and appended to ``bad_lines`` as (lineno, message)."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                if skip_bad:
                    if bad_lines is not None:
                        bad_lines.append((lineno, f"not JSON: {e}"))
                    continue
                raise ValueError(f"{path}:{lineno}: not JSON: {e}") from e
            if validate:
                try:
                    validate_event(rec)
                except ValueError as e:
                    if skip_bad:
                        if bad_lines is not None:
                            bad_lines.append((lineno, str(e)))
                        continue
                    raise ValueError(f"{path}:{lineno}: {e}") from e
            yield rec


def _rss_mb() -> float:
    """Resident set size in MB via /proc (no psutil on this image);
    0.0 where /proc is absent — the heartbeat still carries the ages."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except (OSError, ValueError, IndexError):
        pass
    return 0.0


class RunLogger:
    """Tee + CSV + structured JSONL sink; a context manager.

    ``with RunLogger(cfg) as logger:`` guarantees the JSONL handle is
    closed and the accuracy CSV is written even when the run raises
    (crash-safe ``close``).  ``finish()`` (CSV + JSONL close) is
    idempotent and leaves the tee handle open so callers can still
    ``print`` a trailing summary line; ``close()`` / ``__exit__`` shut
    everything.

    ``heartbeat_every > 0`` starts a daemon thread that appends a small
    'heartbeat' event (schema v2) every N seconds: last-seen round, a
    rounds/s EMA, resident set size, and the age of the last REAL event
    — so ``tail -f run.jsonl`` distinguishes a stalled TPU capture or a
    dead relay (age grows unbounded, rss flat) from a long compile or a
    long fused span (age grows, then one burst of round events).
    Heartbeats never update the last-event clock — they must not mask
    the very stall they exist to expose."""

    def __init__(self, config, output: Optional[str] = None,
                 log_dir: str = "logs", jsonl_name: Optional[str] = None,
                 heartbeat_every: float = 0.0):
        self.config = config
        self.output = output
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)  # the reference crashes when
        # logs/ is missing (main.py:100, readme.md:25); we create it.
        base = jsonl_name or config.csv_name().replace(".csv", "")
        self.jsonl_path = os.path.join(log_dir, base + ".jsonl")
        self._jsonl = open(self.jsonl_path, "a")
        # Reference-style tee (main.py:13-18): append semantics, but the
        # handle is opened ONCE and kept — the reference reopened the
        # file on every print.
        self._tee = open(self.output, "a") if self.output else None
        self._finished = False
        self.accuracies: list = []
        self.accuracies_epochs: list = []
        self._t0 = time.time()
        # Heartbeat state (written by record() under the lock, read by
        # the beat thread).  The JSONL handle is shared with the beat
        # thread, so every write serializes through _write_lock.
        self._write_lock = threading.Lock()
        self._last_event_time = time.time()
        self._last_round = None
        self._last_round_time = None
        self._rps_ema = None
        self._hb_stop = None
        self._hb_thread = None
        if heartbeat_every and heartbeat_every > 0:
            self._start_heartbeat(float(heartbeat_every))

    # --- context manager ------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # --- reference-style tee (main.py:13-18) ---------------------------
    def print(self, s, end="\n"):
        if self._tee is not None:
            self._tee.write(str(s) + end)
            self._tee.flush()  # per-call reopen flushed implicitly
        else:
            print(s, end=end, flush=True)

    def dump_config(self):
        self.print(dataclasses.asdict(self.config))

    # --- heartbeat (schema v2) -----------------------------------------
    def _start_heartbeat(self, every: float):
        self._hb_stop = threading.Event()

        def beat():
            while not self._hb_stop.wait(every):
                if self._finished:
                    return
                try:
                    self.record(**self.heartbeat_fields())
                except ValueError:
                    return      # closed mid-beat; the stop flag races
        self._hb_thread = threading.Thread(
            target=beat, name="runlogger-heartbeat", daemon=True)
        self._hb_thread.start()

    def heartbeat_fields(self) -> dict:
        """One heartbeat payload (also callable without the thread —
        tests and ad-hoc probes)."""
        now = time.time()
        rec = dict(kind="heartbeat",
                   rss_mb=round(_rss_mb(), 1),
                   last_event_age_s=round(now - self._last_event_time, 3))
        if self._last_round is not None:
            rec["round"] = self._last_round
        if self._rps_ema is not None:
            rec["rounds_per_s"] = round(self._rps_ema, 4)
        return rec

    def _note_progress(self, fields):
        """Track round progress for the heartbeat: any event carrying a
        numeric 'round' advances the last-seen round and feeds the
        rounds/s EMA.  Heartbeats themselves are excluded — they must
        not reset the stall clock they measure."""
        if fields.get("kind") == "heartbeat":
            return
        now = time.time()
        self._last_event_time = now
        rnd = fields.get("round")
        if not isinstance(rnd, (int, float)):
            return
        if (self._last_round is not None and rnd > self._last_round
                and now > self._last_round_time):
            rps = (rnd - self._last_round) / (now - self._last_round_time)
            self._rps_ema = (rps if self._rps_ema is None
                             else 0.3 * rps + 0.7 * self._rps_ema)
        if self._last_round is None or rnd >= self._last_round:
            self._last_round = rnd
            self._last_round_time = now

    # --- structured records --------------------------------------------
    def record(self, **fields):
        fields.setdefault("t", round(time.time() - self._t0, 3))
        if "kind" in fields:
            # Validate at the emitter: a malformed event fails the run
            # that produced it, not a later reader.
            fields.setdefault("v", SCHEMA_VERSION)
            validate_event(fields)
        with self._write_lock:
            if self._finished:
                # The beat thread can race finish(); a write to a closed
                # handle would turn a clean shutdown into a crash.
                raise ValueError("record() after finish()")
            self._note_progress(fields)
            self._jsonl.write(json.dumps(fields, default=float) + "\n")
            self._jsonl.flush()

    def record_eval(self, epoch, test_loss, correct, test_size, asr=None,
                    **extra):
        accuracy = 100.0 * float(correct) / test_size
        self.accuracies.append(accuracy)
        self.accuracies_epochs.append(epoch)
        # Line format mirrors reference main.py:77-80.
        self.print("Test set: [{:3d}] Average loss: {:.4f}, "
                   "Accuracy: {}/{} ({:.2f}%)".format(
                       epoch, float(test_loss), int(correct), test_size,
                       accuracy))
        rec = dict(kind="eval", round=epoch, test_loss=float(test_loss),
                   accuracy=accuracy, correct=int(correct),
                   test_size=test_size, **extra)
        if asr is not None:
            rec["attack_success_rate"] = float(asr)
        self.record(**rec)
        return accuracy

    def finish(self):
        """Write the CSV and close the JSONL.  Idempotent; the tee stays
        open (trailing summary prints still tee) until close().  The
        heartbeat thread is stopped first — the JSONL handle it writes
        through is about to close."""
        if self._finished:
            return
        if self._hb_stop is not None:
            self._hb_stop.set()
        with self._write_lock:
            if self._finished:
                return
            self._finished = True
            self._jsonl.close()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        if self.accuracies:
            self.print("Max accuracy: {}".format(max(self.accuracies)))
            # CSV with the reference's filename schema (main.py:100).
            np.savetxt(os.path.join(self.log_dir, self.config.csv_name()),
                       np.asarray(self.accuracies), delimiter=",")

    def close(self):
        self.finish()
        if self._tee is not None and not self._tee.closed:
            self._tee.close()
