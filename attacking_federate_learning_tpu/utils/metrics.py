"""Structured run metrics and logging.

The reference logs via a print/file tee closure (reference main.py:13-18), a
``locals()`` config dump (main.py:19), accuracy lines every TEST_STEP rounds
(main.py:77-80) and a CSV of the accuracy trajectory whose filename encodes
every hyperparameter (main.py:100).  This module keeps all of those outputs
(tee, config dump, CSV with the same filename schema) and adds what the
reference lacks (SURVEY.md §5): structured per-round JSONL records with
round, lr, clean accuracy, loss, attack-success rate and wall-clock phase
timings.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

import numpy as np


class RunLogger:
    def __init__(self, config, output: Optional[str] = None,
                 log_dir: str = "logs", jsonl_name: Optional[str] = None):
        self.config = config
        self.output = output
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)  # the reference crashes when
        # logs/ is missing (main.py:100, readme.md:25); we create it.
        base = jsonl_name or config.csv_name().replace(".csv", "")
        self.jsonl_path = os.path.join(log_dir, base + ".jsonl")
        self._jsonl = open(self.jsonl_path, "a")
        self.accuracies: list = []
        self.accuracies_epochs: list = []
        self._t0 = time.time()

    # --- reference-style tee (main.py:13-18) ---------------------------
    def print(self, s, end="\n"):
        if self.output:
            with open(self.output, "a+") as f:
                f.write(str(s) + end)
        else:
            print(s, end=end, flush=True)

    def dump_config(self):
        self.print(dataclasses.asdict(self.config))

    # --- structured records --------------------------------------------
    def record(self, **fields):
        fields.setdefault("t", round(time.time() - self._t0, 3))
        self._jsonl.write(json.dumps(fields, default=float) + "\n")
        self._jsonl.flush()

    def record_eval(self, epoch, test_loss, correct, test_size, asr=None,
                    **extra):
        accuracy = 100.0 * float(correct) / test_size
        self.accuracies.append(accuracy)
        self.accuracies_epochs.append(epoch)
        # Line format mirrors reference main.py:77-80.
        self.print("Test set: [{:3d}] Average loss: {:.4f}, "
                   "Accuracy: {}/{} ({:.2f}%)".format(
                       epoch, float(test_loss), int(correct), test_size,
                       accuracy))
        rec = dict(kind="eval", round=epoch, test_loss=float(test_loss),
                   accuracy=accuracy, correct=int(correct),
                   test_size=test_size, **extra)
        if asr is not None:
            rec["attack_success_rate"] = float(asr)
        self.record(**rec)
        return accuracy

    def finish(self):
        if self.accuracies:
            self.print("Max accuracy: {}".format(max(self.accuracies)))
            # CSV with the reference's filename schema (main.py:100).
            np.savetxt(os.path.join(self.log_dir, self.config.csv_name()),
                       np.asarray(self.accuracies), delimiter=",")
        self._jsonl.close()
