from attacking_federate_learning_tpu.utils.flatten import (  # noqa: F401
    FlatParams, make_flattener
)
from attacking_federate_learning_tpu.utils.plugins import Registry  # noqa: F401
