"""Tracing / profiling hooks.

The reference's only timing artifact is a wall-clock timestamp printed at run
end (reference main.py:97; SURVEY.md §5 "tracing: absent").  Here every round
phase (grads / attack / aggregate / eval) can be timed with a context-manager
stopwatch that blocks on device completion, and a full XLA trace can be
captured with ``jax.profiler`` around any region for TensorBoard/Perfetto.

``device_trace`` is the backend-aware capture wrapper the measured-walls
layer (utils/walls.py, ``--profile-every``) runs through: on the CPU
backend a capture is always safe and always taken; on any other backend
it is a no-op unless ``FL_TEST_TPU=1`` — the same gate the
hardware-bound tests use, so harness code can wrap capture regions
unconditionally without risking a TPU touch while the relay may be
dead (CLAUDE.md).  ``ensure_op_profiling`` arms the XLA flag that makes
CPU captures carry per-op events at all.
"""

from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Optional

import jax

# The TFRT CPU runtime only emits per-op TraceMe annotations (one X
# event per thunk, named by HLO instruction) when this debug flag is
# set; without it a CPU capture carries runtime spans only and every
# wall books to 'unattributed'.
OP_TRACE_FLAG = "--xla_cpu_enable_xprof_traceme=true"


def ensure_op_profiling() -> bool:
    """Arm per-op CPU trace events by appending :data:`OP_TRACE_FLAG`
    to ``XLA_FLAGS``.  XLA parses the env variable ONCE, at the first
    compilation of the process — so this must run before anything is
    compiled (cli.py calls it at --profile-every setup, tools set it at
    main() entry; measured on this box: effective even though
    sitecustomize imported jax long before).  Returns True when the
    flag is present afterwards; callers that might be late (a warm
    pytest process) still get a valid, fully-unattributed booking, not
    a crash."""
    flags = os.environ.get("XLA_FLAGS", "")
    if OP_TRACE_FLAG not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + OP_TRACE_FLAG).strip()
    return True


class PhaseTimer:
    """Accumulates per-phase wall-clock, device-synchronized."""

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str, sync_on=None):
        """``sync_on``: array (or zero-arg callable returning one, evaluated
        after the block so it can reference freshly produced state) to
        block on before stopping the clock.  The phase is accounted even
        when the block or the sync target raises — the wall-clock was
        spent either way."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            try:
                if sync_on is not None:
                    jax.block_until_ready(sync_on() if callable(sync_on)
                                          else sync_on)
            finally:
                dt = time.perf_counter() - t0
                self.totals[name] += dt
                self.counts[name] += 1

    def summary(self) -> dict:
        return {name: {"total_s": round(self.totals[name], 4),
                       "count": self.counts[name],
                       "mean_ms": round(1e3 * self.totals[name]
                                        / max(self.counts[name], 1), 3)}
                for name in self.totals}


@contextlib.contextmanager
def xla_trace(log_dir: Optional[str]):
    """Capture a jax.profiler trace if log_dir is given, else no-op."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def device_trace(log_dir: Optional[str], require_gate: bool = False):
    """Backend-aware profiler capture around a block.

    Capture runs when a ``log_dir`` is given AND either the backend is
    CPU (always safe on this box) or ``FL_TEST_TPU=1`` (the explicit
    hardware opt-in); any other combination is a no-op, so a capture
    region can never be the thing that touches a TPU whose relay is
    dead.  ``require_gate=True`` restores the stricter pre-walls
    contract (no capture without FL_TEST_TPU, even on CPU) that
    utils/trace_export.py pins for its callers.  The env gate is
    checked before any jax attribute so the no-op paths never
    initialize a backend."""
    if not log_dir:
        yield
        return
    gated = os.environ.get("FL_TEST_TPU") == "1"
    if require_gate and not gated:
        yield
        return
    if not gated and jax.default_backend() != "cpu":
        yield
        return
    with xla_trace(log_dir):
        yield
