"""Tracing / profiling hooks.

The reference's only timing artifact is a wall-clock timestamp printed at run
end (reference main.py:97; SURVEY.md §5 "tracing: absent").  Here every round
phase (grads / attack / aggregate / eval) can be timed with a context-manager
stopwatch that blocks on device completion, and a full XLA trace can be
captured with ``jax.profiler`` around any region for TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional

import jax


class PhaseTimer:
    """Accumulates per-phase wall-clock, device-synchronized."""

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str, sync_on=None):
        """``sync_on``: array (or zero-arg callable returning one, evaluated
        after the block so it can reference freshly produced state) to
        block on before stopping the clock.  The phase is accounted even
        when the block or the sync target raises — the wall-clock was
        spent either way."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            try:
                if sync_on is not None:
                    jax.block_until_ready(sync_on() if callable(sync_on)
                                          else sync_on)
            finally:
                dt = time.perf_counter() - t0
                self.totals[name] += dt
                self.counts[name] += 1

    def summary(self) -> dict:
        return {name: {"total_s": round(self.totals[name], 4),
                       "count": self.counts[name],
                       "mean_ms": round(1e3 * self.totals[name]
                                        / max(self.counts[name], 1), 3)}
                for name in self.totals}


@contextlib.contextmanager
def xla_trace(log_dir: Optional[str]):
    """Capture a jax.profiler trace if log_dir is given, else no-op."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
