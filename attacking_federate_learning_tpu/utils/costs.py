"""Static compile-and-cost accounting for jitted entry points.

Wall-clock benchmarks on this box are scarce (TPU relay windows) and
noisy (one shared core), so the performance layer is anchored on facts
that are DETERMINISTIC for a given (HLO, XLA version, platform) triple
and need no timer:

- ``cost_analysis()``: XLA's static FLOP and bytes-accessed count for
  the optimized executable — the O(n^2 d) Krum/Bulyan distance engine
  shows up here as real numbers per compiled round program;
- ``memory_analysis()``: argument/output/temp/alias buffer sizes, from
  which a peak-usage proxy is derived (jaxlib 0.4's
  ``CompiledMemoryStats`` has no explicit peak field on CPU).

:func:`analyze_lowered` runs ``.compile()`` on a ``jax.stages.Lowered``
ONCE, times the compile, attributes it to the persistent compile cache
(hit / miss / uncached) and returns a :class:`CostRecord`.  The records
feed the versioned ``compile`` / ``cost`` event kinds
(utils/metrics.py schema v2), the ``report`` subcommand's
"compile & cost" table, ``bench.py`` metadata, and the deterministic
perf-regression gate (tools/perf_gate.py) — which can therefore run on
CPU, without a TPU or a stopwatch.

Cache attribution is two-source, because neither source alone is
conclusive on this jax (0.4.37):

- a process-wide hit/miss counter fed by jax's own monitoring events
  (``/jax/compilation_cache/cache_hits`` / ``cache_misses``), installed
  lazily by :func:`install_cache_counters`;
- a before/after scan of the fingerprinted cache directory
  (utils/backend.py:host_cache_fingerprint keys the dir): a compile
  that ADDS an entry is a certain miss even if monitoring is silent.

A compile that neither bumped a counter nor wrote an entry is reported
``uncached`` (persistent cache disabled, or the compile finished under
``jax_persistent_cache_min_compile_time_secs``).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional


# Cost-analysis keys we surface (cost_analysis() returns many more
# per-operand utilization entries; these are the stable, comparable ones).
_COST_KEYS = {"flops": "flops", "bytes accessed": "bytes_accessed"}


@dataclasses.dataclass
class CostRecord:
    """Static facts for one compiled entry point.

    ``flops`` / ``bytes_accessed`` are exact for a given (HLO, XLA,
    platform); ``peak_bytes`` is the argument+output+temp−alias proxy
    (an upper bound on resident executable memory, compared with a
    tolerance by the perf gate).  ``collective_bytes`` sums the output
    bytes of every cross-device collective in the compiled (post-SPMD)
    program — 0 for single-device programs, the wire-traffic witness
    for sharded ones (tools/perf_gate.py ``--shardproof`` pins the
    hierarchical SPMD round at O(S·d)).  ``cache`` is 'hit' | 'miss' |
    'uncached'; ``compile_s`` is the observed ``.compile()`` wall time
    (diagnostic only — never gated on)."""

    name: str
    platform: str
    flops: float = -1.0
    bytes_accessed: float = -1.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0
    generated_code_bytes: int = 0
    collective_bytes: int = 0
    compile_s: float = 0.0
    cache: str = "uncached"

    @property
    def peak_bytes(self) -> int:
        return (self.argument_bytes + self.output_bytes + self.temp_bytes
                - self.alias_bytes)

    def cost_event(self) -> dict:
        """Payload for a 'cost' event (metrics.py schema v2)."""
        return dict(kind="cost", name=self.name, flops=self.flops,
                    bytes_accessed=self.bytes_accessed,
                    peak_bytes=self.peak_bytes,
                    argument_bytes=self.argument_bytes,
                    output_bytes=self.output_bytes,
                    temp_bytes=self.temp_bytes,
                    generated_code_bytes=self.generated_code_bytes,
                    collective_bytes=self.collective_bytes)

    def compile_event(self) -> dict:
        """Payload for a 'compile' event (metrics.py schema v2)."""
        return dict(kind="compile", name=self.name,
                    compile_s=round(self.compile_s, 4), cache=self.cache,
                    platform=self.platform)

    def gate_facts(self) -> dict:
        """The facts tools/perf_gate.py diffs: exact ones first, then
        the tolerance-compared memory sizes."""
        return {"flops": self.flops, "bytes_accessed": self.bytes_accessed,
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "temp_bytes": self.temp_bytes,
                "peak_bytes": self.peak_bytes,
                "collective_bytes": self.collective_bytes}


# --- persistent-cache hit/miss accounting ------------------------------

class _CacheCounters:
    hits = 0
    misses = 0
    installed = False


def install_cache_counters() -> None:
    """Count persistent-compile-cache hits/misses process-wide via jax's
    monitoring events.  Idempotent; safe on any jax that lacks the
    events (the listener just never fires)."""
    if _CacheCounters.installed:
        return
    _CacheCounters.installed = True
    try:
        from jax._src import monitoring
    except Exception:      # private module — may move between versions
        return

    def listen(event, **kw):
        if event == "/jax/compilation_cache/cache_hits":
            _CacheCounters.hits += 1
        elif event == "/jax/compilation_cache/cache_misses":
            _CacheCounters.misses += 1

    monitoring.register_event_listener(listen)


def cache_counts() -> dict:
    """Process-wide persistent-cache hit/miss totals (zeros until
    install_cache_counters ran AND a cached compile happened)."""
    return {"hits": _CacheCounters.hits, "misses": _CacheCounters.misses}


def compilation_cache_dir() -> Optional[str]:
    """The active persistent-cache directory, or None when disabled."""
    import jax

    try:
        path = jax.config.jax_compilation_cache_dir
    except AttributeError:
        path = None
    return path or None


def _cache_entries(path: Optional[str]) -> Optional[frozenset]:
    if not path or not os.path.isdir(path):
        return None
    try:
        return frozenset(f for f in os.listdir(path)
                         if not f.endswith("-atime"))
    except OSError:
        return None


# --- collective (cross-device) traffic accounting ----------------------

# Collective ops as they appear in optimized HLO text; async pairs
# (-start/-done) are counted once via -start, and '-done' is excluded
# so the same transfer is never double-billed.
_COLLECTIVE_RE = None

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
                "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
                "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}


def collective_hlo_bytes(text: str) -> dict:
    """Sum output bytes of every cross-device collective in an HLO
    module text (compiled/post-SPMD: shapes are per-device, so the
    totals are what one device moves).  Returns ``{'total': int,
    'per_op': {op: bytes}}``; 0/empty for single-device programs.

    The byte count is the op's OUTPUT shape(s) — the received data,
    the convention the perf gate's O(S·d) bound is written against
    (an all-gather's output is the gathered matrix; a ppermute's is
    one block)."""
    import re

    global _COLLECTIVE_RE
    if _COLLECTIVE_RE is None:
        _COLLECTIVE_RE = re.compile(
            r"=\s+(?P<out>[^=]*?)\s+"
            r"(?P<op>all-gather|all-reduce|reduce-scatter|"
            r"collective-permute|all-to-all)(?P<start>-start)?\(")
    shape_re = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
    per_op: dict = {}
    for m in _COLLECTIVE_RE.finditer(text):
        op = m.group("op")
        nbytes = 0
        for dtype, dims in shape_re.findall(m.group("out")):
            width = _DTYPE_BYTES.get(dtype)
            if width is None:
                continue          # layout braces etc. never match here
            elems = 1
            for d in filter(None, dims.split(",")):
                elems *= int(d)
            nbytes += elems * width
        per_op[op] = per_op.get(op, 0) + nbytes
    return {"total": sum(per_op.values()), "per_op": per_op}


# --- per-entry-point analysis ------------------------------------------

def _first(d):
    """cost_analysis() returns a list of per-program dicts on this
    jaxlib (one element for single-device programs) but a bare dict on
    newer ones — normalize."""
    if isinstance(d, (list, tuple)):
        return d[0] if d else {}
    return d or {}


def compiled_cost_facts(compiled) -> dict:
    """Extract the deterministic facts from a ``jax.stages.Compiled``.
    Missing analyses (some backends return None) yield -1 sentinels so
    a reader can tell "not measured" from a real zero."""
    out = {"flops": -1.0, "bytes_accessed": -1.0, "argument_bytes": 0,
           "output_bytes": 0, "temp_bytes": 0, "alias_bytes": 0,
           "generated_code_bytes": 0, "collective_bytes": 0}
    try:
        ca = _first(compiled.cost_analysis())
    except Exception:
        ca = {}
    for key, field in _COST_KEYS.items():
        if key in ca:
            out[field] = float(ca[key])
    try:
        out["collective_bytes"] = collective_hlo_bytes(
            compiled.as_text())["total"]
    except Exception:
        pass                       # text unavailable on some backends
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        out["argument_bytes"] = int(ma.argument_size_in_bytes)
        out["output_bytes"] = int(ma.output_size_in_bytes)
        out["temp_bytes"] = int(ma.temp_size_in_bytes)
        out["alias_bytes"] = int(ma.alias_size_in_bytes)
        out["generated_code_bytes"] = int(ma.generated_code_size_in_bytes)
    return out


def analyze_lowered(name: str, lowered) -> CostRecord:
    """Compile a ``jax.stages.Lowered`` once; return its CostRecord.

    Cache attribution: monitoring counters are snapshotted around the
    compile (exact when they fire), with the fingerprint-dir scan as
    the fallback witness — an entry added during the compile is a miss
    even when monitoring is unavailable."""
    import jax

    install_cache_counters()
    platform = jax.devices()[0].platform
    cdir = compilation_cache_dir()
    before = _cache_entries(cdir)
    hits0, misses0 = _CacheCounters.hits, _CacheCounters.misses
    t0 = time.perf_counter()
    compiled = lowered.compile()
    dt = time.perf_counter() - t0
    after = _cache_entries(cdir)
    if _CacheCounters.hits > hits0:
        cache = "hit"
    elif _CacheCounters.misses > misses0:
        cache = "miss"
    elif before is not None and after is not None and after - before:
        cache = "miss"
    else:
        cache = "uncached"
    rec = CostRecord(name=name, platform=platform, compile_s=dt,
                     cache=cache, **compiled_cost_facts(compiled))
    return rec


class CompileLedger:
    """Per-run collection of CostRecords (core/engine.py:cost_report
    fills one; report.py renders it as the compile & cost table)."""

    def __init__(self):
        self.records: list = []
        self.errors: list = []   # (name, message) for entries that
        # failed to lower/compile — kept out of records so the gate
        # never diffs a partial fact set silently

    def analyze(self, name: str, lowered) -> CostRecord:
        rec = analyze_lowered(name, lowered)
        self.records.append(rec)
        return rec

    def emit(self, logger) -> None:
        """Write one 'compile' + one 'cost' event per record."""
        for rec in self.records:
            logger.record(**rec.compile_event())
            logger.record(**rec.cost_event())

    def summary(self) -> dict:
        """{name: gate_facts} — the shape PERF_BASELINE.json stores."""
        return {rec.name: rec.gate_facts() for rec in self.records}
