"""Static compile-and-cost accounting for jitted entry points.

Wall-clock benchmarks on this box are scarce (TPU relay windows) and
noisy (one shared core), so the performance layer is anchored on facts
that are DETERMINISTIC for a given (HLO, XLA version, platform) triple
and need no timer:

- ``cost_analysis()``: XLA's static FLOP and bytes-accessed count for
  the optimized executable — the O(n^2 d) Krum/Bulyan distance engine
  shows up here as real numbers per compiled round program;
- ``memory_analysis()``: argument/output/temp/alias buffer sizes, from
  which a peak-usage proxy is derived (jaxlib 0.4's
  ``CompiledMemoryStats`` has no explicit peak field on CPU).

:func:`analyze_lowered` runs ``.compile()`` on a ``jax.stages.Lowered``
ONCE, times the compile, attributes it to the persistent compile cache
(hit / miss / uncached) and returns a :class:`CostRecord`.  The records
feed the versioned ``compile`` / ``cost`` event kinds
(utils/metrics.py schema v2), the ``report`` subcommand's
"compile & cost" table, ``bench.py`` metadata, and the deterministic
perf-regression gate (tools/perf_gate.py) — which can therefore run on
CPU, without a TPU or a stopwatch.

Cache attribution is two-source, because neither source alone is
conclusive on this jax (0.4.37):

- a process-wide hit/miss counter fed by jax's own monitoring events
  (``/jax/compilation_cache/cache_hits`` / ``cache_misses``), installed
  lazily by :func:`install_cache_counters`;
- a before/after scan of the fingerprinted cache directory
  (utils/backend.py:host_cache_fingerprint keys the dir): a compile
  that ADDS an entry is a certain miss even if monitoring is silent.

A compile that neither bumped a counter nor wrote an entry is reported
``uncached`` (persistent cache disabled, or the compile finished under
``jax_persistent_cache_min_compile_time_secs``).

Stage & wire ledger (ISSUE 15).  The whole-program numbers above answer
"what does a round cost"; two further instruments answer "where":

- **Stage attribution**: the engines annotate their round programs with
  :func:`stage_scope` — ``jax.named_scope`` under the canonical stage
  taxonomy :data:`STAGES` (``deliver → quarantine → protect →
  tier1_aggregate → tier2_aggregate → apply``).  The scopes are
  metadata-only: the optimized HLO stays computation-identical
  (:func:`canonical_hlo` strips op metadata and canonicalizes value
  names, so :func:`hlo_fingerprint` hashes the same program with scopes
  on or off — ``tools/perf_gate.py --stageproof`` proves it per pinned
  cell).  :func:`stage_attribution` then walks the annotated HLO text,
  models per-instruction FLOPs/bytes from opcode+shapes, buckets each
  instruction by the stage token in its ``op_name`` path, and
  partitions the *actual* whole-program totals proportionally to the
  modeled masses — so stage sums equal the program totals exactly by
  construction, and ``coverage`` reports the modeled share that landed
  in a named stage.

- **Wire ledger**: :func:`wire_ledger` prices every protocol seam a
  round crosses (broadcast down, client→tier-1 updates, tier-1→tier-2
  all_gather, secagg mask exchange + dropout recovery, async delivery
  ring) in bytes per round from the topology parameters alone.  The
  hierarchical ``tier1_to_tier2`` seam is ``S·d·4`` — the same number
  the SPMD round's measured ``collective_bytes`` pins (PR 12), which
  ``--stageproof`` cross-checks.  Both instruments emit as schema-v9
  events (``stage_cost`` / ``wire_bytes``) via CompileLedger.emit.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional

# Canonical stage taxonomy, in round order.  ``deliver`` covers batch
# gather + client update + attack craft (and the async delivery ring);
# ``quarantine`` the fault-injection screen + async re-mask;
# ``protect`` the secagg mask/unmask protocol; the two aggregate stages
# the tier-1 defense kernel and the tier-2 shard reduction; ``apply``
# the server momentum/LR update (+ round diagnostics riders).
STAGES = ("deliver", "quarantine", "protect",
          "tier1_aggregate", "tier2_aggregate", "apply")
_STAGE_SET = frozenset(STAGES)

_STAGE_ENV = "FL_STAGE_SCOPES"
_stage_scopes_on = True


def stage_scopes_enabled() -> bool:
    """Stage scopes are on unless FL_STAGE_SCOPES=0 (env, checked per
    trace so tests can flip it) or :func:`set_stage_scopes` disabled
    them (how --stageproof builds the scope-free twin program)."""
    if os.environ.get(_STAGE_ENV, "1") == "0":
        return False
    return _stage_scopes_on


def set_stage_scopes(enabled: bool) -> bool:
    """Process-wide stage-scope switch; returns the previous value."""
    global _stage_scopes_on
    prev = _stage_scopes_on
    _stage_scopes_on = bool(enabled)
    return prev


def stage_scope(name: str):
    """``jax.named_scope(name)`` for a canonical stage — metadata-only
    annotation (op_name path component) on every op traced under it,
    or a no-op context when scopes are disabled.  Importable without
    jax; jax loads on first enabled use."""
    assert name in STAGES, f"unknown stage {name!r} (taxonomy: {STAGES})"
    if not stage_scopes_enabled():
        import contextlib

        return contextlib.nullcontext()
    import jax

    return jax.named_scope(name)


# Cost-analysis keys we surface (cost_analysis() returns many more
# per-operand utilization entries; these are the stable, comparable ones).
_COST_KEYS = {"flops": "flops", "bytes accessed": "bytes_accessed"}


@dataclasses.dataclass
class CostRecord:
    """Static facts for one compiled entry point.

    ``flops`` / ``bytes_accessed`` are exact for a given (HLO, XLA,
    platform); ``peak_bytes`` is the argument+output+temp−alias proxy
    (an upper bound on resident executable memory, compared with a
    tolerance by the perf gate).  ``collective_bytes`` sums the output
    bytes of every cross-device collective in the compiled (post-SPMD)
    program — 0 for single-device programs, the wire-traffic witness
    for sharded ones (tools/perf_gate.py ``--shardproof`` pins the
    hierarchical SPMD round at O(S·d)).  ``cache`` is 'hit' | 'miss' |
    'uncached'; ``compile_s`` is the observed ``.compile()`` wall time
    (diagnostic only — never gated on)."""

    name: str
    platform: str
    flops: float = -1.0
    bytes_accessed: float = -1.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0
    generated_code_bytes: int = 0
    collective_bytes: int = 0
    compile_s: float = 0.0
    cache: str = "uncached"
    # Per-stage partition of the totals above (stage_attribution output;
    # None when the backend withheld HLO text).  Deliberately NOT part
    # of gate_facts — the attribution is derived from the same program
    # the exact facts already pin.
    attribution: Optional[dict] = None

    @property
    def peak_bytes(self) -> int:
        return (self.argument_bytes + self.output_bytes + self.temp_bytes
                - self.alias_bytes)

    def cost_event(self) -> dict:
        """Payload for a 'cost' event (metrics.py schema v2)."""
        return dict(kind="cost", name=self.name, flops=self.flops,
                    bytes_accessed=self.bytes_accessed,
                    peak_bytes=self.peak_bytes,
                    argument_bytes=self.argument_bytes,
                    output_bytes=self.output_bytes,
                    temp_bytes=self.temp_bytes,
                    generated_code_bytes=self.generated_code_bytes,
                    collective_bytes=self.collective_bytes)

    def compile_event(self) -> dict:
        """Payload for a 'compile' event (metrics.py schema v2)."""
        return dict(kind="compile", name=self.name,
                    compile_s=round(self.compile_s, 4), cache=self.cache,
                    platform=self.platform)

    def stage_event(self) -> Optional[dict]:
        """Payload for a 'stage_cost' event (metrics.py schema v9), or
        None when no attribution was computable for this entry."""
        if self.attribution is None:
            return None
        att = self.attribution
        return dict(kind="stage_cost", name=self.name,
                    stages=att["stages"],
                    unattributed=att["unattributed"],
                    coverage=att["coverage"])

    def gate_facts(self) -> dict:
        """The facts tools/perf_gate.py diffs: exact ones first, then
        the tolerance-compared memory sizes."""
        return {"flops": self.flops, "bytes_accessed": self.bytes_accessed,
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "temp_bytes": self.temp_bytes,
                "peak_bytes": self.peak_bytes,
                "collective_bytes": self.collective_bytes}


# --- persistent-cache hit/miss accounting ------------------------------

class _CacheCounters:
    hits = 0
    misses = 0
    installed = False


def install_cache_counters() -> None:
    """Count persistent-compile-cache hits/misses process-wide via jax's
    monitoring events.  Idempotent; safe on any jax that lacks the
    events (the listener just never fires)."""
    if _CacheCounters.installed:
        return
    _CacheCounters.installed = True
    try:
        from jax._src import monitoring
    except Exception:      # private module — may move between versions
        return

    def listen(event, **kw):
        if event == "/jax/compilation_cache/cache_hits":
            _CacheCounters.hits += 1
        elif event == "/jax/compilation_cache/cache_misses":
            _CacheCounters.misses += 1

    monitoring.register_event_listener(listen)


def cache_counts() -> dict:
    """Process-wide persistent-cache hit/miss totals (zeros until
    install_cache_counters ran AND a cached compile happened)."""
    return {"hits": _CacheCounters.hits, "misses": _CacheCounters.misses}


def compilation_cache_dir() -> Optional[str]:
    """The active persistent-cache directory, or None when disabled."""
    import jax

    try:
        path = jax.config.jax_compilation_cache_dir
    except AttributeError:
        path = None
    return path or None


def _cache_entries(path: Optional[str]) -> Optional[frozenset]:
    if not path or not os.path.isdir(path):
        return None
    try:
        return frozenset(f for f in os.listdir(path)
                         if not f.endswith("-atime"))
    except OSError:
        return None


# --- collective (cross-device) traffic accounting ----------------------

# Collective ops as they appear in optimized HLO text; async pairs
# (-start/-done) are counted once via -start, and '-done' is excluded
# so the same transfer is never double-billed.
_COLLECTIVE_RE = None

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
                "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
                "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}


def collective_hlo_bytes(text: str) -> dict:
    """Sum output bytes of every cross-device collective in an HLO
    module text (compiled/post-SPMD: shapes are per-device, so the
    totals are what one device moves).  Returns ``{'total': int,
    'per_op': {op: bytes}}``; 0/empty for single-device programs.

    The byte count is the op's OUTPUT shape(s) — the received data,
    the convention the perf gate's O(S·d) bound is written against
    (an all-gather's output is the gathered matrix; a ppermute's is
    one block)."""
    import re

    global _COLLECTIVE_RE
    if _COLLECTIVE_RE is None:
        _COLLECTIVE_RE = re.compile(
            r"=\s+(?P<out>[^=]*?)\s+"
            r"(?P<op>all-gather|all-reduce|reduce-scatter|"
            r"collective-permute|all-to-all)(?P<start>-start)?\(")
    shape_re = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
    per_op: dict = {}
    for m in _COLLECTIVE_RE.finditer(text):
        op = m.group("op")
        nbytes = 0
        for dtype, dims in shape_re.findall(m.group("out")):
            width = _DTYPE_BYTES.get(dtype)
            if width is None:
                continue          # layout braces etc. never match here
            elems = 1
            for d in filter(None, dims.split(",")):
                elems *= int(d)
            nbytes += elems * width
        per_op[op] = per_op.get(op, 0) + nbytes
    return {"total": sum(per_op.values()), "per_op": per_op}


# --- canonical HLO (metadata-stripped computation identity) ------------

# One attribute blob: metadata={op_type="..." op_name="..." ...}.
# Brace-free except inside the quoted strings, which the alternation
# steps over — so op_name paths may contain anything but a quote.
_METADATA_RE = None
_VALUE_NAME_RE = None


def canonical_hlo(text: str) -> str:
    """The computation-identity view of an HLO module text: op metadata
    stripped and every %value/%computation name rewritten to its
    first-appearance ordinal.  Two programs are computation-identical
    iff their canonical texts match — op_name scopes, source lines and
    instruction-id drift are all erased, while opcodes, shapes, operand
    wiring and attributes all still compare."""
    import re

    global _METADATA_RE, _VALUE_NAME_RE
    if _METADATA_RE is None:
        _METADATA_RE = re.compile(
            r",?\s*metadata=\{(?:[^{}\"]|\"[^\"]*\")*\}")
        _VALUE_NAME_RE = re.compile(r"%[\w.\-]+")
    stripped = _METADATA_RE.sub("", text)
    names: dict = {}

    def rename(m):
        return names.setdefault(m.group(0), f"%v{len(names)}")

    return _VALUE_NAME_RE.sub(rename, stripped)


def hlo_fingerprint(text: str) -> str:
    """sha256 of :func:`canonical_hlo` — the hash the byte-identical-HLO
    gates compare now that stage scopes legally perturb metadata."""
    import hashlib

    return hashlib.sha256(canonical_hlo(text).encode()).hexdigest()


# --- per-stage static attribution --------------------------------------

# Instruction lines whose cost is carried elsewhere (callees are listed
# as their own computations and counted there; parameters/constants/
# tuple plumbing move no unique data):
_SKIP_OPS = frozenset({
    "parameter", "constant", "get-tuple-element", "tuple", "fusion",
    "while", "call", "conditional", "bitcast", "after-all",
    "opt-barrier", "partition-id", "replica-id",
})
# Elementwise-ish opcodes modeled at one FLOP per output element:
_EW_OPS = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "abs", "negate", "exponential", "exponential-minus-one", "log",
    "log-plus-one", "power", "sqrt", "rsqrt", "cbrt", "tanh",
    "logistic", "sine", "cosine", "sign", "floor", "ceil",
    "round-nearest-afz", "round-nearest-even", "compare", "select",
    "clamp", "and", "or", "xor", "not", "shift-left",
    "shift-right-logical", "shift-right-arithmetic", "remainder",
    "atan2", "is-finite", "rng-bit-generator",
})

_INSTR_RE = None
_SHAPE_RE = None
_OPNAME_RE = None
_CDIMS_RE = None


def _shape_bytes_elems(shape_text: str):
    """[(bytes, elems)] for every dtype[dims] shape in a text span."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        width = _DTYPE_BYTES.get(dtype)
        if width is None:
            continue              # 'devices[8,1]' etc. never bill
        elems = 1
        for d in filter(None, dims.split(",")):
            elems *= int(d)
        out.append((elems * width, elems))
    return out


def _instr_flops(op: str, out_shapes, operand_text: str) -> float:
    """Modeled FLOPs for one instruction — a *mass* used only to split
    the program's actual totals proportionally, so relative fidelity is
    what matters, not absolute counts."""
    out_elems = sum(e for _, e in out_shapes)
    if op == "dot":
        contract = 1
        m = _CDIMS_RE.search(operand_text)
        lhs_dims = _SHAPE_RE.search(operand_text)
        if m and lhs_dims:
            dims = [int(d) for d in
                    filter(None, lhs_dims.group(2).split(","))]
            for idx in filter(None, m.group(1).split(",")):
                i = int(idx)
                if i < len(dims):
                    contract *= dims[i]
        return 2.0 * out_elems * contract
    if op == "convolution":
        ops = _shape_bytes_elems(operand_text)
        kernel = ops[1][1] if len(ops) > 1 else 1
        return 2.0 * out_elems * kernel
    if op in ("reduce", "reduce-window"):
        ops = _shape_bytes_elems(operand_text)
        return float(ops[0][1]) if ops else float(out_elems)
    if op == "sort":
        import math

        return out_elems * max(1.0, math.log2(max(out_elems, 2)))
    if op in _EW_OPS:
        return float(out_elems)
    return 0.0


def stage_attribution(text: str, totals: Optional[dict] = None) -> dict:
    """Partition whole-program cost per canonical stage from annotated
    HLO text.

    Walks every instruction line in the module (fusion/while bodies are
    their own computations, so each op is seen exactly once), models
    its FLOPs (opcode+shapes) and bytes (all typed shapes on the line),
    and buckets both by the first :data:`STAGES` token in the op's
    ``op_name`` metadata path — ``unattributed`` when no stage scope
    encloses it.  When ``totals`` carries the program's actual
    ``flops`` / ``bytes_accessed`` / ``temp_bytes`` (compiled_cost_facts),
    each metric is split proportionally to the modeled masses with the
    residual folded into ``unattributed`` — so the per-stage values sum
    to the program total *exactly*.  ``coverage`` is the modeled share
    attributed to named stages (the --stageproof ≥95% bar)."""
    import math
    import re

    global _INSTR_RE, _SHAPE_RE, _OPNAME_RE, _CDIMS_RE
    global _METADATA_RE
    if _METADATA_RE is None:
        canonical_hlo("")         # compile the shared metadata regex
    if _INSTR_RE is None:
        _INSTR_RE = re.compile(
            r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*"
            r"(?P<shape>\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\]"
            r"(?:\{[^}]*\})?)\s+(?P<op>[\w\-]+)\(")
        _SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
        _OPNAME_RE = re.compile(r'op_name="([^"]*)"')
        _CDIMS_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
    mass: dict = {s: {"flops": 0.0, "bytes": 0.0} for s in STAGES}
    mass["unattributed"] = {"flops": 0.0, "bytes": 0.0}
    for line in text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None or m.group("op") in _SKIP_OPS:
            continue
        nm = _OPNAME_RE.search(line)
        # Innermost taxonomy token wins: an outer scope around a whole
        # call region (e.g. the hierarchical megabatch scan) attributes
        # the region's *plumbing* (carry writes, estimate stacking)
        # without clobbering the finer stages annotated inside it.
        sm = ([t for t in nm.group(1).split("/") if t in _STAGE_SET]
              if nm else None)
        stage = sm[-1] if sm else "unattributed"
        body = _METADATA_RE.sub("", line) if _METADATA_RE else line
        after = body.split(m.group("op") + "(", 1)
        operand_text = after[1] if len(after) > 1 else ""
        out_shapes = _shape_bytes_elems(m.group("shape"))
        mass[stage]["flops"] += _instr_flops(
            m.group("op"), out_shapes, operand_text)
        mass[stage]["bytes"] += sum(
            b for b, _ in _shape_bytes_elems(body))
    named_f = math.fsum(mass[s]["flops"] for s in STAGES)
    named_b = math.fsum(mass[s]["bytes"] for s in STAGES)
    total_f = named_f + mass["unattributed"]["flops"]
    total_b = named_b + mass["unattributed"]["bytes"]
    out = {
        "stages": {}, "unattributed": {},
        "coverage": {
            "flops": named_f / total_f if total_f else 0.0,
            "bytes_accessed": named_b / total_b if total_b else 0.0,
        },
    }
    # Metric → which modeled mass splits it.
    metric_mass = {"flops": "flops", "bytes_accessed": "bytes",
                   "temp_bytes": "bytes"}
    totals = totals or {}
    for metric, mkey in metric_mass.items():
        total = totals.get(metric)
        if total is None or total < 0:
            continue
        denom = math.fsum(mass[s][mkey] for s in STAGES) \
            + mass["unattributed"][mkey]
        shares = {}
        for s in STAGES:
            shares[s] = total * (mass[s][mkey] / denom) if denom else 0.0
            out["stages"].setdefault(s, {})[metric] = shares[s]
        # Residual → unattributed, so the partition sums exactly.
        out["unattributed"][metric] = total - math.fsum(
            shares[s] for s in STAGES)
    out["model_mass"] = {s: dict(v) for s, v in mass.items()}
    return out


# --- per-seam wire ledger ----------------------------------------------

# Every protocol seam a round can cross, in round order.  Absent seams
# (e.g. tier1_to_tier2 on a flat topology) are omitted, zero-byte seams
# (secagg on, nobody dropped) are kept — the column exists, it is empty.
WIRE_SEAMS = ("broadcast", "client_update", "tier1_to_tier2",
              "secagg_mask_exchange", "secagg_recovery",
              "async_delivery")


def wire_ledger(*, cohort: int, dim: int, grad_bytes: int = 4,
                topology: str = "flat", num_shards: Optional[int] = None,
                megabatch: Optional[int] = None, spmd_parts: int = 1,
                secagg: str = "off", key_bytes: int = 32,
                dropped: int = 0,
                async_buffer: Optional[int] = None) -> dict:
    """Bytes-per-round on every protocol seam, priced from the topology
    parameters alone (f32 model wire; ``grad_bytes`` prices a quantized
    client→server leg, ROADMAP item 4's baseline column).

    Seams: server→client ``broadcast`` (every cohort member pulls the
    d-dim f32 model), ``client_update`` (cohort·d·grad_bytes up),
    hierarchical ``tier1_to_tier2`` (S estimates to the tier-2 reducer
    — exactly the ``S·d·4`` the SPMD all_gather moves per device, the
    PR 12 measured-collective cross-check), secagg ``mask_exchange``
    (one pairwise key/masked-seed exchange per client pair — vanilla
    C(n,2), groupwise S·C(m,2)) + ``recovery`` (each dropout makes
    every survivor reveal one pairwise secret), and the ``async
    delivery`` ring (buffer-capacity updates of d·grad_bytes per round,
    the capacity bound on what one round can deliver)."""
    seams: dict = {}
    seams["broadcast"] = {"bytes": cohort * dim * 4}
    seams["client_update"] = {"bytes": cohort * dim * grad_bytes}
    if topology == "hierarchical" and num_shards:
        seams["tier1_to_tier2"] = {
            "bytes": num_shards * dim * 4,
            "collective": spmd_parts > 1,
        }
    if secagg != "off":
        if secagg == "groupwise" and num_shards and megabatch:
            pairs = num_shards * (megabatch * (megabatch - 1) // 2)
        else:
            pairs = cohort * (cohort - 1) // 2
        seams["secagg_mask_exchange"] = {"bytes": pairs * key_bytes}
        seams["secagg_recovery"] = {
            "bytes": dropped * max(cohort - 1, 0) * key_bytes}
    if topology == "async" and async_buffer:
        seams["async_delivery"] = {
            "bytes": async_buffer * dim * grad_bytes}
    return {
        "topology": topology, "cohort": cohort, "dim": dim,
        "grad_bytes": grad_bytes,
        "seams": seams,
        "total_bytes": sum(s["bytes"] for s in seams.values()),
    }


# --- per-entry-point analysis ------------------------------------------

def _first(d):
    """cost_analysis() returns a list of per-program dicts on this
    jaxlib (one element for single-device programs) but a bare dict on
    newer ones — normalize."""
    if isinstance(d, (list, tuple)):
        return d[0] if d else {}
    return d or {}


def compiled_cost_facts(compiled) -> dict:
    """Extract the deterministic facts from a ``jax.stages.Compiled``.
    Missing analyses (some backends return None) yield -1 sentinels so
    a reader can tell "not measured" from a real zero."""
    out = {"flops": -1.0, "bytes_accessed": -1.0, "argument_bytes": 0,
           "output_bytes": 0, "temp_bytes": 0, "alias_bytes": 0,
           "generated_code_bytes": 0, "collective_bytes": 0}
    try:
        ca = _first(compiled.cost_analysis())
    except Exception:
        ca = {}
    for key, field in _COST_KEYS.items():
        if key in ca:
            out[field] = float(ca[key])
    try:
        out["collective_bytes"] = collective_hlo_bytes(
            compiled.as_text())["total"]
    except Exception:
        pass                       # text unavailable on some backends
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        out["argument_bytes"] = int(ma.argument_size_in_bytes)
        out["output_bytes"] = int(ma.output_size_in_bytes)
        out["temp_bytes"] = int(ma.temp_size_in_bytes)
        out["alias_bytes"] = int(ma.alias_size_in_bytes)
        out["generated_code_bytes"] = int(ma.generated_code_size_in_bytes)
    return out


def analyze_lowered(name: str, lowered) -> CostRecord:
    """Compile a ``jax.stages.Lowered`` once; return its CostRecord.

    Cache attribution: monitoring counters are snapshotted around the
    compile (exact when they fire), with the fingerprint-dir scan as
    the fallback witness — an entry added during the compile is a miss
    even when monitoring is unavailable."""
    import jax

    install_cache_counters()
    platform = jax.devices()[0].platform
    cdir = compilation_cache_dir()
    before = _cache_entries(cdir)
    hits0, misses0 = _CacheCounters.hits, _CacheCounters.misses
    t0 = time.perf_counter()
    compiled = lowered.compile()
    dt = time.perf_counter() - t0
    after = _cache_entries(cdir)
    if _CacheCounters.hits > hits0:
        cache = "hit"
    elif _CacheCounters.misses > misses0:
        cache = "miss"
    elif before is not None and after is not None and after - before:
        cache = "miss"
    else:
        cache = "uncached"
    facts = compiled_cost_facts(compiled)
    rec = CostRecord(name=name, platform=platform, compile_s=dt,
                     cache=cache, **facts)
    try:
        rec.attribution = stage_attribution(compiled.as_text(), facts)
    except Exception:
        rec.attribution = None     # text unavailable on some backends
    return rec


class CompileLedger:
    """Per-run collection of CostRecords (core/engine.py:cost_report
    fills one; report.py renders it as the compile & cost table)."""

    def __init__(self):
        self.records: list = []
        self.errors: list = []   # (name, message) for entries that
        # failed to lower/compile — kept out of records so the gate
        # never diffs a partial fact set silently
        self.wire: Optional[dict] = None   # wire_ledger() output —
        # core/engine.py:cost_report attaches the run's per-seam
        # bytes-on-wire so emit() can version it as one event

    def analyze(self, name: str, lowered) -> CostRecord:
        rec = analyze_lowered(name, lowered)
        self.records.append(rec)
        return rec

    def emit(self, logger) -> None:
        """Write one 'compile' + one 'cost' (+ one 'stage_cost' when
        attribution was computable) event per record, and one
        'wire_bytes' event when a wire ledger is attached."""
        for rec in self.records:
            logger.record(**rec.compile_event())
            logger.record(**rec.cost_event())
            stage = rec.stage_event()
            if stage is not None:
                logger.record(**stage)
        if self.wire is not None:
            logger.record(kind="wire_bytes", **self.wire)

    def summary(self) -> dict:
        """{name: gate_facts} — the shape PERF_BASELINE.json stores."""
        return {rec.name: rec.gate_facts() for rec in self.records}
