"""Tiny name->factory registry.

The reference dispatches defenses through a module-level dict
(reference defences.py:73-75); this generalizes that seam to defenses,
attacks, models and partitioners so new plugins register by decorator.

(Lived in utils/registry.py through PR 4; that module is now the
cross-RUN registry — the queryable index over ``runs/`` — so the
factory registry moved here.  Importers updated in place;
``utils.Registry`` keeps re-exporting it.)
"""

from __future__ import annotations


class Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._entries = {}

    def register(self, name: str, obj=None):
        if obj is None:  # decorator form
            def deco(fn):
                self._entries[name] = fn
                return fn
            return deco
        self._entries[name] = obj
        return obj

    def __getitem__(self, name: str):
        try:
            return self._entries[name]
        except KeyError:
            raise KeyError(
                f"Unknown {self.kind} {name!r}; available: {sorted(self._entries)}"
            ) from None

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def names(self):
        return sorted(self._entries)
