"""Backend liveness watchdog for driver entry points.

On this image a relay process brokers the TPU; when it is dead, jax
backend initialization blocks forever in a connect-retry loop
(CLAUDE.md).  Entry points that must always complete (bench.py, the
benchmarks runner) call :func:`ensure_live_backend` before importing jax
for real: a ~2 s port probe short-circuits the plainly-dead case, a
subprocess probe catches the subtler ones, and either failure re-execs
the process pinned to CPU.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys


def relay_ports_listening(ports=(8082, 8083, 8087), timeout=2.0):
    """Fast liveness check for the TPU relay's local ports."""
    for port in ports:
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=timeout):
                return True
        except OSError:
            continue
    return False


def relay_ports_listening_retry(ports=None, timeout=1.0, retries=3,
                                backoff=0.5, sleep=None):
    """Bounded retry-with-backoff wrapper around the port probe.

    A single short probe misclassifies a slow-but-alive relay (accept
    queue full, listener mid-restart) as dead, silently benching an
    accelerator run on CPU.  This probes up to ``retries`` times with
    doubling backoff (0.5 s then 1 s between the default 3 probes —
    worst case a few seconds, still bounded) and returns on the first
    success.  ``sleep`` is injectable for tests; ``ports=None`` keeps
    the probe's default port set (and its monkeypatchability)."""
    import time

    sleep = sleep or time.sleep
    kw = {} if ports is None else {"ports": ports}
    delay = backoff
    for attempt in range(max(1, retries)):
        if relay_ports_listening(timeout=timeout, **kw):
            return True
        if attempt + 1 < retries:
            sleep(delay)
            delay *= 2
    return False


def _fallback_to_cpu(reason: str):
    print(reason + "; falling back to CPU", file=sys.stderr, flush=True)
    os.environ.update(_BENCH_BACKEND_CHECKED="1", JAX_PLATFORMS="cpu",
                      PALLAS_AXON_POOL_IPS="")
    # The exec'd image inherits fd 2; if the AOT-warning collapse pipe
    # is installed it must be unwound first — the pump thread dies with
    # the exec and a pipe nobody drains would block the child's stderr
    # after 64 KB.
    if _AOT_COLLAPSE["real_fd"] is not None:
        os.dup2(_AOT_COLLAPSE["real_fd"], 2)
        _AOT_COLLAPSE["real_fd"] = None
    os.execve(sys.executable, [sys.executable] + sys.argv, os.environ)


# --- cpu_aot_loader SIGILL false-positive collapse ----------------------
#
# XLA's CPU AOT loader warns — one multi-KB line on fd 2, C++-side, so
# neither `warnings` nor sys.stderr can intercept it — whenever a
# persistent-cache executable's LLVM feature string differs from its
# host enumeration.  On this box the mismatch is a SAME-HOST false
# positive: the only "unsupported" names are +prefer-no-scatter /
# +prefer-no-gather, LLVM *tuning* flags the host enumeration never
# lists (CLAUDE.md; VERDICT_RESPONSE r4 weak #3).  A real cross-host
# mismatch names ISA features (amx-*, avx512*) and must stay loud.

_AOT_TUNING_FLAGS = frozenset({"prefer-no-scatter", "prefer-no-gather"})
_AOT_COLLAPSE = {"real_fd": None}


def classify_aot_warning(line: str):
    """Classify one stderr line: ``(is_aot_warning, benign, note)``.

    ``is_aot_warning`` — the line is the loader's SIGILL feature-dump;
    ``benign`` — every executable feature missing from the host list
    is a known LLVM tuning flag (the same-host false positive);
    ``note`` — the one-line replacement to emit when benign.  A
    warning naming any real ISA feature classifies non-benign and the
    caller must pass the full line through untouched."""
    if "SIGILL" not in line or "host machine features" not in line:
        return False, False, None
    import re

    lists = re.findall(r"\[([^][]*)\]", line)
    if len(lists) < 2:
        return True, False, None
    exe = {t.strip()[1:] for t in lists[-2].split(",")
           if t.strip().startswith("+")}
    host = {t.strip() for t in lists[-1].split(",") if t.strip()}
    unsupported = exe - host
    if not unsupported <= _AOT_TUNING_FLAGS:
        return True, False, None
    note = ("[cpu_aot_loader] same-host SIGILL false positive collapsed: "
            f"unsupported={sorted(unsupported) or ['<none>']} — LLVM "
            "tuning flags, not ISA features (CLAUDE.md); feature dump "
            "suppressed")
    return True, True, note


def install_aot_warning_collapse():
    """Route fd 2 through a filter thread that collapses the benign
    cpu_aot_loader SIGILL feature dump into one annotated line
    (ISSUE 11 bench-hygiene satellite): the multi-KB dump polluted
    every BENCH tail the driver records.  Python-side writers keep a
    direct handle (sys.stderr is rebound to a dup of the REAL stderr),
    so the recap/deadline escape hatches never depend on the pump
    thread; only C++-side writes (the XLA logger) cross the pipe.
    Idempotent; FL_NO_AOT_COLLAPSE=1 disables."""
    import threading

    if (_AOT_COLLAPSE["real_fd"] is not None
            or os.environ.get("FL_NO_AOT_COLLAPSE") == "1"):
        return
    real = os.dup(2)
    _AOT_COLLAPSE["real_fd"] = real
    sys.stderr = os.fdopen(os.dup(real), "w", buffering=1,
                           errors="replace")
    r, w = os.pipe()
    os.dup2(w, 2)
    os.close(w)

    def pump():
        buf = b""
        while True:
            try:
                chunk = os.read(r, 65536)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                text = line.decode("utf-8", "replace")
                is_warn, benign, note = classify_aot_warning(text)
                if is_warn and benign:
                    os.write(real, (note + "\n").encode())
                else:
                    os.write(real, line + b"\n")
        if buf:
            os.write(real, buf)

    threading.Thread(target=pump, daemon=True,
                     name="aot-warning-collapse").start()


def host_cache_fingerprint():
    """Host fingerprint for the persistent-compile-cache directory.

    XLA's persistent cache keys entries on the HLO and compile options
    but NOT on the host CPU's feature set, and this repo's .jax_cache
    survives across rounds on hosts that are not identical: BENCH_r04's
    tail opened with XLA's warning that a cached executable "was
    compiled for a different CPU feature set" and "could lead to
    execution errors such as SIGILL".  A SIGILL inside the short TPU
    capture window would burn it.  Keying the cache *directory* on the
    CPU feature flags (+ arch + jax version) makes a different host a
    different, initially-empty directory instead of a crash risk, while
    same-host processes still share warm compiles.
    """
    import hashlib
    import platform

    bits = [platform.machine()]
    try:
        wanted = ("flags", "Features", "model", "stepping", "bugs",
                  "model name")
        seen = set()
        with open("/proc/cpuinfo") as f:
            for line in f:
                # One core suffices (all cores report the same); the
                # feature flags alone do NOT discriminate the physical
                # hosts behind this VM (observed: identical flags lines
                # while XLA's AOT loader warned about foreign
                # +prefer-no-scatter executables), so the model/
                # stepping/bugs lines ride along.
                key = line.split(":")[0].strip()
                if key in wanted and key not in seen:
                    seen.add(key)
                    bits.append(line.strip())
                if len(seen) == len(wanted):
                    break
    except OSError:
        bits.append(platform.processor())
    try:
        # The strongest available proxy for the cpuid view the JIT's
        # own host detection uses (and the piece /proc/cpuinfo masks on
        # this VM): gcc's -march=native resolution enumerates every
        # cpuid-detected target flag.  ~30 ms, once per process.
        import subprocess
        out = subprocess.run(
            ["g++", "-march=native", "-Q", "--help=target"],
            capture_output=True, timeout=10).stdout
        bits.append(str(len(out)))
        bits.append(out.decode("utf-8", "replace"))
    except Exception:
        pass
    try:
        # Version via metadata, NOT `import jax`: callers (conftest)
        # need the fingerprint before jax is imported, because jax 0.9
        # reads JAX_COMPILATION_CACHE_DIR only at import time.
        from importlib.metadata import version
        bits.append(version("jax"))
    except Exception:
        pass
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:12]


def enable_compile_cache(path=None):
    """Persistent XLA compile cache shared by every entry point (tests
    already use it via conftest, anchored to the same repo-root
    .jax_cache).  Compiles survive across processes — critical when TPU
    relay windows are short: a second bench/benchmarks run skips the
    20-40 s first compiles.  A user-set JAX_COMPILATION_CACHE_DIR wins
    verbatim (no fingerprint appended — explicit settings are obeyed);
    the default path gains a host-fingerprint subdirectory so stale
    cross-host executables can never SIGILL a capture run (see
    :func:`host_cache_fingerprint`).  jax.config.update is just the
    explicit (import-order-proof) way to apply the same setting."""
    import jax

    if path is None:
        path = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache",
            host_cache_fingerprint())
    jax.config.update("jax_compilation_cache_dir", path)
    if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    # Compile observability (utils/costs.py): every entry point that
    # enables the cache also counts its hits/misses, so bench.py and
    # the cost report can attribute "fast because warm" vs "fast,
    # period" — installed here (before the first compile) rather than
    # per caller.
    from attacking_federate_learning_tpu.utils.costs import (
        install_cache_counters
    )

    install_cache_counters()


def ensure_live_backend(probe_timeout=240):
    """Guard against a dead TPU tunnel; must run before jax init."""
    if os.environ.get("_BENCH_BACKEND_CHECKED"):
        return
    if (os.environ.get("PALLAS_AXON_POOL_IPS")
            and not relay_ports_listening_retry(timeout=2.0)):
        # Retry-with-backoff: a slow-but-alive relay must not be
        # misclassified as dead at the one probe that decides the
        # backend for the whole run.
        _fallback_to_cpu("TPU relay ports closed (3 probes)")
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=probe_timeout, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        os.environ["_BENCH_BACKEND_CHECKED"] = "1"
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        _fallback_to_cpu("TPU backend unreachable")
