"""Backend liveness watchdog for driver entry points.

On this image a relay process brokers the TPU; when it is dead, jax
backend initialization blocks forever in a connect-retry loop
(CLAUDE.md).  Entry points that must always complete (bench.py, the
benchmarks runner) call :func:`ensure_live_backend` before importing jax
for real: a ~2 s port probe short-circuits the plainly-dead case, a
subprocess probe catches the subtler ones, and either failure re-execs
the process pinned to CPU.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys


def relay_ports_listening(ports=(8082, 8083, 8087), timeout=2.0):
    """Fast liveness check for the TPU relay's local ports."""
    for port in ports:
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=timeout):
                return True
        except OSError:
            continue
    return False


def _fallback_to_cpu(reason: str):
    print(reason + "; falling back to CPU", file=sys.stderr, flush=True)
    os.environ.update(_BENCH_BACKEND_CHECKED="1", JAX_PLATFORMS="cpu",
                      PALLAS_AXON_POOL_IPS="")
    os.execve(sys.executable, [sys.executable] + sys.argv, os.environ)


def ensure_live_backend(probe_timeout=240):
    """Guard against a dead TPU tunnel; must run before jax init."""
    if os.environ.get("_BENCH_BACKEND_CHECKED"):
        return
    if (os.environ.get("PALLAS_AXON_POOL_IPS")
            and not relay_ports_listening()):
        _fallback_to_cpu("TPU relay ports closed")
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=probe_timeout, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        os.environ["_BENCH_BACKEND_CHECKED"] = "1"
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        _fallback_to_cpu("TPU backend unreachable")
