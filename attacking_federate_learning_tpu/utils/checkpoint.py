"""Checkpoint / resume.

The reference is save-only: ``torch.save({'epoch','state_dict','acc'})`` to
``runs/<dataset>/checkpoint.pth.tar`` whenever accuracy exceeds 70%, always
overwriting, and the momentum velocity is not saved so even a hand-written
resume would be inexact (reference server.py:40-48, main.py:84-89;
SURVEY.md §5).  This module checkpoints the *complete* server state —
weights, velocity, round — plus accuracy and the config, and restores it
exactly: ``resume()`` returns a ServerState that continues the run
bit-for-bit (tests/test_checkpoint.py::test_resume_continues_bit_for_bit).

Format: a single .npz + a JSON sidecar, portable and dependency-free; the
flat weight vector inside is wire-format compatible with the reference.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from attacking_federate_learning_tpu.core.server import ServerState


class Checkpointer:
    def __init__(self, cfg, run_dir: Optional[str] = None,
                 keep_best: bool = True):
        # Directory schema mirrors the reference: runs/<dataset>/
        # (server.py:42).
        self.dir = run_dir or os.path.join(cfg.run_dir, cfg.dataset)
        os.makedirs(self.dir, exist_ok=True)
        self.cfg = cfg
        self.keep_best = keep_best
        self.best_acc = -1.0

    @property
    def path(self) -> str:
        return os.path.join(self.dir, "checkpoint.npz")

    def save(self, state: ServerState, accuracy: float, tag: str = None):
        if self.keep_best and tag is None and accuracy < self.best_acc:
            # Don't let a later, worse state overwrite the best checkpoint
            # (the reference always overwrites, server.py:40-48).
            return self.path
        path = (os.path.join(self.dir, f"checkpoint-{tag}.npz")
                if tag else self.path)
        np.savez(path,
                 weights=np.asarray(state.weights),
                 velocity=np.asarray(state.velocity),
                 round=np.asarray(state.round),
                 accuracy=np.float32(accuracy))
        with open(path.replace(".npz", ".json"), "w") as f:
            json.dump({"accuracy": float(accuracy),
                       "round": int(state.round),
                       "config": dataclasses.asdict(self.cfg)}, f, indent=1,
                      default=str)
        if self.keep_best and accuracy > self.best_acc:
            self.best_acc = accuracy
        return path

    def resume(self, path: Optional[str] = None) -> ServerState:
        path = path or self.path
        z = np.load(path)
        return ServerState(weights=jnp.asarray(z["weights"]),
                           velocity=jnp.asarray(z["velocity"]),
                           round=jnp.asarray(z["round"]))

    def exists(self) -> bool:
        return os.path.exists(self.path)
