"""Checkpoint / resume.

The reference is save-only: ``torch.save({'epoch','state_dict','acc'})`` to
``runs/<dataset>/checkpoint.pth.tar`` whenever accuracy exceeds 70%, always
overwriting, and the momentum velocity is not saved so even a hand-written
resume would be inexact (reference server.py:40-48, main.py:84-89;
SURVEY.md §5).  This module checkpoints the *complete* server state —
weights, velocity, round — plus accuracy and the config, and restores it
exactly: ``resume()`` returns a ServerState that continues the run
bit-for-bit (tests/test_checkpoint.py::test_resume_continues_bit_for_bit).

Format: a single .npz + a JSON sidecar, portable and dependency-free; the
flat weight vector inside is wire-format compatible with the reference.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from attacking_federate_learning_tpu.core.server import ServerState


class Checkpointer:
    """Best-accuracy checkpoint (the reference behavior) plus rotated
    periodic auto-checkpoints (``checkpoint-auto-<round>.npz``) for the
    engine's fault-recovery path (core/engine.py).

    Every write is ATOMIC: the .npz and its .json sidecar land in a
    temp file in the same directory and ``os.replace`` into place, so a
    crash (or the SIGKILL the resume tests simulate) can never leave a
    torn checkpoint behind.  Auto-checkpoints rotate (``keep_last``),
    so an aggressive cadence can't fill ``runs/``.

    ``extra``: a dict of named arrays saved alongside the server state
    — the engine checkpoints its fault-injection state (the straggler
    ring buffer) here so a resumed faulted run continues bit-for-bit.

    ``auto_dir``: where the rotated auto-checkpoints live.  Default is
    the best-checkpoint dir itself (the pre-PR-5 shared layout);
    journaled runs pass their own ``runs/<run_id>/`` so two runs over
    the same dataset can no longer adopt each other's resume points
    (the collision PR 4's supervisor had to gate on run-id progress).
    The best-accuracy ``checkpoint.npz`` stays in ``runs/<dataset>/``
    — that path is reference behavior (server.py:42).  Back-compat
    reader: when the private auto dir has no autos yet, ``latest()``
    falls back to autos in the legacy shared dir (pre-migration
    artifacts; the registry refresh migrates the manifest-referenced
    one on first sight, utils/registry.py).
    """

    _AUTO_PREFIX = "checkpoint-auto-"

    def __init__(self, cfg, run_dir: Optional[str] = None,
                 keep_best: bool = True, keep_last: int = 3,
                 auto_dir: Optional[str] = None):
        # Directory schema mirrors the reference: runs/<dataset>/
        # (server.py:42).
        self.dir = run_dir or os.path.join(cfg.run_dir, cfg.dataset)
        self.auto_dir = auto_dir or self.dir
        os.makedirs(self.dir, exist_ok=True)
        os.makedirs(self.auto_dir, exist_ok=True)
        self.cfg = cfg
        self.keep_best = keep_best
        self.keep_last = max(1, int(keep_last))
        self.best_acc = -1.0

    @property
    def path(self) -> str:
        return os.path.join(self.dir, "checkpoint.npz")

    def _write_atomic(self, path: str, arrays: dict, meta: dict):
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        jpath = path.replace(".npz", ".json")
        jtmp = jpath + ".tmp"
        with open(jtmp, "w") as f:
            json.dump(meta, f, indent=1, default=str)
        os.replace(jtmp, jpath)

    def save(self, state: ServerState, accuracy: float, tag: str = None,
             extra: Optional[dict] = None):
        if self.keep_best and tag is None and accuracy < self.best_acc:
            # Don't let a later, worse state overwrite the best checkpoint
            # (the reference always overwrites, server.py:40-48).
            return self.path
        path = (os.path.join(self.auto_dir, f"checkpoint-{tag}.npz")
                if tag else self.path)
        arrays = dict(weights=np.asarray(state.weights),
                      velocity=np.asarray(state.velocity),
                      round=np.asarray(state.round),
                      accuracy=np.float32(accuracy))
        for k, v in (extra or {}).items():
            arrays[f"extra_{k}"] = np.asarray(v)
        self._write_atomic(path, arrays,
                           {"accuracy": float(accuracy),
                            "round": int(state.round),
                            "config": dataclasses.asdict(self.cfg)})
        if self.keep_best and tag is None and accuracy > self.best_acc:
            self.best_acc = accuracy
        return path

    # --- periodic / on-failure auto-checkpoints ------------------------
    def save_auto(self, state: ServerState, extra: Optional[dict] = None):
        """Rotated auto-checkpoint at the state's current round; the
        rollback target for the divergence watchdog and the --resume
        target after a kill.  Accuracy is recorded as -1 (unknown at a
        round boundary) so keep_best seeding never mistakes an auto
        save for a best save."""
        path = self.save(state, accuracy=-1.0,
                         tag=f"auto-{int(state.round):08d}", extra=extra)
        self._rotate()
        return path

    def _auto_paths(self) -> list:
        names = sorted(n for n in os.listdir(self.auto_dir)
                       if n.startswith(self._AUTO_PREFIX)
                       and n.endswith(".npz"))
        return [os.path.join(self.auto_dir, n) for n in names]

    def _legacy_auto_paths(self) -> list:
        """Autos still sitting in the shared legacy dir (pre-PR-5
        layout, pre-migration) — resume candidates only when the
        private auto dir has none, and never rotation victims (another
        run may still own them)."""
        if os.path.abspath(self.auto_dir) == os.path.abspath(self.dir):
            return []
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith(self._AUTO_PREFIX)
                           and n.endswith(".npz"))
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def _rotate(self):
        for p in self._auto_paths()[: -self.keep_last]:
            for victim in (p, p.replace(".npz", ".json")):
                try:
                    os.remove(victim)
                except OSError:
                    pass

    def latest_auto(self) -> Optional[str]:
        autos = self._auto_paths()
        return autos[-1] if autos else None

    def latest(self) -> Optional[str]:
        """Newest checkpoint by saved round — auto saves and the best
        save compete, so ``--resume`` (no path) continues from wherever
        the run actually got to."""
        candidates = self._auto_paths() or self._legacy_auto_paths()
        if os.path.exists(self.path):
            candidates = candidates + [self.path]
        best, best_round = None, -1
        for p in candidates:
            try:
                r = int(np.load(p)["round"])
            except Exception:
                continue
            if r >= best_round:
                best, best_round = p, r
        return best

    def load_best_acc(self) -> float:
        """Accuracy recorded in the best checkpoint's sidecar (or the
        .npz), for keep_best seeding after an auto-checkpoint resume."""
        if not os.path.exists(self.path):
            return -1.0
        try:
            return float(np.load(self.path)["accuracy"])
        except Exception:
            return -1.0

    def resume(self, path: Optional[str] = None, with_extra: bool = False):
        path = path or self.latest() or self.path
        z = np.load(path)
        state = ServerState(weights=jnp.asarray(z["weights"]),
                            velocity=jnp.asarray(z["velocity"]),
                            round=jnp.asarray(z["round"]))
        if not with_extra:
            return state
        extra = {k[len("extra_"):]: z[k] for k in z.files
                 if k.startswith("extra_")}
        return state, extra

    def exists(self) -> bool:
        return os.path.exists(self.path)


# Torch buffer entries that appear in a state_dict but not in
# ``.parameters()`` — the reference wire format is parameters-only
# (reference user.py:17-28), so they are excluded on import.
_TORCH_BUFFER_SUFFIXES = ("running_mean", "running_var",
                          "num_batches_tracked")


def import_reference_checkpoint(path: str, expected_dim: Optional[int] =
                                None):
    """One-way importer for a reference-produced checkpoint.

    The reference saves ``torch.save({'epoch','state_dict','acc'})`` to
    ``runs/<dataset>/checkpoint.pth.tar`` (reference server.py:40-48).
    This reads that file (or a bare state_dict) and flattens the
    parameters in registration order — identical to the reference's
    ``flatten_params`` over ``.parameters()`` (user.py:17-18) — so runs
    can be cross-validated against reference-produced weights.

    Returns ``(ServerState, accuracy)``.  The velocity is zero: the
    reference never checkpoints it (server.py:36 excluded; SURVEY.md §5),
    so a resume from a reference checkpoint is inexact by construction —
    exactly as inexact as resuming the reference itself would be.
    """
    import torch

    blob = torch.load(path, map_location="cpu", weights_only=False)
    if isinstance(blob, dict) and "state_dict" in blob:
        state_dict, epoch = blob["state_dict"], int(blob.get("epoch", 0))
        acc = float(blob.get("acc", 0.0))
    else:
        state_dict, epoch, acc = blob, 0, 0.0
    chunks = [np.asarray(v.detach().cpu().numpy(), np.float32).ravel()
              for k, v in state_dict.items()
              if not k.endswith(_TORCH_BUFFER_SUFFIXES)]
    flat = np.concatenate(chunks)
    if expected_dim is not None and flat.size != expected_dim:
        raise ValueError(
            f"reference checkpoint has {flat.size} parameters, "
            f"model expects {expected_dim}")
    state = ServerState(weights=jnp.asarray(flat),
                        velocity=jnp.zeros(flat.size, jnp.float32),
                        round=jnp.asarray(epoch, jnp.int32))
    return state, acc
