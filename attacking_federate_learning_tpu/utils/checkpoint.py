"""Checkpoint / resume.

The reference is save-only: ``torch.save({'epoch','state_dict','acc'})`` to
``runs/<dataset>/checkpoint.pth.tar`` whenever accuracy exceeds 70%, always
overwriting, and the momentum velocity is not saved so even a hand-written
resume would be inexact (reference server.py:40-48, main.py:84-89;
SURVEY.md §5).  This module checkpoints the *complete* server state —
weights, velocity, round — plus accuracy and the config, and restores it
exactly: ``resume()`` returns a ServerState that continues the run
bit-for-bit (tests/test_checkpoint.py::test_resume_continues_bit_for_bit).

Format: a single .npz + a JSON sidecar, portable and dependency-free; the
flat weight vector inside is wire-format compatible with the reference.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from attacking_federate_learning_tpu.core.server import ServerState


class Checkpointer:
    def __init__(self, cfg, run_dir: Optional[str] = None,
                 keep_best: bool = True):
        # Directory schema mirrors the reference: runs/<dataset>/
        # (server.py:42).
        self.dir = run_dir or os.path.join(cfg.run_dir, cfg.dataset)
        os.makedirs(self.dir, exist_ok=True)
        self.cfg = cfg
        self.keep_best = keep_best
        self.best_acc = -1.0

    @property
    def path(self) -> str:
        return os.path.join(self.dir, "checkpoint.npz")

    def save(self, state: ServerState, accuracy: float, tag: str = None):
        if self.keep_best and tag is None and accuracy < self.best_acc:
            # Don't let a later, worse state overwrite the best checkpoint
            # (the reference always overwrites, server.py:40-48).
            return self.path
        path = (os.path.join(self.dir, f"checkpoint-{tag}.npz")
                if tag else self.path)
        np.savez(path,
                 weights=np.asarray(state.weights),
                 velocity=np.asarray(state.velocity),
                 round=np.asarray(state.round),
                 accuracy=np.float32(accuracy))
        with open(path.replace(".npz", ".json"), "w") as f:
            json.dump({"accuracy": float(accuracy),
                       "round": int(state.round),
                       "config": dataclasses.asdict(self.cfg)}, f, indent=1,
                      default=str)
        if self.keep_best and accuracy > self.best_acc:
            self.best_acc = accuracy
        return path

    def resume(self, path: Optional[str] = None) -> ServerState:
        path = path or self.path
        z = np.load(path)
        return ServerState(weights=jnp.asarray(z["weights"]),
                           velocity=jnp.asarray(z["velocity"]),
                           round=jnp.asarray(z["round"]))

    def exists(self) -> bool:
        return os.path.exists(self.path)


# Torch buffer entries that appear in a state_dict but not in
# ``.parameters()`` — the reference wire format is parameters-only
# (reference user.py:17-28), so they are excluded on import.
_TORCH_BUFFER_SUFFIXES = ("running_mean", "running_var",
                          "num_batches_tracked")


def import_reference_checkpoint(path: str, expected_dim: Optional[int] =
                                None):
    """One-way importer for a reference-produced checkpoint.

    The reference saves ``torch.save({'epoch','state_dict','acc'})`` to
    ``runs/<dataset>/checkpoint.pth.tar`` (reference server.py:40-48).
    This reads that file (or a bare state_dict) and flattens the
    parameters in registration order — identical to the reference's
    ``flatten_params`` over ``.parameters()`` (user.py:17-18) — so runs
    can be cross-validated against reference-produced weights.

    Returns ``(ServerState, accuracy)``.  The velocity is zero: the
    reference never checkpoints it (server.py:36 excluded; SURVEY.md §5),
    so a resume from a reference checkpoint is inexact by construction —
    exactly as inexact as resuming the reference itself would be.
    """
    import torch

    blob = torch.load(path, map_location="cpu", weights_only=False)
    if isinstance(blob, dict) and "state_dict" in blob:
        state_dict, epoch = blob["state_dict"], int(blob.get("epoch", 0))
        acc = float(blob.get("acc", 0.0))
    else:
        state_dict, epoch, acc = blob, 0, 0.0
    chunks = [np.asarray(v.detach().cpu().numpy(), np.float32).ravel()
              for k, v in state_dict.items()
              if not k.endswith(_TORCH_BUFFER_SUFFIXES)]
    flat = np.concatenate(chunks)
    if expected_dim is not None and flat.size != expected_dim:
        raise ValueError(
            f"reference checkpoint has {flat.size} parameters, "
            f"model expects {expected_dim}")
    state = ServerState(weights=jnp.asarray(flat),
                        velocity=jnp.zeros(flat.size, jnp.float32),
                        round=jnp.asarray(epoch, jnp.int32))
    return state, acc
