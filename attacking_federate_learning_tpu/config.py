"""Experiment configuration.

One dataclass surfaces every knob of the reference, including the constants
hardcoded after argparse in reference main.py:138-149 (momentum 0.9,
mal_epochs 5, alpha 4, per-dataset fading_rate) and defaults buried in
signatures (reference main.py:12 batch_size=83 vs CLI default 128;
backdoor.py:14 BackdoorAttack(batch_size=200, learning_rate=0.1)).

Reference-behavior parity quirks (SURVEY.md §2.4) are explicit flags with the
reference behavior as the default, so a run is reproducible against the
reference while the paper-faithful behavior stays one flag away.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


MNIST = "MNIST"
CIFAR10 = "CIFAR10"
CIFAR100 = "CIFAR100"
SYNTH_MNIST = "SYNTH_MNIST"      # MNIST-shaped deterministic synthetic data
SYNTH_CIFAR10 = "SYNTH_CIFAR10"  # CIFAR10-shaped deterministic synthetic data
SYNTH_MNIST_HARD = "SYNTH_MNIST_HARD"  # low-SNR variant for behavioral tests
SYNTH_CIFAR10_HARD = "SYNTH_CIFAR10_HARD"  # low-SNR CIFAR-shaped variant

# Per-dataset LR fading constants, reference main.py:144-149.
FADING_RATES = {CIFAR10: 2000.0, MNIST: 10000.0, CIFAR100: 1500.0,
                SYNTH_MNIST: 10000.0, SYNTH_CIFAR10: 2000.0,
                SYNTH_MNIST_HARD: 10000.0, SYNTH_CIFAR10_HARD: 2000.0}


@dataclasses.dataclass
class FaultConfig:
    """Deterministic client-side fault model (core/faults.py).

    Every rate is a per-client, per-round probability drawn from a PRNG
    keyed on ``(seed, round)`` — the schedule is a pure function of the
    config, so two runs (or a run and its resumed half) inject byte-
    identical faults, and a host-side replay of the draw reproduces the
    exact injected counts (tools/fault_matrix.py validates emitted
    'fault' events against that replay).

    Fault kinds (applied to the SUBMITTED update matrix, after the
    attack seam — the attack owns rows [0, f); corruption is restricted
    to honest rows so the two threat models never alias):

    - ``dropout``: the client returns no update this round.  Its row is
      zeroed and excluded from aggregation via the quarantine mask.
    - ``straggler``: the client submits the gradient it computed
      ``straggler_delay`` rounds ago (carried in a fixed-shape ring
      buffer inside the fused round program).  Stale updates are valid
      — they are aggregated, not quarantined.
    - ``corrupt``: an honest client's row is damaged in flight —
      ``'nan'``/``'inf'`` make it non-finite (caught and quarantined
      pre-aggregation), ``'scale'`` multiplies it by ``corrupt_scale``
      (finite garbage: what the robust aggregation itself — or, failing
      that, the divergence watchdog — must absorb).
    - ``shard_dropout``: the correlated shard-DOMAIN axis
      (hierarchical aggregation only): each megabatch/device domain
      draws a per-round death onset with this probability and stays
      dead for ``shard_dropout_dwell`` consecutive rounds — a whole
      megabatch vanishes at once (rack/device loss), its tier-1
      estimate is excluded from tier-2 through the ``alive_counts``
      seam, and the tier-2 defense-validity watchdog degrades through
      the remask → bounds-valid-fallback → hold ladder
      (core/population.py ordering) when too few shards survive.

    The watchdog fields govern server-side graceful degradation
    (core/engine.py): at span boundaries a non-finite or norm-exploded
    server state triggers a rollback to the last good auto-checkpoint
    (cfg.checkpoint_every) instead of an abort, at most
    ``max_rollbacks`` times.
    """

    dropout: float = 0.0
    straggler: float = 0.0
    corrupt: float = 0.0
    shard_dropout: float = 0.0   # correlated shard-domain death rate
    shard_dropout_dwell: int = 1  # rounds a dead domain stays dead
    straggler_delay: int = 1     # rounds of staleness (ring-buffer depth)
    corrupt_mode: str = "nan"    # 'nan' | 'inf' | 'scale'
    corrupt_scale: float = 1e30  # multiplier for corrupt_mode='scale'
    watchdog: bool = True        # divergence watchdog + rollback
    watchdog_norm: float = 1e8   # ||weights|| explosion threshold
    max_rollbacks: int = 3       # rollback attempts before aborting
    seed: Optional[int] = None   # None -> derived from the experiment seed

    def __post_init__(self):
        for name in ("dropout", "straggler", "corrupt", "shard_dropout"):
            v = getattr(self, name)
            if not (0.0 <= v < 1.0):
                raise ValueError(
                    f"fault {name} rate must be in [0, 1), got {v}")
        if self.shard_dropout_dwell < 1:
            raise ValueError(
                f"shard_dropout_dwell must be >= 1, got "
                f"{self.shard_dropout_dwell}")
        if self.straggler_delay < 1:
            raise ValueError(
                f"straggler_delay must be >= 1, got {self.straggler_delay}")
        if self.corrupt_mode not in ("nan", "inf", "scale"):
            raise ValueError(
                f"corrupt_mode must be 'nan', 'inf' or 'scale', "
                f"got {self.corrupt_mode!r}")
        if self.watchdog_norm <= 0:
            raise ValueError(
                f"watchdog_norm must be > 0, got {self.watchdog_norm}")
        if self.max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {self.max_rollbacks}")

    @property
    def enabled(self) -> bool:
        return (self.dropout > 0 or self.straggler > 0
                or self.corrupt > 0 or self.shard_dropout > 0)


@dataclasses.dataclass
class TrafficConfig:
    """Population & traffic model (core/population.py).

    ``population`` > 0 turns the subsystem on: each round's cohort is
    sampled from a registry of P clients whose per-client persistent
    state (data-shard archetype, femnist-style transform, reliability,
    churn dwell, latency profile) is derived lazily from counter-based
    PRNG streams — never materialized as a (P,)-sized tensor.  The
    arrival process is a diurnal-modulated base rate with per-client
    blockwise on/off churn (correlated dropout episodes of ~churn_dwell
    rounds) and heavy-tail (Pareto ``latency_tail``) straggler
    latencies feeding the async delivery ring.  The schedule is a pure
    function of ``(TrafficConfig, seed, round)``: replayable on host
    (population.replay_traffic), resume-exact with no carried state.

    The sybil burst window makes participation an attack axis: with
    ``sybil_burst_period`` > 0 colluders arrive only in the first
    ``sybil_burst_width`` rounds of each period, boosted by
    period/width so the AVERAGE arrived-colluder mass matches the
    uniform profile (fixed average f).

    Robustness half: when churn under-fills a round, the
    defense-validity watchdog degrades through a declared ladder —
    re-mask the configured defense to the arrived sub-cohort while its
    bound holds (Krum m_eff >= 2f+3, Bulyan >= 4f+3), else run
    ``fallback_defense``, else hold the round as a no-op — each
    decision a versioned 'traffic' event (schema v11), never a crash
    or a silent invalid aggregate.
    """

    population: int = 0          # P registered clients; 0 = disabled
    rate: float = 0.9            # base per-round arrival probability scale
    diurnal_amp: float = 0.0     # rate modulation amplitude in [0, 1]
    diurnal_period: int = 24     # rounds per diurnal cycle
    reliability_lo: float = 0.6  # per-client reliability spread
    reliability_hi: float = 0.95
    churn_dwell: int = 4         # mean on/off episode length (rounds)
    latency_scale: float = 1.0   # async delay scale (rounds)
    latency_tail: float = 1.5    # Pareto tail index (smaller = heavier)
    sybil_burst_period: int = 0  # 0 = colluders arrive like honest clients
    sybil_burst_width: int = 1   # rounds of each period colluders arrive in
    fallback_defense: str = "Median"  # ladder step 2 kernel
    min_cohort: int = 1          # hold below this many arrivals regardless
    seed: Optional[int] = None   # None -> derived from the experiment seed

    def __post_init__(self):
        if self.population < 0:
            raise ValueError(
                f"traffic population must be >= 0, got {self.population}")
        if self.rate <= 0:
            raise ValueError(f"traffic rate must be > 0, got {self.rate}")
        if not (0.0 <= self.diurnal_amp <= 1.0):
            raise ValueError(
                f"diurnal_amp must be in [0, 1], got {self.diurnal_amp}")
        if self.diurnal_period < 1:
            raise ValueError(
                f"diurnal_period must be >= 1, got {self.diurnal_period}")
        if not (0.0 < self.reliability_lo <= self.reliability_hi <= 1.0):
            raise ValueError(
                f"need 0 < reliability_lo <= reliability_hi <= 1, got "
                f"{self.reliability_lo}/{self.reliability_hi}")
        if self.churn_dwell < 1:
            raise ValueError(
                f"churn_dwell must be >= 1, got {self.churn_dwell}")
        if self.latency_scale <= 0 or self.latency_tail <= 0:
            raise ValueError(
                f"latency_scale and latency_tail must be > 0, got "
                f"{self.latency_scale}/{self.latency_tail}")
        if self.sybil_burst_period < 0:
            raise ValueError(
                f"sybil_burst_period must be >= 0, got "
                f"{self.sybil_burst_period}")
        if self.sybil_burst_period > 0 and not (
                1 <= self.sybil_burst_width <= self.sybil_burst_period):
            raise ValueError(
                f"sybil_burst_width must be in [1, period="
                f"{self.sybil_burst_period}], got {self.sybil_burst_width}")
        if self.fallback_defense not in ("Median", "TrimmedMean",
                                         "NoDefense"):
            raise ValueError(
                f"fallback_defense must be 'Median', 'TrimmedMean' or "
                f"'NoDefense' (the bounds-valid ladder kernels), got "
                f"{self.fallback_defense!r}")
        if self.min_cohort < 1:
            raise ValueError(
                f"min_cohort must be >= 1, got {self.min_cohort}")

    @property
    def enabled(self) -> bool:
        return self.population > 0


@dataclasses.dataclass
class ExperimentConfig:
    # --- topology -------------------------------------------------------
    users_count: int = 10            # reference main.py:118
    mal_prop: float = 0.24           # reference main.py:106
    dataset: str = MNIST             # reference main.py:114
    model: Optional[str] = None      # default: dataset's canonical model

    # --- optimization ---------------------------------------------------
    learning_rate: float = 0.1       # server base lr, reference main.py:127
    fading_rate: Optional[float] = None  # None -> FADING_RATES[dataset]
    momentum: float = 0.9            # reference main.py:138
    batch_size: int = 128            # reference main.py:121
    epochs: int = 300                # rounds, reference main.py:124
    # FedAvg-style local SGD steps per round (beyond-reference; the
    # reference is strictly FedSGD — its client optimizer never steps,
    # user.py:80).  k > 1 clients run k local steps at the faded lr and
    # report (w0 - w_k) divided by the lr the SERVER will multiply back
    # in, so the FedAvg-as-FedSGD reduction is exact
    # (core/client.py:make_client_update_fn).
    local_steps: int = 1

    # --- attack ---------------------------------------------------------
    # ALIE z, reference main.py:109.  'auto' (beyond-reference) resolves
    # at construction to the ALIE paper's z_max via attacks/alie.py:
    # paper_z(n, f), so every consumer (and the CSV name schema) sees
    # the numeric value.
    num_std: "float | str" = 1.5
    backdoor: object = False         # False | 'pattern' | int sample index
    alpha: float = 4.0               # anchor-loss weight, reference main.py:142
    mal_epochs: int = 5              # shadow-net epochs, reference main.py:139
    mal_batch_size: int = 200        # reference backdoor.py:14
    mal_learning_rate: float = 0.1   # shadow SGD lr, reference backdoor.py:132
    mal_weight_decay: float = 1e-4   # reference backdoor.py:132
    # (the reference's shadow-SGD momentum is inert — fresh optimizer per
    # batch, backdoor.py:132 — so it is not a knob here)
    # Fuse the (pure, jitted) shadow-train + clip pipeline into the round
    # program so backdoor rounds run without a per-round host hop; False
    # restores the staged path with the reference's per-round nan guard
    # (backdoor.py:145-152) — fused mode tracks an in-program isnan flag
    # over the crafted rows, raised at the next host boundary.
    backdoor_fused: bool = True

    # --- defense --------------------------------------------------------
    defense: str = "NoDefense"       # reference main.py:112

    # --- hierarchical (two-tier) aggregation ----------------------------
    # 'flat' (the default) is the reference path: one (n, d) gradient
    # matrix, one defense call.  'hierarchical' streams the client axis
    # through lax.scan megabatches of static size `megabatch` (m ≪ n):
    # per-megabatch tier-1 robust estimates (the same mask-aware kernels,
    # `defense` above), then a tier-2 robust reduction over the (n/m, d)
    # estimate matrix (defenses/kernels.py shard_* entries) — the full
    # (n, d) and (n, n) arrays never exist (ops/federated.py;
    # ARCHITECTURE.md "Hierarchical aggregation").  The flat path's
    # compiled HLO is byte-identical with these knobs at any value
    # (tests/test_hierarchy.py pins it).
    aggregation: str = "flat"        # 'flat' | 'hierarchical' | 'async'
    # Megabatch (tier-1 shard) size m; must divide users_count with at
    # least 2 shards.  Peak round memory scales with m·d, not n·d.
    megabatch: int = 0
    # Tier-2 reducer over shard estimates; None = same family as
    # `defense`.  Restricted to the mask-aware kernel set.
    tier2_defense: Optional[str] = None
    # Colluder placement across megabatches — a genuine Byzantine
    # surface, not an implementation detail (ops/federated.py):
    # 'spread' deals the malicious ids [0, f) round-robin over shards,
    # 'concentrated' packs them into the fewest shards.
    mal_placement: str = "spread"
    # Assumed corrupted bounds per tier; None derives the spread-worst-
    # case defaults ceil(f/S) and ceil(f/m) (ops/federated.py
    # tier1_assumed/tier2_assumed).  Explicit values let experiments
    # probe mismatched-assumption regimes (and keep Bulyan's
    # 4f+3 validity satisfiable at small shard counts).
    tier1_corrupted: Optional[int] = None
    tier2_corrupted: Optional[int] = None

    # --- asynchronous buffered rounds (core/async_rounds.py) ------------
    # 'async' is the third engine topology: every client still computes
    # a fresh update each round, but it ARRIVES a PRNG-drawn number of
    # rounds later; the server consumes the first `async_buffer`
    # pending arrivals per round FIFO (FedBuff-style), weighting each
    # delivered row's contribution by its staleness through the
    # mask-aware kernels' `weights=` seam.  All three knobs are inert
    # (ignored, like `megabatch` under flat) unless
    # aggregation='async'; the flat/hierarchical HLO is byte-identical
    # at any value (tests/test_async.py pins it).
    # k: pending updates aggregated per round (FIFO; required >= 1
    # under aggregation='async').
    async_buffer: int = 0
    # Eviction bound: a pending update older than this many rounds is
    # discarded (masked), never aggregated; arrival delays draw
    # uniformly from [0, max_staleness] (ring depth = max_staleness+1).
    async_max_staleness: int = 2
    # Contribution discount by staleness s (core/async_rounds.py):
    # 'none' = 1 (pure first-k), 'poly' = 1/sqrt(1+s) (the FedBuff
    # paper's discount), 'const' = 0.5 for any stale row.
    staleness_weight: str = "none"

    # --- evaluation / io ------------------------------------------------
    test_step: int = 5               # reference main.py:58
    # Measured-walls observatory (utils/walls.py): 0 = off; K > 0 times
    # every span/eval on the host clock at the existing eval-boundary
    # fetch (schema-v10 'wall' events, source='host') and captures one
    # profiler trace per K eval intervals, booked onto the stage
    # taxonomy (source='trace').  Capture is CPU-safe / TPU-gated
    # (utils/profiling.py:device_trace); the compiled round programs
    # are pinned byte-identical with this on or off.
    profile_every: int = 0
    checkpoint_acc_threshold: float = 70.0  # reference main.py:84
    output: Optional[str] = None     # tee file, reference main.py:13-18
    log_dir: str = "logs"
    run_dir: str = "runs"
    data_dir: str = "data"           # raw MNIST idx / CIFAR pickle location

    # --- determinism ----------------------------------------------------
    # The reference seeds only the metadata split (random_state=42,
    # user.py:65); everything else (init, shard permutation) is implicit.
    # Here every random choice flows from this seed (SURVEY.md §2.4 #13).
    seed: int = 0

    # --- synthetic dataset sizing (SYNTH_* / air-gapped fallbacks) ------
    # Part of the config (not a CLI side-channel) so checkpoints record
    # them and --resume rebuilds the identical dataset.
    synth_train: int = 10000
    synth_test: int = 2000

    # --- data partition -------------------------------------------------
    # 'iid' (DistributedSampler-equivalent, reference user.py:49-54) |
    # 'dirichlet' (label skew) | 'femnist_style' (per-client affine
    # input transform over IID shards — the feature-shift axis of
    # SURVEY §7.2 M4's "FEMNIST"; data/partition.py
    # client_style_params).
    partition: str = "iid"
    dirichlet_alpha: float = 0.5
    style_strength: float = 0.25     # 'femnist_style' contrast/brightness
                                     # spread; 0 degenerates to IID

    # --- per-round client participation (beyond-reference) -------------
    # Fraction of clients sampled each round (the reference uses every
    # client every round).  Cohort sizes are STATIC — round(p*f) malicious
    # + the honest remainder — with random identities per round, so jit
    # shapes never change and the rows-[0, f_round) attack invariant
    # holds (core/engine.py:_participants).
    participation: float = 1.0

    # --- train-time augmentation ---------------------------------------
    # Reference parity: only the CIFAR100 train pipeline augments
    # (reflect-pad 4 + RandomCrop(32) + RandomHorizontalFlip, reference
    # data_sets.py:157-166); None follows that rule, True/False overrides.
    data_augment: Optional[bool] = None

    # --- backend / parallelism -----------------------------------------
    backend: str = "auto"            # 'auto' | 'cpu' | 'tpu'
    # 'device' keeps the whole training set in HBM (MNIST/CIFAR fit);
    # 'host_stream' keeps it in host RAM and double-buffers each round's
    # (n, B) batch onto the device (data/stream.py — the beyond-HBM /
    # FEMNIST-scale mode, SURVEY.md §7.3 #5).  Streaming feeds one round
    # per device program, so eval-to-eval span fusion is off in that mode.
    data_placement: str = "device"
    # host_stream pipeline tuning (data/stream.py): how many rounds of
    # batches stay in flight, and whether gather+transfer run on a
    # background thread (workers=1) so the host gather overlaps device
    # compute instead of sitting on the round path.  Defaults reproduce
    # the single-slot async-put double buffer.
    stream_prefetch: int = 1
    stream_workers: int = 0
    mesh_shape: Optional[tuple] = None  # (clients_devices, model_devices);
                                        # None -> all devices on client axis
    grad_dtype: str = "float32"      # dtype of the (n, d) gradient matrix;
                                     # 'bfloat16' halves HBM at large n
                                     # (distances still accumulate in f32)
    # jax.checkpoint the client loss: backward recomputes activations
    # instead of storing (n, B, activations) — the HBM/FLOPs trade for
    # WRN-scale models or very large cohorts (core/client.py).
    remat: bool = False

    # --- reference-parity quirk flags (SURVEY.md §2.4) ------------------
    # Server momentum step uses the *constant* base lr, not the faded lr
    # (reference server.py:89 — the faded lr reaches only the clients'
    # never-stepped optimizers and the attacker's arithmetic).
    server_uses_faded_lr: bool = False
    # Krum scores sum the n-f smallest distances (reference defences.py:26,
    # 33-34) rather than the paper's n-f-2.
    krum_paper_scoring: bool = False
    # Score evaluation strategy: 'sort' (default — oracle-verified and
    # cancellation-free under arbitrary attacker magnitudes), 'topk'
    # (complement subtraction — cheaper at large n / small f; carries a
    # runtime cancellation guard that re-evaluates via the sort path
    # whenever the subtraction would lose precision, so it is safe under
    # adversarial magnitudes too — kernels.py:_krum_scores), or 'auto'
    # (pick by shape).  The round-1 CPU bench regression attributed to
    # 'sort' was actually the XLA:CPU gemm — see distance_impl below.
    krum_scoring_method: str = "sort"
    # Distance engine for Krum/Bulyan (defenses/kernels.py):
    #   'auto'      xla inside the engine's traced round programs (a host
    #               round-trip there would cost more than it saves —
    #               core/engine.py:_wire_distance_defense); host BLAS for
    #               eager CPU-backend kernel calls (the bench fallback)
    #   'xla'       Gram matmul + epilogue (ops/distances.py)
    #   'pallas'    fused-epilogue TPU kernel (ops/pallas_distances.py)
    #   'host'      NumPy/BLAS (defenses/host.py; pure_callback in-jit)
    #   'ring'      blockwise ppermute schedule over the clients mesh axis
    #   'allgather' one all_gather + per-device tiles
    # (ring/allgather require a device mesh, parallel/distances.py).
    distance_impl: str = "auto"
    # Distance computation dtype (defenses/kernels.py:_distances_for):
    # 'bfloat16' casts the (n, d) operand for the DISTANCE computation
    # only — the Gram matmul rides the MXU at native bf16 throughput
    # (vs the multi-pass f32 HIGHEST emulation) with f32 accumulation
    # and f32 norms; training numerics are untouched.  An explicit,
    # flagged deviation for the 10k north-star regime; 'float32' (the
    # default) is reference-parity.  Ignored by the 'host' engine.
    distance_dtype: str = "float32"
    # Bulyan selection batching (defenses/kernels.py:bulyan): q>1 is an
    # explicit, flagged relaxation of the reference's strictly sequential
    # selection for the large-n regime — each trip selects the q
    # lowest-scoring clients against the same scores, re-scoring between
    # trips (ceil(set_size/q) trips instead of set_size).  1 = the
    # reference's exact semantics (the default, like every quirk flag).
    bulyan_batch_select: int = 1
    # Bulyan selection engine (defenses/kernels.py:bulyan): 'xla' (the
    # traced fixed-trip loop — reference-exact, compiles into the fused
    # round program), 'host' — the HYBRID exact path for the
    # accelerator at large n: distances stay on the MXU, the (n, n) D
    # ships to the host once for the native O(n^2) incremental
    # selection, and the gather + trimmed mean run back on the device —
    # or 'pallas': the ALL-ON-DEVICE exact route (ISSUE 11) — the
    # (n, n) D from the fused-epilogue pallas kernel feeds the same
    # traced selection loop as 'xla', so exact q=1 semantics survive
    # with NO pure_callback marshal at all.
    # Opt-in (not auto): host ties resolve by the native comparator
    # (ulp-band only — tests/test_native.py; pallas distances carry the
    # same ulp-band vs the XLA Gram), and the pure_callback
    # marshal is only worth it when set_size sequential XLA trips cost
    # more than one D transfer (the 10k north-star regime).
    bulyan_selection_impl: str = "xla"
    # Defense-kernel implementation suite (ops/pallas_defense.py):
    # 'xla' (the default — every path unchanged) or 'pallas', the
    # on-device tier-1 pipeline: Krum scores via the fused
    # distance->score kernel (no (n, n) matrix, one HBM sweep),
    # TrimmedMean/Median via the tiled per-d-block selection kernels
    # (masked/weighted seams included, so fault/async/hierarchical
    # rounds compose), Bulyan via pallas distances + the traced
    # selection loop + the pallas trim tail.  Falls back to
    # interpret=True off-TPU so CPU CI runs the same kernel bodies.
    # Composition matrix (rejected loudly below): covers the mask-aware
    # kernel family only, excludes the host kernels and the staged
    # (host-eager) backdoor seam, and needs an in-program distance
    # engine (auto/pallas).
    aggregation_impl: str = "xla"
    # Bulyan's final trimmed-mean tail: 'xla' (default, bit-stable with
    # the traced path) or 'host' (native column-blocked kernel — the
    # CPU-backend 10k opt-in; at full scale the XLA:CPU stable argsort
    # over the (n-2f, d) selection dominates the whole hybrid).  Same
    # opt-in standard and ulps caveat as trimmed_mean_impl.
    bulyan_trim_impl: str = "xla"
    # Attack statistics over the malicious cohort only (reference
    # malicious.py:14-19), matching the ALIE threat model.

    # --- beyond-reference attack/defense knobs --------------------------
    # Perturbation direction for the min-max/min-sum attacks
    # (attacks/minmax.py): cohort negative std ('std', the NDSS'21 paper's
    # best performer), -sign(mean) ('sign'), or negative unit mean ('unit').
    attack_direction: str = "std"
    # DnC spectral defense constants (defenses/dnc.py) — the most
    # constant-sensitive defense, so its knobs live in the config like
    # every other quirk flag.  Sketch keys derive from (seed, round, iter),
    # so repeat runs with different seeds draw different coordinate
    # subsets (the paper's random-subsampling assumption).
    dnc_iters: int = 5
    dnc_sketch_dim: int = 2048
    dnc_filter_frac: float = 1.5
    # GeoMedian smoothed-Weiszfeld constants (defenses/geomed.py) — same
    # config-surface standard as the DnC knobs above.
    geomed_iters: int = 10
    geomed_eps: float = 1e-6
    # CenteredClip constants (defenses/centeredclip.py, ICML'21): clip
    # radius and fixed re-centering trips.
    cclip_tau: float = 10.0
    cclip_iters: int = 5
    # Coordinate-wise kernels: 'xla' (default — keeps staged/fused
    # rounds on the same kernel, preserving bit-identity) or 'host'
    # (opt-in: the native column-blocked kernels, ~minutes -> ~25 s at
    # the 10k scale on the CPU backend; defenses/kernels.py:trimmed_mean,
    # defenses/median.py).
    trimmed_mean_impl: str = "xla"
    median_impl: str = "xla"

    # --- metadata subsystem (reference C12, vestigial there) ------------
    collect_metadata: bool = False
    metadata_fraction: float = 0.11  # reference user.py:65 test_size=0.11

    # --- faults & recovery (core/faults.py; ARCHITECTURE.md) ------------
    # None (the default) is the zero-fault reference path: the compiled
    # round program is bit-identical to the pre-fault-subsystem one.  A
    # FaultConfig (or an equivalent dict, coerced below) with any rate
    # > 0 turns on in-jit deterministic fault injection + the
    # pre-aggregation quarantine mask + the divergence watchdog.
    faults: Optional[FaultConfig] = None
    # --- population & traffic (core/population.py; ARCHITECTURE.md) -----
    # None (the default) is the resident-cohort reference path: every
    # compiled round program is bit-identical to the pre-population one.
    # A TrafficConfig (or an equivalent dict, coerced below) with
    # population > 0 samples each round's cohort from the lazy client
    # registry, injects correlated churn + the defense-validity
    # degradation ladder (flat), draws async arrival delay from the
    # latency profile (async), and resamples megabatch slots per round
    # (hierarchical).
    traffic: Optional["TrafficConfig"] = None
    # Auto-checkpoint cadence in rounds (0 = off): the engine writes a
    # rotated, atomically-replaced checkpoint-auto-<round>.npz every N
    # rounds (utils/checkpoint.py) — the rollback target for the
    # watchdog and the --resume target after a kill.
    checkpoint_every: int = 0

    # --- secure aggregation (protocols/secagg.py; ARCHITECTURE.md) ------
    # Server-visibility mode for client updates:
    #   'off'       the reference fiction — the server sees every row in
    #               the clear (byte-identical HLO to the pre-protocol
    #               engine, pinned by PERF_BASELINE + tests/test_secagg.py)
    #   'vanilla'   Bonawitz-style pairwise-masked sums inside the fused
    #               round: per-pair counter-based PRNG masks in the
    #               uint32 bitcast domain (bit-exact cancellation), the
    #               server sees only the masked wire + the recovered
    #               sum.  Robust per-client defenses CANNOT run (no
    #               rows to defend over) — NoDefense is required, and a
    #               --fault-dropout round becomes a mask-reconstruction
    #               round (simulated seed-reveal, exact sum recovery).
    #   'groupwise' NET-SA-style group-wise secagg composed with
    #               aggregation='hierarchical': each megabatch's sum is
    #               secure-aggregated (masks within the group, keyed on
    #               global client ids) and the server sees per-GROUP
    #               sums — tier-2 robust kernels (--tier2-defense) run
    #               over the (n/m, d) group-sum matrix.
    secagg: str = "off"

    # --- observability --------------------------------------------------
    # Per-round structured diagnostics (gradient-norm stats, aggregate
    # norm, faded lr) written to the JSONL log.  The reference logs only
    # eval-time accuracy (SURVEY.md §5).
    log_round_stats: bool = False
    # Aggregation forensics (utils/metrics.py event schema): defenses
    # return their fixed-shape diagnostics pytrees (Krum/Bulyan selection
    # masks + scores, trim fractions, clip counts, FLTrust trust scores;
    # defenses/kernels.py telemetry seam), attacks their envelope stats
    # (ALIE z-bounds, backdoor shadow loss; attacks/base.py
    # envelope_stats), plus per-client norms and cosine-to-mean — all
    # carried as auxiliary outputs of the jitted round, stacked across
    # rounds and fetched once per eval interval (NO host callbacks
    # inside the jit), then written as 'defense'/'attack'/
    # 'selection_hist' events.  Under aggregation='hierarchical' the
    # same flag threads the stacked per-shard tier-1 diagnostics and
    # the tier-2 shard-selection record out of the scanned round as
    # 'shard_selection' events (schema v6; read with 'report
    # forensics'); under --secagg groupwise only the tier-2
    # (group-sum-level) view appears — per-client rows are not
    # server-visible there.  Off by default: the compiled round
    # program is bit-identical to the pre-telemetry one.
    telemetry: bool = False
    # Robustness-margin observatory (utils/margins.py; ISSUE 18): the
    # defenses additionally return their DECISION MARGINS — Krum's
    # winner/runner-up gap and every row's signed distance to the
    # selection threshold, the trim kernels' per-coordinate boundary
    # distance and kept-coordinate fractions, Bulyan's per-iteration
    # selection slack — as fixed-shape fields riding the same telemetry
    # diagnostics pytree (no host callbacks in-jit), and attacks their
    # envelope utilization (attacks/base.py margin_stats).  The engine
    # rolls them up host-side into one 'margin' event per round (schema
    # v12): the colluder-survival ledger ('runs margins' renders the
    # trajectories).  Requires a margin-bearing defense (Krum /
    # TrimmedMean / Median / Bulyan) on the on-device score path —
    # host-marshalled impls never materialize the scores the margins
    # are read from.  Off by default: the compiled round program is
    # bit-identical to the margins-less one (PERF_BASELINE pins this).
    margins: bool = False
    # Numerics & determinism observatory (utils/numerics.py; ISSUE 20):
    # in-jit numeric health counters — per-stage nonfinite counts
    # (post-attack wire / post-quarantine / applied update), the
    # gradient-norm dynamic range, the distance-Gram cancellation-depth
    # estimate, and tie-proximity counters that band the PR 18 margin
    # tensors at k ulp of their decision boundary (no new O(n^2 d)
    # reductions) — emitted as one schema-v14 'numerics' event per
    # round ('runs numerics' renders the health trajectories).  Works
    # with any defense (the stage counters are defense-free); on a
    # margin-bearing defense the kernels additionally report tie_rows /
    # cancel_bits, which needs the same on-device score path --margins
    # does.  Off by default: the compiled round program is bit-identical
    # to the numerics-less one (PERF_BASELINE pins this).
    numerics: bool = False

    def __post_init__(self):
        if self.model is not None and self.model in MODEL_FAMILY:
            want = DATASET_FAMILY.get(self.dataset)
            if want is not None and MODEL_FAMILY[self.model] != want:
                raise ValueError(
                    f"model {self.model!r} expects {MODEL_FAMILY[self.model]}"
                    f"-shaped inputs but dataset {self.dataset!r} is "
                    f"{want}-shaped")
        if self.krum_scoring_method not in ("sort", "topk", "auto"):
            raise ValueError(
                f"krum_scoring_method must be 'sort', 'topk' or 'auto', "
                f"got {self.krum_scoring_method!r}")
        if self.distance_impl not in ("auto", "xla", "pallas", "host",
                                      "ring", "allgather"):
            raise ValueError(
                f"distance_impl must be one of auto/xla/pallas/host/ring/"
                f"allgather, got {self.distance_impl!r}")
        if self.distance_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"distance_dtype must be 'float32' or 'bfloat16', "
                f"got {self.distance_dtype!r}")
        if self.data_placement not in ("device", "host_stream"):
            raise ValueError(
                f"data_placement must be 'device' or 'host_stream', "
                f"got {self.data_placement!r}")
        if self.stream_prefetch < 1 or self.stream_workers not in (0, 1):
            raise ValueError(
                f"stream_prefetch must be >= 1 and stream_workers 0 or 1, "
                f"got {self.stream_prefetch}/{self.stream_workers}")
        if self.mesh_shape is not None:
            # Normalized to a tuple so a JSON campaign spec's list and
            # the CLI's tuple hash to the same run/cell identity.
            ms = tuple(self.mesh_shape)
            if len(ms) != 2 or any(
                    not isinstance(x, int) or x < 1 for x in ms):
                raise ValueError(
                    f"mesh_shape must be two positive ints "
                    f"(clients_devices, model_devices), "
                    f"got {self.mesh_shape!r}")
            self.mesh_shape = ms
        if self.bulyan_batch_select < 1:
            raise ValueError(
                f"bulyan_batch_select must be >= 1, got "
                f"{self.bulyan_batch_select}")
        if self.bulyan_selection_impl not in ("xla", "host", "pallas"):
            raise ValueError(
                f"bulyan_selection_impl must be 'xla', 'host' or "
                f"'pallas', got {self.bulyan_selection_impl!r}")
        if self.aggregation_impl not in ("xla", "pallas"):
            raise ValueError(
                f"aggregation_impl must be 'xla' or 'pallas', "
                f"got {self.aggregation_impl!r}")
        _PALLAS_KERNELS = ("Krum", "TrimmedMean", "Bulyan", "Median")
        if self.aggregation_impl == "pallas":
            # The pallas suite covers the mask-aware kernel family;
            # everything that would mix it with a host engine or pull
            # the aggregation out of the device program is rejected
            # here, loudly, with the offending flag named (the same
            # standard as the secagg/hierarchical matrices; campaign
            # cells pre-validate through this via construction).
            if self.defense not in _PALLAS_KERNELS:
                raise ValueError(
                    f"aggregation_impl='pallas' covers the Pallas "
                    f"defense-kernel suite {_PALLAS_KERNELS} "
                    f"(ops/pallas_defense.py); defense "
                    f"{self.defense!r} has no pallas kernel — drop "
                    f"--aggregation-impl pallas")
            for knob in ("trimmed_mean_impl", "median_impl",
                         "bulyan_trim_impl"):
                if getattr(self, knob) != "xla":
                    raise ValueError(
                        f"aggregation_impl='pallas' already routes the "
                        f"coordinate-wise kernels on-device; mixing it "
                        f"with {knob}={getattr(self, knob)!r} would "
                        f"dispatch two engines for one estimator "
                        f"(leave {knob}='xla')")
            if self.bulyan_selection_impl == "host":
                raise ValueError(
                    "aggregation_impl='pallas' is the no-marshal "
                    "on-device route; bulyan_selection_impl='host' "
                    "reintroduces the (n, n) pure_callback marshal — "
                    "pick one (the hybrid OR the pallas suite)")
            if self.distance_impl not in ("auto", "pallas"):
                raise ValueError(
                    f"aggregation_impl='pallas' computes distances "
                    f"inside its fused kernels; "
                    f"distance_impl={self.distance_impl!r} would "
                    f"silently not run — set distance_impl to "
                    f"'auto' or 'pallas'")
        if "pallas" in (self.aggregation_impl,
                        self.bulyan_selection_impl):
            if self.backdoor and not self.backdoor_fused:
                raise ValueError(
                    "--backdoor-staged aggregates eagerly on the host "
                    "between compute and craft; the Pallas defense "
                    "suite is a device-kernel route (and the "
                    "staged==fused bit-identity pin needs both modes "
                    "on one kernel) — drop --backdoor-staged")
            if self.bulyan_selection_impl == "pallas" and (
                    self.distance_impl in ("host", "ring", "allgather")):
                raise ValueError(
                    f"bulyan_selection_impl='pallas' selects over the "
                    f"pallas distance kernel's on-device D; "
                    f"distance_impl={self.distance_impl!r} computes D "
                    f"elsewhere — set distance_impl to 'auto', 'xla' "
                    f"or 'pallas'")
        if self.bulyan_trim_impl not in ("xla", "host"):
            raise ValueError(
                f"bulyan_trim_impl must be 'xla' or 'host', "
                f"got {self.bulyan_trim_impl!r}")
        if self.attack_direction not in ("std", "sign", "unit"):
            raise ValueError(
                f"attack_direction must be 'std', 'sign' or 'unit', "
                f"got {self.attack_direction!r}")
        if self.dnc_iters < 1 or self.dnc_sketch_dim < 1:
            raise ValueError(
                f"dnc_iters/dnc_sketch_dim must be >= 1, got "
                f"{self.dnc_iters}/{self.dnc_sketch_dim}")
        if self.dnc_filter_frac <= 0:
            raise ValueError(
                f"dnc_filter_frac must be > 0, got {self.dnc_filter_frac}")
        if self.cclip_iters < 1 or self.cclip_tau <= 0:
            raise ValueError(
                f"cclip_iters must be >= 1 and cclip_tau > 0, got "
                f"{self.cclip_iters}/{self.cclip_tau}")
        if self.geomed_iters < 1 or self.geomed_eps <= 0:
            raise ValueError(
                f"geomed_iters must be >= 1 and geomed_eps > 0, got "
                f"{self.geomed_iters}/{self.geomed_eps}")
        if self.trimmed_mean_impl not in ("xla", "host"):
            raise ValueError(
                f"trimmed_mean_impl must be 'xla' or 'host', "
                f"got {self.trimmed_mean_impl!r}")
        if self.median_impl not in ("xla", "host"):
            raise ValueError(
                f"median_impl must be 'xla' or 'host', "
                f"got {self.median_impl!r}")
        if self.aggregation not in ("flat", "hierarchical", "async"):
            raise ValueError(
                f"aggregation must be 'flat', 'hierarchical' or "
                f"'async', got {self.aggregation!r}")
        if self.staleness_weight not in ("none", "poly", "const"):
            raise ValueError(
                f"staleness_weight must be 'none', 'poly' or 'const', "
                f"got {self.staleness_weight!r}")
        if self.async_buffer < 0 or self.async_max_staleness < 0:
            raise ValueError(
                f"async_buffer/async_max_staleness must be >= 0, got "
                f"{self.async_buffer}/{self.async_max_staleness}")
        if self.aggregation == "async" and self.async_buffer < 1:
            raise ValueError(
                "--aggregation async needs --async-buffer >= 1 (k, the "
                "pending updates aggregated per round — FedBuff's "
                "buffer size; core/async_rounds.py)")
        if self.mal_placement not in ("spread", "concentrated"):
            raise ValueError(
                f"mal_placement must be 'spread' or 'concentrated', "
                f"got {self.mal_placement!r}")
        if self.megabatch < 0:
            raise ValueError(f"megabatch must be >= 0, got {self.megabatch}")
        _TIER2 = ("NoDefense", "Krum", "TrimmedMean", "Bulyan", "Median")
        if self.tier2_defense is not None and self.tier2_defense not in _TIER2:
            raise ValueError(
                f"tier2_defense must be one of {_TIER2}, "
                f"got {self.tier2_defense!r}")
        for name in ("tier1_corrupted", "tier2_corrupted"):
            v = getattr(self, name)
            if v is not None and v < 0:
                raise ValueError(f"{name} must be >= 0, got {v}")
        if self.aggregation == "hierarchical":
            if self.megabatch < 1:
                raise ValueError(
                    "hierarchical aggregation needs megabatch >= 1 "
                    "(the tier-1 shard size; --megabatch)")
            if self.users_count % self.megabatch:
                raise ValueError(
                    f"megabatch must divide users_count "
                    f"({self.users_count} % {self.megabatch} != 0)")
            if self.users_count // self.megabatch < 2:
                raise ValueError(
                    f"hierarchical aggregation needs >= 2 shards "
                    f"(n={self.users_count}, m={self.megabatch})")
        if isinstance(self.faults, dict):
            # Checkpoint-JSON round trips and kwargs-style callers hand
            # a plain dict; coerce so every consumer sees a FaultConfig.
            self.faults = FaultConfig(**self.faults)
        if isinstance(self.traffic, dict):
            # Same coercion seam as faults: journal/checkpoint JSON and
            # campaign specs hand plain dicts.
            self.traffic = TrafficConfig(**self.traffic)
        if self.checkpoint_every < 0:
            raise ValueError(
                f"checkpoint_every must be >= 0, got "
                f"{self.checkpoint_every}")
        if self.secagg not in ("off", "vanilla", "groupwise"):
            raise ValueError(
                f"--secagg must be 'off', 'vanilla' or 'groupwise', "
                f"got {self.secagg!r}")
        if self.secagg != "off":
            # Secure aggregation inverts the server's visibility: every
            # feature that reads per-client rows server-side is
            # structurally impossible and rejected here, loudly, with
            # the offending flag named (tests/test_secagg.py pins the
            # message contract).
            if self.defense != "NoDefense":
                hint = ("use --secagg groupwise with --tier2-defense to "
                        "defend over per-group sums"
                        if self.secagg == "vanilla" else
                        "move the robust kernel to --tier2-defense (it "
                        "runs over the per-group sums)")
                raise ValueError(
                    f"--secagg {self.secagg}: defense {self.defense!r} "
                    f"cannot run — the server never sees per-client "
                    f"updates, so there are no rows to defend over; "
                    f"set -d NoDefense ({hint})")
            if self.secagg == "vanilla" and self.aggregation != "flat":
                raise ValueError(
                    "--secagg vanilla masks the whole cohort into one "
                    "sum and requires --aggregation flat; use --secagg "
                    "groupwise for the hierarchical composition")
            if self.secagg == "groupwise" and self.aggregation != (
                    "hierarchical"):
                raise ValueError(
                    "--secagg groupwise exposes per-megabatch sums and "
                    "requires --aggregation hierarchical (+ --megabatch)")
            if self.telemetry and self.secagg == "vanilla":
                raise ValueError(
                    "--telemetry is server-side forensics; under "
                    "--secagg vanilla the server sees only one masked "
                    "cohort sum — there is nothing per-client OR "
                    "per-group to observe (groupwise supports "
                    "--telemetry: tier-2 selection over group sums is "
                    "server-visible)")
            if self.log_round_stats and self.secagg == "vanilla":
                raise ValueError(
                    "--round-stats reads per-client gradient norms "
                    "server-side; under --secagg vanilla the server "
                    "sees no per-client rows (groupwise supports "
                    "--round-stats over the per-group sums)")
            if self.backdoor and not self.backdoor_fused:
                raise ValueError(
                    "--backdoor-staged crafts on the host between "
                    "compute and aggregation; --secagg masks inside "
                    "the fused round program (drop --backdoor-staged)")
            if self.participation < 1.0:
                raise ValueError(
                    "--secagg requires --participation 1.0: pairwise "
                    "masks are keyed on client identity, and partial "
                    "cohorts re-key every row each round")
            if self.grad_dtype != "float32":
                raise ValueError(
                    f"--secagg masks in the uint32 bitcast domain of "
                    f"f32 wire updates; grad_dtype={self.grad_dtype!r} "
                    f"is not maskable (set grad_dtype='float32')")
            if self.faults is not None and (self.faults.straggler > 0
                                            or self.faults.corrupt > 0):
                raise ValueError(
                    "--secagg composes only with --fault-dropout / "
                    "--fault-shard-dropout (dropout is the secure-"
                    "aggregation protocol event: a mask-reconstruction "
                    "round; a dead shard domain drops its whole "
                    "group); --fault-straggler/--fault-corrupt mutate "
                    "the masked wire, which the protocol cannot model "
                    "yet")
        if self.local_steps < 1:
            raise ValueError(
                f"local_steps must be >= 1, got {self.local_steps}")
        _MARGIN_DEFENSES = ("Krum", "TrimmedMean", "Median", "Bulyan")
        if self.margins:
            # Margins are read from the ON-DEVICE score/rank tensors the
            # robust kernels already build; every config that never
            # materializes them is rejected here, loudly, with the
            # offending knob named (tests/test_margins.py pins the
            # message contract).
            if self.defense not in _MARGIN_DEFENSES:
                raise ValueError(
                    f"--margins measures a robust defense's decision "
                    f"margins; defense {self.defense!r} makes no "
                    f"selection/trim decision to measure (use one of "
                    f"{'/'.join(_MARGIN_DEFENSES)})")
        if self.margins or (self.numerics
                            and self.defense in _MARGIN_DEFENSES):
            # The numerics tie-proximity counters reuse those same
            # margin tensors (utils/numerics.py), so --numerics on a
            # margin-bearing defense shares the on-device-impl
            # requirement (on any other defense only the stage
            # counters run and no impl constraint applies).
            flag = "--margins" if self.margins else "--numerics"
            for knob in ("trimmed_mean_impl", "median_impl",
                         "bulyan_trim_impl", "distance_impl",
                         "bulyan_selection_impl"):
                if getattr(self, knob) == "host":
                    raise ValueError(
                        f"{flag} reads the on-device score/rank "
                        f"tensors inside the fused round program; "
                        f"{knob}='host' marshals that stage to a native "
                        f"kernel that returns only its aggregate, never "
                        f"the per-row margins (set {knob} to an "
                        f"on-device impl)")
        if not (0.0 < self.participation <= 1.0):
            raise ValueError(
                f"participation must be in (0, 1], got "
                f"{self.participation}")
        if self.num_std == "auto":
            from attacking_federate_learning_tpu.attacks.alie import paper_z
            self.num_std = paper_z(self.users_count, self.corrupted_count)
        elif (isinstance(self.num_std, bool)
                or not isinstance(self.num_std, (int, float))):
            # bool is an int subclass; num_std=True silently meaning
            # z=1.0 would be a config typo accepted as physics.
            raise ValueError(
                f"num_std must be a number or 'auto', got "
                f"{self.num_std!r}")
        if self.fading_rate is None:
            self.fading_rate = FADING_RATES.get(self.dataset, 10000.0)
        if self.model is None:
            self.model = default_model_for(self.dataset)
        if self.backdoor == "No":
            self.backdoor = False  # reference main.py:135-136
        elif isinstance(self.backdoor, str) and self.backdoor.isdigit():
            # reference main.py:116 leaves '1'|'2'|'3' as strings, which
            # crashes at backdoor.py:34 (str - int); we coerce instead.
            self.backdoor = int(self.backdoor)

    @property
    def corrupted_count(self) -> int:
        # reference main.py:21 / server.py:87
        return int(self.mal_prop * self.users_count)

    def csv_name(self) -> str:
        # Filename schema of reference main.py:100.
        return ("{}_stdev_{}_{}_backdoor-{}_mal_prop_{}_users_{}_alpha_{}_lr_{}"
                ".csv").format(self.dataset, self.num_std, self.defense,
                               self.backdoor, self.mal_prop, self.users_count,
                               self.alpha, self.learning_rate)


# Input-shape families for fail-fast model/dataset validation (a wrong
# pairing otherwise surfaces as a reshape error deep inside the jit trace).
MODEL_FAMILY = {"mnist_mlp": "mnist", "mnist_cnn": "mnist",
                "cifar10_cnn": "cifar", "resnet20": "cifar",
                "wideresnet40_4": "cifar"}
DATASET_FAMILY = {MNIST: "mnist", SYNTH_MNIST: "mnist",
                  SYNTH_MNIST_HARD: "mnist", CIFAR10: "cifar",
                  SYNTH_CIFAR10: "cifar", SYNTH_CIFAR10_HARD: "cifar",
                  CIFAR100: "cifar"}


def default_model_for(dataset: str) -> str:
    return {
        MNIST: "mnist_mlp", SYNTH_MNIST: "mnist_mlp",
        CIFAR10: "cifar10_cnn", SYNTH_CIFAR10: "cifar10_cnn",
        SYNTH_CIFAR10_HARD: "cifar10_cnn",
        CIFAR100: "wideresnet40_4",
    }.get(dataset, "mnist_mlp")
