"""Explicit blockwise pairwise-distance kernels over the client mesh axis.

The automatic path (ops/distances.py under pjit) lets XLA turn the Gram
matmul into a collective matmul.  These shard_map variants make the
communication schedule explicit for the 10k-client regime (SURVEY.md §5
"long-context": ring-blockwise over *clients* instead of sequence):

- ``allgather``: each device all-gathers G once and computes its
  (n/p, n) distance tile.  One collective, peak memory O(n*d) per device.
- ``ring``: each device holds only its (n/p, d) block; blocks rotate around
  the ring via ``ppermute`` while each device accumulates one
  (n/p, n/p) output tile per step.  Peak memory O(n*d/p) — the
  ring-attention-style schedule for client counts where a replicated G
  would not fit.

Both return the full (n, n) matrix sharded over rows, bitwise-matching the
single-device kernel to f32 tolerance.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from attacking_federate_learning_tpu.ops.distances import cross_sq_distances
from attacking_federate_learning_tpu.parallel.mesh import CLIENTS

# shard_map's spelling has moved across jax versions: top-level
# ``jax.shard_map`` in current releases, ``jax.experimental.shard_map``
# before that.  Resolve once at import so these kernels run on either —
# an AttributeError at call time (the old hardcoded ``jax.shard_map``)
# took every blockwise-distance test down with it.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:                                           # pragma: no cover
    from jax.experimental.shard_map import shard_map


def _pvary(x, axis):
    """Mark a scan carry device-varying where the running jax requires
    it (``lax.pvary`` in current jax, ``lax.pcast`` in the 0.9-era
    spelling); older versions have no varying-type system and take the
    carry as-is."""
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis)
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis, to="varying")
    return x


def _tile(a_blk, b_blk):
    # Shared math with the single-device kernel (incl. the bf16 f32-accum
    # policy) so blockwise results match it exactly.
    return cross_sq_distances(a_blk, b_blk)


def pairwise_distances_allgather(G, mesh, axis=CLIENTS):
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=P(axis, None), out_specs=P(axis, None))
    def block(gb):
        g_all = lax.all_gather(gb, axis, tiled=True)      # (n, d)
        return jnp.sqrt(_tile(gb, g_all))                 # (n/p, n)

    D = block(G)
    n = G.shape[0]
    return D * (1.0 - jnp.eye(n, dtype=D.dtype))


def pairwise_distances_ring(G, mesh, axis=CLIENTS):
    p = mesh.shape[axis]

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=P(axis, None), out_specs=P(axis, None))
    def block(gb):
        me = lax.axis_index(axis)
        blk = gb.shape[0]
        n = blk * p
        perm = [(i, (i + 1) % p) for i in range(p)]  # ring schedule

        def step(carry, _):
            remote, src, out = carry
            tile = jnp.sqrt(_tile(gb, remote))            # (n/p, n/p)
            out = lax.dynamic_update_slice(out, tile, (0, src * blk))
            remote = lax.ppermute(remote, axis, perm)
            # After a shift we hold the previous neighbor's block.
            src = ((src + p - 1) % p).astype(jnp.int32)
            return (remote, src, out), None

        # Varying carry: the accumulator is device-varying (holds
        # per-shard tiles); jax versions with a varying-type system
        # require the scan carry marked so (_pvary resolves the
        # spelling).  f32 always: the cross_sq_distances tiles
        # accumulate f32 even for bf16 operands
        # (distance_dtype='bfloat16'), and the carry must match the
        # tile dtype.
        out0 = _pvary(jnp.zeros((blk, n), jnp.float32), axis)
        src0 = jnp.asarray(me, jnp.int32)
        (_, _, out), _ = lax.scan(step, (gb, src0, out0), None, length=p)
        return out

    D = block(G)
    n = G.shape[0]
    return D * (1.0 - jnp.eye(n, dtype=D.dtype))
