"""Device mesh and sharding layout for the client axis.

The reference has no distributed backend at all — "broadcast" is a Python
loop handing one numpy array to N objects and "gather" is a row-copy into a
preallocated matrix (reference server.py:54-56, :81-83; SURVEY.md §2.3).
The TPU-native equivalent is a ``jax.sharding.Mesh`` with axes

    ('clients', 'model')

where the (n, d) gradient matrix is sharded ('clients', 'model'), client
batches are sharded along 'clients', and the flat weight/velocity vectors
are sharded along 'model' (replicated when the model axis is 1).  Broadcast
is then free (XLA replicates as needed over ICI) and every defense collective
(Gram matmul, sorts, psum) is inserted by the compiler from these
annotations.  Multi-host spanning over DCN falls out of
``jax.distributed.initialize`` + a global mesh; there is no transport code
to write.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENTS = "clients"
MODEL = "model"


def make_mesh(mesh_shape: Optional[tuple] = None,
              devices=None) -> Mesh:
    """Mesh over all (or the given) devices.

    ``mesh_shape=(c, m)`` splits devices between the client axis and the
    model (d-sharding) axis; default puts every device on the client axis.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if mesh_shape is None:
        mesh_shape = (n, 1)
    c, m = mesh_shape
    if c * m != n:
        raise ValueError(f"mesh_shape {mesh_shape} != {n} devices")
    return Mesh(devices.reshape(c, m), (CLIENTS, MODEL))


class MeshPlan(NamedTuple):
    """Placement/annotation bundle consumed by the engine."""
    mesh: Mesh

    def _model_axis_or_none(self, dim: int):
        # device_put requires even shards; replicate dims the model axis
        # doesn't divide (e.g. d=79510 on a 4-way model axis).
        return MODEL if dim % self.mesh.shape[MODEL] == 0 else None

    def grads_spec(self, d: int):
        return P(CLIENTS, self._model_axis_or_none(d))

    def weights_spec(self, d: int):
        return P(self._model_axis_or_none(d))

    def sharding(self, spec):
        return NamedSharding(self.mesh, spec)

    def place_state(self, state):
        """Rank-aware server-state placement: vectors (weights, velocity)
        shard over the model axis, scalars (round counter) replicate."""
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(
                leaf, self.sharding(self.weights_spec(leaf.shape[0])
                                    if leaf.ndim >= 1 else P())),
            state)

    @property
    def clients_parts(self) -> int:
        """Clients-axis device count — > 1 switches the hierarchical
        engine onto the SPMD client_map (ops/federated.py, ISSUE 12)."""
        return self.mesh.shape[CLIENTS]

    def place(self, shards, train_x, train_y, state,
              replicate_shards=False):
        """Initial placement: client-index matrix sharded over clients,
        dataset replicated (MNIST/CIFAR fit in HBM; beyond-HBM data stays
        on host via data/stream.py, SURVEY.md §7.3 #5), server state
        sharded over the model axis.

        ``replicate_shards``: the SPMD hierarchical engine closes over
        the client->sample matrix inside shard_map, where captures are
        replicated by definition — placing it replicated up front keeps
        the capture from smuggling a resharding collective into every
        round (the megabatch id grids are the sharded operands there)."""
        shard_spec = P() if replicate_shards else P(CLIENTS, None)
        shards = jax.device_put(shards, self.sharding(shard_spec))
        train_x = jax.device_put(train_x, self.sharding(P()))
        train_y = jax.device_put(train_y, self.sharding(P()))
        return shards, train_x, train_y, self.place_state(state)

    def constrain_grads(self, grads):
        return jax.lax.with_sharding_constraint(
            grads, self.sharding(self.grads_spec(grads.shape[-1])))

    # --- hierarchical (megabatch) composition --------------------------
    # Two regimes (core/engine.py decides by clients_parts):
    #
    # 1-device clients axis — the sequential scan: inside the scan each
    # (m, d) megabatch gradient matrix carries the SAME
    # ('clients', model) layout as the flat (n, d) matrix — the scan
    # axis replaces n, the mesh axes are untouched, so constrain_grads
    # composes unchanged (GSPMD pads an uneven m over the clients axis
    # the same way it pads n).  estimates_spec/constrain_estimates
    # below annotate the (n/m, d) shard-estimate matrix for THIS
    # regime's tier-2 pass (it rides the clients axis only when the
    # shard count divides it; otherwise it replicates).
    #
    # Multi-device clients axis — the SPMD client_map (ISSUE 12,
    # ops/federated.py:_client_map_spmd): the MEGABATCH axis is the
    # sharded axis (id grids enter shard_map split P(clients, None)),
    # each device scans its own megabatches, and the estimates come
    # back replicated from one explicit tiled all_gather — so the
    # tier-2 pass needs NO estimates constraint at all; re-annotating
    # the replicated matrix would reintroduce the GSPMD resharding
    # seam the mapping exists to retire.

    def estimates_spec(self, num_shards: int, d: int):
        clients = (CLIENTS if num_shards % self.mesh.shape[CLIENTS] == 0
                   else None)
        return P(clients, self._model_axis_or_none(d))

    def constrain_estimates(self, estimates):
        return jax.lax.with_sharding_constraint(
            estimates, self.sharding(self.estimates_spec(*estimates.shape)))


def make_plan(mesh_shape=None, devices=None) -> MeshPlan:
    return MeshPlan(mesh=make_mesh(mesh_shape, devices))
