# Device-mesh / sharding layer (no reference analog: the reference has no
# distributed backend, SURVEY.md §2.3).
from attacking_federate_learning_tpu.parallel.mesh import (  # noqa: F401
    CLIENTS, MODEL, MeshPlan, make_mesh, make_plan
)
from attacking_federate_learning_tpu.parallel import multihost  # noqa: F401
