# Device-mesh / sharding layer (no reference analog: the reference has no
# distributed backend, SURVEY.md §2.3).  Populated by parallel/mesh.py.
