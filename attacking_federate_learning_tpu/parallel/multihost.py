"""Multi-host (DCN) initialization.

The reference has no distributed communication backend at all (SURVEY.md
§2.3: no NCCL/MPI/Gloo — its "broadcast" is a Python loop over objects in
one process).  The TPU-native equivalent needs no bespoke transport either:
``jax.distributed.initialize`` joins this process into a multi-host
jax runtime, after which ``jax.devices()`` spans every host's chips, a
single ``Mesh`` laid over them routes intra-slice collectives over ICI and
cross-slice traffic over DCN, and every kernel in this framework
(the Gram-matmul distances, the sharded sorts, the psum-style reductions
XLA inserts) works unchanged.

On a single host this module is a no-op, so the same experiment script runs
anywhere:

    from attacking_federate_learning_tpu.parallel import multihost
    multihost.initialize()            # env-driven; no-op locally
    plan = make_plan((jax.device_count(), 1))
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join the multi-host runtime; returns True if distributed mode is on.

    With no arguments, reads the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, or the
    cluster autodetection jax.distributed supports on TPU pods).  Single
    process with no coordinator configured -> no-op.
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    num_processes = num_processes or _env_int("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _env_int(
        "JAX_PROCESS_ID")

    if coordinator_address is None and num_processes in (None, 1):
        return False  # single-host: nothing to join

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def _env_int(name):
    v = os.environ.get(name)
    return int(v) if v is not None else None


def is_primary() -> bool:
    """True on the process that should write logs/checkpoints."""
    return jax.process_index() == 0
