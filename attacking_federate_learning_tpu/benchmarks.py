"""Runners for the BASELINE benchmark configs.

BASELINE.md lists five benchmark configurations (from BASELINE.json) to
fill with measured numbers.  This driver runs them end to end through the
real engine and emits one JSON line per cell (rounds/sec, final accuracy,
ASR where applicable):

    python -m attacking_federate_learning_tpu.benchmarks --rounds 10

``--scale`` shrinks client counts for CPU runs (defaults to 1.0 on an
accelerator, 0.1 on CPU — the shapes stay faithful, only n shrinks);
``--cells`` selects a subset.  Cell 5 (the 10k-client non-IID grid) is the
overnight north star and only runs when asked for explicitly.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _cells():
    from attacking_federate_learning_tpu import config as C

    # (name, cfg overrides, attack, baseline.json description)
    return [
        ("ref_default",
         dict(dataset=C.MNIST, users_count=10, mal_prop=0.0,
              defense="NoDefense"),
         "none",
         "MNIST MLP, 10 clients, FedAvg (no attack) - reference default"),
        ("mnist_cnn_krum_alie",
         dict(dataset=C.MNIST, model="mnist_cnn", users_count=100,
              mal_prop=0.24, defense="Krum"),
         "alie",
         "MNIST CNN, 100 clients, Krum vs ALIE"),
        ("cifar10_resnet20_trimmed_backdoor",
         dict(dataset=C.CIFAR10, model="resnet20", users_count=100,
              mal_prop=0.24, defense="TrimmedMean", backdoor="pattern",
              batch_size=32),
         "backdoor",
         "CIFAR-10 ResNet-20, 100 clients, trimmed_mean vs backdoor"),
        ("cifar10_bulyan_alie_1000c",
         dict(dataset=C.CIFAR10, users_count=1000, mal_prop=0.2,
              defense="Bulyan", batch_size=32),
         "alie",
         "CIFAR-10, 1000 clients, Bulyan vs ALIE - O(n^2 d) stress"),
        ("noniid_10k_grid",
         # bulyan_selection_impl='host': at full scale the traced exact
         # selection is ~5,200 sequential O(n^2) trips PER ROUND; the
         # hybrid (device Gram -> one (n,n) marshal -> native selection)
         # is the affordable exact-semantics route on both backends.
         dict(dataset=C.MNIST, users_count=10_000, mal_prop=0.24,
              partition="dirichlet", batch_size=32,
              data_placement="host_stream",
              bulyan_selection_impl="host"),
         "grid",
         "non-IID, 10k clients, {Krum,TrimmedMean,Bulyan} x "
         "{ALIE,backdoor} grid - overnight north star"),
    ]


def run_cell(name, overrides, attack, rounds, scale, log_dir):
    import jax

    from attacking_federate_learning_tpu.attacks import make_attacker
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.grid import run_grid

    overrides = dict(overrides)
    overrides["users_count"] = max(4, int(overrides["users_count"] * scale))
    cfg = ExperimentConfig(epochs=rounds, log_dir=log_dir,
                           synth_train=4096, synth_test=512, **overrides)
    t0 = time.time()
    if attack == "grid":
        cells = run_grid(cfg, defenses=["Krum", "TrimmedMean", "Bulyan"],
                         attacks=["alie", "backdoor"])
        return {"cell": name, "clients": cfg.users_count,
                "wall_s": round(time.time() - t0, 2),
                "grid_cells": len(cells),
                "final_accuracies": {f"{c['defense']}/{c['attack']}":
                                     c.get("final_accuracy")
                                     for c in cells}}
    ds = load_dataset(cfg.dataset, cfg.data_dir, cfg.seed,
                      synth_train=cfg.synth_train, synth_test=cfg.synth_test)
    attacker = make_attacker(cfg, dataset=ds,
                             name=None if cfg.backdoor else attack)
    exp = FederatedExperiment(cfg, attacker=attacker, dataset=ds)
    # Warm round first: rounds_per_sec reports steady-state throughput,
    # not XLA compile + dataset synthesis (those go to setup_s).
    exp.run_span(0, 1)
    jax.block_until_ready(exp.state.weights)
    setup_s = time.time() - t0
    t1 = time.time()
    exp.run_span(1, rounds)
    jax.block_until_ready(exp.state.weights)
    wall = time.time() - t1
    _, correct = exp.evaluate(exp.state.weights)
    out = {"cell": name, "clients": cfg.users_count, "rounds": rounds,
           "dataset": ds.name, "model": cfg.model,
           "rounds_per_sec": round(rounds / wall, 3),
           "setup_s": round(setup_s, 2), "wall_s": round(wall, 2),
           "final_accuracy": round(100 * float(correct)
                                   / len(ds.test_y), 2)}
    if cfg.backdoor and hasattr(attacker, "test_asr"):
        out["asr"] = round(float(attacker.test_asr(exp.state.weights)), 2)
    return out


def main(argv=None):
    from attacking_federate_learning_tpu.utils.backend import (
        enable_compile_cache, ensure_live_backend
    )

    ensure_live_backend()
    enable_compile_cache()
    import jax

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rounds", type=int, default=10)
    p.add_argument("--scale", type=float, default=None,
                   help="client-count multiplier (default 1.0 on an "
                        "accelerator, 0.1 on CPU)")
    p.add_argument("--cells", type=str, default=None,
                   help="comma-separated 1-based cell indices; default "
                        "1,2,3,4 on an accelerator, 1,2,4 on CPU (cell "
                        "3's ResNet shadow-train compile is impractical "
                        "on one CPU core; 5 = the 10k grid north star)")
    p.add_argument("--log-dir", type=str, default="logs")
    p.add_argument("--strict", dest="strict", action="store_true",
                   default=True,
                   help="exit nonzero if any requested cell failed "
                        "(default: on — an unattended end-of-round sweep "
                        "must distinguish 'failed' from 'not requested')")
    p.add_argument("--no-strict", dest="strict", action="store_false")
    args = p.parse_args(argv)

    on_accel = jax.devices()[0].platform not in ("cpu",)
    scale = args.scale if args.scale is not None else (
        1.0 if on_accel else 0.1)
    cells_arg = args.cells or ("1,2,3,4" if on_accel else "1,2,4")
    wanted = {int(x) for x in cells_arg.split(",")}
    results = []
    for i, (name, overrides, attack, desc) in enumerate(_cells(), 1):
        if i not in wanted:
            continue
        if name == "noniid_10k_grid" and not on_accel:
            # The documented CPU-backend policy (BASELINE.md round 5):
            # 'xla' stays the product default for bit-stability, and the
            # benchmark drivers opt into the native host kernel
            # explicitly in the 10k regime — the XLA:CPU stable argsort
            # at full scale is ~minutes PER ROUND (measured 943.5 s per
            # call at n=10,240), vs ~27.5 s native.
            overrides = dict(overrides, trimmed_mean_impl="host",
                             bulyan_trim_impl="host")
        print(f"# cell {i}: {desc} (scale {scale})", file=sys.stderr,
              flush=True)
        try:
            cell = run_cell(name, overrides, attack, args.rounds, scale,
                            args.log_dir)
        except Exception as e:  # record, keep going
            cell = {"cell": name, "failed": f"{type(e).__name__}: {e}"}
        results.append(cell)
        print(json.dumps(cell), flush=True)
    failed = [c["cell"] for c in results if "failed" in c]
    if args.strict and failed:
        # Loud failure for unattended sweeps: a failed cell must not look
        # like an unrequested one.  The full result list (successful
        # cells included) rides on the exception for programmatic
        # callers that catch SystemExit.
        err = SystemExit(
            f"benchmarks: {len(failed)} cell(s) failed: {', '.join(failed)}")
        err.results = results
        raise err
    return results


if __name__ == "__main__":
    main()
