"""Pallas TPU kernel: fused pairwise-distance tiles.

The XLA path (ops/distances.py) materializes the full Gram matrix to HBM and
then runs the ``sq_i + sq_j - 2*gram -> sqrt`` epilogue as a second
HBM-bound pass.  This kernel fuses the epilogue into the matmul's output
tile while it is still in VMEM: grid (n/BM, n/BN, d/BK) with the contraction
innermost, an f32 VMEM accumulator per (BM, BN) tile, and the
distance transform applied on the final k step — one HBM write of D and no
Gram round-trip.  This is the 10k-client regime kernel (SURVEY.md §5
"long-context"): at n=10240, skipping the Gram round-trip saves ~800 MB of
HBM traffic per aggregation.

Falls back to ``interpret=True`` off-TPU so CPU CI exercises the same code.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Importable without TPU hardware; interpret=True runs the same kernel on CPU.
from jax.experimental.pallas import tpu as pltpu

from attacking_federate_learning_tpu.ops.distances import zero_diagonal


def _dist_kernel(nk, gi_ref, gj_ref, sqi_ref, sqj_ref, out_ref, acc_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(gi_ref[:], gj_ref[:].T,
                          preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _epilogue():
        d2 = sqi_ref[:] + sqj_ref[:] - 2.0 * acc_ref[:]
        out_ref[:] = jnp.sqrt(jnp.maximum(d2, 0.0)).astype(out_ref.dtype)


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit,
                   static_argnames=("bm", "bn", "bk", "interpret"))
def pallas_pairwise_distances(G, bm=128, bn=128, bk=512, interpret=None):
    """(n, d) -> (n, n) Euclidean distances, zero diagonal.

    Matches ops.distances.pairwise_distances to f32 tolerance; zero-padding
    of n and d is harmless (zero rows/columns change neither norms nor
    dots) and sliced off the output.
    """
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    n, d = G.shape
    # bf16 inputs keep their dtype into the matmul (MXU-native throughput,
    # f32 accumulation via preferred_element_type in _dist_kernel); norms
    # are always f32.  Everything else computes in f32.
    if G.dtype != jnp.bfloat16:
        G = G.astype(jnp.float32)
    # lcm: rows enter the grid as both i-blocks (bm) and j-blocks (bn); a
    # max() pad would leave output tiles unwritten when bm != bn.
    Gp = _pad_to(_pad_to(G, 1, bk), 0, math.lcm(bm, bn))
    np_, dp = Gp.shape
    # One hoisted f32 view feeds the squared norms; the matmul operand
    # stays Gp (bf16 rides the MXU natively), so at most one f32 cast of
    # the padded matrix exists in the program (pinned by
    # tests/test_distance_impl.py — a second materialization would show
    # up as ~np*dp*4 extra temp bytes).
    Gf = Gp.astype(jnp.float32)
    sq = jnp.sum(Gf * Gf, axis=1)
    sq_col = sq[:, None]                      # (np, 1) row norms
    sq_row = sq[None, :]                      # (1, np) col norms
    nk = dp // bk

    grid = (np_ // bm, np_ // bn, nk)
    kernel = functools.partial(_dist_kernel, nk)
    scratch = [pltpu.VMEM((bm, bn), jnp.float32)]
    D = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((np_, np_), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),   # G rows
            pl.BlockSpec((bn, bk), lambda i, j, k: (j, k)),   # G cols
            pl.BlockSpec((bm, 1), lambda i, j, k: (i, 0)),    # ||g_i||^2
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),    # ||g_j||^2
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(Gp, Gp, sq_col, sq_row)
    D = D[:n, :n]
    # Iota-select diagonal zeroing (ops/distances.py:zero_diagonal):
    # the eye spelling would materialize a second (n, n) f32 buffer.
    return zero_diagonal(D)
