"""Pallas TPU defense-kernel suite: the tier-1 pipeline on-device.

ops/pallas_distances.py fused the distance epilogue into the Gram
matmul's output tile; this module grows that into the full defense hot
path (ROADMAP item 1, ISSUE 11) so the O(n^2 d) tier-1 estimators run
on the accelerator end to end — no Gram round-trip, no second HBM pass
over the (n, n) matrix, and no ``pure_callback`` host marshal:

- :func:`pallas_krum_scores` — fused **distance -> Krum score** kernel.
  Same grid as the distance kernel ((n/bm, n/bn, d/bk), contraction
  innermost), but the (bm, bn) distance tile never leaves VMEM: the
  epilogue folds it into a per-row running ``rowsum`` and a running
  top-``c`` *largest* buffer (the complement identity of
  defenses/kernels.py:_krum_scores — a row always has exactly k + c
  scoring entries with c = f - 1, +2 under paper scoring, so
  sum-of-k-smallest = rowsum − sum-of-c-largest), and the (n,) scores
  are written on the last j step.  The (n, n) matrix is never
  materialized: output bytes drop from n²·4 to n·4 and the second
  HBM read of D disappears (:func:`krum_scores_cost` is the exact
  declared tile-traffic model, pinned against the XLA Gram+epilogue
  path by tools/perf_gate.py --pallasproof).
- :func:`pallas_trimmed_mean_of` / :func:`pallas_median_of` — tiled
  **coordinate-wise selection** over (n, d): each grid step owns one
  (n, bd) column block in VMEM and runs the reference estimator's
  median/stable-argsort/keep pipeline inside it, replacing the
  whole-matrix XLA sort whose CPU cost motivated the native host
  escape (defenses/host.py).
- :func:`pallas_masked_trimmed_mean` / :func:`pallas_masked_median` —
  the same tiles with the quarantine ``mask=`` / staleness ``weights=``
  seam (core/faults.py, core/async_rounds.py) replicated INSIDE the
  kernel, so fault/async/hierarchical rounds ride the pallas route
  unchanged.  These replicate defenses/kernels.py's masked estimators
  op for op and are pinned BIT-EXACT against them
  (tests/test_pallas.py); the unmasked kernels are ulp-bounded instead
  (XLA fuses the full-matrix mean+median differently than the tiled
  program — the same summation-order contract as the native host
  kernels, PARITY.md).

Numerics contract: the fused Krum scores are the complement
evaluation — numerically the ``krum_scoring_method='topk'`` class, so
the kernels.py dispatch wraps them in the same cancellation guard
(kept mass vs the subtraction noise floor) with a ``lax.cond``
fallback to the exact sort path over the pallas distance matrix.
Selection outputs (Krum/Bulyan return input rows) are therefore
bit-exact whenever the score gap clears the f32 tie band — the same
measured-band contract tests/test_native.py pins for the native
comparator.

Every kernel resolves ``interpret=None`` to interpret mode off-TPU, so
CPU CI exercises the exact kernel bodies; the Mosaic-compiled parity
tests are hardware-gated (``FL_TEST_TPU=1``, tests/test_pallas.py) and
``tools/pallas_microbench.py`` is the capture-window payload.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Importable without TPU hardware; interpret=True runs the same kernels
# on CPU (tests/conftest.py pins the backend there).
from jax.experimental.pallas import tpu as pltpu

from attacking_federate_learning_tpu.ops.pallas_distances import _pad_to

_INF = jnp.inf


def _interpret_default(interpret):
    if interpret is None:
        return jax.default_backend() not in ("tpu", "axon")
    return interpret


def _lane_pad(c, lanes=128):
    """Round a scratch lane count up to the TPU lane width (>= 1 tile)."""
    return max(-(-max(c, 1) // lanes) * lanes, lanes)


# ---------------------------------------------------------------------------
# fused distance -> Krum score
# ---------------------------------------------------------------------------

def krum_scores_cost(n, d, corrupted_count=0, bm=128, bn=128, bk=512):
    """Exact declared cost of the fused kernel, deterministic in the
    shapes alone, in BOTH accounting conventions:

    - ``bytes_accessed``: XLA ``cost_analysis`` semantics — every
      logical operand/output counted ONCE per op (the convention the
      whole cost observatory gates on).  For the fused kernel that is
      the two G operand views, the norm vectors and the (n,)-class
      outputs: ~2·n·d·4 bytes.  The XLA Gram+epilogue path pays the
      same operand term PLUS one n²·4 pass per (n, n) intermediate
      (Gram write, distance transform, sort, prefix reduce), which is
      exactly what the fusion deletes — the perf-gate pallasproof pins
      this model strictly below the XLA path's measured number.
    - ``hbm_tile_bytes``: the physical tile traffic the BlockSpecs
      stream per sweep (each G tile is re-read once per opposing row
      block — the ``pl.CostEstimate`` handed to Mosaic).  Shrinks
      with bm/bn; the CI defaults favor small-n coverage, the
      capture-window micro-bench (tools/pallas_microbench.py) runs
      the balanced large-tile configuration.

    The interpret-mode emulation's cost_analysis is NEITHER number
    (the grid loop body is counted once and the emulation copies
    inflate temp bytes), which is why the proof pins the model, not
    the emulation."""
    np_ = -(-n // math.lcm(bm, bn)) * math.lcm(bm, bn)
    dp = -(-d // bk) * bk
    ni, nj, nk = np_ // bm, np_ // bn, dp // bk
    steps = ni * nj * nk
    tile_bytes = (steps * 4 * (bm * bk + bn * bk)
                  + ni * nj * 4 * (bm + bn) + 2 * np_ * 4)
    once_bytes = 4 * (2 * np_ * dp + 4 * np_)
    flops = 2 * np_ * np_ * dp + 8 * np_ * np_  # matmul + epilogue
    return {"flops": float(flops),
            "bytes_accessed": float(once_bytes),
            "hbm_tile_bytes": float(tile_bytes)}


def _krum_score_kernel(n, nk, nj, comp, cp, gi_ref, gj_ref, sqi_ref,
                       sqj_ref, score_ref, rowsum_ref, acc_ref, top_ref):
    i, j, k = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(gi_ref[:], gj_ref[:].T,
                          preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _epilogue():
        bm, bn = acc_ref.shape
        d2 = sqi_ref[:] + sqj_ref[:] - 2.0 * acc_ref[:]
        dist = jnp.sqrt(jnp.maximum(d2, 0.0))
        # Padding columns and the diagonal never score: the reference
        # dict holds no self-distance (defences.py:16-21) and zero
        # rows are an artifact of the lcm/bk padding.
        rows = i * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 0)
        cols = j * bn + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 1)
        valid = (cols < n) & (rows != cols)

        @pl.when(j == 0)
        def _reset():
            rowsum_ref[:] = jnp.zeros_like(rowsum_ref)
            top_ref[:] = jnp.full_like(top_ref, -_INF)

        rowsum_ref[:] += jnp.sum(jnp.where(valid, dist, 0.0), axis=1,
                                 keepdims=True)
        if comp > 0:
            # Streaming top-c largest per row: merge this tile's
            # candidates into the running buffer (one descending sort
            # of (bm, cp + bn) — O((c+bn) log) per tile, amortized
            # noise next to the bm·bn·bk matmul).
            cand = jnp.where(valid, dist, -_INF)
            merged = jnp.concatenate([top_ref[:], cand], axis=1)
            top_ref[:] = -jnp.sort(-merged, axis=1)[:, :cp]

        @pl.when(j == nj - 1)
        def _write():
            if comp > 0:
                t = top_ref[:, :comp]
                tsum = jnp.sum(jnp.where(jnp.isfinite(t), t, 0.0),
                               axis=1, keepdims=True)
                score_ref[:] = rowsum_ref[:] - tsum
            else:
                score_ref[:] = rowsum_ref[:]


@functools.partial(jax.jit,
                   static_argnames=("users_count", "corrupted_count",
                                    "paper_scoring", "bm", "bn", "bk",
                                    "interpret"))
def pallas_krum_scores(G, users_count, corrupted_count,
                       paper_scoring=False, bm=128, bn=128, bk=512,
                       interpret=None):
    """(n, d) -> ((n,) Krum scores, (n,) distance rowsums), one sweep.

    Reference scoring semantics (defenses/kernels.py:_krum_scores):
    each row's score sums its k = users_count - corrupted_count
    (- 2 under ``paper_scoring``) smallest distances to the other
    rows, evaluated via the complement identity (rowsum minus the
    c = f - 1 (+2) largest).  The rowsum comes back too so the caller
    can apply the topk cancellation guard without a second pass.

    bf16 operands ride the MXU natively with f32 accumulation and f32
    norms, mirroring pallas_pairwise_distances; anything else computes
    in f32.  Static pool only — the quarantine-masked path keeps the
    exact sort evaluator over the pallas distance matrix
    (defenses/kernels.py dispatch)."""
    interpret = _interpret_default(interpret)
    n, d = G.shape
    comp = corrupted_count - 1 + (2 if paper_scoring else 0)
    if not 0 <= comp <= max(n - 1, 0):
        raise ValueError(
            f"fused Krum scores need 0 <= f-1(+2) <= n-1 entries per "
            f"row (n={n}, f={corrupted_count}, "
            f"paper_scoring={paper_scoring})")
    if G.dtype != jnp.bfloat16:
        G = G.astype(jnp.float32)
    Gp = _pad_to(_pad_to(G, 1, bk), 0, math.lcm(bm, bn))
    np_, dp = Gp.shape
    Gf = Gp.astype(jnp.float32)
    sq = jnp.sum(Gf * Gf, axis=1)
    cp = _lane_pad(comp)
    nk, nj = dp // bk, np_ // bn
    cost = krum_scores_cost(n, d, corrupted_count, bm, bn, bk)
    kernel = functools.partial(_krum_score_kernel, n, nk, nj, comp, cp)
    scores, rowsum = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((np_, 1), jnp.float32),
                   jax.ShapeDtypeStruct((np_, 1), jnp.float32)),
        grid=(np_ // bm, nj, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),   # G rows
            pl.BlockSpec((bn, bk), lambda i, j, k: (j, k)),   # G cols
            pl.BlockSpec((bm, 1), lambda i, j, k: (i, 0)),    # ||g_i||^2
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),    # ||g_j||^2
        ],
        out_specs=(pl.BlockSpec((bm, 1), lambda i, j, k: (i, 0)),
                   pl.BlockSpec((bm, 1), lambda i, j, k: (i, 0))),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32),
                        pltpu.VMEM((bm, cp), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=cost["flops"],
            bytes_accessed=cost["hbm_tile_bytes"], transcendentals=0),
        interpret=interpret,
    )(Gp, Gp, sq[:, None], sq[None, :])
    return scores[:n, 0], rowsum[:n, 0]


# ---------------------------------------------------------------------------
# tiled coordinate-wise kernels (trimmed mean / median, masked/weighted)
# ---------------------------------------------------------------------------

def _coord_block(n, d, bd):
    """Default column-tile width: (n, bd) f32 + sort temps must sit in
    VMEM, so the tile narrows as the client axis grows."""
    if bd is None:
        bd = 256 if n <= 4096 else 128
    return min(bd, _lane_pad(d))


def _trim_kernel(number_to_consider, g_ref, out_ref):
    # Reference estimator, verbatim per column block
    # (defenses/kernels.py:trimmed_mean_of): median anchor, stable
    # |deviation| argsort along the client axis, mean of the kept
    # deviations plus the anchor.
    G = g_ref[:]
    med = jnp.median(G, axis=0)
    dev = G - med[None, :]
    order = jnp.argsort(jnp.abs(dev), axis=0, stable=True)
    kept = jnp.take_along_axis(dev, order[:number_to_consider], axis=0)
    out_ref[0, :] = jnp.mean(kept, axis=0) + med


@functools.partial(jax.jit, static_argnames=("number_to_consider", "bd",
                                             "interpret"))
def pallas_trimmed_mean_of(G, number_to_consider, bd=None, interpret=None):
    """Tiled median-anchored trimmed mean: (n, d) -> (d,), keep count
    static.  Matches defenses/kernels.py:trimmed_mean_of to summation-
    order ulps (the whole-matrix XLA program fuses its mean+median
    arithmetic differently than the tiled one — PARITY.md)."""
    interpret = _interpret_default(interpret)
    n, d = G.shape
    bd = _coord_block(n, d, bd)
    Gp = _pad_to(G.astype(jnp.float32), 1, bd)
    dp = Gp.shape[1]
    out = pl.pallas_call(
        functools.partial(_trim_kernel, int(number_to_consider)),
        out_shape=jax.ShapeDtypeStruct((1, dp), jnp.float32),
        grid=(dp // bd,),
        in_specs=[pl.BlockSpec((n, bd), lambda j: (0, j))],
        out_specs=pl.BlockSpec((1, bd), lambda j: (0, j)),
        interpret=interpret,
    )(Gp)
    return out[0, :d]


def _median_kernel(g_ref, out_ref):
    out_ref[0, :] = jnp.median(g_ref[:], axis=0)


@functools.partial(jax.jit, static_argnames=("bd", "interpret"))
def pallas_median_of(G, bd=None, interpret=None):
    """Tiled coordinate-wise median: (n, d) -> (d,)."""
    interpret = _interpret_default(interpret)
    n, d = G.shape
    bd = _coord_block(n, d, bd)
    Gp = _pad_to(G.astype(jnp.float32), 1, bd)
    dp = Gp.shape[1]
    out = pl.pallas_call(
        _median_kernel,
        out_shape=jax.ShapeDtypeStruct((1, dp), jnp.float32),
        grid=(dp // bd,),
        in_specs=[pl.BlockSpec((n, bd), lambda j: (0, j))],
        out_specs=pl.BlockSpec((1, bd), lambda j: (0, j)),
        interpret=interpret,
    )(Gp)
    return out[0, :d]


def _masked_median_cols(G, mask, maskv, w_ref, weighted):
    """kernels.masked_median replicated on one (n, bd) column block;
    ``mask`` is the (n, 1) bool column, ``maskv`` its (n,) view."""
    vals = jnp.where(mask, G, _INF)
    srt = jnp.sort(vals, axis=0)
    if weighted:
        order = jnp.argsort(vals, axis=0)
        w = jnp.where(mask, w_ref[:], 0.0)
        w_srt = jnp.take_along_axis(jnp.broadcast_to(w, vals.shape),
                                    order, axis=0)
        cum = jnp.cumsum(w_srt, axis=0)
        half = jnp.sum(w) / 2.0
        pick = jnp.argmax(cum >= half, axis=0)
        return jnp.take_along_axis(srt, pick[None, :], axis=0)[0]
    e = jnp.sum(maskv).astype(jnp.int32)
    lo = jnp.take(srt, (e - 1) // 2, axis=0)
    hi = jnp.take(srt, e // 2, axis=0)
    return (lo + hi) / 2


def _masked_median_kernel(weighted, g_ref, m_ref, w_ref, out_ref):
    mask = m_ref[:] > 0
    out_ref[0, :] = _masked_median_cols(g_ref[:], mask, mask[:, 0],
                                        w_ref, weighted)


def _masked_trim_kernel(k_delta, weighted, g_ref, m_ref, w_ref, out_ref):
    # kernels.masked_trimmed_mean_of, verbatim per column block: alive
    # median anchor (always unweighted), dead rows carry an +inf
    # deviation key (stable argsort puts them last), keep count
    # k = max(e - k_delta, 1) derived from the mask INSIDE the kernel
    # so no traced scalar crosses the pallas boundary.
    G = g_ref[:]
    n = G.shape[0]
    mask = m_ref[:] > 0
    maskv = mask[:, 0]
    med = _masked_median_cols(G, mask, maskv, w_ref, False)
    dev = G - med[None, :]
    key = jnp.where(mask, jnp.abs(dev), _INF)
    order = jnp.argsort(key, axis=0, stable=True)
    sdev = jnp.take_along_axis(dev, order, axis=0)
    e = jnp.sum(maskv).astype(jnp.int32)
    k = jnp.maximum(e - k_delta, 1)
    keep = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0) < k
    if weighted:
        w = jnp.where(mask, w_ref[:], 0.0)
        w_s = jnp.take_along_axis(jnp.broadcast_to(w, sdev.shape),
                                  order, axis=0)
        wk = jnp.where(keep, w_s, 0.0)
        mass = jnp.maximum(jnp.sum(wk, axis=0), 1e-12)
        out_ref[0, :] = jnp.sum(wk * sdev, axis=0) / mass + med
    else:
        out_ref[0, :] = jnp.sum(jnp.where(keep, sdev, 0.0),
                                axis=0) / k + med


def _masked_coord_call(kernel, G, mask, weights, bd, interpret):
    interpret = _interpret_default(interpret)
    n, d = G.shape
    bd = _coord_block(n, d, bd)
    Gp = _pad_to(G.astype(jnp.float32), 1, bd)
    dp = Gp.shape[1]
    m2 = mask.astype(jnp.float32)[:, None]
    w = (weights if weights is not None
         else jnp.ones((n,), jnp.float32)).astype(jnp.float32)[:, None]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, dp), jnp.float32),
        grid=(dp // bd,),
        in_specs=[pl.BlockSpec((n, bd), lambda j: (0, j)),
                  pl.BlockSpec((n, 1), lambda j: (0, 0)),
                  pl.BlockSpec((n, 1), lambda j: (0, 0))],
        out_specs=pl.BlockSpec((1, bd), lambda j: (0, j)),
        interpret=interpret,
    )(Gp, m2, w)
    return out[0, :d]


@functools.partial(jax.jit, static_argnames=("k_delta", "weighted", "bd",
                                             "interpret"))
def pallas_masked_trimmed_mean(G, mask, k_delta, weights=None,
                               weighted=False, bd=None, interpret=None):
    """Mask-aware tiled trimmed mean — the quarantine/staleness seam on
    the pallas route.  ``k_delta`` is the STATIC part of the keep
    count: k = max(alive - k_delta, 1), i.e. k_delta = f + 1 for
    TrimmedMean and 2f + 1 for Bulyan's tail — the traced alive count
    is derived from the mask inside the kernel.  Bit-exact against
    kernels.masked_trimmed_mean_of (pinned, tests/test_pallas.py);
    ``weighted`` must say statically whether ``weights`` is real
    (a None weights with weighted=True averages unit weights)."""
    return _masked_coord_call(
        functools.partial(_masked_trim_kernel, int(k_delta),
                          bool(weighted)),
        G, mask, weights, bd, interpret)


@functools.partial(jax.jit, static_argnames=("weighted", "bd",
                                             "interpret"))
def pallas_masked_median(G, mask, weights=None, weighted=False, bd=None,
                         interpret=None):
    """Mask-aware tiled median (weighted = the lower weighted median,
    kernels.masked_median's one documented deviation).  Bit-exact
    against kernels.masked_median (pinned, tests/test_pallas.py)."""
    return _masked_coord_call(
        functools.partial(_masked_median_kernel, bool(weighted)),
        G, mask, weights, bd, interpret)
