from attacking_federate_learning_tpu.ops.distances import (  # noqa: F401
    pairwise_distances, pairwise_sq_distances
)
