"""Pairwise Euclidean distances over the client axis.

The reference builds an O(n^2) dict-of-dicts of ``np.linalg.norm(g_i - g_j)``
in a Python double loop (reference defences.py:16-21) — the #1 hotspot for
Krum/Bulyan.  On TPU the whole matrix is one Gram matmul on the MXU:

    D^2 = ||g_i||^2 + ||g_j||^2 - 2 G G^T

computed in f32 with HIGHEST matmul precision so it agrees with the
reference's float computation to test tolerance.  For the multi-device path
G arrives row-sharded over the 'clients' mesh axis and XLA turns the Gram
matmul into a collective matmul over ICI — see parallel/distances.py for the
explicit blockwise shard_map variant.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def cross_sq_distances(A, B, precision=None):
    """(m, d), (n, d) -> (m, n) squared Euclidean distances in f32.

    f32 inputs use HIGHEST matmul precision (parity with the reference's
    float math); bf16 inputs ride the MXU at native precision with f32
    accumulation (``preferred_element_type``) and f32 squared norms — the
    large-n memory/speed mode (config.grad_dtype='bfloat16').  Shared by
    the single-device kernel and the blockwise shard_map tiles
    (parallel/distances.py) so every path computes identical values.
    """
    if precision is None:
        precision = (lax.Precision.DEFAULT if A.dtype == jnp.bfloat16
                     else lax.Precision.HIGHEST)
    sq_a = jnp.sum(A.astype(jnp.float32) * A.astype(jnp.float32), axis=-1)
    sq_b = jnp.sum(B.astype(jnp.float32) * B.astype(jnp.float32), axis=-1)
    gram = jnp.matmul(A, B.T, precision=precision,
                      preferred_element_type=jnp.float32)
    d2 = sq_a[:, None] + sq_b[None, :] - 2.0 * gram
    return jnp.maximum(d2, 0.0)


def pairwise_sq_distances(G, precision=None):
    """(n, d) -> (n, n) squared Euclidean distance matrix in f32."""
    return cross_sq_distances(G, G, precision)


def zero_diagonal(D):
    """Exact zeros on the diagonal of a square matrix.

    An iota comparison select, NOT ``D * (1 - eye(n))``: the eye
    spelling materializes an (n, n) f32 intermediate on the hot path
    (~420 MB at n=10,240) before the multiply, while broadcasted iotas
    fuse into the consumer — same values, one fewer n² buffer
    (pinned by tests/test_distance_impl.py cost assertions).
    """
    n = D.shape[0]
    i = lax.broadcasted_iota(jnp.int32, (n, n), 0)
    j = lax.broadcasted_iota(jnp.int32, (n, n), 1)
    return jnp.where(i == j, jnp.zeros((), D.dtype), D)


def pairwise_distances(G, precision=None):
    """(n, d) -> (n, n) Euclidean distance matrix, zero diagonal."""
    D = jnp.sqrt(pairwise_sq_distances(G, precision))
    # Exact zeros on the diagonal (the matmul identity can leave ~1e-4 noise).
    return zero_diagonal(D)
