"""Pairwise Euclidean distances over the client axis.

The reference builds an O(n^2) dict-of-dicts of ``np.linalg.norm(g_i - g_j)``
in a Python double loop (reference defences.py:16-21) — the #1 hotspot for
Krum/Bulyan.  On TPU the whole matrix is one Gram matmul on the MXU:

    D^2 = ||g_i||^2 + ||g_j||^2 - 2 G G^T

computed in f32 with HIGHEST matmul precision so it agrees with the
reference's float computation to test tolerance.  For the multi-device path
G arrives row-sharded over the 'clients' mesh axis and XLA turns the Gram
matmul into a collective matmul over ICI — see parallel/distances.py for the
explicit blockwise shard_map variant.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def pairwise_sq_distances(G, precision=lax.Precision.HIGHEST):
    """(n, d) -> (n, n) squared Euclidean distance matrix."""
    sq = jnp.sum(G * G, axis=-1)
    gram = jnp.matmul(G, G.T, precision=precision)
    d2 = sq[:, None] + sq[None, :] - 2.0 * gram
    return jnp.maximum(d2, 0.0)


def pairwise_distances(G, precision=lax.Precision.HIGHEST):
    """(n, d) -> (n, n) Euclidean distance matrix, zero diagonal."""
    D = jnp.sqrt(pairwise_sq_distances(G, precision))
    # Exact zeros on the diagonal (the matmul identity can leave ~1e-4 noise).
    n = G.shape[0]
    return D * (1.0 - jnp.eye(n, dtype=D.dtype))
