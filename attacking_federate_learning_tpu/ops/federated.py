"""Explicit federated primitives: broadcast / client-map / shard-reduce.

The flat engine materializes the full (n, d) gradient matrix every round
and (for Krum/Bulyan) an (n, n) distance matrix on top — the O(n·d) /
O(n²·d) memory wall that caps the client axis around n≈10k (at n=1M the
gradient matrix alone is ~300 TB).  DrJAX (arXiv 2403.07128) shows that
federated computations decompose into three primitives that compose
with sharding and scan; this module is that decomposition for the round
engine's client axis:

- :func:`broadcast` — server state to every client.  In jax this is
  free (closure capture + XLA replication), so the primitive is an
  annotation hook: under a MeshPlan it pins the replicated layout.
- :func:`client_map` — apply a per-megabatch function over the client
  axis as a ``lax.scan`` of static-size *megabatches* (m ≪ n clients at
  a time).  Only one megabatch's gradients are ever live; XLA reuses
  the loop carry buffers across iterations, so the round's peak memory
  scales with m·d, not n·d (pinned by tools/perf_gate.py memproof).
- :func:`shard_reduce` — the cross-shard reduction over the (n/m, d)
  shard-estimate matrix (tier-2 of the two-tier robust aggregation,
  defenses/kernels.py shard_* entries).

The megabatch *placement* (which client ids land in which megabatch,
and where the colluding malicious rows [0, f) sit) is a host-side pure
function of the config (:func:`make_placement`).  Placement is a real
Byzantine surface, not a systems detail (NET-SA, arXiv 2501.01187):
colluders *concentrated* in one shard overwhelm its tier-1 estimator
but present tier-2 with a single outlier estimate; *spread* colluders
stay under every shard's tier-1 tolerance but tint every estimate.
``config.mal_placement`` selects the scenario; GRID_RESULTS.md banks
the measured flip.

Attack-seam semantics under client_map (the documented change behind
``aggregation='hierarchical'``): ``Attack.craft`` runs once per
megabatch and sees only that megabatch's malicious rows — cohort
statistics (ALIE's mean/std envelope) are per-megabatch, not global.
Scan shapes must be static, so megabatches are grouped by their
malicious-row count and one scan runs per distinct count (≤ 3 groups:
full/partial/zero under 'concentrated', hi/lo under 'spread').

SPMD tier-1 (ISSUE 12): with a MeshPlan whose ``clients`` axis holds
more than one device, ``client_map`` stops being a sequential scan and
becomes one ``shard_map`` program over the clients axis: each device
scans ONLY its own megabatches locally (one megabatch's intermediates
live per device — the O(m·d) contract survives per shard), and the
stacked per-device outputs meet in one explicit tiled ``all_gather``
— O(S·d) bytes on the wire — so tier-2 reads a replicated, ordered
(S, d) estimate matrix with no GSPMD resharding seam (the
"involuntary full rematerialization" warning the MULTICHIP dryruns
logged came from exactly that seam).  :func:`spmd_schedule` is the
host-side plan: S must divide by the clients axis (rejected loudly —
silent replication would defeat the sharding), and a placement group
whose megabatch count does not divide evenly is padded with DUPLICATE
megabatches (bounded: < clients-axis extra rows per group, dropped
after the gather by the ``select`` index) so every device runs the
same static program without changing any estimate.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class Placement(NamedTuple):
    """Host-side megabatch layout: a pure function of the config.

    ``grid[s]`` lists megabatch s's client ids, malicious ids first
    (the per-megabatch mirror of the engine's rows-[0, f) attack
    invariant); ``mal_counts[s]`` is that static count.  ``groups``
    pairs each distinct malicious count with the megabatch ids that
    share it — one ``lax.scan`` per group keeps every shape static.
    """

    grid: np.ndarray                       # (S, m) int32 client ids
    mal_counts: Tuple[int, ...]            # per-megabatch malicious rows
    groups: Tuple[Tuple[int, Tuple[int, ...]], ...]
    megabatch: int                         # m
    num_shards: int                        # S = n / m


def tier1_assumed(f: int, num_shards: int) -> int:
    """Default per-shard corrupted bound the tier-1 estimator assumes:
    the server doesn't know the placement, so it budgets for the
    evenly-spread worst case, ceil(f / S)."""
    return -(-f // num_shards) if f > 0 else 0


def tier2_assumed(f: int, megabatch: int) -> int:
    """Default corrupted-shard bound for tier-2: the number of shards
    the f colluders could fill outright, ceil(f / m) (capped below by 1
    whenever any colluder exists — one partially-filled shard can still
    carry a poisoned estimate)."""
    return -(-f // megabatch) if f > 0 else 0


def make_placement(n: int, f: int, megabatch: int,
                   mal_placement: str = "spread") -> Placement:
    """Assign the n clients (malicious = ids [0, f)) to n/m megabatches.

    'spread' deals malicious ids round-robin across megabatches
    (counts differ by at most one); 'concentrated' packs them into the
    fewest megabatches (the colluders-own-a-shard scenario).  Honest
    ids fill the remaining slots in id order.  Deterministic — no RNG:
    the placement is part of the run's identity.
    """
    if megabatch < 1 or n % megabatch:
        raise ValueError(
            f"megabatch must divide users_count (n={n}, m={megabatch})")
    if mal_placement not in ("spread", "concentrated"):
        raise ValueError(f"mal_placement must be 'spread' or "
                         f"'concentrated', got {mal_placement!r}")
    m, S = megabatch, n // megabatch
    shards: list = [[] for _ in range(S)]
    for k in range(f):
        shards[k % S if mal_placement == "spread" else k // m].append(k)
    counts = tuple(len(s) for s in shards)
    honest = iter(range(f, n))
    for rows in shards:
        while len(rows) < m:
            rows.append(next(honest))
    grouped: dict = {}
    for sid, c in enumerate(counts):
        grouped.setdefault(c, []).append(sid)
    groups = tuple((c, tuple(sids)) for c, sids in grouped.items())
    return Placement(grid=np.asarray(shards, np.int32), mal_counts=counts,
                     groups=groups, megabatch=m, num_shards=S)


class SpmdSchedule(NamedTuple):
    """Host-side SPMD plan for :func:`client_map` over the mesh
    ``clients`` axis: one padded id grid per placement group (shape
    ``(k_g * parts, m)`` — device q owns rows ``[q*k_g, (q+1)*k_g)``),
    the group's static malicious counts, and ``select`` — for each
    megabatch id, the row it lands on in the device-major
    ``all_gather`` order (also the dedup: padded duplicate rows are
    simply never selected)."""

    grids: Tuple[np.ndarray, ...]      # per group: (k_g*parts, m) ids
    counts: Tuple[int, ...]            # per group static malicious rows
    select: np.ndarray                 # (S,) gathered-row index per shard
    parts: int                         # mesh clients-axis size
    padded_shards: int                 # total scheduled rows (>= S)
    sids: Tuple[np.ndarray, ...] = ()  # per group: (k_g*parts,) shard ids


def spmd_schedule(placement: Placement, parts: int) -> SpmdSchedule:
    """Deal the placement's megabatches across the mesh clients axis.

    ``parts`` is the clients-axis device count.  The shard count S must
    be divisible by it — anything else would silently replicate work
    (the exact failure mode the SPMD mapping exists to retire), so it
    is rejected loudly with the knobs named.  WITHIN a group a
    non-divisible megabatch count is legal: the group is padded with
    duplicates of its first megabatch (< parts extra rows per group,
    pure redundant compute whose outputs ``select`` drops), because
    every device must run the same static per-group scan."""
    S = placement.num_shards
    if parts < 1:
        raise ValueError(f"mesh clients axis must be >= 1, got {parts}")
    if S % parts:
        raise ValueError(
            f"hierarchical SPMD tier-1 needs the megabatch count "
            f"S = users_count/megabatch divisible by the mesh clients "
            f"axis (S={S}, clients axis={parts}): pick --megabatch / "
            f"--mesh-shape so S % clients == 0 — silently replicating "
            f"megabatches across devices would defeat the sharding")
    grids, counts, per_dev, sid_rows = [], [], [], []
    for count, sids in placement.groups:
        k = -(-len(sids) // parts)
        padded = list(sids) + [sids[0]] * (k * parts - len(sids))
        grids.append(placement.grid[padded])
        counts.append(count)
        per_dev.append(k)
        sid_rows.append(np.asarray(padded, np.int32))
    k_sum = sum(per_dev)
    select = np.empty(S, np.int64)
    for gi, (_, sids) in enumerate(placement.groups):
        k, off = per_dev[gi], sum(per_dev[:gi])
        for r, sid in enumerate(sids):
            q, j = divmod(r, k)
            select[sid] = q * k_sum + off + j
    return SpmdSchedule(grids=tuple(grids), counts=tuple(counts),
                        select=select, parts=parts,
                        padded_shards=k_sum * parts,
                        sids=tuple(sid_rows))


def _client_map_spmd(shard_fn, placement: Placement, plan, *args,
                     with_sid=False):
    """One true SPMD program for the megabatch axis: a ``shard_map``
    over the mesh ``clients`` axis in which each device runs the
    group scans over ITS megabatch rows only, then one explicit tiled
    ``all_gather`` per output leaf — O(S · leaf_row_bytes) collective
    traffic — hands every device the full device-major stack, and the
    host-computed ``select`` gather restores megabatch order (and
    drops padding duplicates).  Output pytree: identical structure,
    shapes and (ulp-band) values to the sequential scan path."""
    import functools

    from attacking_federate_learning_tpu.parallel.distances import (
        _pvary, shard_map
    )
    from attacking_federate_learning_tpu.parallel.mesh import CLIENTS
    from jax.sharding import PartitionSpec as P

    sched = spmd_schedule(placement, plan.mesh.shape[CLIENTS])
    grids = tuple(jnp.asarray(g) for g in sched.grids)
    sid_ops = (tuple(jnp.asarray(s) for s in sched.sids) if with_sid
               else ())
    in_specs = (tuple(P(CLIENTS, None) for _ in grids)
                + tuple(P(CLIENTS) for _ in sid_ops))

    @functools.partial(
        shard_map, mesh=plan.mesh, in_specs=in_specs,
        out_specs=P(), check_rep=False)
    def run(*operands):
        dev_grids = operands[:len(grids)]
        dev_sids = operands[len(grids):]
        pieces = []
        for gi, (count, grid) in enumerate(zip(sched.counts,
                                               dev_grids)):
            if with_sid:
                # shard ids ride the scan beside the id grid so the
                # per-shard fault stream replays exactly (ISSUE 19)
                def body(carry, x, _c=count):
                    sid, ids = x
                    return carry, shard_fn(sid, ids, _c, *args)

                xs = (dev_sids[gi], grid)
            else:
                def body(carry, ids, _c=count):
                    return carry, shard_fn(ids, _c, *args)

                xs = grid
            _, stacked = lax.scan(
                body, _pvary(jnp.zeros((), jnp.int32), CLIENTS), xs)
            pieces.append(stacked)
        local = (pieces[0] if len(pieces) == 1
                 else jax.tree_util.tree_map(
                     lambda *xs: jnp.concatenate(xs, axis=0), *pieces))
        return jax.tree_util.tree_map(
            lambda x: lax.all_gather(x, CLIENTS, tiled=True), local)

    out = run(*grids, *sid_ops)
    sel = jnp.asarray(sched.select)
    return jax.tree_util.tree_map(lambda a: a[sel], out)


def broadcast(value, plan=None):
    """Server -> clients broadcast.  Functionally the identity (the
    scanned client_map closes over the value and XLA replicates it);
    under a MeshPlan it additionally pins the replicated layout so the
    broadcast operand never picks up a stray sharding from its
    producer."""
    if plan is None:
        return value
    from jax.sharding import PartitionSpec as P

    return lax.with_sharding_constraint(value, plan.sharding(P()))


def client_map(shard_fn, placement: Placement, *args, plan=None,
               with_sid=False):
    """Stream ``shard_fn`` over the client axis, one megabatch at a time.

    ``shard_fn(ids, mal_count, *args) -> pytree`` receives a traced
    (m,) int32 id vector and its megabatch's STATIC malicious-row
    count; ``*args`` are broadcast operands (server state, round
    index).  Returns the per-megabatch pytrees stacked along a leading
    shard axis, in megabatch order — the (n/m, ...) shard-estimate
    matrix.  One ``lax.scan`` per placement group (distinct malicious
    count), so only one megabatch's intermediates are live at a time.

    ``with_sid=True`` threads each megabatch's SHARD id through the
    scan — ``shard_fn(sid, ids, mal_count, *args)`` — so a per-shard
    PRNG stream (the ISSUE 19 fault draw, keyed ``fold_in(fold_in(key,
    t), sid)``) replays identically on the host regardless of group
    order or SPMD padding.  Off by default: the False path traces the
    exact pre-ISSUE-19 program (HLO byte-identity of faults-off runs).

    ``plan``: a MeshPlan whose ``clients`` axis holds > 1 device
    switches to the SPMD mapping (:func:`_client_map_spmd`) — devices
    scan their own megabatches concurrently and meet in one explicit
    all_gather.  ``None`` (or a 1-device clients axis) is the
    sequential scan, byte-for-byte the pre-SPMD program.
    """
    if plan is not None:
        from attacking_federate_learning_tpu.parallel.mesh import CLIENTS

        if plan.mesh.shape[CLIENTS] > 1:
            return _client_map_spmd(shard_fn, placement, plan, *args,
                                    with_sid=with_sid)
    pieces, order = [], []
    for count, sids in placement.groups:
        grid = jnp.asarray(placement.grid[list(sids)])

        if with_sid:
            def body(carry, x, _c=count):
                sid, ids = x
                return carry, shard_fn(sid, ids, _c, *args)

            xs = (jnp.asarray(list(sids), jnp.int32), grid)
        else:
            def body(carry, ids, _c=count):
                return carry, shard_fn(ids, _c, *args)

            xs = grid
        _, stacked = lax.scan(body, jnp.zeros((), jnp.int32), xs)
        pieces.append(stacked)
        order.extend(sids)
    out = (pieces[0] if len(pieces) == 1
           else jax.tree_util.tree_map(
               lambda *xs: jnp.concatenate(xs, axis=0), *pieces))
    if order == sorted(order):
        return out
    inv = jnp.asarray(np.argsort(np.asarray(order)))
    return jax.tree_util.tree_map(lambda a: a[inv], out)


def shard_reduce(tier2_fn, estimates, num_shards: int,
                 corrupted_shards: int, alive_counts=None, plan=None,
                 **kw):
    """Cross-shard (tier-2) robust reduction over the (n/m, d)
    shard-estimate matrix.

    ``tier2_fn`` is a defenses/kernels.py ``shard_*`` entry (or any
    ``(G, n, f, alive_counts=None) -> (d,)`` reducer);
    ``alive_counts`` (S,) carries each shard's effective cohort from
    the fault masks — a fully-dead shard's estimate is excluded.
    Under a MeshPlan the estimate matrix is constrained to the
    clients-axis layout first so the reduction's collectives are
    explicit.  ``telemetry=True`` (forwarded through ``**kw`` to the
    shard_* entry) additionally returns the tier-2 diagnostics pytree
    — (S,)-shaped selection masks/scores over the SHARD axis, the
    which-estimates-were-rejected record the forensics layer
    attributes colluder placement from (report.py).

    Stage ledger (utils/costs.py): the reduction — resharding
    constraint included — is the ``tier2_aggregate`` stage, whatever
    ``tier2_fn`` the caller passes (the engine's dispatch wrap covers
    its own; raw kernels from tests/bench get it here)."""
    from attacking_federate_learning_tpu.utils.costs import stage_scope

    with stage_scope("tier2_aggregate"):
        estimates = estimates.astype(jnp.float32)
        if plan is not None:
            estimates = plan.constrain_estimates(estimates)
        return tier2_fn(estimates, num_shards, corrupted_shards,
                        alive_counts=alive_counts, **kw)


def two_tier_aggregate(users_grads, placement: Placement, tier1_fn,
                       tier2_fn, tier1_corrupted: int,
                       tier2_corrupted: int, mask=None, weights=None,
                       plan=None, telemetry=False):
    """Reference two-tier aggregation over a MATERIALIZED (n, d) matrix.

    The engine's hierarchical round never builds this matrix (gradients
    are computed inside client_map); this helper exists for the places
    that already hold one — kernel-level tests (each tier-1 estimate
    must bit-match the flat kernel on that shard's rows) and the
    aggregation-only benchmarks.  ``mask`` (n,) is the quarantine seam:
    each megabatch's tier-1 runs mask-aware over its rows and tier-2
    receives the per-shard alive counts.

    ``telemetry=True`` (trace-time, like the kernels' flag) returns
    ``(agg, tier1_diag, tier2_diag)``: ``tier1_diag`` is the flat
    kernel's diagnostics pytree stacked along a leading shard axis —
    each row is BY CONSTRUCTION the flat kernel's telemetry on that
    shard's sub-matrix, the bit-match contract the engine's
    shard_selection events inherit — and ``tier2_diag`` is the
    shard_* entry's (S,)-shaped selection record.

    ``weights`` (n,) threads each megabatch's rows through the
    kernels' staleness-weight seam (requires ``mask`` — the kernels
    reject weights without a delivered-cohort mask); ``plan`` with a
    multi-device clients axis runs the SPMD client_map (the estimates
    come back replicated from the explicit all_gather, so the tier-2
    resharding constraint is skipped — there is nothing to reshard).
    """
    m = placement.megabatch
    if weights is not None and mask is None:
        from attacking_federate_learning_tpu.defenses.kernels import (
            check_weight_seam
        )

        check_weight_seam(mask, weights)   # raises, naming the seam

    def shard_fn(ids, _c, G, gmask, gw):
        rows = G[ids]
        if gmask is None:
            if not telemetry:
                return tier1_fn(rows, m,
                                tier1_corrupted).astype(jnp.float32)
            est, diag = tier1_fn(rows, m, tier1_corrupted,
                                 telemetry=True)
            return est.astype(jnp.float32), diag
        sm = gmask[ids]
        kw = {} if gw is None else {"weights": gw[ids]}
        if not telemetry:
            est = tier1_fn(rows, m, tier1_corrupted, mask=sm, **kw)
            return est.astype(jnp.float32), jnp.sum(sm).astype(jnp.int32)
        est, diag = tier1_fn(rows, m, tier1_corrupted, mask=sm,
                             telemetry=True, **kw)
        return (est.astype(jnp.float32), jnp.sum(sm).astype(jnp.int32),
                diag)

    out = client_map(shard_fn, placement, users_grads, mask, weights,
                     plan=plan)
    spmd = False
    if plan is not None:
        from attacking_federate_learning_tpu.parallel.mesh import CLIENTS

        spmd = plan.mesh.shape[CLIENTS] > 1
    tier2_plan = None if spmd else plan
    t1_diag = None
    if mask is None:
        if telemetry:
            estimates, t1_diag = out
            alive = None
        else:
            estimates, alive = out, None
    elif telemetry:
        estimates, alive, t1_diag = out
    else:
        estimates, alive = out
    if not telemetry:
        return shard_reduce(tier2_fn, estimates, placement.num_shards,
                            tier2_corrupted, alive_counts=alive,
                            plan=tier2_plan)
    agg, t2_diag = shard_reduce(tier2_fn, estimates,
                                placement.num_shards, tier2_corrupted,
                                alive_counts=alive, plan=tier2_plan,
                                telemetry=True)
    return agg, t1_diag, t2_diag


# Megabatch sizing helper for callers that only know n (bench, docs):
# the largest power-of-two megabatch ≤ cap that divides n.
def auto_megabatch(n: int, cap: int = 512) -> Optional[int]:
    for m in (2 ** k for k in range(int(math.log2(max(cap, 1))), -1, -1)):
        if m <= cap and n % m == 0 and n // m >= 2:
            return m
    return None
