"""'A Little Is Enough' (ALIE) mean-shift drift attack.

Reference ``DriftAttack`` (malicious.py:30-36): the crafted gradient is the
malicious cohort's mean shifted down by z standard deviations per coordinate,
``mean - z * sigma`` (the reference mutates grads_mean in place; the value is
identical).  z is the fixed CLI constant num_std (default 1.5, reference
main.py:109-110) — the reference does not derive the paper's z_max from the
phi-quantile formula, and neither does this default path (SURVEY.md §2.4 #3).
``num_std='auto'`` (beyond-reference) computes it via :func:`paper_z`.
"""

from __future__ import annotations

from statistics import NormalDist

import jax.numpy as jnp

from attacking_federate_learning_tpu.attacks.base import (
    Attack, delivered_cohort_stats
)


def paper_z(users_count: int, corrupted_count: int) -> float:
    """The ALIE paper's z_max (Baruch et al., NeurIPS'19 §3.1): the
    largest shift such that the crafted value still looks like a
    majority-side sample to a trimming defense.  With
    ``s = floor(n/2 + 1) - f`` honest supporters required,

        z_max = Phi^-1((n - f - s) / (n - f))

    — the quantile below which fewer than s honest workers are expected.
    The reference never computes this (its z is the CLI constant);
    ``num_std='auto'`` opts in.  The result is clamped to [0, z(0.9999)]:
    p <= 0.5 means the formula grants no positive hiding room (small
    cohorts / few attackers) and returns z = 0 — a negative z would
    invert the shift AND the backdoor clip envelope — while s <= 0
    (attacker majority) drives p past 1, where z_max is unbounded, so
    it caps at the 0.9999 quantile (z ~ 3.72)."""
    n, f = int(users_count), int(corrupted_count)
    honest = n - f
    if honest <= 0:
        return 0.0
    s = n // 2 + 1 - f
    p = (honest - s) / honest
    if p <= 0.5:
        return 0.0
    return float(NormalDist().inv_cdf(min(p, 0.9999)))


class DriftAttack(Attack):
    name = "alie"

    def craft(self, mal_grads, ctx=None):
        # Async rounds (ctx.staleness set): the statistics come from
        # the DELIVERED malicious rows only — the colluders coordinate
        # at the aggregation boundary and hide inside the envelope the
        # server actually aggregates (base.py:delivered_cohort_stats);
        # synchronous topologies keep the reference full-cohort stats.
        mean, stdev = delivered_cohort_stats(mal_grads, ctx)
        return mean - self.num_std * stdev

    def envelope_stats(self, users_grads, corrupted_count, ctx=None):
        """z-bound envelope telemetry: the cohort mean/sigma norms and
        the drift magnitude ``||z*sigma||`` — how far the crafted vector
        sits from the honest mean, in the same units a clip envelope
        (backdoor.py) or a trimming defense measures it."""
        f = corrupted_count
        if f == 0 or self.num_std == 0:
            return {}
        mean, stdev = delivered_cohort_stats(users_grads[:f], ctx)
        sigma_norm = jnp.linalg.norm(stdev)
        return {"z": jnp.asarray(self.num_std, jnp.float32),
                "mean_norm": jnp.linalg.norm(mean),
                "sigma_norm": sigma_norm,
                "drift_norm": jnp.asarray(self.num_std,
                                          jnp.float32) * sigma_norm}

    def margin_stats(self, users_grads, corrupted_count, ctx=None,
                     crafted=None):
        """Envelope utilization (cfg.margins, ISSUE 18): the z the
        attack spends vs. the paper's z_max for this cohort —
        ``z_utilization`` < 1 means hiding room left on the table, > 1
        means the drift has left the regime the paper's majority
        argument covers (inf when z_max is 0: no hiding room exists at
        this n/f) — plus the drift magnitude in envelope units."""
        f = corrupted_count
        if f == 0 or self.num_std == 0:
            return {}
        z = float(self.num_std)
        z_max = paper_z(users_grads.shape[0], f)
        util = z / z_max if z_max > 0 else float("inf")
        _, stdev = delivered_cohort_stats(users_grads[:f], ctx)
        return {"z_used": jnp.asarray(z, jnp.float32),
                "z_max": jnp.asarray(z_max, jnp.float32),
                "z_utilization": jnp.asarray(util, jnp.float32),
                "drift_norm": jnp.asarray(z, jnp.float32)
                * jnp.linalg.norm(stdev)}
