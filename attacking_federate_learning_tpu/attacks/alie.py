"""'A Little Is Enough' (ALIE) mean-shift drift attack.

Reference ``DriftAttack`` (malicious.py:30-36): the crafted gradient is the
malicious cohort's mean shifted down by z standard deviations per coordinate,
``mean - z * sigma`` (the reference mutates grads_mean in place; the value is
identical).  z is the fixed CLI constant num_std (default 1.5, reference
main.py:109-110) — the reference does not derive the paper's z_max from the
phi-quantile formula, and neither does this default path (SURVEY.md §2.4 #3).
"""

from __future__ import annotations

from attacking_federate_learning_tpu.attacks.base import Attack, cohort_stats


class DriftAttack(Attack):
    name = "alie"

    def craft(self, mal_grads, ctx=None):
        mean, stdev = cohort_stats(mal_grads)
        return mean - self.num_std * stdev
