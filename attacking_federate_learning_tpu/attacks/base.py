"""Attack framework.

The reference's attack seam is ``Attack.attack(mal_users)`` called once per
round between client compute and gradient collection (reference main.py:66-68,
malicious.py:10-27): it computes the mean and population std of the malicious
cohort's *honest* gradients, asks the subclass for one crafted vector, and
overwrites every malicious client's gradient with that same vector
(malicious.py:26-27).

Here the seam is functional: ``craft(mal_grads (m, d), ctx) -> (d,)``
produces the crafted vector and the engine broadcasts it into the first f
rows of the (n, d) gradient matrix (malicious clients are the first f ids,
reference main.py:28).  ``ctx`` carries what the reference stashes on user 0
(user.py:84-86): the round's broadcast weights and the faded learning rate.

``num_std == 0`` disables crafting and leaves the honest gradients in place
(reference malicious.py:21-22).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class AttackContext(NamedTuple):
    original_params: jax.Array   # (d,) weights broadcast this round
    learning_rate: jax.Array     # faded lr (reference server.py:50-52)
    round: jax.Array = 0         # () int32 round index (rng derivation)
    # Asynchronous rounds only (core/async_rounds.py): the (m,) int32
    # per-row staleness view of the DELIVERED cohort — t - birth on
    # delivered rows, -1 on undelivered ones.  None under the
    # synchronous topologies, where every row is fresh by construction.
    # The attack seam runs at DELIVERY time in async mode, so crafting
    # statistics must come from the delivered sub-cohort
    # (:func:`delivered_cohort_stats`) — the aggregation never sees the
    # rest.
    staleness: Optional[jax.Array] = None


def cohort_stats(mal_grads):
    """Mean and population std over the malicious cohort
    (reference malicious.py:18-19: np.var ** 0.5, i.e. ddof=0)."""
    mean = jnp.mean(mal_grads, axis=0)
    stdev = jnp.sqrt(jnp.var(mal_grads, axis=0))
    return mean, stdev


def masked_cohort_stats(mal_grads, delivered):
    """Mean and population std over the DELIVERED malicious rows only
    (``delivered`` (f,) bool) — fixed shapes, traced delivered count.
    With every row delivered this computes exactly
    :func:`cohort_stats` up to summation order (mean-of-all vs
    sum/count are the same reduction here: sum over the full axis
    divided by the full count)."""
    e = jnp.maximum(jnp.sum(delivered), 1)
    mean = jnp.sum(jnp.where(delivered[:, None], mal_grads, 0.0),
                   axis=0) / e
    var = jnp.sum(jnp.where(delivered[:, None],
                            (mal_grads - mean[None, :]) ** 2, 0.0),
                  axis=0) / e
    return mean, jnp.sqrt(var)


def delivered_cohort_stats(mal_grads, ctx):
    """The crafting statistics an attack seam should use: the classic
    full-cohort stats under the synchronous topologies, the
    delivered-sub-cohort stats in async mode (``ctx.staleness >= 0``
    marks delivery) — how ALIE "recalibrates its envelope to the
    delivered cohort" (ISSUE 9)."""
    if ctx is None or ctx.staleness is None:
        return cohort_stats(mal_grads)
    f = mal_grads.shape[0]
    return masked_cohort_stats(mal_grads, ctx.staleness[:f] >= 0)


class Attack:
    """Base class; subclasses implement ``craft``."""

    name = "none"

    def __init__(self, num_std: float):
        self.num_std = num_std

    def craft(self, mal_grads, ctx: AttackContext):
        """(m, d) honest malicious-cohort grads -> (d,) crafted vector."""
        raise NotImplementedError

    def apply(self, users_grads, corrupted_count: int,
              ctx: Optional[AttackContext] = None):
        """Full seam: returns users_grads with the first f rows replaced.

        No-ops when there are no malicious users (reference malicious.py:11)
        or num_std == 0 (malicious.py:21).
        """
        f = corrupted_count
        if f == 0 or self.num_std == 0:
            return users_grads
        crafted = self.craft(users_grads[:f], ctx)
        return users_grads.at[:f].set(crafted[None, :])

    def envelope_stats(self, users_grads, corrupted_count: int,
                       ctx: Optional[AttackContext] = None) -> dict:
        """Telemetry seam (core/engine.py, cfg.telemetry): fixed-shape,
        device-side stats of the attack's crafting envelope, computed on
        the PRE-attack gradient matrix — the same honest malicious-cohort
        view ``craft`` derives its statistics from.  Must stay pure jax
        (it runs inside the fused round program; no host callbacks).
        Default: nothing to report."""
        return {}

    def margin_stats(self, users_grads, corrupted_count: int,
                     ctx: Optional[AttackContext] = None,
                     crafted=None) -> dict:
        """Margin-observatory seam (core/engine.py, cfg.margins; ISSUE
        18): fixed-shape, device-side ENVELOPE-UTILIZATION margins —
        how much of the defense-evading envelope the attack actually
        spends (the attack-side complement of the defenses' decision
        margins, utils/margins.py).  ``users_grads`` is the PRE-attack
        matrix (the honest view ``craft`` derives its statistics
        from); ``crafted`` is the POST-attack matrix, for attacks
        whose utilization is a property of the delivered rows (the
        backdoor's clip saturation).  Must stay pure jax (it runs
        inside the fused round program; no host callbacks).  Default:
        nothing to report."""
        return {}


class NoAttack(Attack):
    name = "none"

    def __init__(self):
        super().__init__(num_std=0.0)

    def apply(self, users_grads, corrupted_count, ctx=None):
        return users_grads
