"""Clipped backdoor attack.

Reproduces the reference ``BackdoorAttack`` pipeline (reference
backdoor.py:13-159), restructured as pure jitted functions:

1. Project where honest descent would land this round:
   ``start = original_params - faded_lr * grads_mean`` (backdoor.py:54).
2. Fine-tune a shadow net from ``start`` on poisoned data — trigger pattern
   with target class 0, or a single sample relabeled (y+1)%5
   (backdoor.py:80-83, :128-131) — with the anchor loss
   ``NLL + alpha * sum_tensors MSE(p, p_start)`` (backdoor.py:140-148),
   skipping training entirely when the backdoor already classifies at 100%
   (backdoor.py:114-116).
3. Re-express the desired parameters as a gradient:
   ``new_grads = (start - (mal_params + lr*mean)) / lr`` (backdoor.py:59-60).
4. Launder it through the ALIE envelope: clip into
   ``[mean - z*sigma, mean + z*sigma]`` (backdoor.py:62-63) — the clipping is
   what defeats the statistical defenses.

Reference quirks preserved: the shadow optimizer is constructed fresh every
batch (backdoor.py:132), making its momentum inert — the effective update is
plain SGD with lr 0.1 and weight decay 1e-4, which is what the jitted
training loop implements; nan guards raise (backdoor.py:145-152).

Deviation (documented): reference 'sample k' mode indexes a shuffled
permutation via DistributedSampler rank k-1 (backdoor.py:33-34) and is
broken from the CLI (argparse leaves k a string, SURVEY.md §2.4 #10); here
'sample k' poisons training image k-1 directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from attacking_federate_learning_tpu.attacks.base import (
    Attack, cohort_stats, masked_cohort_stats
)
from attacking_federate_learning_tpu.core.evaluate import (
    masked_nll_metrics, pad_to_batches
)
from attacking_federate_learning_tpu.data import triggers
from attacking_federate_learning_tpu.models.base import get_model
from attacking_federate_learning_tpu.models.layers import nll_loss
from attacking_federate_learning_tpu.utils.flatten import make_flattener


class BackdoorAttack(Attack):
    name = "backdoor"
    # The engine checks aggregated weights for finiteness after fused
    # rounds/spans — the in-program replacement for the reference's
    # host-side nan raise (backdoor.py:145-152), see craft() below.
    checks_finite = True

    def __init__(self, cfg, dataset, model=None, flat=None, rng=None):
        super().__init__(cfg.num_std)
        self.cfg = cfg
        # The whole pipeline (shadow train included) is pure jitted jax,
        # so the round can fuse it (cfg.backdoor_fused, default).  Staged
        # mode retains the reference's exact per-round host nan guard.
        self.fusable = bool(getattr(cfg, "backdoor_fused", True))
        self.backdoor = cfg.backdoor
        self.alpha = cfg.alpha
        self.model = model or get_model(cfg.model)
        if flat is None:
            flat = make_flattener(self.model.init(jax.random.key(cfg.seed)))
        self.flat = flat
        self._build_poison_set(dataset, rng or np.random.default_rng(cfg.seed))
        self._build_fns()

    # ------------------------------------------------------------------
    def _build_poison_set(self, dataset, rng):
        B = self.cfg.mal_batch_size
        x, y = dataset.train_x, dataset.train_y
        if self.backdoor == "pattern":
            # A random 1/u strided shard, u = len/batch/10 (reference
            # backdoor.py:37-42) — about 10 batches of mal_batch_size.
            u = max(1, len(x) // B // 10)
            perm = rng.permutation(len(x))
            shard = perm[int(rng.integers(u))::u]
            px = jnp.asarray(x[shard])
            px = triggers.add_pattern(px)
            py = jnp.asarray(y[shard])
        else:
            # 'sample k': the single training image k-1 (see module
            # docstring on the reference's broken indexing).
            k = int(self.backdoor) - 1
            px = jnp.asarray(x[k: k + 1])
            py = jnp.asarray(y[k: k + 1])
        py = triggers.backdoor_targets(py, self.backdoor)

        # Pad to whole batches with a validity mask (static shapes; shared
        # helper with the server eval path).
        n = px.shape[0]
        bx, by, bm = pad_to_batches(np.asarray(px), np.asarray(py),
                                    min(B, n))
        self.poison_x = jnp.asarray(bx)
        self.poison_y = jnp.asarray(by)
        self.poison_mask = jnp.asarray(bm)
        self.poison_count = float(n)

    # ------------------------------------------------------------------
    def _build_fns(self):
        model, flat, cfg = self.model, self.flat, self.cfg
        alpha = self.alpha
        px, py, pm = self.poison_x, self.poison_y, self.poison_mask
        n_steps = cfg.mal_epochs * px.shape[0]
        lr, wd = cfg.mal_learning_rate, cfg.mal_weight_decay

        def poison_metrics(flat_w):
            """(loss, correct) over the poisoned set (reference
            backdoor.py:67-102; test_loader is the train loader,
            backdoor.py:43; loss is the sum of per-batch mean NLLs divided
            by the set size, matching backdoor.py:89, :93)."""
            params = flat.unravel(flat_w)
            loss_sum, correct = masked_nll_metrics(model.apply, params,
                                                   px, py, pm)
            return loss_sum / self.poison_count, correct

        def poison_accuracy(flat_w):
            _, correct = poison_metrics(flat_w)
            return 100.0 * correct / self.poison_count

        def shadow_loss(params, anchor, x, y, m):
            logp = model.apply(params, x)
            per_ex = -jnp.take_along_axis(logp, y[:, None], axis=1).squeeze(1)
            cls = jnp.sum(per_ex * m) / jnp.maximum(jnp.sum(m), 1.0)
            # Anchor: sum over parameter tensors of per-tensor mean MSE
            # (torch MSELoss summed across parameters, backdoor.py:142-144).
            dist = sum(jnp.mean((p - a) ** 2)
                       for p, a in zip(jax.tree_util.tree_leaves(params),
                                       jax.tree_util.tree_leaves(anchor)))
            return cls + alpha * dist

        grad_fn = jax.grad(shadow_loss)

        def train_shadow(start_flat):
            anchor = flat.unravel(start_flat)

            def do_train(w0):
                def step(params, i):
                    b = i % px.shape[0]
                    g = grad_fn(params, anchor, px[b], py[b], pm[b])
                    # Fresh-optimizer-per-batch quirk: momentum buffer is
                    # always zero, so the update is SGD + weight decay
                    # (reference backdoor.py:132, SURVEY.md §2.4 #9).
                    params = jax.tree_util.tree_map(
                        lambda p, gi: p - lr * (gi + wd * p), params, g)
                    return params, None

                params, _ = jax.lax.scan(step, flat.unravel(w0),
                                         jnp.arange(n_steps))
                return flat.ravel(params)

            # Early-out when the backdoor already fires at 100%
            # (reference backdoor.py:114-116).
            return jax.lax.cond(poison_accuracy(start_flat) >= 100.0,
                                lambda w: w, do_train, start_flat)

        def craft(mal_grads, original_params, learning_rate,
                  delivered=None):
            # ``delivered`` (async rounds, core/async_rounds.py): the
            # clip envelope and the descent projection come from the
            # DELIVERED malicious rows only — the server never
            # aggregates the rest, so laundering against the full
            # cohort would clip into an envelope nobody measures.
            if delivered is None:
                mean, stdev = cohort_stats(mal_grads)
            else:
                mean, stdev = masked_cohort_stats(mal_grads, delivered)
            start = original_params - learning_rate * mean
            mal_params = train_shadow(start)
            new_params = mal_params + learning_rate * mean
            new_grads = (start - new_params) / learning_rate
            return jnp.clip(new_grads,
                            mean - self.num_std * stdev,
                            mean + self.num_std * stdev)

        self._craft = jax.jit(craft)
        self._poison_metrics = jax.jit(poison_metrics)

    # ------------------------------------------------------------------
    def craft(self, mal_grads, ctx):
        if ctx is not None and ctx.staleness is not None:
            f = mal_grads.shape[0]
            out = self._craft(mal_grads, ctx.original_params,
                              ctx.learning_rate, ctx.staleness[:f] >= 0)
        else:
            out = self._craft(mal_grads, ctx.original_params,
                              ctx.learning_rate)
        if not isinstance(out, jax.core.Tracer):
            # Staged/eager path: the reference's per-round host nan guard
            # (backdoor.py:145-152).  Inside a fused round program the
            # engine checks the aggregated weights instead (checks_finite).
            if not bool(jnp.isfinite(out).all()):
                raise FloatingPointError(
                    "Got nan in backdoor shadow training")
        return out

    def envelope_stats(self, users_grads, corrupted_count, ctx=None):
        """Telemetry: the ALIE clip envelope the crafted gradient is
        laundered through (``||z*sigma||`` halfwidth) plus the shadow
        objective's state — poison-set loss/accuracy of the CURRENT
        global weights (when did the backdoor embed?).  Pure jitted jax,
        so the fused round program carries it without a host hop."""
        f = corrupted_count
        if f == 0 or self.num_std == 0:
            return {}
        if ctx is not None and ctx.staleness is not None:
            _, stdev = masked_cohort_stats(users_grads[:f],
                                           ctx.staleness[:f] >= 0)
        else:
            _, stdev = cohort_stats(users_grads[:f])
        loss, correct = self._poison_metrics(ctx.original_params)
        return {"z": jnp.asarray(self.num_std, jnp.float32),
                "clip_halfwidth_norm": jnp.asarray(
                    self.num_std, jnp.float32) * jnp.linalg.norm(stdev),
                "shadow_loss": loss,
                "poison_acc": 100.0 * correct / self.poison_count}

    def margin_stats(self, users_grads, corrupted_count, ctx=None,
                     crafted=None):
        """Boost headroom (cfg.margins, ISSUE 18): how hard the crafted
        rows press against the ALIE clip envelope they were laundered
        through.  ``clip_saturation`` — the fraction of malicious
        coordinates pinned at a clip boundary (1.0 means the shadow
        objective wanted more than the envelope allows everywhere);
        ``boost_headroom`` — the mean remaining distance to the nearer
        clip edge, normalized by the envelope halfwidth (0 = at the
        boundary, 1 = at the honest mean).  Measured on the POST-attack
        rows against the PRE-attack envelope — no shadow-train
        re-run."""
        f = corrupted_count
        if f == 0 or self.num_std == 0 or crafted is None:
            return {}
        if ctx is not None and ctx.staleness is not None:
            mean, stdev = masked_cohort_stats(users_grads[:f],
                                              ctx.staleness[:f] >= 0)
        else:
            mean, stdev = cohort_stats(users_grads[:f])
        half = jnp.asarray(self.num_std, jnp.float32) * stdev
        lo, hi = mean - half, mean + half
        rows = crafted[:f]
        sat = jnp.mean(((rows <= lo[None, :]) | (rows >= hi[None, :]))
                       .astype(jnp.float32))
        head = jnp.minimum(hi[None, :] - rows, rows - lo[None, :])
        return {"clip_saturation": sat,
                "boost_headroom": jnp.mean(
                    head / jnp.maximum(half[None, :], 1e-12))}

    def test_asr(self, flat_w, logger=None, tag="POST"):
        """Attack success rate of the *server* weights on the poisoned set
        (reference main.py:91-95 + backdoor.py:67-102); log line format
        matches reference backdoor.py:97-101."""
        loss, correct = self._poison_metrics(jnp.asarray(flat_w))
        acc = 100.0 * float(correct) / self.poison_count
        if logger is not None:
            logger.print(
                "##Test malicious net: [{}] Average loss: {:.4f}, "
                "Accuracy: {}/{} ({:.2f}%)".format(
                    tag, float(loss), int(correct), self.poison_count, acc))
        return acc


class TimedBackdoorAttack(BackdoorAttack):
    """The async timing-channel backdoor (ISSUE 9): identical crafting
    pipeline, but the attacker GAMES THE ARRIVAL SCHEDULE — its rows
    always emit with delay 0 (``timed``, read by
    core/async_rounds.py:draw_delays), so every delivered malicious row
    is fresh: full staleness weight, and a clip envelope computed
    against whatever stale honest rows share its bus.  The price is
    FIFO priority — freshest-born rows board the k-bus last — so the
    timing channel is a measured trade, not a free win (GRID_RESULTS
    round-9).  The attacker controls content and emission time only;
    arrival timestamps (hence weights) are the server's.

    Only meaningful under ``aggregation='async'`` — the engine and CLI
    reject it elsewhere (there is no arrival time to game)."""

    name = "backdoor_timed"
    timed = True
