from attacking_federate_learning_tpu.attacks.base import (  # noqa: F401
    Attack, AttackContext, NoAttack, cohort_stats
)
from attacking_federate_learning_tpu.attacks.alie import DriftAttack  # noqa: F401
from attacking_federate_learning_tpu.utils.plugins import Registry

# Factories with the uniform signature (cfg, dataset) -> Attack, so new
# attacks plug in the way new defenses do (the reference hardwires its two
# attacks at main.py:44-54).
ATTACKS = Registry("attack")
ATTACKS.register("none", lambda cfg, dataset=None: NoAttack())
ATTACKS.register("alie", lambda cfg, dataset=None: DriftAttack(cfg.num_std))


def _make_backdoor(cfg, dataset=None):
    from attacking_federate_learning_tpu.attacks.backdoor import (
        BackdoorAttack
    )
    return BackdoorAttack(cfg, dataset=dataset)


def _make_backdoor_timed(cfg, dataset=None):
    from attacking_federate_learning_tpu.attacks.backdoor import (
        TimedBackdoorAttack
    )
    return TimedBackdoorAttack(cfg, dataset=dataset)


ATTACKS.register("backdoor", _make_backdoor)
ATTACKS.register("backdoor_timed", _make_backdoor_timed)

from attacking_federate_learning_tpu.attacks.baselines import (  # noqa: E402
    GaussianNoiseAttack, SignFlipAttack
)

ATTACKS.register("signflip",
                 lambda cfg, dataset=None: SignFlipAttack(cfg.num_std))
ATTACKS.register("noise",
                 lambda cfg, dataset=None: GaussianNoiseAttack(
                     cfg.num_std, seed=cfg.seed))

from attacking_federate_learning_tpu.attacks.minmax import (  # noqa: E402
    MinMaxAttack, MinSumAttack
)

ATTACKS.register("minmax",
                 lambda cfg, dataset=None: MinMaxAttack(
                     cfg.num_std, direction=cfg.attack_direction))
ATTACKS.register("minsum",
                 lambda cfg, dataset=None: MinSumAttack(
                     cfg.num_std, direction=cfg.attack_direction))


def make_attacker(cfg, dataset=None, name=None):
    """Attack selection mirroring reference main.py:44-54: a backdoor option
    picks BackdoorAttack, otherwise ALIE DriftAttack."""
    if name is None:
        name = "backdoor" if cfg.backdoor else "alie"
    return ATTACKS[name](cfg, dataset=dataset)
