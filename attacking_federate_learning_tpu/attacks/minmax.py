"""AGR-agnostic min-max / min-sum attacks (Shejwalkar & Houmansadr,
NDSS'21, "Manipulating the Byzantine").

Beyond-reference additions (the reference ships only ALIE + backdoor):
the crafted gradient is ``mean + gamma * p`` for a perturbation direction
``p``, with gamma pushed as large as possible subject to staying
inside the benign cohort's own spread:

- min-max:  max_i ||crafted - g_i||  <=  max_{i,j} ||g_i - g_j||
- min-sum:  sum_i ||crafted - g_i||^2  <=  max_i sum_j ||g_i - g_j||^2

Both constraints are monotone in gamma, so gamma* is found by a
fixed-trip bisection (fully jittable -> the attack fuses into the round
program like ALIE).  Directions: the cohort's negative std ('std', the
paper's best performer), -sign(mean) ('sign'), or the negative unit mean
('unit').
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from attacking_federate_learning_tpu.attacks.base import Attack, cohort_stats


_BISECT_STEPS = 25
_GAMMA_INIT = 10.0


def _direction(mal_grads, kind):
    mean, stdev = cohort_stats(mal_grads)
    if kind == "std":
        p = -stdev
    elif kind == "sign":
        p = -jnp.sign(mean)
    else:  # 'unit'
        p = -mean / jnp.maximum(jnp.linalg.norm(mean), 1e-12)
    return mean, p


def _bisect_gamma(feasible, hi0=_GAMMA_INIT, steps=_BISECT_STEPS):
    """Largest gamma with feasible(gamma) True, via doubling + bisection
    in a fixed-trip fori_loop (static shapes, jit-friendly)."""
    def grow(_, hi):
        return jnp.where(feasible(hi), hi * 2.0, hi)

    hi = lax.fori_loop(0, 10, grow, jnp.asarray(hi0, jnp.float32))

    def shrink(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        ok = feasible(mid)
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo, _ = lax.fori_loop(0, steps, shrink,
                          (jnp.asarray(0.0, jnp.float32), hi))
    return lo


class MinMaxAttack(Attack):
    """Crafted gradient's max distance to any cohort member stays within
    the cohort's own max pairwise distance."""

    name = "minmax"

    def __init__(self, num_std=1.5, direction="std"):
        # num_std is unused by the optimization but kept for the uniform
        # Attack signature (z=0 still disables the attack, base.apply).
        super().__init__(num_std)
        self.direction = direction

    def _threshold(self, G):
        sq = jnp.sum(G * G, axis=1)
        d2 = sq[:, None] + sq[None, :] - 2.0 * (G @ G.T)
        return jnp.max(jnp.maximum(d2, 0.0))          # max pairwise^2

    def _violation(self, crafted, G):
        return jnp.max(jnp.sum((G - crafted[None, :]) ** 2, axis=1))

    def craft(self, mal_grads, ctx=None):
        G = mal_grads.astype(jnp.float32)
        mean, p = _direction(G, self.direction)
        budget = self._threshold(G)

        def feasible(gamma):
            return self._violation(mean + gamma * p, G) <= budget

        gamma = _bisect_gamma(feasible)
        return (mean + gamma * p).astype(mal_grads.dtype)


class MinSumAttack(MinMaxAttack):
    """Crafted gradient's summed squared distance to the cohort stays
    within the worst cohort member's own sum."""

    name = "minsum"

    def _threshold(self, G):
        sq = jnp.sum(G * G, axis=1)
        d2 = sq[:, None] + sq[None, :] - 2.0 * (G @ G.T)
        return jnp.max(jnp.sum(jnp.maximum(d2, 0.0), axis=1))

    def _violation(self, crafted, G):
        return jnp.sum(jnp.sum((G - crafted[None, :]) ** 2, axis=1))
