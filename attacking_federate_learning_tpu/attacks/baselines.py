"""Baseline Byzantine attacks for grid comparisons.

The reference ships exactly two attacks (ALIE and the clipped backdoor);
these textbook baselines give the defense grid its classical comparison
points.  Same pure ``craft`` seam as every other attack.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from attacking_federate_learning_tpu.attacks.base import Attack, cohort_stats


class SignFlipAttack(Attack):
    """Submit the negated cohort mean scaled by num_std — classic
    gradient-ascent Byzantine behavior."""

    name = "signflip"

    def craft(self, mal_grads, ctx=None):
        mean, _ = cohort_stats(mal_grads)
        return -self.num_std * mean


class GaussianNoiseAttack(Attack):
    """Replace the cohort gradient with pure Gaussian noise at num_std
    times the cohort's per-coordinate std."""

    name = "noise"

    def __init__(self, num_std: float, seed: int = 0):
        super().__init__(num_std)
        self._key = jax.random.key(seed)

    def craft(self, mal_grads, ctx=None):
        mean, stdev = cohort_stats(mal_grads)
        # Per-round key keeps the fused round a pure function of its
        # inputs while varying the noise each round.
        rnd = ctx.round if ctx is not None else 0
        key = jax.random.fold_in(self._key, jnp.asarray(rnd, jnp.int32))
        noise = jax.random.normal(key, mean.shape, mean.dtype)
        return mean + self.num_std * stdev * noise
