"""Simulated wire protocols between clients and the server.

The reference simulator (and the faithful rebuild) hands the server
every client's update in the clear; this package models the protocols a
production deployment actually speaks on that wire.  First resident:
:mod:`secagg` — Bonawitz-style pairwise-masked secure aggregation
(arXiv 1611.04482), simulated *inside* the fused round program with
bit-exact mask cancellation (core/engine.py ``cfg.secagg``).
"""

from attacking_federate_learning_tpu.protocols.secagg import (  # noqa: F401
    SECAGG_MODES, secagg_cohort, secagg_key
)
