"""Simulated secure aggregation: pairwise masks that cancel bit-exactly.

Bonawitz et al. (arXiv 1611.04482) let a federated server learn ONLY the
sum of client updates: every pair of clients (i, j) agrees on a shared
mask; client i adds it, client j subtracts it, and the masks cancel in
the server's sum.  Dropped clients are handled by reconstructing their
pairwise masks from the survivors' secret shares.  This module is that
protocol as a pure jax computation that runs *inside* the fused round
program (core/engine.py ``cfg.secagg``), with two deliberate
simulation choices:

**Masking lives in the uint32 bitcast domain.**  f32 addition is not
exactly invertible (``(x + m) - m != x`` in general), so float masks
could never cancel bit-exactly.  Instead the (d,) f32 update is
bitcast to uint32 and masked with mod-2^32 addition, which IS exactly
invertible and exactly associative: ``u + delta - delta == u`` for
every bit pattern (NaN/Inf rows included), and the mod-2^32 column sum
of masked rows equals the mod-2^32 column sum of the clear bit
patterns — pairwise cancellation is a theorem of integer arithmetic,
not a numerical accident.  :func:`unmask_sum` verifies that identity
bitwise every round (``sum_check_ok``), and the per-row unmask
reproduces the clear matrix bit-for-bit, so the protocol layer is
behaviorally invisible: a masked run's final weights are bit-equal to
the clear run's (tests/test_secagg.py pins it).

**The optimization barrier is the network.**  Without it XLA's
algebraic simplifier would cancel ``(u + delta) - delta`` at compile
time and delete the protocol from the program.  The
``lax.optimization_barrier`` on the wire tensor marks the
client->server transfer: everything before it is client-side compute,
everything after is what the server received, and the compiler may not
reason across it.  The HLO consequence is checkable
(:func:`wire_hlo_facts`): the masked u32 wire exists in the compiled
round, and past the wire no per-client f32 (n, d) tensor is
materialized at the top level — the server-visible program only ever
reduces the wire (the ``perf_gate``-style structural pin).

**Mask derivation is counter-based and stateless.**  The pair (i, j)
mask for round t is ``random.bits(fold_in(fold_in(fold_in(key, t),
min(i, j)), max(i, j)))`` with sign +1 for the lower id and -1 for the
higher — antisymmetric by construction, derived (never stored), so a
preempted run re-derives byte-identical masks on resume and the
groupwise mode keys masks on GLOBAL client ids (two groups never share
a mask stream).

**Dropout is a protocol event.**  A dropped client (PR 2's fault
harness) never submits its wire; the survivors' wires still carry the
masks they agreed with it.  :func:`recovery_residue` re-derives every
(survivor, dropped) pair mask — the simulated seed-reveal round — and
the sum check then verifies ``modsum(wire[alive]) - residue ==
modsum(clear[alive])`` bitwise: exact sum recovery, counted per round
as ``masks_reconstructed``.

What the simulation does and does not claim: privacy here is
*structural*, not cryptographic — the server-side code path consumes
only the wire and the sanctioned :func:`unmask_sum` output, robust
per-client defenses are rejected at init (config.py), and the sum
check reads the clear matrix only as a verification witness.  The
threat-model writeup lives in ARCHITECTURE.md "Secure aggregation".

Cost model: deriving the full pairwise mask stream is O(n^2 · d) PRNG
work per round under ``vanilla`` (every pair in the cohort) and
O(S · m^2 · d) = O(n · m · d) under ``groupwise`` (pairs within each
megabatch only) — the same scalability argument NET-SA
(arXiv 2501.01187) makes for in-network/group-wise aggregation.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax import lax


SECAGG_MODES = ("off", "vanilla", "groupwise")


def secagg_key(cfg):
    """The protocol's own key stream, derived from the experiment seed
    (core/faults.py:fault_key precedent).  Derived, not stored: a
    resumed run rebuilds the identical stream from the config alone."""
    return jax.random.key(cfg.seed ^ 0x5EC466)


def _pair_key(key_t, a, b):
    """Counter-based key for the UNORDERED pair {a, b}: both members
    derive the same stream (fold the lower id first)."""
    lo, hi = jnp.minimum(a, b), jnp.maximum(a, b)
    return jax.random.fold_in(jax.random.fold_in(key_t, lo), hi)


def pairwise_deltas(key_t, ids, d):
    """Per-row net mask ``delta_i = sum_j sign(i, j) * m_ij (mod 2^32)``
    over every pair in ``ids``.

    ``ids`` is an (n,) int32 id vector (``jnp.arange(n)`` for the flat
    cohort under full participation; a megabatch's global client ids
    under groupwise).  Sign is +1 when ``ids[i] < ids[j]`` and -1
    otherwise, so the deltas are antisymmetric by construction and
    ``sum_i delta_i == 0 (mod 2^32)`` exactly.  Returns (n, d) uint32.
    """
    n = ids.shape[0]

    def row(a):
        def body(b, acc):
            m = jax.random.bits(_pair_key(key_t, ids[a], ids[b]), (d,),
                                jnp.uint32)
            signed = jnp.where(ids[a] < ids[b], m, jnp.uint32(0) - m)
            return acc + jnp.where(a == b, jnp.uint32(0), signed)

        return lax.fori_loop(0, n, body, jnp.zeros((d,), jnp.uint32))

    return jax.vmap(row)(jnp.arange(n))


def mask_rows(grads, deltas):
    """Client side: bitcast each f32 row to uint32, add its net mask
    mod 2^32, and ship it.  The optimization barrier IS the network:
    the compiler may not cancel the mask against the server's unmask
    (it would delete the protocol from the program), and everything
    past the barrier is the server-visible computation."""
    bits = lax.bitcast_convert_type(grads.astype(jnp.float32), jnp.uint32)
    return lax.optimization_barrier(bits + deltas)


def unmask_rows(wire, deltas, alive=None):
    """The trusted-decrypt seam of the simulation: remove each
    surviving row's net mask (exact mod-2^32 inverse) and bitcast back
    — bit-identical to the clear submission, NaN/Inf patterns
    included.  Dropped rows (``alive`` False) never submitted a wire
    and come back zeroed, matching the fault quarantine's zeroing."""
    clear = lax.bitcast_convert_type(wire - deltas, jnp.float32)
    if alive is not None:
        clear = jnp.where(alive[:, None], clear, 0.0)
    return clear


def modular_sum(bits, alive=None):
    """Mod-2^32 column sum of uint32 rows — exactly associative, so
    the reduction order can never matter (unlike f32 sums)."""
    if alive is not None:
        bits = jnp.where(alive[:, None], bits, jnp.uint32(0))
    return jnp.sum(bits, axis=0, dtype=jnp.uint32)


def recovery_residue(key_t, ids, alive, d):
    """The simulated seed-reveal round: re-derive every
    (survivor, dropped) pair mask and accumulate the net residue those
    unpaired masks leave in the survivors' modular sum.  Returns
    ``(residue (d,) uint32, reconstructed_pairs int32)``."""
    n = ids.shape[0]

    def outer(i, carry):
        acc, pairs = carry

        def inner(j, c2):
            a2, p2 = c2
            m = jax.random.bits(_pair_key(key_t, ids[i], ids[j]), (d,),
                                jnp.uint32)
            signed = jnp.where(ids[i] < ids[j], m, jnp.uint32(0) - m)
            take = alive[i] & ~alive[j] & (i != j)
            return (a2 + jnp.where(take, signed, jnp.uint32(0)),
                    p2 + take.astype(jnp.int32))

        return lax.fori_loop(0, n, inner, (acc, pairs))

    return lax.fori_loop(0, n, outer,
                         (jnp.zeros((d,), jnp.uint32),
                          jnp.zeros((), jnp.int32)))


def unmask_sum(wire, deltas, clear, alive, key_t, ids):
    """Server side of the protocol round: recover the aggregable
    matrix and verify exact sum recovery bitwise.

    With everyone alive the check is pure pairwise cancellation:
    ``modsum(wire) == modsum(clear)`` (the antisymmetric deltas sum to
    zero mod 2^32).  With dropouts it is the full Bonawitz recovery
    identity: ``modsum(wire[alive]) - residue == modsum(clear[alive])``
    where the residue is rebuilt pair-by-pair from the dropped
    clients' revealed seeds (:func:`recovery_residue`).  ``clear`` is
    read ONLY by this verification — a simulation witness, not a
    server capability.  Returns ``(recovered (n, d) f32, stats)`` with
    fixed-shape ``secagg_*`` scalars that ride the engine's telemetry
    plumbing into per-round 'secagg' events (schema v5)."""
    clear_bits = lax.bitcast_convert_type(clear.astype(jnp.float32),
                                          jnp.uint32)
    if alive is None:
        s_wire = modular_sum(wire)
        residue = jnp.zeros_like(s_wire)
        pairs = jnp.zeros((), jnp.int32)
        dropped = jnp.zeros((), jnp.int32)
        s_clear = modular_sum(clear_bits)
    else:
        s_wire = modular_sum(wire, alive)
        residue, pairs = recovery_residue(key_t, ids, alive,
                                          wire.shape[1])
        dropped = jnp.sum(~alive).astype(jnp.int32)
        s_clear = modular_sum(clear_bits, alive)
    ok = jnp.all(s_wire - residue == s_clear).astype(jnp.int32)
    recovered = unmask_rows(wire, deltas, alive)
    stats = {
        "secagg_sum_check_ok": ok,
        "secagg_dropped": dropped,
        "secagg_masks_reconstructed": pairs,
        "secagg_recovery": (dropped > 0).astype(jnp.int32),
    }
    return recovered, stats


def secagg_cohort(grads, alive, key, t, ids=None):
    """One full protocol round over an (n, d) f32 cohort matrix:
    derive the round-t mask stream, mask every row (clients), then
    recover + verify (server).  ``alive`` is the quarantine mask from
    the fault harness (None = everyone submitted); ``ids`` the global
    client ids behind the rows (defaults to row indices — the flat
    engine's full-participation identity).  Returns
    ``(recovered, stats)``; ``recovered`` is bit-identical to the
    clear matrix with dropped rows zeroed, so the downstream
    aggregation is byte-for-byte the clear computation's.

    Stage ledger (utils/costs.py): the whole protocol — mask
    derivation, wire masking, server-side recovery — is the
    ``protect`` stage, for every caller (flat secagg_step, groupwise
    :func:`secagg_group`)."""
    from attacking_federate_learning_tpu.utils.costs import stage_scope

    n, d = grads.shape
    with stage_scope("protect"):
        if ids is None:
            ids = jnp.arange(n, dtype=jnp.int32)
        key_t = jax.random.fold_in(key, t)
        deltas = pairwise_deltas(key_t, ids, d)
        wire = mask_rows(grads, deltas)
        return unmask_sum(wire, deltas, grads, alive, key_t, ids)


def secagg_group(grads, key, t, ids, alive=None):
    """Groupwise mode's per-megabatch protocol round: masks keyed on
    GLOBAL client ids.  With everyone submitting (``alive=None``)
    recovery is trivial and the return is the compact
    ``(recovered, sum_check_ok int32)`` pair — byte-identical to the
    pre-fault program.  ``alive`` (m,) bool is the hier fault
    harness's per-group dropout mask (ISSUE 19): the dropped members'
    pair masks are reconstructed over the group's global client ids
    (:func:`recovery_residue` — the per-group Bonawitz seed-reveal)
    and the full ``secagg_*`` stats pytree rides out instead:
    ``(recovered, stats)``."""
    if alive is None:
        recovered, stats = secagg_cohort(grads, None, key, t, ids=ids)
        return recovered, stats["secagg_sum_check_ok"]
    return secagg_cohort(grads, alive, key, t, ids=ids)


def group_envelope_stats(group_means, megabatch):
    """Envelope view of the server-visible tensor under groupwise
    secagg: per-group sum norms and cosine-to-mean over the (S, d)
    group-estimate matrix (``group_means`` = sums / m, the tensor the
    tier-2 kernels consume) — the group-level mirror of
    defenses/kernels.py:population_telemetry, observable WITHOUT
    per-client visibility.  The norm spelling (``norm(mean) * m``)
    matches the pre-telemetry v5 event's ``group_sum_norms`` bit for
    bit; the cosine is scale-invariant so the mean matrix serves
    directly.  Fixed shapes: two (S,) f32 vectors."""
    E = group_means.astype(jnp.float32)
    norms = jnp.linalg.norm(E, axis=1)
    mean = jnp.mean(E, axis=0)
    cos = (E @ mean) / (norms * jnp.linalg.norm(mean) + 1e-12)
    return {"group_sum_norms": norms * megabatch,
            "group_cos_to_mean": cos}


# --- structural HLO witness (the perf_gate-memproof-style pin) ----------

_NAME_RE = re.compile(r"\s*(%[\w.\-]+)\s*=\s*(\w+)\[([\d,]*)\]")


def wire_hlo_facts(hlo_text, n, d):
    """Parse a compiled round's optimized HLO for the vanilla-secagg
    structural facts (tests/test_secagg.py and ``tools/perf_gate.py
    --memproof`` gate them):

    - ``wire_present`` — a top-level u32 (n, d) tensor exists: the
      masked wire really is in the program (the optimization barrier
      kept the compiler from cancelling the protocol away);
    - ``unmask_instructions`` / ``unmask_reduce_only`` — every
      top-level f32 (n, d) instruction built FROM u32 (n, d) operands
      is the server's reconstruction of the aggregable matrix (the
      trusted-decrypt seam); the pin demands its ONLY consumers are
      client-axis ``reduce`` instructions producing the (d,) sum — no
      other server-side op (a defense, a sort, a per-row diagnostic)
      may read per-client rows post-masking;
    - ``distance_matrix`` — an f32 (n, n) tensor anywhere in the
      program means a pairwise-distance defense ran over per-client
      rows (must be absent under secagg).

    Fusion bodies are loop-/register-local values, never
    server-readable buffers, so the ENTRY computation is the
    allocation-level view this check wants."""
    wire_shape = f"u32[{n},{d}]"
    clear_shape = f"f32[{n},{d}]"
    entry_lines = []
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            entry_lines.append(line)
    wire_present = False
    unmask = []
    for line in entry_lines:
        m = _NAME_RE.match(line)
        if not m:
            continue
        shape = f"{m.group(2)}[{m.group(3)}]"
        if shape == wire_shape:
            wire_present = True
        operands = line.split("=", 1)[1]
        if shape == clear_shape and wire_shape in operands:
            unmask.append(m.group(1))
    reduce_only = True
    for name in unmask:
        for line in entry_lines:
            m = _NAME_RE.match(line)
            if not m or m.group(1) == name:
                continue
            if (name + " " in line or name + "," in line
                    or name + ")" in line):
                if not (" reduce(" in line
                        and f"= f32[{d}]" in line.replace("{0}", "")):
                    reduce_only = False
    return {
        "wire_present": wire_present,
        "unmask_instructions": len(unmask),
        "unmask_reduce_only": bool(unmask) and reduce_only,
        "distance_matrix": f"f32[{n},{n}]" in hlo_text,
    }
