"""Datasets as device-residable arrays.

The reference streams data through torchvision ``DataLoader``s with one
DataLoader per client (reference user.py:46-55) — a host-side Python iterator
per client, which is exactly what serializes its round loop.  Here a dataset
is a pair of dense arrays (images normalized up-front, labels int32) that
lives in HBM; clients are rows of an index matrix and a "batch" is one
gather.  MNIST/CIFAR fit comfortably in HBM (MNIST train = 179 MB f32).

Loaders read the raw distribution files directly (MNIST IDX, CIFAR-10/100
python pickles) — no torchvision dependency.  When raw files are absent
(e.g. an air-gapped machine) the SYNTH_* datasets provide deterministic,
learnable class-structured data with identical shapes and normalization, so
every code path (training, triggers, defenses) exercises the same math.

Normalization matches the reference transforms: MNIST (x-0.1307)/0.3081
(reference data_sets.py:26-27), CIFAR10 (x-0.5)/0.5 (data_sets.py:56-57),
CIFAR100 per-channel stats (data_sets.py:154-155).  Backdoor triggers are
applied *after* normalization, as in the reference (data_sets.py:26-30
appends the trigger transform after Normalize; backdoor.py:49).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import NamedTuple, Optional

import numpy as np

from attacking_federate_learning_tpu import config as C


class Dataset(NamedTuple):
    name: str
    train_x: np.ndarray   # (N, ...) normalized float32
    train_y: np.ndarray   # (N,) int32
    test_x: np.ndarray
    test_y: np.ndarray
    num_classes: int


MNIST_MEAN, MNIST_STD = 0.1307, 0.3081
CIFAR10_MEAN, CIFAR10_STD = 0.5, 0.5
CIFAR100_MEAN = np.array([125.3, 123.0, 113.9], np.float32) / 255.0
CIFAR100_STD = np.array([63.0, 62.1, 66.7], np.float32) / 255.0


# --------------------------------------------------------------------------
# raw-file loaders
# --------------------------------------------------------------------------

def _open_maybe_gz(path):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def _read_idx(path) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


def load_mnist(data_dir: str) -> Dataset:
    d = os.path.join(data_dir, "MNIST", "raw")
    if not os.path.isdir(d):
        d = data_dir
    tx = _read_idx(os.path.join(d, "train-images-idx3-ubyte"))
    ty = _read_idx(os.path.join(d, "train-labels-idx1-ubyte"))
    vx = _read_idx(os.path.join(d, "t10k-images-idx3-ubyte"))
    vy = _read_idx(os.path.join(d, "t10k-labels-idx1-ubyte"))

    def norm(x):
        x = x.astype(np.float32) / 255.0
        return ((x - MNIST_MEAN) / MNIST_STD)[:, None, :, :]  # (N,1,28,28)

    return Dataset("MNIST", norm(tx), ty.astype(np.int32),
                   norm(vx), vy.astype(np.int32), 10)


def _load_cifar_pickles(paths, key_x=b"data", key_y=b"labels"):
    xs, ys = [], []
    for p in paths:
        with open(p, "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        xs.append(batch[key_x])
        ys.extend(batch[key_y])
    x = np.concatenate(xs).reshape(-1, 3, 32, 32)
    return x, np.asarray(ys, np.int32)


def load_cifar10(data_dir: str) -> Dataset:
    d = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(d):
        d = data_dir
    tx, ty = _load_cifar_pickles(
        [os.path.join(d, f"data_batch_{i}") for i in range(1, 6)])
    vx, vy = _load_cifar_pickles([os.path.join(d, "test_batch")])

    def norm(x):
        return (x.astype(np.float32) / 255.0 - CIFAR10_MEAN) / CIFAR10_STD

    return Dataset("CIFAR10", norm(tx), ty, norm(vx), vy, 10)


def load_cifar100(data_dir: str) -> Dataset:
    d = os.path.join(data_dir, "cifar-100-python")
    if not os.path.isdir(d):
        d = data_dir
    tx, ty = _load_cifar_pickles([os.path.join(d, "train")],
                                 key_y=b"fine_labels")
    vx, vy = _load_cifar_pickles([os.path.join(d, "test")],
                                 key_y=b"fine_labels")

    def norm(x):
        x = x.astype(np.float32) / 255.0
        return (x - CIFAR100_MEAN[:, None, None]) / CIFAR100_STD[:, None, None]

    return Dataset("CIFAR100", norm(tx), ty, norm(vx), vy, 100)


# --------------------------------------------------------------------------
# deterministic synthetic datasets (shape/normalization-identical stand-ins)
# --------------------------------------------------------------------------

def make_synthetic(shape, num_classes: int, n_train: int, n_test: int,
                   seed: int, name: str,
                   mean, std, signal: float = 0.35,
                   noise_scale: float = 0.25,
                   smooth_protos: bool = False) -> Dataset:
    """Class-prototype Gaussians in pixel space, then normalized.

    Each class c gets a fixed prototype image p_c; samples are
    clip(0.5 + signal*p_c + noise_scale*noise, 0, 1).  The defaults make
    classes separable enough that an MLP clears 70% within a handful of FL
    rounds (the reference's checkpoint threshold, main.py:84); lower
    signal-to-noise (e.g. the *_HARD variants) slows convergence so
    attack-vs-defense accuracy deltas are visible in behavioral tests.

    ``smooth_protos``: draw the prototypes on a coarse (H/4, W/4) grid
    and nearest-upsample, giving them the low-frequency spatial
    structure conv+pool architectures are biased toward.  Per-pixel
    i.i.d. prototypes are near-invisible to a CNN (pooling averages
    them out — measured: cifar10_cnn stays at random accuracy on them
    while an MLP learns fine), so CNN-targeted synthetics must be
    spatially smooth to exercise real convergence.
    """
    rng = np.random.default_rng(seed)
    if smooth_protos and len(shape) == 3 and shape[1] % 4 == 0 \
            and shape[2] % 4 == 0:
        coarse = rng.standard_normal(
            (num_classes, shape[0], shape[1] // 4, shape[2] // 4)
        ).astype(np.float32)
        protos = np.kron(coarse, np.ones((1, 1, 4, 4), np.float32))
    else:
        protos = rng.standard_normal(
            (num_classes,) + shape).astype(np.float32)
    protos /= np.linalg.norm(protos.reshape(num_classes, -1), axis=1).reshape(
        (num_classes,) + (1,) * len(shape)) / np.sqrt(np.prod(shape))

    # MNIST-like quiet border: real digits leave the image margin near zero,
    # which is what lets a corner trigger persist (honest gradients barely
    # constrain border weights).  Applies only to 1-channel (MNIST-shaped)
    # synthetics — real CIFAR images have no quiet border.
    border = 4 if (shape[0] == 1 and shape[-1] >= 28) else 0
    if border:
        edge_mask = np.zeros(shape, np.float32)
        edge_mask[..., border:-border, border:-border] = 1.0
    else:
        edge_mask = np.ones(shape, np.float32)

    def gen(n):
        y = rng.integers(0, num_classes, size=n).astype(np.int32)
        noise = rng.standard_normal((n,) + shape).astype(np.float32)
        x = np.clip((0.5 + signal * protos[y] + noise_scale * noise)
                    * edge_mask, 0.0, 1.0)
        return (x - mean) / std, y

    tx, ty = gen(n_train)
    vx, vy = gen(n_test)
    return Dataset(name, tx, ty, vx, vy, num_classes)


# --------------------------------------------------------------------------
# dispatch
# --------------------------------------------------------------------------

def load_dataset(name: str, data_dir: str = "data", seed: int = 0,
                 synth_train: int = 10000, synth_test: int = 2000,
                 ) -> Dataset:
    if name == C.MNIST:
        try:
            return load_mnist(data_dir)
        except (FileNotFoundError, OSError):
            name = C.SYNTH_MNIST
    if name == C.CIFAR10:
        try:
            return load_cifar10(data_dir)
        except (FileNotFoundError, OSError):
            name = C.SYNTH_CIFAR10
    if name == C.CIFAR100:
        try:
            return load_cifar100(data_dir)
        except (FileNotFoundError, OSError):
            return make_synthetic(
                (3, 32, 32), 100, synth_train, synth_test, seed,
                C.CIFAR100 + "_SYNTH",
                CIFAR100_MEAN[:, None, None], CIFAR100_STD[:, None, None])
    if name == C.SYNTH_MNIST:
        return make_synthetic((1, 28, 28), 10, synth_train, synth_test, seed,
                              C.SYNTH_MNIST, MNIST_MEAN, MNIST_STD)
    if name == C.SYNTH_CIFAR10:
        return make_synthetic((3, 32, 32), 10, synth_train, synth_test, seed,
                              C.SYNTH_CIFAR10, CIFAR10_MEAN, CIFAR10_STD)
    if name == C.SYNTH_MNIST_HARD:
        # Low SNR: converges over tens of rounds instead of a handful, so
        # Byzantine attacks produce measurable accuracy deltas.
        return make_synthetic((1, 28, 28), 10, synth_train, synth_test, seed,
                              name, MNIST_MEAN, MNIST_STD,
                              signal=0.12, noise_scale=0.30)
    if name == C.SYNTH_CIFAR10_HARD:
        # CIFAR-shaped stand-in for convergence studies of the conv-net
        # + shadow-train composition (reference backdoor.py:108-159 at
        # data_sets.py:33-61 scale): spatially-smooth prototypes so a
        # CNN can actually learn them (see make_synthetic), at an SNR
        # low enough that training stays non-saturated over ~100+
        # rounds — the regime where the backdoor clip envelope is alive
        # (CLAUDE.md behavioral facts).
        return make_synthetic((3, 32, 32), 10, synth_train, synth_test, seed,
                              name, CIFAR10_MEAN, CIFAR10_STD,
                              signal=0.20, noise_scale=0.30,
                              smooth_protos=True)
    raise ValueError(f"Unknown dataset {name!r}")
