"""Train-time image augmentation inside the round program.

The reference's CIFAR100 train transform (reference data_sets.py:157-166) is
reflect-pad 4 -> RandomCrop(32) -> RandomHorizontalFlip -> normalize, applied
per sample by host-side torchvision workers.  Here the same augmentation is
a pure jittable op over the whole (n_clients, batch, C, H, W) gather — it
runs inside the fused round program on device, keyed from the experiment
seed and round index, so every round (and every resume) sees the same
deterministic stream (SURVEY.md §2.4 #13: all randomness is explicit
jax.random plumbing).

Crop/flip act on *normalized* images while the reference crops before
normalizing — elementwise normalization commutes with crop/flip, so the
pixel streams are identical.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def reflect_crop_flip(images, key, pad: int = 4):
    """Random crop-from-reflect-pad + horizontal flip, per image.

    images: (..., C, H, W); any number of leading batch axes.  Each image
    draws its own crop offset (uniform over the (2*pad+1)^2 grid, matching
    RandomCrop(H) on an H+2*pad padded image) and flip bit (p=0.5).
    """
    *lead, c, h, w = images.shape
    flat = images.reshape((-1, c, h, w))
    m = flat.shape[0]
    k_off, k_flip = jax.random.split(key)
    offsets = jax.random.randint(k_off, (m, 2), 0, 2 * pad + 1)
    flips = jax.random.bernoulli(k_flip, 0.5, (m,))

    def one(img, off, flip):
        padded = jnp.pad(img, ((0, 0), (pad, pad), (pad, pad)),
                         mode="reflect")
        crop = lax.dynamic_slice(padded, (0, off[0], off[1]), (c, h, w))
        return jnp.where(flip, crop[..., ::-1], crop)

    out = jax.vmap(one)(flat, offsets, flips)
    return out.reshape(images.shape)


def round_augment_key(seed: int, t):
    """Per-round augmentation key: fold the round index into the
    experiment's seed stream (works with a traced ``t`` inside jit)."""
    return jax.random.fold_in(jax.random.key(seed ^ 0x5EED_A06), t)
