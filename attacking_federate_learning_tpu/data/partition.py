"""Client data partitioning.

The reference partitions via ``DistributedSampler(num_replicas=users_count,
rank=user_id)`` (reference user.py:49-54): one global permutation, padded to a
multiple of n by wrapping, then strided by rank — an IID equal shard per
client.  Because the reference never advances the sampler epoch, the
permutation is identical on every pass (SURVEY.md §2.4 #13); we reproduce
that by computing the shard matrix once per experiment.

The partition is materialized as an int32 index matrix ``shards`` of shape
(n_clients, shard_len); a round's batch for all clients at once is

    idx = shards[:, (t*B + arange(B)) % shard_len]          # (n, B)
    batch_x, batch_y = X[idx], Y[idx]                       # one gather

which keeps shapes static under jit (the reference's DataLoader yields a
short final batch instead; wrap-around is the jit-friendly equivalent).

Also provides a Dirichlet label-skew partitioner for non-IID experiments
(no reference analog — the reference is IID-only).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def iid_shards(n_examples: int, n_clients: int, seed: int) -> np.ndarray:
    """DistributedSampler-equivalent IID shards: (n_clients, shard_len)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n_examples)
    shard_len = -(-n_examples // n_clients)  # ceil
    total = shard_len * n_clients
    padded = np.concatenate([perm, perm[: total - n_examples]])
    # rank r takes padded[r::n_clients] — the sampler's strided subsample.
    return np.stack([padded[r::n_clients] for r in range(n_clients)]).astype(
        np.int32)


def dirichlet_shards(labels: np.ndarray, n_clients: int, alpha: float,
                     seed: int) -> np.ndarray:
    """Label-skew non-IID shards via per-class Dirichlet allocation.

    Shards are equalized to a common length by wrapping each client's own
    indices so the result is still a dense (n_clients, shard_len) matrix.
    """
    rng = np.random.default_rng(seed)
    n_classes = int(labels.max()) + 1
    per_client: list = [[] for _ in range(n_clients)]
    for c in range(n_classes):
        idx = np.flatnonzero(labels == c)
        rng.shuffle(idx)
        props = rng.dirichlet(np.full(n_clients, alpha))
        cuts = (np.cumsum(props) * len(idx)).astype(int)[:-1]
        for client, chunk in enumerate(np.split(idx, cuts)):
            per_client[client].extend(chunk.tolist())
    shard_len = max(1, max(len(s) for s in per_client))
    out = np.empty((n_clients, shard_len), np.int32)
    for i, s in enumerate(per_client):
        if not s:  # degenerate client: give it one wrapped global sample
            s = [int(rng.integers(len(labels)))]
        reps = -(-shard_len // len(s))
        out[i] = np.tile(np.array(s, np.int32), reps)[:shard_len]
    return out


def client_style_params(n_clients: int, strength: float,
                        seed: int) -> tuple[np.ndarray, np.ndarray]:
    """Per-client affine style parameters for 'femnist_style' partition.

    FEMNIST's defining non-IIDness is *feature/style* shift — each
    writer's pen, pressure, and slant shifts the input distribution even
    when the label mix is identical (SURVEY.md §7.2 M4 names
    "FEMNIST/Dirichlet"; Dirichlet covers the label axis only).  Real
    FEMNIST cannot be downloaded on this zero-egress box, so the
    air-gapped stand-in transforms each client's view of the shared
    pool: client i sees ``a_i * x + b_i`` — a per-writer
    contrast/brightness transform, the first-order model of writer
    style.  Drawn once per experiment from the config seed:

        a_i = 1 + strength * u1   (u1 ~ U[-1, 1])   # contrast
        b_i = strength/2 * u2     (u2 ~ U[-1, 1])   # brightness

    Unlike Dirichlet label skew, this gives HONEST clients' gradients
    systematic structure (each client's input statistics differ), which
    is the adversarial condition distance-based defenses (Krum/Bulyan)
    are weakest under — label skew alone is kind to them.
    """
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xFE30]))
    a = 1.0 + strength * rng.uniform(-1.0, 1.0, n_clients)
    b = 0.5 * strength * rng.uniform(-1.0, 1.0, n_clients)
    return a.astype(np.float32), b.astype(np.float32)


def make_shards(partition: str, labels: np.ndarray, n_clients: int,
                seed: int, dirichlet_alpha: float = 0.5) -> np.ndarray:
    if partition in ("iid", "femnist_style"):
        # femnist_style shares the IID index assignment: its non-IIDness
        # lives in the per-client input transform (client_style_params),
        # not in which examples a client holds.
        return iid_shards(len(labels), n_clients, seed)
    if partition == "dirichlet":
        return dirichlet_shards(labels, n_clients, dirichlet_alpha, seed)
    raise ValueError(f"Unknown partition {partition!r}")


def round_batch_indices(shards, round_idx: int, batch_size: int):
    """(n_clients, B) gather indices for one round, cycling each shard.

    Mirrors the reference's infinite ``cycle`` over each client's loader
    (reference user.py:11-14, :55) with wrap-around instead of short final
    batches, so shapes stay static under jit.
    """
    shard_len = shards.shape[1]
    offs = (round_idx * batch_size + jnp.arange(batch_size)) % shard_len
    return shards[:, offs]
