"""Host-resident streaming batch feeder for beyond-HBM datasets.

The default engine path device-puts the whole training set once and gathers
every round's (n_clients, B) batch on device — ideal while the dataset fits
HBM (MNIST/CIFAR do).  For FEMNIST-scale corpora (SURVEY.md §7.3 #5) the
training arrays must stay in host RAM; this feeder gathers each round's
batch on the host and overlaps the host->device transfer of upcoming
rounds with the current round's compute:

    xs, ys = stream.get(t)     # returns round t (already on device),
                               # then issues prefetches for t+1..t+depth

``jax.device_put`` is asynchronous on accelerator backends, so with the
default ``workers=0`` the prefetch costs no threads — the same single-slot
double buffering a tf.data/grain input pipeline would do, minus the
dependency.  When the HOST GATHER itself binds (the (m, k·B) fancy-index
over a 10k-client shard table is real CPU work that ``workers=0`` performs
synchronously on the round path), ``workers=1`` moves gather+put onto one
background thread so they overlap device compute; ``prefetch`` deepens the
pipeline so a slow round can't starve the next.  Round-batch semantics are
identical to the device path either way (data/partition.py
round_batch_indices: cycling wrap-around, static shapes; the per-round
cohort derivation is deterministic, so prefetched rounds see exactly the
cohort the round will use).
"""

from __future__ import annotations

import time

import jax
import numpy as np


class HostStream:
    def __init__(self, train_x, train_y, shards, batch_size: int,
                 plan=None, n_rounds=None, participants_fn=None,
                 cohort_rows=None, prefetch: int = 1, workers: int = 0):
        self.x = np.asarray(train_x)
        self.y = np.asarray(train_y)
        self.shards = np.asarray(shards)
        self.batch_size = int(batch_size)
        # Prefetch horizon: no useless gather/transfer past the last round
        # (None = unbounded, for open-ended callers).
        self.n_rounds = n_rounds
        # Optional per-round cohort: t -> index array (deterministic, so
        # prefetching t+1 sees the same cohort the round will use).
        self.participants_fn = participants_fn
        self.prefetch = max(int(prefetch), 1)
        self._pool = None
        if workers:
            # One worker keeps issue order = round order (a deeper pool
            # would reorder gathers without helping: they contend on the
            # same host memory bandwidth).
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(max_workers=1)
        self._cache: dict = {}
        # Stall accounting (VERDICT r2 #3: "record whether HostStream.get
        # stalls the round"): wall time get() spends blocked on the gather
        # + transfer instead of overlapping device compute.
        self.stall_s = 0.0
        self.cold_misses = 0
        self.gets = 0
        self._sharding_x = self._sharding_y = None
        if plan is not None:
            # Batches shard over the clients mesh axis when it divides the
            # per-round row count (the cohort size under participation
            # sampling, else n) — mirroring MeshPlan.place's evenness rule.
            from jax.sharding import PartitionSpec as P
            from attacking_federate_learning_tpu.parallel.mesh import CLIENTS
            n = (cohort_rows if cohort_rows is not None
                 else self.shards.shape[0])
            axis = CLIENTS if n % plan.mesh.shape[CLIENTS] == 0 else None
            self._sharding_x = plan.sharding(
                P(*((axis,) + (None,) * self.x.ndim)))
            self._sharding_y = plan.sharding(P(axis, None))

    # ------------------------------------------------------------------
    def _host_gather(self, t: int):
        shard_len = self.shards.shape[1]
        offs = (t * self.batch_size
                + np.arange(self.batch_size)) % shard_len
        shards = self.shards
        if self.participants_fn is not None:
            part = self.participants_fn(t)
            if part is not None:
                shards = shards[np.asarray(part)]
        idx = shards[:, offs]                           # (m, B)
        return self.x[idx], self.y[idx]

    def _produce(self, t: int):
        xs, ys = self._host_gather(t)
        return (jax.device_put(xs, self._sharding_x),
                jax.device_put(ys, self._sharding_y))

    def _issue(self, t: int):
        if t in self._cache:
            return
        self._cache[t] = (self._pool.submit(self._produce, t)
                          if self._pool is not None else self._produce(t))

    def get(self, t: int):
        """Device batch for round t; prefetches rounds t+1..t+prefetch
        (within the horizon)."""
        t = int(t)
        self.gets += 1
        t0 = time.perf_counter()
        if t not in self._cache:
            self.cold_misses += 1
        self._issue(t)                    # hit if prefetched, else sync
        out = self._cache.pop(t)
        # Drop stale slots (e.g. after a resume jump), keep memory at
        # `prefetch` in-flight rounds.  Dropped futures are cancelled:
        # a queued-but-unstarted stale gather would otherwise delay the
        # next round's (it shares the single worker), and a failed one
        # would swallow its exception.
        stale = [v for k, v in self._cache.items()
                 if not (t < k <= t + self.prefetch)]
        self._cache = {k: v for k, v in self._cache.items()
                       if t < k <= t + self.prefetch}
        if self._pool is not None:
            for fut in stale:
                fut.cancel()
        for u in range(t + 1, t + 1 + self.prefetch):
            if self.n_rounds is None or u < self.n_rounds:
                self._issue(u)            # async: overlaps round t compute
        if self._pool is not None:
            out = out.result()
        self.stall_s += time.perf_counter() - t0
        return out

    def stall_stats(self) -> dict:
        """Cumulative stall diagnostics for the run's structured log."""
        return {"stream_stall_s": round(self.stall_s, 4),
                "stream_gets": self.gets,
                "stream_cold_misses": self.cold_misses,
                "stream_stall_per_get_ms": round(
                    1e3 * self.stall_s / max(self.gets, 1), 3)}
