"""Host-resident streaming batch feeder for beyond-HBM datasets.

The default engine path device-puts the whole training set once and gathers
every round's (n_clients, B) batch on device — ideal while the dataset fits
HBM (MNIST/CIFAR do).  For FEMNIST-scale corpora (SURVEY.md §7.3 #5) the
training arrays must stay in host RAM; this feeder gathers each round's
batch on the host and overlaps the host->device transfer of round t+1 with
round t's compute:

    xs, ys = stream.get(t)     # returns round t (already on device),
                               # then issues the async device_put for t+1

``jax.device_put`` is asynchronous on accelerator backends, so the prefetch
is one round deep with no threads — the same single-slot double buffering a
tf.data/grain input pipeline would do, minus the dependency.  Round-batch
semantics are identical to the device path (data/partition.py
round_batch_indices: cycling wrap-around, static shapes).
"""

from __future__ import annotations

import jax
import numpy as np


class HostStream:
    def __init__(self, train_x, train_y, shards, batch_size: int,
                 plan=None, n_rounds=None, participants_fn=None,
                 cohort_rows=None):
        self.x = np.asarray(train_x)
        self.y = np.asarray(train_y)
        self.shards = np.asarray(shards)
        self.batch_size = int(batch_size)
        # Prefetch horizon: no useless gather/transfer past the last round
        # (None = unbounded, for open-ended callers).
        self.n_rounds = n_rounds
        # Optional per-round cohort: t -> index array (deterministic, so
        # prefetching t+1 sees the same cohort the round will use).
        self.participants_fn = participants_fn
        self._cache: dict = {}
        self._sharding_x = self._sharding_y = None
        if plan is not None:
            # Batches shard over the clients mesh axis when it divides the
            # per-round row count (the cohort size under participation
            # sampling, else n) — mirroring MeshPlan.place's evenness rule.
            from jax.sharding import PartitionSpec as P
            from attacking_federate_learning_tpu.parallel.mesh import CLIENTS
            n = (cohort_rows if cohort_rows is not None
                 else self.shards.shape[0])
            axis = CLIENTS if n % plan.mesh.shape[CLIENTS] == 0 else None
            self._sharding_x = plan.sharding(
                P(*((axis,) + (None,) * self.x.ndim)))
            self._sharding_y = plan.sharding(P(axis, None))

    # ------------------------------------------------------------------
    def _host_gather(self, t: int):
        shard_len = self.shards.shape[1]
        offs = (t * self.batch_size
                + np.arange(self.batch_size)) % shard_len
        shards = self.shards
        if self.participants_fn is not None:
            part = self.participants_fn(t)
            if part is not None:
                shards = shards[np.asarray(part)]
        idx = shards[:, offs]                           # (m, B)
        return self.x[idx], self.y[idx]

    def _issue(self, t: int):
        if t in self._cache:
            return
        xs, ys = self._host_gather(t)
        self._cache[t] = (jax.device_put(xs, self._sharding_x),
                          jax.device_put(ys, self._sharding_y))

    def get(self, t: int):
        """Device batch for round t; prefetches round t+1 (within the
        horizon)."""
        t = int(t)
        self._issue(t)                    # hit if prefetched, else sync
        out = self._cache.pop(t)
        # Drop stale slots (e.g. after a resume jump), keep memory at one
        # in-flight round.
        self._cache = {k: v for k, v in self._cache.items() if k == t + 1}
        if self.n_rounds is None or t + 1 < self.n_rounds:
            self._issue(t + 1)            # async: overlaps round t compute
        return out
