"""Backdoor triggers and target remapping.

The reference's pattern trigger writes 2.8 into the top-left 5x5 patch of
every channel *after* normalization (reference backdoor.py:47-50; the
transform is appended after Normalize, data_sets.py:26-30) and remaps targets
to class 0 (backdoor.py:81, :129).  'sample k' mode instead trains on the
single training image k with label (y+1) % 5 (backdoor.py:83, :131).
"""

from __future__ import annotations

import jax.numpy as jnp


PATTERN_VALUE = 2.8   # normalized units, reference backdoor.py:49
PATTERN_SIZE = 5


def add_pattern(x):
    """Apply the 5x5 corner trigger to a (..., C, H, W) image batch."""
    return x.at[..., :PATTERN_SIZE, :PATTERN_SIZE].set(PATTERN_VALUE)


def backdoor_targets(y, backdoor):
    """Poisoned labels: class 0 for 'pattern', (y+1)%5 for sample mode
    (reference backdoor.py:80-83)."""
    if backdoor == "pattern":
        return jnp.zeros_like(y)
    return (y + 1) % 5
