from attacking_federate_learning_tpu.data.datasets import (  # noqa: F401
    Dataset, load_dataset
)
from attacking_federate_learning_tpu.data.partition import (  # noqa: F401
    make_shards, round_batch_indices
)
from attacking_federate_learning_tpu.data import triggers  # noqa: F401
