"""Run-report tool: read one or more run JSONLs, print forensics.

The reference's only artifacts are a print tee and an accuracy CSV
(SURVEY.md §5); every analysis in GRID_RESULTS.md (selection
concentration, timing attribution, ASR trajectories) was hand-rolled per
study.  This module automates them over the structured event schema
(utils/metrics.py):

- **selection concentration** — distinct winners, top-1 share, malicious
  share, per-client histogram, from 'defense' events' selection masks
  (Krum one-hot, Bulyan multi-hot) or the end-of-run 'selection_hist';
- **phase timing** — the PhaseTimer summary from 'profile' events;
- **trajectories** — accuracy from 'eval' events, attack success from
  'asr' events.

Usage (cli.py dispatches the subcommand)::

    python -m attacking_federate_learning_tpu.cli report logs/run.jsonl
    python -m attacking_federate_learning_tpu.cli report --json a.jsonl b.jsonl

Multiple files print side by side plus a concentration comparison table —
the iid-vs-femnist_style trend (GRID_RESULTS round-5 row) is one report
invocation over the two run logs.
"""

from __future__ import annotations

import argparse
import json
from collections import Counter

from attacking_federate_learning_tpu.utils.metrics import iter_events


def load_events(paths, validate: bool = True) -> list:
    """All events from the given JSONLs, schema-validated by default."""
    events = []
    for p in paths:
        events.extend(iter_events(p, validate=validate))
    return events


def selection_concentration(events):
    """The GRID_RESULTS top-1-share analysis, automated.

    Winners come from 'defense' events' ``selection_mask`` vectors.  A
    run of one-hot masks (Krum) yields a winner histogram with integer
    counts and a malicious-picks total; multi-hot masks (Bulyan) yield
    selection-mass shares.  Returns None when no masks were recorded.
    NaN masks (host engines that never ship the selection back) are
    skipped."""
    masks = []
    for e in events:
        if e.get("kind") == "defense" and "selection_mask" in e:
            m = e["selection_mask"]
            if all(x == x for x in m):      # NaN-free (x != x iff NaN)
                masks.append((m, e.get("malicious_count", 0)))
    if not masks:
        return None
    one_hot = all(abs(sum(m) - 1.0) < 1e-6 for m, _ in masks)
    counts: Counter = Counter()
    mal_mass = total = 0.0
    for m, f in masks:
        for i, x in enumerate(m):
            if x > 0:
                counts[i] += x
                total += x
                if i < f:
                    mal_mass += x
    top1_client, top1 = counts.most_common(1)[0]
    out = {
        "rounds": len(masks),
        "distinct_winners": len(counts),
        "top1_share": round(top1 / total, 4),
        "top1_client": top1_client,
        "malicious_share": round(mal_mass / total, 4),
        "histogram": {str(k): (int(v) if one_hot else round(v, 2))
                      for k, v in sorted(counts.items())},
    }
    if one_hot:
        out["malicious_picks"] = int(round(mal_mass))
    return out


def fault_recovery(events):
    """Fault/recovery accounting from 'fault' events (core/faults.py +
    the engine watchdog): total injected per kind, quarantined rows,
    rounds touched, and every rollback record.  Returns None when the
    run emitted no fault events (faults off)."""
    injected = Counter()
    quarantined = rounds = 0
    rollbacks = []
    for e in events:
        if e.get("kind") != "fault":
            continue
        if e.get("rolled_back"):
            rollbacks.append({"round": e["round"],
                              "restored_round": e.get("restored_round"),
                              "rollbacks_total": e.get("rollbacks_total")})
            continue
        rounds += 1
        quarantined += int(e.get("quarantined", 0))
        for k, v in e.items():
            if k.startswith("injected_"):
                injected[k[len("injected_"):]] += int(v)
    if not rounds and not rollbacks:
        return None
    return {"rounds": rounds, "injected": dict(injected),
            "quarantined": quarantined, "rollbacks": rollbacks}


def summarize_run(events):
    """One run's report payload from its event list."""
    kinds = Counter(e["kind"] for e in events)
    out = {"events": len(events), "kinds": dict(kinds)}
    for e in events:
        if e["kind"] == "defense":
            out["defense"] = e["defense"]
            break
    for e in events:
        if e["kind"] == "attack":
            out["attack"] = e["attack"]
            break
    evals = [(e["round"], e["accuracy"]) for e in events
             if e["kind"] == "eval"]
    if evals:
        out["accuracy"] = {
            "trajectory": [[r, round(a, 2)] for r, a in evals],
            "final": round(evals[-1][1], 2),
            "max": round(max(a for _, a in evals), 2)}
    asrs = [(e["round"], e["attack_success_rate"]) for e in events
            if e["kind"] == "asr"]
    if asrs:
        out["attack_success"] = {
            "trajectory": [[r, round(a, 2)] for r, a in asrs],
            "final": round(asrs[-1][1], 2)}
    sel = selection_concentration(events)
    if sel:
        out["selection"] = sel
    faults = fault_recovery(events)
    if faults:
        out["faults"] = faults
    hists = [e for e in events if e["kind"] == "selection_hist"]
    if hists:
        out["selection_hist"] = {
            k: hists[-1][k] for k in ("counts", "rounds", "distinct_winners",
                                      "top1_share", "top1_client",
                                      "malicious_picks")
            if k in hists[-1]}
    profiles = [e for e in events if e["kind"] == "profile"]
    if profiles:
        out["phases"] = profiles[-1]["phases"]
    streams = [e for e in events if e["kind"] == "stream"]
    if streams:
        out["stream"] = {k: v for k, v in streams[-1].items()
                         if k.startswith("stream_")}
    return out


def _print_run(path, s, out):
    out(f"== {path} ==")
    head = [f"{s['events']} events"]
    if "defense" in s:
        head.append(f"defense={s['defense']}")
    if "attack" in s:
        head.append(f"attack={s['attack']}")
    out("  " + "  ".join(head))
    if "accuracy" in s:
        traj = " -> ".join(f"[{r}] {a:.2f}%"
                           for r, a in s["accuracy"]["trajectory"])
        out(f"  accuracy: {traj}  (max {s['accuracy']['max']:.2f}%)")
    if "attack_success" in s:
        traj = " -> ".join(f"[{r}] {a:.2f}%"
                           for r, a in s["attack_success"]["trajectory"])
        out(f"  attack success: {traj}")
    sel = s.get("selection")
    if sel:
        out(f"  selection concentration over {sel['rounds']} rounds:")
        out(f"    distinct winners {sel['distinct_winners']}, "
            f"top-1 share {sel['top1_share']:.3f} "
            f"(client {sel['top1_client']}), "
            f"malicious share {sel['malicious_share']:.3f}"
            + (f", malicious picks {sel['malicious_picks']}"
               if "malicious_picks" in sel else ""))
        hist = "  ".join(f"{k}:{v}" for k, v in sel["histogram"].items())
        out(f"    histogram  {hist}")
    flt = s.get("faults")
    if flt:
        inj = "  ".join(f"{k}:{v}" for k, v in sorted(
            flt["injected"].items())) or "none"
        out(f"  faults over {flt['rounds']} rounds: injected [{inj}]  "
            f"quarantined {flt['quarantined']}")
        for rb in flt["rollbacks"]:
            out(f"    rollback at round {rb['round']} -> restored round "
                f"{rb['restored_round']} (total {rb['rollbacks_total']})")
    if "phases" in s:
        out("  phase timing:")
        for name, row in s["phases"].items():
            out(f"    {name:10s} total {row['total_s']:9.3f} s   "
                f"count {row['count']:5d}   mean {row['mean_ms']:8.3f} ms")
    if "stream" in s:
        out("  stream: " + "  ".join(f"{k}={v}"
                                     for k, v in s["stream"].items()))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="attacking_federate_learning_tpu report",
        description="Summarize structured run JSONLs: selection "
                    "concentration, phase timing, accuracy/ASR "
                    "trajectories (utils/metrics.py event schema).")
    p.add_argument("paths", nargs="+", metavar="RUN_JSONL")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (one object keyed by "
                        "path)")
    p.add_argument("--no-validate", action="store_true",
                   help="skip schema validation (reading logs from a "
                        "newer/older writer)")
    args = p.parse_args(argv)

    runs = {}
    for path in args.paths:
        runs[path] = summarize_run(
            load_events([path], validate=not args.no_validate))

    if args.json:
        print(json.dumps(runs))
        return 0
    for path, s in runs.items():
        _print_run(path, s, print)
    with_sel = {p: s["selection"] for p, s in runs.items()
                if "selection" in s}
    if len(with_sel) > 1:
        print("== selection concentration across runs ==")
        for path, sel in with_sel.items():
            print(f"  top-1 share {sel['top1_share']:.3f}  "
                  f"distinct {sel['distinct_winners']:3d}  "
                  f"malicious {sel['malicious_share']:.3f}  {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
