"""Run-report tool: read one or more run JSONLs, print forensics.

The reference's only artifacts are a print tee and an accuracy CSV
(SURVEY.md §5); every analysis in GRID_RESULTS.md (selection
concentration, timing attribution, ASR trajectories) was hand-rolled per
study.  This module automates them over the structured event schema
(utils/metrics.py):

- **selection concentration** — distinct winners, top-1 share, malicious
  share, per-client histogram, from 'defense' events' selection masks
  (Krum one-hot, Bulyan multi-hot) or the end-of-run 'selection_hist';
- **phase timing** — the PhaseTimer summary from 'profile' events;
- **trajectories** — accuracy from 'eval' events, attack success from
  'asr' events;
- **staleness rollup** — per-round delivered counts, the aggregate
  staleness histogram and the weight mass per staleness bucket from
  v7 'async' events (asynchronous buffered rounds,
  core/async_rounds.py).

Usage (cli.py dispatches the subcommand)::

    python -m attacking_federate_learning_tpu.cli report logs/run.jsonl
    python -m attacking_federate_learning_tpu.cli report --json a.jsonl b.jsonl

Multiple files print side by side plus a concentration comparison table —
the iid-vs-femnist_style trend (GRID_RESULTS round-5 row) is one report
invocation over the two run logs.
"""

from __future__ import annotations

import argparse
import json
import os
from collections import Counter

from attacking_federate_learning_tpu.utils.metrics import iter_events


def load_events(paths, validate: bool = True, skip_bad: bool = False,
                bad_lines: list = None) -> list:
    """All events from the given JSONLs, schema-validated by default.
    ``skip_bad`` tolerates torn/invalid lines (counted into
    ``bad_lines`` as (lineno, msg)) — mixed-version and crash-truncated
    logs summarize instead of aborting the whole invocation."""
    events = []
    for p in paths:
        events.extend(iter_events(p, validate=validate,
                                  skip_bad=skip_bad,
                                  bad_lines=bad_lines))
    return events


def selection_concentration(events):
    """The GRID_RESULTS top-1-share analysis, automated.

    Winners come from 'defense' events' ``selection_mask`` vectors.  A
    run of one-hot masks (Krum) yields a winner histogram with integer
    counts and a malicious-picks total; multi-hot masks (Bulyan) yield
    selection-mass shares.  Returns None when no masks were recorded.
    NaN masks (host engines that never ship the selection back) are
    skipped."""
    masks = []
    for e in events:
        if e.get("kind") == "defense" and "selection_mask" in e:
            m = e["selection_mask"]
            if all(x == x for x in m):      # NaN-free (x != x iff NaN)
                masks.append((m, e.get("malicious_count", 0)))
    if not masks:
        return None
    one_hot = all(abs(sum(m) - 1.0) < 1e-6 for m, _ in masks)
    counts: Counter = Counter()
    mal_mass = total = 0.0
    for m, f in masks:
        for i, x in enumerate(m):
            if x > 0:
                counts[i] += x
                total += x
                if i < f:
                    mal_mass += x
    top1_client, top1 = counts.most_common(1)[0]
    out = {
        "rounds": len(masks),
        "distinct_winners": len(counts),
        "top1_share": round(top1 / total, 4),
        "top1_client": top1_client,
        "malicious_share": round(mal_mass / total, 4),
        "histogram": {str(k): (int(v) if one_hot else round(v, 2))
                      for k, v in sorted(counts.items())},
    }
    if one_hot:
        out["malicious_picks"] = int(round(mal_mass))
    return out


def tier2_attribution(event):
    """Per-shard tier-2 selection mass and the rejected-shard set for
    one 'shard_selection' event (schema v6).

    Selection kernels (Krum one-hot, Bulyan multi-hot) attribute by
    mask: a shard with zero mass was rejected outright.  The trimmed
    mean attributes by kept fraction: a shard kept on fewer than half
    its fair share of coordinates was substantially trimmed out.
    Returns ``(mass, rejected)`` — a length-S float list and a set of
    shard ids — or ``(None, None)`` when the tier-2 kernel exposes no
    selection record (mean, median) or the mask is NaN (host engines).
    Shared with utils/trace_export.py (the tier-2 rejection track)."""
    mask = event.get("tier2_selection_mask")
    if isinstance(mask, list) and all(x == x for x in mask):
        mass = [float(x) for x in mask]
        return mass, {i for i, x in enumerate(mass) if x <= 0.0}
    kept = event.get("tier2_kept_fraction")
    if isinstance(kept, list) and all(x == x for x in kept):
        mass = [float(x) for x in kept]
        fair = sum(mass) / max(len(mass), 1)
        return mass, {i for i, x in enumerate(mass) if x < 0.5 * fair}
    return None, None


def _tier1_concentration(recs):
    """Per-shard tier-1 selection rollup from the stacked (S, m)
    'shard_selection_mask' fields: each shard's top-1 row share and
    the selection mass its own malicious rows (rows [0, mal_counts[s])
    — the placement's malicious-first invariant) captured.  Returns
    None when no tier-1 masks were recorded (NoDefense tier-1, or
    groupwise secagg where per-client rows are invisible)."""
    per_shard: dict = {}
    for e in recs:
        masks = e.get("shard_selection_mask")
        if not isinstance(masks, list) or not masks:
            continue
        if not isinstance(masks[0], list):
            continue
        counts = e.get("mal_counts") or [0] * len(masks)
        for s, row in enumerate(masks):
            if not all(x == x for x in row):
                continue                      # NaN: not measured
            d = per_shard.setdefault(
                s, {"mass": [0.0] * len(row), "total": 0.0,
                    "mal_mass": 0.0, "rounds": 0,
                    "mal_rows": int(counts[s]) if s < len(counts)
                    else 0})
            d["rounds"] += 1
            for i, x in enumerate(row):
                if x > 0:
                    d["mass"][i] += x
                    d["total"] += x
                    if i < d["mal_rows"]:
                        d["mal_mass"] += x
    if not per_shard:
        return None
    out = []
    for s in sorted(per_shard):
        d = per_shard[s]
        top1 = max(d["mass"]) if d["total"] else 0.0
        out.append({
            "shard": s, "mal_rows": d["mal_rows"],
            "rounds": d["rounds"],
            "top1_share": round(top1 / d["total"], 4) if d["total"]
            else None,
            "top1_row": (int(d["mass"].index(top1)) if d["total"]
                         else None),
            "malicious_share": round(d["mal_mass"] / d["total"], 4)
            if d["total"] else None,
        })
    return out


def forensics_summary(events):
    """The ISSUE 8 forensics layer over a hierarchical run's
    'shard_selection' stream (schema v6):

    - **tier-2 rejection attribution** — which megabatch groups' tier-1
      estimates the cross-shard reduction rejected, round by round;
    - **shard-level selection concentration** — each shard's tier-1
      top-1 share and the mass its own malicious rows captured;
    - **the colluder-localization verdict** — did tier-2 isolate the
      malicious shards (ground truth: the placement's per-shard
      malicious counts, carried by every event), and at what round did
      the localization stabilize (the earliest round from which every
      malicious shard stays rejected through the end of the run).

    Returns None when the run carries no shard_selection events (flat
    runs, telemetry off)."""
    recs = sorted((e for e in events
                   if e.get("kind") == "shard_selection"),
                  key=lambda e: e.get("round", 0))
    if not recs:
        return None
    last = recs[-1]
    mal_counts = last.get("mal_counts")
    mal_shards = ([s for s, c in enumerate(mal_counts) if c > 0]
                  if isinstance(mal_counts, list) else None)
    out = {
        "rounds": len(recs),
        "defense": last.get("defense"),
        "tier2_defense": last.get("tier2_defense"),
        "megabatch": last.get("megabatch"),
        "mal_placement": last.get("mal_placement"),
        "mal_counts": mal_counts,
        "malicious_shards": mal_shards,
    }
    t1 = _tier1_concentration(recs)
    if t1:
        out["tier1"] = t1

    per_round = []                 # (round, mass, rejected)
    for e in recs:
        mass, rejected = tier2_attribution(e)
        if mass is not None:
            per_round.append((int(e.get("round", 0)), mass, rejected))
    if not per_round:
        out["localization"] = {"verdict": "no_attribution"}
        return out
    S = len(per_round[0][1])
    total = [0.0] * S
    rejections = [0] * S
    for _, mass, rejected in per_round:
        for s in range(S):
            total[s] += mass[s]
        for s in rejected:
            rejections[s] += 1
    grand = sum(total) or 1.0
    tier2 = {
        "rounds": len(per_round),
        "selection_share": [round(x / grand, 4) for x in total],
        "rejections": {str(s): rejections[s] for s in range(S)
                       if rejections[s]},
    }
    if mal_shards is not None:
        tier2["malicious_share"] = round(
            sum(total[s] for s in mal_shards) / grand, 4)
        tier2["mal_rejected_rounds"] = sum(
            1 for _, _, rej in per_round
            if all(s in rej for s in mal_shards))
    out["tier2"] = tier2

    if mal_shards is None:
        loc = {"verdict": "no_ground_truth"}
    elif not mal_shards:
        loc = {"verdict": "no_malicious"}
    else:
        # Stabilization: the earliest recorded round from which every
        # malicious shard stays rejected through the end of the run.
        stabilized = None
        for i in range(len(per_round) - 1, -1, -1):
            _, _, rej = per_round[i]
            if all(s in rej for s in mal_shards):
                stabilized = per_round[i][0]
            else:
                break
        if stabilized is not None and all(
                s in per_round[-1][2] for s in mal_shards):
            loc = {"verdict": "localized",
                   "isolated_shards": mal_shards,
                   "stabilized_round": stabilized}
        else:
            loc = {"verdict": "not_localized",
                   "stabilized_round": None}
    out["localization"] = loc
    return out


def fault_recovery(events):
    """Fault/recovery accounting from 'fault' events (core/faults.py +
    the engine watchdog): total injected per kind, quarantined rows,
    rounds touched, and every rollback record.  Returns None when the
    run emitted no fault events (faults off).

    Hierarchical (schema v13) events are shard-qualified — one event
    per ROUND whose scalar counts already sum over shards, with the
    per-shard survivor vector riding along as ``shard_alive`` — so the
    per-round accumulation above needs no change (summing the vector
    AND the scalars would double count; only the scalars are summed).
    The shard-domain axis gets its own rollup: rounds with at least
    one dead domain, total domain deaths, the minimum surviving-shard
    count, and the tier-2 ladder action histogram
    (remask/fallback/hold, core/population.py ACTION_NAMES)."""
    from attacking_federate_learning_tpu.core.population import (
        ACTION_NAMES
    )

    injected = Counter()
    quarantined = rounds = 0
    rollbacks = []
    dead_rounds = shards_dead_total = 0
    min_alive = None
    actions = Counter()
    for e in events:
        if e.get("kind") != "fault":
            continue
        if e.get("rolled_back"):
            rollbacks.append({"round": e["round"],
                              "restored_round": e.get("restored_round"),
                              "rollbacks_total": e.get("rollbacks_total")})
            continue
        rounds += 1
        quarantined += int(e.get("quarantined", 0))
        for k, v in e.items():
            if k.startswith("injected_"):
                injected[k[len("injected_"):]] += int(v)
        if "shards_dead" in e:
            dead = int(e["shards_dead"])
            shards_dead_total += dead
            dead_rounds += dead > 0
            alive = int(e.get("shards_alive", 0))
            min_alive = (alive if min_alive is None
                         else min(min_alive, alive))
        if "tier2_action" in e:
            act = int(e["tier2_action"])
            actions[ACTION_NAMES[act] if 0 <= act < len(ACTION_NAMES)
                    else str(act)] += 1
    if not rounds and not rollbacks:
        return None
    out = {"rounds": rounds, "injected": dict(injected),
           "quarantined": quarantined, "rollbacks": rollbacks}
    if min_alive is not None:
        out["shard_domains"] = {
            "dead_rounds": dead_rounds,
            "shards_dead_total": shards_dead_total,
            "min_shards_alive": min_alive,
            "tier2_actions": dict(actions)}
    return out


def async_summary(events):
    """Staleness rollup from v7 'async' events (core/async_rounds.py):
    per-round delivered counts, the aggregate staleness histogram, the
    weight mass by staleness bucket (how much aggregation influence
    each staleness level actually carried — the staleness-weighting
    policy's measured effect), buffer occupancy, and the
    eviction/supersession/quarantine totals.  Returns None when the
    run emitted no async events (synchronous topologies)."""
    recs = sorted((e for e in events if e.get("kind") == "async"),
                  key=lambda e: e.get("round", 0))
    if not recs:
        return None
    hists = [e.get("staleness_hist") for e in recs
             if isinstance(e.get("staleness_hist"), list)]
    masses = [e.get("weight_mass") for e in recs
              if isinstance(e.get("weight_mass"), list)]
    delivered = [int(e.get("delivered", 0)) for e in recs]
    out = {
        "rounds": len(recs),
        "delivered_per_round": delivered,
        "delivered_total": sum(delivered),
        "delivered_mean": round(sum(delivered) / len(recs), 3),
        "empty_rounds": sum(1 for d in delivered if d == 0),
        "evicted_total": sum(int(e.get("evicted", 0)) for e in recs),
        "superseded_total": sum(int(e.get("superseded", 0))
                                for e in recs),
        "quarantined_total": sum(int(e.get("quarantined", 0))
                                 for e in recs),
        "pending_last": int(recs[-1].get("pending", 0)),
        "in_flight_mean": round(
            sum(int(e.get("in_flight", 0)) for e in recs) / len(recs),
            2),
    }
    if hists:
        depth = max(len(h) for h in hists)
        agg = [0] * depth
        for h in hists:
            for s, v in enumerate(h):
                agg[s] += int(v)
        out["staleness_hist"] = agg
    if masses:
        depth = max(len(w) for w in masses)
        agg_w = [0.0] * depth
        for w in masses:
            for s, v in enumerate(w):
                agg_w[s] += float(v)
        out["weight_mass"] = [round(x, 3) for x in agg_w]
    return out


def traffic_summary(events):
    """Population-traffic rollup from v11 'traffic' events
    (core/population.py): per-round arrived counts and effective-f,
    the degradation-ladder action histogram (remask/fallback/hold),
    which defenses actually aggregated, and the under-fill rounds.
    Returns None when the run emitted no traffic events (a
    static-cohort run)."""
    recs = sorted((e for e in events if e.get("kind") == "traffic"),
                  key=lambda e: e.get("round", 0))
    if not recs:
        return None
    arrived = [int(e.get("arrived", 0)) for e in recs]
    f_eff = [int(e.get("f_eff", 0)) for e in recs]
    actions = {}
    defenses = {}
    for e in recs:
        a = str(e.get("action", "?"))
        actions[a] = actions.get(a, 0) + 1
        d = str(e.get("defense", "?"))
        defenses[d] = defenses.get(d, 0) + 1
    degraded = [int(e.get("round", -1)) for e in recs
                if e.get("action") in ("fallback", "hold")]
    return {
        "rounds": len(recs),
        "arrived_per_round": arrived,
        "arrived_mean": round(sum(arrived) / len(recs), 3),
        "arrived_min": min(arrived),
        "f_eff_per_round": f_eff,
        "f_eff_mean": round(sum(f_eff) / len(recs), 3),
        "f_eff_max": max(f_eff) if f_eff else 0,
        "actions": actions,
        "defenses": defenses,
        "degraded_rounds": degraded,
    }


def secagg_summary(events):
    """Secure-aggregation protocol rollup from 'secagg' events (schema
    v5, protocols/secagg.py): rounds under the protocol, dropout-
    recovery rounds and total masks reconstructed (the simulated
    seed-reveal work), bitwise sum-check failures (must be 0 — the
    mask-cancellation identity is exact), and under groupwise the
    last round's per-group sum norms (the server-visible quantity).
    Returns None when the run emitted no secagg events (secagg off)."""
    recs = [e for e in events if e.get("kind") == "secagg"]
    if not recs:
        return None
    out = {"rounds": len(recs),
           "recovery_rounds": sum(1 for e in recs
                                  if e.get("recovery")),
           "masks_reconstructed": sum(
               int(e.get("masks_reconstructed", 0)) for e in recs),
           "sum_check_failures": sum(
               1 for e in recs if not e.get("sum_check_ok", 1))}
    norms = [e["group_sum_norms"] for e in recs
             if isinstance(e.get("group_sum_norms"), list)]
    if norms:
        out["groups"] = len(norms[-1])
        out["group_sum_norms_last"] = [round(float(x), 3)
                                       for x in norms[-1]]
    return out


def compile_cost(events):
    """The compile & cost table ('compile'/'cost' events, schema v2 —
    utils/costs.py): per entry point, static FLOPs / bytes-accessed /
    peak-memory facts joined with compile wall time and persistent-
    cache attribution, plus a hit/miss/compile-seconds rollup.  Returns
    None when the run recorded neither kind (cost report off)."""
    compiles = {e["name"]: e for e in events if e.get("kind") == "compile"}
    costs = {e["name"]: e for e in events if e.get("kind") == "cost"}
    if not compiles and not costs:
        return None
    names = list(costs)
    names += [n for n in compiles if n not in costs]
    rows = []
    for name in names:
        c, k = costs.get(name, {}), compiles.get(name, {})
        rows.append({
            "name": name,
            "flops": c.get("flops"),
            "bytes_accessed": c.get("bytes_accessed"),
            "peak_bytes": c.get("peak_bytes"),
            "compile_s": k.get("compile_s"),
            "cache": k.get("cache"),
        })
    cache_tags = [k.get("cache") for k in compiles.values()]
    return {
        "entries": rows,
        "compile_total_s": round(sum(k.get("compile_s", 0.0)
                                     for k in compiles.values()), 3),
        "cache_hits": sum(1 for t in cache_tags if t == "hit"),
        "cache_misses": sum(1 for t in cache_tags if t == "miss"),
    }


def lifecycle_summary(events):
    """Run-lifecycle rollup from 'lifecycle' events (schema v3,
    utils/lifecycle.py): per-phase transition counts, the attempt
    count, any degradations applied, and failure classes seen — one
    glance answers "did this run preempt/resume/degrade, and how many
    times did the supervisor have to step in".  Returns None when the
    run recorded no lifecycle events (unsupervised, pre-v3)."""
    lcs = [e for e in events if e.get("kind") == "lifecycle"]
    if not lcs:
        return None
    out = {"transitions": len(lcs),
           "phases": dict(Counter(e["phase"] for e in lcs)),
           "last_phase": lcs[-1]["phase"]}
    attempts = [e["attempt"] for e in lcs
                if isinstance(e.get("attempt"), (int, float))]
    if attempts:
        out["attempts"] = int(max(attempts))
    degradations = [e.get("step") for e in lcs
                    if e["phase"] == "degrade" and e.get("step")]
    if degradations:
        out["degradations"] = degradations
    failures = [e["failure"] for e in lcs if e.get("failure")]
    if failures:
        out["failures"] = dict(Counter(failures))
    return out


def heartbeat_summary(events):
    """Liveness rollup from 'heartbeat' events: count, max last-event
    age (the stall witness) and the final rounds/s EMA."""
    beats = [e for e in events if e.get("kind") == "heartbeat"]
    if not beats:
        return None
    out = {"beats": len(beats),
           "max_event_age_s": max(e["last_event_age_s"] for e in beats),
           "rss_mb_last": beats[-1]["rss_mb"]}
    with_rps = [e for e in beats if "rounds_per_s" in e]
    if with_rps:
        out["rounds_per_s_last"] = with_rps[-1]["rounds_per_s"]
    return out


def numerics_summary(events):
    """Numeric-health rollup from schema-v14 'numerics' events
    (--numerics runs; ISSUE 20): rounds observed, total nonfinite
    count across every stage counter, rounds with any decision inside
    the tie band (tie-locked — the Bulyan-collapse signature when
    pinned at the round count), the peak tie-proximity count, and the
    peak cancellation depth in bits."""
    from attacking_federate_learning_tpu.utils.numerics import (
        numerics_series
    )

    series = numerics_series(events)
    if not series:
        return None
    rounds = sorted({r for v in series.values() for r, _ in v})
    out = {"rounds": len(rounds),
           "nonfinite_total": int(sum(
               v for _, v in series.get("nonfinite_total", []))),
           "tie_locked_rounds": sum(
               1 for _, v in series.get("tie_locked", []) if v)}
    ties = [v for key, vals in series.items()
            if key.endswith("tie_rows") for _, v in vals]
    if ties:
        out["tie_rows_max"] = int(max(ties))
    bits = [v for key, vals in series.items()
            if key.endswith("cancel_bits") for _, v in vals]
    if bits:
        out["cancel_bits_max"] = round(float(max(bits)), 2)
    return out


def summarize_run(events):
    """One run's report payload from its event list."""
    kinds = Counter(e["kind"] for e in events)
    out = {"events": len(events), "kinds": dict(kinds)}
    for e in events:
        if e["kind"] == "defense":
            out["defense"] = e["defense"]
            break
    for e in events:
        if e["kind"] == "attack":
            out["attack"] = e["attack"]
            break
    evals = [(e["round"], e["accuracy"]) for e in events
             if e["kind"] == "eval"]
    if evals:
        out["accuracy"] = {
            "trajectory": [[r, round(a, 2)] for r, a in evals],
            "final": round(evals[-1][1], 2),
            "max": round(max(a for _, a in evals), 2)}
    asrs = [(e["round"], e["attack_success_rate"]) for e in events
            if e["kind"] == "asr"]
    if asrs:
        out["attack_success"] = {
            "trajectory": [[r, round(a, 2)] for r, a in asrs],
            "final": round(asrs[-1][1], 2)}
    sel = selection_concentration(events)
    if sel:
        out["selection"] = sel
    faults = fault_recovery(events)
    if faults:
        out["faults"] = faults
    sec = secagg_summary(events)
    if sec:
        out["secagg"] = sec
    asy = async_summary(events)
    if asy:
        out["async"] = asy
    fx = forensics_summary(events)
    if fx:
        out["forensics"] = fx
    hists = [e for e in events if e["kind"] == "selection_hist"]
    if hists:
        out["selection_hist"] = {
            k: hists[-1][k] for k in ("counts", "rounds", "distinct_winners",
                                      "top1_share", "top1_client",
                                      "malicious_picks")
            if k in hists[-1]}
    cc = compile_cost(events)
    if cc:
        out["compile_cost"] = cc
    lc = lifecycle_summary(events)
    if lc:
        out["lifecycle"] = lc
    hb = heartbeat_summary(events)
    if hb:
        out["heartbeat"] = hb
    nm = numerics_summary(events)
    if nm:
        out["numerics"] = nm
    profiles = [e for e in events if e["kind"] == "profile"]
    if profiles:
        out["phases"] = profiles[-1]["phases"]
    streams = [e for e in events if e["kind"] == "stream"]
    if streams:
        out["stream"] = {k: v for k, v in streams[-1].items()
                         if k.startswith("stream_")}
    return out


def _print_run(path, s, out):
    out(f"== {path} ==")
    head = [f"{s['events']} events"]
    if s.get("bad_lines"):
        head.append(f"{s['bad_lines']} torn/invalid line(s) skipped")
    if "defense" in s:
        head.append(f"defense={s['defense']}")
    if "attack" in s:
        head.append(f"attack={s['attack']}")
    out("  " + "  ".join(head))
    if "accuracy" in s:
        traj = " -> ".join(f"[{r}] {a:.2f}%"
                           for r, a in s["accuracy"]["trajectory"])
        out(f"  accuracy: {traj}  (max {s['accuracy']['max']:.2f}%)")
    if "attack_success" in s:
        traj = " -> ".join(f"[{r}] {a:.2f}%"
                           for r, a in s["attack_success"]["trajectory"])
        out(f"  attack success: {traj}")
    sel = s.get("selection")
    if sel:
        out(f"  selection concentration over {sel['rounds']} rounds:")
        out(f"    distinct winners {sel['distinct_winners']}, "
            f"top-1 share {sel['top1_share']:.3f} "
            f"(client {sel['top1_client']}), "
            f"malicious share {sel['malicious_share']:.3f}"
            + (f", malicious picks {sel['malicious_picks']}"
               if "malicious_picks" in sel else ""))
        hist = "  ".join(f"{k}:{v}" for k, v in sel["histogram"].items())
        out(f"    histogram  {hist}")
    flt = s.get("faults")
    if flt:
        inj = "  ".join(f"{k}:{v}" for k, v in sorted(
            flt["injected"].items())) or "none"
        out(f"  faults over {flt['rounds']} rounds: injected [{inj}]  "
            f"quarantined {flt['quarantined']}")
        sd = flt.get("shard_domains")
        if sd:
            acts = "  ".join(f"{k}:{v}" for k, v in sorted(
                sd["tier2_actions"].items())) or "none"
            out(f"    shard domains: {sd['dead_rounds']} round(s) with "
                f"a dead domain ({sd['shards_dead_total']} shard-round "
                f"deaths), min shards alive "
                f"{sd['min_shards_alive']}  tier-2 ladder [{acts}]")
        for rb in flt["rollbacks"]:
            out(f"    rollback at round {rb['round']} -> restored round "
                f"{rb['restored_round']} (total {rb['rollbacks_total']})")
    sec = s.get("secagg")
    if sec:
        line = (f"  secagg: {sec['rounds']} masked rounds, "
                f"{sec['recovery_rounds']} recovery round(s), "
                f"{sec['masks_reconstructed']} masks reconstructed, "
                f"{sec['sum_check_failures']} sum-check failure(s)")
        if "groups" in sec:
            line += f", {sec['groups']} groups"
        out(line)
        if "group_sum_norms_last" in sec:
            out("    group sum norms (last round): "
                + "  ".join(f"{x:.3f}"
                            for x in sec["group_sum_norms_last"]))
    asy = s.get("async")
    if asy:
        out(f"  async rounds: {asy['rounds']}  delivered "
            f"{asy['delivered_total']} total "
            f"({asy['delivered_mean']}/round, {asy['empty_rounds']} "
            f"empty)  evicted {asy['evicted_total']}  superseded "
            f"{asy['superseded_total']}  quarantined "
            f"{asy['quarantined_total']}  in-flight mean "
            f"{asy['in_flight_mean']}  pending at end "
            f"{asy['pending_last']}")
        traj = "  ".join(str(d) for d in asy["delivered_per_round"])
        out(f"    delivered per round: {traj}")
        if "staleness_hist" in asy:
            hist = asy["staleness_hist"]
            mass = asy.get("weight_mass", [None] * len(hist))
            out("    staleness   rows   weight mass")
            for sname, (h, w) in enumerate(zip(hist, mass)):
                wtxt = f"{w:11.3f}" if w is not None else "          -"
                out(f"      s={sname}     {h:5d}  {wtxt}")
    fx = s.get("forensics")
    if fx:
        _print_forensics(fx, out, indent="  ")
    cc = s.get("compile_cost")
    if cc:
        out(f"  compile & cost ({cc['compile_total_s']:.2f} s total "
            f"compile; cache {cc['cache_hits']} hit / "
            f"{cc['cache_misses']} miss):")
        for r in cc["entries"]:
            flops = (f"{r['flops']:.3e}" if r.get("flops") is not None
                     else "-")
            byts = (f"{r['bytes_accessed']:.3e}"
                    if r.get("bytes_accessed") is not None else "-")
            peak = (f"{r['peak_bytes'] / 1e6:8.1f} MB"
                    if r.get("peak_bytes") is not None else "        -")
            comp = (f"{r['compile_s']:6.2f} s"
                    if r.get("compile_s") is not None else "     -")
            out(f"    {r['name']:16s} flops {flops:>10s}   "
                f"bytes {byts:>10s}   peak {peak}   "
                f"compile {comp} ({r.get('cache', '-')})")
    lc = s.get("lifecycle")
    if lc:
        phases = "  ".join(f"{k}:{v}" for k, v in sorted(
            lc["phases"].items()))
        line = (f"  lifecycle: {phases}  (last {lc['last_phase']}")
        if "attempts" in lc:
            line += f", {lc['attempts']} attempt(s)"
        line += ")"
        out(line)
        if "degradations" in lc:
            out(f"    degradations: {', '.join(lc['degradations'])}")
        if "failures" in lc:
            fl = "  ".join(f"{k}:{v}" for k, v in sorted(
                lc["failures"].items()))
            out(f"    failures seen: {fl}")
    hb = s.get("heartbeat")
    if hb:
        line = (f"  heartbeat: {hb['beats']} beats, max event age "
                f"{hb['max_event_age_s']:.1f} s, rss "
                f"{hb['rss_mb_last']:.0f} MB")
        if "rounds_per_s_last" in hb:
            line += f", {hb['rounds_per_s_last']:.2f} rounds/s"
        out(line)
    nm = s.get("numerics")
    if nm:
        line = (f"  numerics: {nm['rounds']} rounds observed, "
                f"nonfinite total {nm['nonfinite_total']}, "
                f"tie-locked {nm['tie_locked_rounds']}/{nm['rounds']} "
                f"rounds")
        if "tie_rows_max" in nm:
            line += f", max tie rows {nm['tie_rows_max']}"
        if "cancel_bits_max" in nm:
            line += f", max cancellation {nm['cancel_bits_max']} bits"
        out(line)
    if "phases" in s:
        out("  phase timing:")
        for name, row in s["phases"].items():
            out(f"    {name:10s} total {row['total_s']:9.3f} s   "
                f"count {row['count']:5d}   mean {row['mean_ms']:8.3f} ms")
    if "stream" in s:
        out("  stream: " + "  ".join(f"{k}={v}"
                                     for k, v in s["stream"].items()))


def campaign_table(manifest, registry_entries=None) -> dict:
    """Defense x attack table for one campaign manifest
    (campaigns/journal.py), with metric values taken from the CROSS-RUN
    REGISTRY (utils/registry.py) — the per-run manifests are the source
    of truth and the registry copies them verbatim, so the rendered
    numbers match the run manifests bit-exactly.  Skipped cells carry
    their composition-rejection reason; a done cell with no registry
    entry (an unjournaled sweep) falls back to the campaign manifest's
    own copy, flagged in ``problems``.

    Returns {rows, cols, cells, problems}: ``cells`` maps
    ``"defense|attack"`` to the list of cell records in that bucket
    (one per cell — seed/epochs axes stack multiple records per
    bucket)."""
    rows, cols, cells, problems = [], [], {}, []
    for cid, row in (manifest.get("cells") or {}).items():
        d = str(row.get("defense", "?"))
        a = str(row.get("attack", "auto"))
        if d not in rows:
            rows.append(d)
        if a not in cols:
            cols.append(a)
        rec = {"cell": cid, "state": row.get("state")}
        if row.get("state") == "done":
            src = None
            if registry_entries is not None:
                src = registry_entries.get(cid)
            if src is not None:
                rec["source"] = "registry"
            else:
                src, rec["source"] = row, "manifest"
                if registry_entries is not None:
                    problems.append(
                        f"{cid}: no registry entry (unjournaled "
                        f"cell?); values from the campaign manifest")
            for k in ("final_accuracy", "max_accuracy", "final_asr",
                      "rounds_per_s", "wall_s"):
                if src.get(k) is not None:
                    rec[k] = src[k]
            # A registry-sourced cell may still carry its wall_s only
            # in the campaign manifest (the scheduler timed the cell;
            # the engine stamped rounds_per_s) — take either headline
            # wherever it lives, so the time column survives both
            # sources.
            for k in ("rounds_per_s", "wall_s"):
                if rec.get(k) is None and row.get(k) is not None:
                    rec[k] = row[k]
        else:
            rec["reason"] = row.get("reason")
        cells.setdefault(f"{d}|{a}", []).append(rec)
    return {"campaign_id": manifest.get("campaign_id"),
            "status": manifest.get("status"), "rows": rows,
            "cols": cols, "cells": cells, "problems": problems}


def _campaign_cell_text(recs) -> str:
    parts = []
    for rec in recs:
        if rec["state"] == "done":
            txt = (f"{rec['final_accuracy']:.2f}"
                   if rec.get("final_accuracy") is not None else "done")
            if rec.get("final_asr") is not None:
                txt += f"/asr {rec['final_asr']:.2f}"
        elif rec["state"] == "skipped":
            txt = "skip"
        elif rec["state"] == "pending":
            txt = "pending"
        else:
            txt = rec["state"].upper()
        parts.append(txt)
    return " ; ".join(parts) if parts else "-"


def _row_time_text(table, d) -> str:
    """The time-column cell for one defense row: the median engine
    rounds/s over the row's done cells (schema-v10 measured-walls
    headline the engine stamps into the registry), falling back to the
    scheduler's cell wall when no engine headline exists."""
    rps = [rec["rounds_per_s"] for a in table["cols"]
           for rec in table["cells"].get(f"{d}|{a}", [])
           if rec.get("rounds_per_s") is not None]
    if rps:
        rps.sort()
        return f"{rps[len(rps) // 2]:.2f} r/s"
    walls = [rec["wall_s"] for a in table["cols"]
             for rec in table["cells"].get(f"{d}|{a}", [])
             if rec.get("wall_s") is not None]
    if walls:
        walls.sort()
        return f"{walls[len(walls) // 2]:.0f} s"
    return "-"


def _print_campaign_table(table, out=print):
    out(f"== campaign {table['campaign_id']}  "
        f"[{table['status']}] ==")
    width = max([len(r) for r in table["rows"]] + [7])
    cw = {a: max(len(a), 12) for a in table["cols"]}
    has_time = any(rec.get("rounds_per_s") is not None
                   or rec.get("wall_s") is not None
                   for recs in table["cells"].values() for rec in recs)
    header = ("  " + " " * width + "  "
              + "  ".join(f"{a:>{cw[a]}s}" for a in table["cols"]))
    if has_time:
        header += f"  {'time':>10s}"
    out(header)
    for d in table["rows"]:
        line = f"  {d:<{width}s}  "
        line += "  ".join(
            f"{_campaign_cell_text(table['cells'].get(f'{d}|{a}', [])):>{cw[a]}s}"
            for a in table["cols"])
        if has_time:
            line += f"  {_row_time_text(table, d):>10s}"
        out(line)
    skips = [(key, rec) for key, recs in table["cells"].items()
             for rec in recs if rec["state"] == "skipped"]
    if skips:
        out("  skipped cells:")
        for key, rec in skips:
            out(f"    {key}: {rec.get('reason')}")
    fails = [(key, rec) for key, recs in table["cells"].items()
             for rec in recs if rec["state"] == "failed"]
    if fails:
        out("  failed cells:")
        for key, rec in fails:
            out(f"    {key}: {rec.get('reason')}")
    for prob in table["problems"]:
        out(f"  WARNING: {prob}")


def _print_forensics(fx, out, indent="  "):
    """Human-readable forensics table (shared by the per-run summary
    and the 'report forensics' subcommand)."""
    out(f"{indent}hierarchical forensics over {fx['rounds']} rounds: "
        f"{fx.get('defense')} tier-1 / {fx.get('tier2_defense')} "
        f"tier-2, megabatch {fx.get('megabatch')}, placement "
        f"{fx.get('mal_placement')}")
    if fx.get("mal_counts") is not None:
        out(f"{indent}  malicious shards (ground truth): "
            f"{fx.get('malicious_shards')}  (per-shard counts "
            f"{fx['mal_counts']})")
    t2 = fx.get("tier2")
    if t2:
        share = "  ".join(f"{s}:{x:.3f}"
                          for s, x in enumerate(t2["selection_share"]))
        out(f"{indent}  tier-2 selection share by shard: {share}")
        rej = "  ".join(f"{s}:{c}" for s, c in
                        sorted(t2["rejections"].items(),
                               key=lambda kv: int(kv[0]))) or "none"
        out(f"{indent}  tier-2 rejections (rounds rejected): {rej}")
        if "malicious_share" in t2:
            out(f"{indent}  malicious selection share "
                f"{t2['malicious_share']:.3f}; all-malicious-rejected "
                f"rounds {t2['mal_rejected_rounds']}/{t2['rounds']}")
    loc = fx.get("localization", {})
    verdict = loc.get("verdict")
    if verdict == "localized":
        out(f"{indent}  localization: LOCALIZED from round "
            f"{loc['stabilized_round']} — tier-2 isolated shard(s) "
            f"{loc['isolated_shards']}")
    else:
        out(f"{indent}  localization: {verdict}")
    for row in fx.get("tier1", []):
        out(f"{indent}  tier-1 shard {row['shard']} "
            f"({row['mal_rows']} malicious rows): top-1 share "
            f"{row['top1_share']} (row {row['top1_row']}), malicious "
            f"share {row['malicious_share']}")


def forensics_main(argv=None) -> int:
    """``report forensics`` — the tier-2 selection forensics +
    colluder-localization verdict over hierarchical runs'
    'shard_selection' streams (schema v6).  Exit 0 when every given
    log yields a verdict, 1 when any log carries no shard_selection
    events (a flat run, or telemetry off — named per file)."""
    p = argparse.ArgumentParser(
        prog="attacking_federate_learning_tpu report forensics",
        description="Tier-2 selection forensics and colluder "
                    "localization from 'shard_selection' events "
                    "(hierarchical + groupwise-secagg runs with "
                    "--telemetry).")
    p.add_argument("paths", nargs="*", metavar="RUN_JSONL")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (one object keyed by "
                        "path)")
    p.add_argument("--skip-bad", action="store_true",
                   help="tolerate torn/invalid lines")
    p.add_argument("--events", default=None, metavar="JSONL",
                   help="append one v6 'forensics' verdict event per "
                        "analyzed log to this run log")
    p.add_argument("--run-id", action="append", default=[],
                   metavar="QUERY",
                   help="resolve a run through the cross-run registry "
                        "(repeatable, mixes with explicit paths)")
    p.add_argument("--run-dir", default="runs",
                   help="registry location for --run-id resolution")
    args = p.parse_args(argv)

    paths = list(args.paths)
    for query in args.run_id:
        from attacking_federate_learning_tpu.utils.registry import (
            RunRegistry
        )

        entry = RunRegistry(args.run_dir).resolve(query)
        events = entry.get("events")
        if not isinstance(events, str) or not os.path.exists(events):
            p.error(f"--run-id {query}: run {entry['run_id']} has no "
                    f"readable event log (events={events!r})")
        paths.append(events)
    if not paths:
        p.error("nothing to analyze: give RUN_JSONL paths and/or "
                "--run-id")

    failed = False
    results = {}
    for path in paths:
        fx = forensics_summary(load_events([path],
                                           skip_bad=args.skip_bad))
        results[path] = fx
        if fx is None:
            failed = True
    if args.events:
        import time

        from attacking_federate_learning_tpu.utils.metrics import (
            SCHEMA_VERSION, validate_event
        )

        with open(args.events, "a") as f:
            for path, fx in results.items():
                if fx is None:
                    continue
                loc = fx.get("localization", {})
                rec = {"kind": "forensics", "v": SCHEMA_VERSION,
                       "t": round(time.time(), 3), "source": path,
                       "verdict": loc.get("verdict"),
                       "rounds": fx["rounds"],
                       "malicious_shards": fx.get("malicious_shards")}
                if "stabilized_round" in loc:
                    rec["stabilized_round"] = loc["stabilized_round"]
                if "isolated_shards" in loc:
                    rec["isolated_shards"] = loc["isolated_shards"]
                t2 = fx.get("tier2", {})
                if "malicious_share" in t2:
                    rec["tier2_malicious_share"] = t2["malicious_share"]
                    rec["mal_rejected_rounds"] = (
                        t2["mal_rejected_rounds"])
                validate_event(rec)
                f.write(json.dumps(rec) + "\n")
    if args.json:
        print(json.dumps(results))
        return 1 if failed else 0
    for path, fx in results.items():
        print(f"== {path} ==")
        if fx is None:
            print("  no 'shard_selection' events: forensics needs a "
                  "hierarchical (or groupwise-secagg) run with "
                  "--telemetry")
            continue
        _print_forensics(fx, print)
    return 1 if failed else 0


def main(argv=None) -> int:
    if argv is None:
        import sys

        argv = sys.argv[1:]
    if argv and argv[0] == "forensics":
        # 'report forensics' — dispatched before argparse like the
        # cli.py subcommands, so the summary flag surface stays as-is.
        return forensics_main(argv[1:])
    p = argparse.ArgumentParser(
        prog="attacking_federate_learning_tpu report",
        description="Summarize structured run JSONLs: selection "
                    "concentration, phase timing, accuracy/ASR "
                    "trajectories, hierarchical forensics "
                    "(utils/metrics.py event schema).")
    p.add_argument("paths", nargs="*", metavar="RUN_JSONL")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (one object keyed by "
                        "path)")
    p.add_argument("--no-validate", action="store_true",
                   help="skip schema validation (reading logs from a "
                        "newer/older writer)")
    p.add_argument("--skip-bad", action="store_true",
                   help="tolerate torn/invalid lines (crash-truncated "
                        "logs): skip them with a per-file count instead "
                        "of aborting")
    p.add_argument("--run-id", action="append", default=[],
                   metavar="QUERY",
                   help="resolve a run through the cross-run registry "
                        "(runs/index.jsonl — exact id, unique prefix "
                        "or tag) and report its event log; repeatable, "
                        "mixes with explicit paths")
    p.add_argument("--run-dir", default="runs",
                   help="registry location for --run-id resolution")
    args = p.parse_args(argv)

    paths = list(args.paths)
    for query in args.run_id:
        from attacking_federate_learning_tpu.utils.registry import (
            RunRegistry
        )

        entry = RunRegistry(args.run_dir).resolve(query)
        events = entry.get("events")
        if not isinstance(events, str) or not os.path.exists(events):
            p.error(f"--run-id {query}: run {entry['run_id']} has no "
                    f"readable event log (events={events!r})")
        paths.append(events)
    if not paths:
        p.error("nothing to report: give RUN_JSONL paths and/or --run-id")

    runs = {}
    for path in paths:
        bad: list = []
        runs[path] = summarize_run(
            load_events([path], validate=not args.no_validate,
                        skip_bad=args.skip_bad, bad_lines=bad))
        if bad:
            runs[path]["bad_lines"] = len(bad)

    if args.json:
        print(json.dumps(runs))
        return 0
    for path, s in runs.items():
        _print_run(path, s, print)
    with_sel = {p: s["selection"] for p, s in runs.items()
                if "selection" in s}
    if len(with_sel) > 1:
        print("== selection concentration across runs ==")
        for path, sel in with_sel.items():
            print(f"  top-1 share {sel['top1_share']:.3f}  "
                  f"distinct {sel['distinct_winners']:3d}  "
                  f"malicious {sel['malicious_share']:.3f}  {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
