"""Centered-clipping robust aggregation (Karimireddy, He & Jaggi,
"Learning from History for Byzantine Robust Optimization", ICML 2021).

Beyond-reference addition (the reference ships Krum/TrimmedMean/Bulyan
only, defences.py): iteratively re-center on the clipped mean —

    v_{k+1} = v_k + mean_i( clip_tau(g_i - v_k) )

where ``clip_tau`` rescales a row to L2 norm at most tau.  Any single
Byzantine row moves the estimate by at most tau/n per iteration
regardless of its magnitude, so the attack surface is bounded by the
clip radius rather than by the adversary's norm — the property the
paper proves gives order-optimal rates under momentum.

This is the stateless variant: v_0 is the coordinate-wise median (a
robust anchor), and the iteration count is static config surface
(``cclip_iters``), so the whole defense is a fixed-trip ``fori_loop``
of row norms and a broadcast multiply-add — bandwidth-bound,
elementwise, shards over both mesh axes, and fuses into the round
program like every other kernel.  With tau large it degenerates to the
exact cohort mean (one re-centering step from any v_0 lands on
``mean(G)``, a fixed point), which the tests pin.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from attacking_federate_learning_tpu.defenses.kernels import DEFENSES


@DEFENSES.register("CenteredClip")
def centered_clip(users_grads, users_count, corrupted_count,
                  tau=10.0, iters=5, telemetry=False):
    """``telemetry=True`` additionally returns ``{'clip_scale': (n,) —
    each client's clip factor wrt the returned estimate (1.0 = inside
    the tau ball), 'clipped_count': () int32 rows strictly clipped}``."""
    G = users_grads.astype(jnp.float32)
    v0 = jnp.median(G, axis=0)

    def body(_, v):
        diff = G - v[None, :]
        norms = jnp.linalg.norm(diff, axis=1)
        scale = jnp.minimum(1.0, tau / jnp.maximum(norms, 1e-12))
        return v + jnp.mean(diff * scale[:, None], axis=0)

    v = lax.fori_loop(0, iters, body, v0)
    if not telemetry:
        return v
    norms = jnp.linalg.norm(G - v[None, :], axis=1)
    scale = jnp.minimum(1.0, tau / jnp.maximum(norms, 1e-12))
    return v, {"clip_scale": scale,
               "clipped_count": jnp.sum(scale < 1.0).astype(jnp.int32)}
